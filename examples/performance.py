#!/usr/bin/env python3
"""Performance harness (reference: examples/rdkafka_performance.c, the
benchmark tool of record — produce mode prints msgs/s and MB/s like
:555-644; latency decomposition comes from the stats blob).

    python examples/performance.py -P               # produce to mock
    python examples/performance.py -P -z lz4 -s 1024 -c 200000
    python examples/performance.py -C               # consume back
    python examples/performance.py -P -b host:9092 -t topic
"""
import argparse
import json
import time

from librdkafka_tpu import Consumer, Producer


def produce_mode(args):
    conf = {"bootstrap.servers": args.bootstrap,
            "linger.ms": args.linger,
            "batch.num.messages": args.batch,
            "compression.codec": args.codec,
            "compression.backend": args.backend,
            "statistics.interval.ms": 3000,
            "stats_cb": lambda js: stats.append(json.loads(js))}
    if not args.bootstrap:
        conf["test.mock.num.brokers"] = args.mock_brokers
        conf["test.mock.default.partitions"] = args.partitions
    stats = []
    delivered = [0]
    errors = [0]

    def on_dr(err, msg):
        if err is None:
            delivered[0] += 1
        else:
            errors[0] += 1

    # -l: per-message produce->delivery latency, the
    # rdkafka_performance latency mode (reference
    # examples/rdkafka_performance.c:70,112-118 — msg opaque carries the
    # send timestamp, the DR handler feeds the histogram). Tracking a
    # timestamp per message forgoes the zero-alloc fast lane, exactly
    # like the reference's -l forgoes its zero-copy path.
    lat = None
    if args.latency:
        from librdkafka_tpu.utils.hdrhistogram import HdrHistogram
        lat = HdrHistogram(1, 60_000_000, 3)   # 1us .. 60s

        def on_dr_lat(err, msg):
            if err is None:
                delivered[0] += 1
                lat.record(max(1, int((time.monotonic() - msg.opaque)
                                      * 1e6)))
            else:
                errors[0] += 1

        conf["dr_msg_cb"] = on_dr_lat
    else:
        conf["dr_msg_cb"] = on_dr
    p = Producer(conf)
    payload = bytes(bytearray(i & 0xFF for i in range(args.size)))
    t0 = time.monotonic()
    for i in range(args.count):
        while True:
            try:
                if lat is not None:
                    p.produce(args.topic, value=payload,
                              partition=i % args.partitions,
                              opaque=time.monotonic())
                else:
                    p.produce(args.topic, value=payload,
                              partition=i % args.partitions)
                break
            except BufferError:
                p.poll(0.01)
        if i % 10000 == 0:
            p.poll(0)
    rem = p.flush(300.0)
    dt = time.monotonic() - t0
    p.close()
    rate = delivered[0] / dt
    mb = delivered[0] * args.size / dt / 1e6
    print(f"% {delivered[0]} msgs delivered ({errors[0]} failed, "
          f"{rem} stuck) in {dt:.3f}s: {rate:,.0f} msgs/s, {mb:.2f} MB/s")
    if lat is not None and delivered[0]:
        p = lat.value_at_percentile
        print(f"% latency (us): min={lat.min_v} avg={lat.mean():.0f} "
              f"p50={p(50)} p95={p(95)} p99={p(99)} p99.99={p(99.99)} "
              f"max={lat.max_v}")
    if stats:
        il = stats[-1]["int_latency"]
        print(f"% int_latency p50={il['p50']}us p99={il['p99']}us")
    return rate


def consume_mode(args):
    conf = {"bootstrap.servers": args.bootstrap,
            "group.id": args.group,
            "auto.offset.reset": "earliest",
            "check.crcs": True}
    c = Consumer(conf)
    c.subscribe([args.topic])
    n = 0
    nbytes = 0
    t0 = None
    idle_deadline = time.monotonic() + 30
    lat = None
    if args.latency:
        from librdkafka_tpu.utils.hdrhistogram import HdrHistogram
        lat = HdrHistogram(1, 60_000_000, 3)
    while time.monotonic() < idle_deadline:
        m = c.poll(0.5)
        if m is None or m.error is not None:
            continue
        if t0 is None:
            t0 = time.monotonic()
        n += 1
        nbytes += len(m.value or b"")
        if lat is not None and m.timestamp:
            # end-to-end latency vs the producer CreateTime stamp
            # (reference -l consume path prints the same delta)
            lat.record(max(1, int(time.time() * 1000 - m.timestamp)
                           * 1000))
        idle_deadline = time.monotonic() + 3
        if args.count and n >= args.count:
            break
    dt = (time.monotonic() - t0) if t0 else 1
    c.close()
    print(f"% consumed {n} msgs in {dt:.3f}s: {n / dt:,.0f} msgs/s, "
          f"{nbytes / dt / 1e6:.2f} MB/s")
    if lat is not None and n:
        p = lat.value_at_percentile
        print(f"% e2e latency (us): min={lat.min_v} avg={lat.mean():.0f} "
              f"p50={p(50)} p99={p(99)} max={lat.max_v}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-P", action="store_true", help="produce mode")
    ap.add_argument("-C", action="store_true", help="consume mode")
    ap.add_argument("-b", dest="bootstrap", default="")
    ap.add_argument("-t", dest="topic", default="perf")
    ap.add_argument("-g", dest="group", default="perf-group")
    ap.add_argument("-s", dest="size", type=int, default=1024)
    ap.add_argument("-c", dest="count", type=int, default=100000)
    ap.add_argument("-l", dest="latency", action="store_true",
                    help="per-message latency mode (produce: "
                         "produce->DR; consume: CreateTime->poll)")
    ap.add_argument("-z", dest="codec", default="none",
                    choices=["none", "gzip", "snappy", "lz4", "zstd"])
    ap.add_argument("--backend", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--linger", type=int, default=50)
    ap.add_argument("--batch", type=int, default=10000)
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--mock-brokers", type=int, default=1)
    args = ap.parse_args()
    if args.C:
        consume_mode(args)
    else:
        produce_mode(args)


if __name__ == "__main__":
    main()
