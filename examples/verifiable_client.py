#!/usr/bin/env python
"""Apache Kafka ducktape-compatible verifiable client (the analog of
the reference's examples/kafkatest_verifiable_client.cpp): emits the
system-test JSON protocol on stdout so this framework can slot into
kafkatest-style orchestration.

Producer mode: sequential integer payloads, `producer_send_success` /
`producer_send_error` per delivery report, `tool_data` summary at exit.
Consumer mode: `records_consumed` batches (count + per-partition
min/max offsets), `offsets_committed` after each commit,
`partitions_assigned` / `partitions_revoked` on rebalance.
Both: `startup_complete` first, `shutdown_complete` last.

Examples:
  verifiable_client.py --producer --topic t --max-messages 1000 \\
      --bootstrap-server host:9092 [--acks -1] [--throughput N]
  verifiable_client.py --consumer --topic t --group-id g \\
      --bootstrap-server host:9092 [--max-messages N]
"""
import argparse
import json
import signal
import sys
import time

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librdkafka_tpu import Consumer, Producer  # noqa: E402
from librdkafka_tpu.client.errors import Err, KafkaException  # noqa: E402

run = True


def out(obj):
    print(json.dumps(obj), flush=True)


def do_producer(args):
    acked = [0]
    errors = [0]

    def dr(err, msg):
        if err is not None:
            errors[0] += 1
            out({"name": "producer_send_error", "message": str(err),
                 "topic": msg.topic, "key": None,
                 "value": msg.value.decode()})
        else:
            acked[0] += 1
            out({"name": "producer_send_success", "topic": msg.topic,
                 "partition": msg.partition, "offset": msg.offset,
                 "key": None, "value": msg.value.decode()})

    p = Producer({"bootstrap.servers": args.bootstrap_server,
                  "acks": args.acks, "linger.ms": 5,
                  "on_delivery": dr})
    out({"name": "startup_complete"})
    interval = 1.0 / args.throughput if args.throughput > 0 else 0
    sent = 0
    # --max-messages < 0 = unlimited (until SIGTERM), like the consumer
    while run and (args.max_messages < 0 or sent < args.max_messages):
        try:
            p.produce(args.topic, value=str(sent).encode())
        except KafkaException as e:
            if e.error.code != Err._QUEUE_FULL:
                raise       # fatal produce errors must surface, not spin
            # local queue full: serve delivery reports and retry
            # (the reference verifiable client does the same)
            p.poll(0.1)
            continue
        sent += 1
        p.poll(0)
        if interval:
            time.sleep(interval)
    p.flush(30.0)
    p.close()
    out({"name": "tool_data", "sent": sent, "acked": acked[0],
         "target_throughput": args.throughput})
    out({"name": "shutdown_complete"})


def do_consumer(args):
    ranges = {}            # (topic, part) -> [min, max]
    consumed = [0, 0]      # total, last-reported

    def report(immediate=False):
        if consumed[0] <= consumed[1] + (0 if immediate else 999):
            return
        out({"name": "records_consumed",
             "_totcount": consumed[0],
             "count": consumed[0] - consumed[1],
             "partitions": [
                 {"topic": t, "partition": pt,
                  "minOffset": lo, "maxOffset": hi}
                 for (t, pt), (lo, hi) in sorted(ranges.items())]})
        consumed[1] = consumed[0]
        ranges.clear()

    def on_assign(consumer, parts):
        # the rebalance-callback contract: the app applies the
        # assignment itself (confluent-kafka / reference rebalance_cb)
        consumer.assign(parts)
        out({"name": "partitions_assigned", "partitions": [
            {"topic": tp.topic, "partition": tp.partition}
            for tp in parts]})

    def on_revoke(consumer, parts):
        report(True)
        out({"name": "partitions_revoked", "partitions": [
            {"topic": tp.topic, "partition": tp.partition}
            for tp in parts]})
        consumer.unassign()

    c = Consumer({"bootstrap.servers": args.bootstrap_server,
                  "group.id": args.group_id,
                  "auto.offset.reset": "earliest",
                  "enable.auto.commit": False})
    c.subscribe([args.topic], on_assign=on_assign, on_revoke=on_revoke)
    out({"name": "startup_complete"})
    last_commit = time.monotonic()
    while run and (args.max_messages < 0 or consumed[0] < args.max_messages):
        m = c.poll(0.5)
        if m is None or m.error is not None:
            continue
        consumed[0] += 1
        key = (m.topic, m.partition)
        lo, hi = ranges.get(key, (m.offset, m.offset))
        ranges[key] = (min(lo, m.offset), max(hi, m.offset))
        report()
        if time.monotonic() - last_commit >= args.commit_interval_ms / 1e3:
            report(True)
            commit(c)
            last_commit = time.monotonic()
    report(True)
    commit(c)
    c.close()
    out({"name": "shutdown_complete"})


def commit(c):
    try:
        offsets = c.commit()
        out({"name": "offsets_committed", "success": True,
             "offsets": [
                 {"topic": tp.topic, "partition": tp.partition,
                  "offset": tp.offset} for tp in (offsets or [])]})
    except Exception as e:
        out({"name": "offsets_committed", "success": False,
             "error": str(e)})


def main():
    ap = argparse.ArgumentParser()
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--producer", action="store_true")
    mode.add_argument("--consumer", action="store_true")
    ap.add_argument("--topic", required=True)
    ap.add_argument("--bootstrap-server", "--broker-list",
                    dest="bootstrap_server", required=True)
    ap.add_argument("--max-messages", type=int, default=-1)
    ap.add_argument("--throughput", type=int, default=-1)
    ap.add_argument("--acks", type=int, default=-1)
    ap.add_argument("--group-id", default="verifiable")
    ap.add_argument("--commit-interval-ms", type=int, default=5000)
    args = ap.parse_args()

    def stop(_sig, _frm):
        global run
        run = False
    signal.signal(signal.SIGTERM, stop)
    signal.signal(signal.SIGINT, stop)

    if args.producer:
        do_producer(args)
    else:
        do_consumer(args)


if __name__ == "__main__":
    main()
