/* C client example over the framework's C ABI (tkafka.h + libtkafka.so,
 * built by `python -m librdkafka_tpu.capi.build_capi`) — the second-
 * language binding surface, playing the role src-cpp/ plays for the
 * reference.
 *
 * Build:
 *   python -m librdkafka_tpu.capi.build_capi
 *   gcc -o capi_client examples/capi_client.c \
 *       -I librdkafka_tpu/capi -L librdkafka_tpu/capi -ltkafka \
 *       -Wl,-rpath,$PWD/librdkafka_tpu/capi
 *   ./capi_client "" 100            # in-process mock cluster
 *   ./capi_client host:9092 100     # external broker/mock
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "tkafka.h"

int main(int argc, char **argv) {
    const char *bootstrap = argc > 1 ? argv[1] : "";
    int count = argc > 2 ? atoi(argv[2]) : 100;
    char errstr[512];
    char conf[512];

    if (bootstrap[0] == '\0')
        snprintf(conf, sizeof(conf),
                 "{\"bootstrap.servers\": \"\","
                 " \"test.mock.num.brokers\": 1,"
                 " \"compression.codec\": \"lz4\", \"linger.ms\": 5}");
    else
        snprintf(conf, sizeof(conf),
                 "{\"bootstrap.servers\": \"%s\","
                 " \"compression.codec\": \"lz4\", \"linger.ms\": 5}",
                 bootstrap);

    tk_handle_t p = tk_producer_new(conf, errstr, sizeof(errstr));
    if (!p) {
        fprintf(stderr, "producer_new failed: %s\n", errstr);
        return 1;
    }
    char payload[128];
    for (int i = 0; i < count; i++) {
        snprintf(payload, sizeof(payload), "c-example-%06d", i);
        if (tk_produce(p, "capi-topic", 0, NULL, 0,
                       payload, strlen(payload)) != 0) {
            fprintf(stderr, "produce %d failed\n", i);
            return 1;
        }
    }
    if (tk_flush(p, 30000) != 0) {
        fprintf(stderr, "flush left messages undelivered\n");
        return 1;
    }
    printf("produced %d messages\n", count);

    char bs[256];
    if (bootstrap[0] == '\0') {
        if (tk_mock_bootstrap(p, bs, sizeof(bs)) <= 0) {
            fprintf(stderr, "mock_bootstrap failed\n");
            return 1;
        }
        bootstrap = bs;
    }
    snprintf(conf, sizeof(conf),
             "{\"bootstrap.servers\": \"%s\", \"group.id\": \"capi-g\","
             " \"auto.offset.reset\": \"earliest\","
             " \"check.crcs\": true}", bootstrap);
    tk_handle_t c = tk_consumer_new(conf, errstr, sizeof(errstr));
    if (!c) {
        fprintf(stderr, "consumer_new failed: %s\n", errstr);
        return 1;
    }
    if (tk_subscribe(c, "capi-topic") != 0) {
        fprintf(stderr, "subscribe failed\n");
        return 1;
    }
    int got = 0, polls = 0;
    while (got < count && polls++ < 600) {
        tk_msg_t m;
        int r = tk_consumer_poll(c, 100, &m);
        if (r < 0) {
            fprintf(stderr, "poll error %d\n", r);
            return 1;
        }
        if (r == 1) {
            if (m.err == 0)
                got++;
            tk_msg_free(&m);
        }
    }
    printf("consumed %d messages\n", got);
    tk_destroy(c);
    tk_destroy(p);
    return got == count ? 0 : 1;
}
