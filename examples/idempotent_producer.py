#!/usr/bin/env python3
"""Idempotent producer example (reference:
examples/idempotent_producer.c): enable.idempotence=true gives strict
ordering and exactly-once delivery per partition; fatal errors indicate
a broken guarantee and must abort."""
import sys

from librdkafka_tpu import Producer


def main():
    bootstrap = sys.argv[1] if len(sys.argv) > 1 else ""
    conf = {"bootstrap.servers": bootstrap,
            "enable.idempotence": True,
            "error_cb": lambda err: (print(f"FATAL: {err}"), sys.exit(1))
            if err.fatal else print(f"error: {err}")}
    if not bootstrap:
        conf["test.mock.num.brokers"] = 1
    p = Producer(conf)
    for i in range(100):
        p.produce("idemp", value=b"exactly-once %d" % i)
    print("flushed,", p.flush(30.0), "remaining;",
          "PID:", p.rk.idemp.pid, "epoch:", p.rk.idemp.epoch)
    p.close()


if __name__ == "__main__":
    main()
