#!/usr/bin/env python3
"""Admin client example (reference: examples/rdkafka_example usage of
the Admin API): create a topic, grow it, inspect configs, list groups.

    python examples/admin.py                 # against an in-process mock
    python examples/admin.py host:9092       # against a real bootstrap
"""
import sys

from librdkafka_tpu import AdminClient, ConfigResource, NewPartitions, NewTopic


def main():
    bootstrap = sys.argv[1] if len(sys.argv) > 1 else ""
    conf = {"bootstrap.servers": bootstrap}
    mock = None
    if not bootstrap:
        from librdkafka_tpu.mock.cluster import MockCluster
        mock = MockCluster(num_brokers=3, auto_create_topics=False)
        conf["bootstrap.servers"] = mock.bootstrap_servers()
    a = AdminClient(conf)

    for topic, fut in a.create_topics([NewTopic("demo", num_partitions=2),
                                       NewTopic("demo2", num_partitions=1)]
                                      ).items():
        try:
            fut.result(15)
            print(f"created {topic}")
        except Exception as e:
            print(f"create {topic} failed: {e}")

    a.create_partitions([NewPartitions("demo", 4)])["demo"].result(15)
    md = a.list_topics(10)
    print("topics:", {t: len(ps) for t, ps in md["topics"].items()},
          "| controller:", md["controller_id"])

    res = ConfigResource(ConfigResource.TOPIC, "demo")
    entries = a.describe_configs([res])[res].result(15)
    for name, e in sorted(entries.items()):
        print(f"  config {name} = {e.value}")

    print("groups:", a.list_groups().result(15))
    a.delete_topics(["demo2"])["demo2"].result(15)
    print("deleted demo2; topics now:",
          list(a.list_topics(10)["topics"]))
    a.close()
    if mock is not None:
        mock.stop()


if __name__ == "__main__":
    main()
