#!/usr/bin/env python3
"""Balanced consumer example (reference: examples/consumer.c):
subscribe, poll, commit via the group coordinator.

    python examples/consumer.py host:9092 mytopic mygroup
"""
import sys

from librdkafka_tpu import Consumer


def main():
    if len(sys.argv) < 4:
        print(f"usage: {sys.argv[0]} <bootstrap> <topic> <group>")
        return
    bootstrap, topic, group = sys.argv[1:4]
    c = Consumer({"bootstrap.servers": bootstrap,
                  "group.id": group,
                  "auto.offset.reset": "earliest",
                  "enable.auto.commit": True})
    c.subscribe([topic])
    try:
        while True:
            m = c.poll(1.0)
            if m is None:
                continue
            if m.error is not None:
                print(f"consumer error: {m.error}")
                continue
            print(f"{m.topic}[{m.partition}]@{m.offset}: "
                  f"key={m.key} value={m.value[:60]}")
    except KeyboardInterrupt:
        pass
    finally:
        c.close()


if __name__ == "__main__":
    main()
