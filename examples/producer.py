#!/usr/bin/env python3
"""Minimal producer example (reference: examples/producer.c).

Run against the in-process mock cluster (no broker needed):
    python examples/producer.py
or a real/bootstrap address:
    python examples/producer.py host:9092 mytopic
"""
import sys

from librdkafka_tpu import Producer


def main():
    bootstrap = sys.argv[1] if len(sys.argv) > 1 else ""
    topic = sys.argv[2] if len(sys.argv) > 2 else "example"
    conf = {"bootstrap.servers": bootstrap, "linger.ms": 5,
            "compression.codec": "lz4"}
    if not bootstrap:
        conf["test.mock.num.brokers"] = 1

    def on_dr(err, msg):
        if err is not None:
            print(f"delivery FAILED: {err}")
        else:
            print(f"delivered to {msg.topic}[{msg.partition}]@{msg.offset}")

    conf["dr_msg_cb"] = on_dr
    p = Producer(conf)
    for i in range(10):
        p.produce(topic, value=b"hello %d" % i, key=b"key%d" % i)
    remaining = p.flush(10.0)
    print(f"flush done, {remaining} messages remaining")
    p.close()


if __name__ == "__main__":
    main()
