/* C++ client example over the tkafka.hpp RAII wrapper (the rebuild's
 * src-cpp analog; reference: examples/rdkafka_example.cpp).
 *
 * Round trip: producer with a DeliveryReportCb + headers -> in-process
 * mock cluster -> consumer group reads everything back, verifies
 * payloads + raw-byte header values, commits. Prints CPP-OK on
 * success; exits non-zero on any failure.
 *
 * Build (see tests/test_0115_capi.py):
 *   g++ -std=c++17 cpp_client.cpp -I<capi> -L<capi> -ltkafka
 */
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "tkafka.hpp"

class CountingDr : public tkafka::DeliveryReportCb {
  public:
    long long ok = 0, failed = 0, opaque_sum = 0;
    void dr_cb(long long opaque, int err, int32_t, int64_t) override {
        if (err == 0) {
            ok++;
            opaque_sum += opaque;
        } else {
            failed++;
        }
    }
};

class StatsEv : public tkafka::EventCb {
  public:
    int stats_seen = 0;
    void stats_cb(const char *json) override {
        if (json && std::strstr(json, "\"brokers\"")) stats_seen++;
    }
};

int main() {
    const int N = 40;
    std::string errstr;

    tkafka::Conf pconf;
    pconf.set("bootstrap.servers", "");
    pconf.set("test.mock.num.brokers", "1");
    pconf.set("linger.ms", "5");
    pconf.set("compression.codec", "lz4");
    pconf.set("statistics.interval.ms", "100");
    std::unique_ptr<tkafka::Producer> p(
        tkafka::Producer::create(pconf, errstr));
    if (!p) {
        std::fprintf(stderr, "producer: %s\n", errstr.c_str());
        return 1;
    }
    CountingDr dr;
    StatsEv ev;
    p->set_dr_cb(&dr);
    p->set_event_cb(&ev);

    if (p->create_topic("cppt", 2) != 0) {
        std::fprintf(stderr, "create_topic failed\n");
        return 1;
    }

    const char binval[4] = {'\0', '\x01', '\xfe', 'z'};
    for (int i = 0; i < N; i++) {
        char val[64], key[16];
        std::snprintf(val, sizeof val, "cpp-message-%03d", i);
        std::snprintf(key, sizeof key, "k%d", i);
        std::vector<tkafka::Header> hs = {
            {"lang", "c++17", false},
            {"bin", std::string(binval, 4), false},
        };
        if (p->produce("cppt", i % 2, val, std::strlen(val), key,
                       std::strlen(key), hs, 0, i) != 0) {
            std::fprintf(stderr, "produce %d failed\n", i);
            return 1;
        }
    }
    if (p->flush(30000) != 0) {
        std::fprintf(stderr, "flush left messages\n");
        return 1;
    }
    if (dr.ok != N || dr.failed != 0
        || dr.opaque_sum != 1LL * N * (N - 1) / 2) {
        std::fprintf(stderr, "dr: ok=%lld failed=%lld opq=%lld\n", dr.ok,
                     dr.failed, dr.opaque_sum);
        return 1;
    }
    for (int i = 0; i < 50 && !ev.stats_seen; i++) p->poll(100);
    if (!ev.stats_seen) {
        std::fprintf(stderr, "no stats event\n");
        return 1;
    }

    tkafka::Conf cconf;
    cconf.set("bootstrap.servers", p->mock_bootstrap());
    cconf.set("group.id", "gcpp");
    cconf.set("auto.offset.reset", "earliest");
    cconf.set("check.crcs", "true");
    std::unique_ptr<tkafka::Consumer> c(
        tkafka::Consumer::create(cconf, errstr));
    if (!c) {
        std::fprintf(stderr, "consumer: %s\n", errstr.c_str());
        return 1;
    }
    c->subscribe({"cppt"});

    int got = 0, hdr_ok = 0, bin_ok = 0;
    for (int polls = 0; got < N && polls < 600; polls++) {
        std::unique_ptr<tkafka::Message> m(c->consume(100));
        if (!m) continue;
        if (m->err() != 0) continue;
        got++;
        if (m->value().rfind("cpp-message-", 0) != 0) {
            std::fprintf(stderr, "bad payload %s\n", m->value().c_str());
            return 1;
        }
        for (const auto &h : m->headers()) {
            if (h.first == "lang" && h.second == "c++17") hdr_ok++;
            if (h.first == "bin" && h.second == std::string(binval, 4))
                bin_ok++;
        }
    }
    if (got != N || hdr_ok != N || bin_ok != N) {
        std::fprintf(stderr, "consume got=%d hdr=%d bin=%d\n", got,
                     hdr_ok, bin_ok);
        return 1;
    }
    if (c->commit(false) != 0) {
        std::fprintf(stderr, "commit failed\n");
        return 1;
    }
    long long c0 = c->committed("cppt", 0), c1 = c->committed("cppt", 1);
    if ((c0 > 0 ? c0 : 0) + (c1 > 0 ? c1 : 0) != N) {
        std::fprintf(stderr, "committed %lld+%lld != %d\n", c0, c1, N);
        return 1;
    }

    /* admin from C++ */
    if (p->create_partitions("cppt", 4) != 0) {
        std::fprintf(stderr, "create_partitions failed\n");
        return 1;
    }
    std::string cfg = p->describe_configs(2 /* TOPIC */, "cppt");
    if (cfg.empty() || cfg[0] != '{') {
        std::fprintf(stderr, "describe_configs: %s\n", cfg.c_str());
        return 1;
    }
    std::string groups = p->list_groups();
    if (groups.find("gcpp") == std::string::npos) {
        std::fprintf(stderr, "list_groups: %s\n", groups.c_str());
        return 1;
    }

    std::printf("CPP-OK produced=%d consumed=%d headers-raw=%d stats=%d "
                "admin-ok v=%s\n",
                N, got, bin_ok, ev.stats_seen,
                tkafka::version().c_str());
    return 0;
}
