"""Protocol constants: ApiKeys, attributes, MessageSet v2 layout offsets.

Mirrors src/rdkafka_proto.h (ApiKeys, RD_KAFKAP_MSGSET_V2_OF_* offsets) —
these are public Apache Kafka protocol constants.
"""
from __future__ import annotations

import enum


class ApiKey(enum.IntEnum):
    Produce = 0
    Fetch = 1
    ListOffsets = 2
    Metadata = 3
    OffsetCommit = 8
    OffsetFetch = 9
    FindCoordinator = 10
    JoinGroup = 11
    Heartbeat = 12
    LeaveGroup = 13
    SyncGroup = 14
    DescribeGroups = 15
    ListGroups = 16
    SaslHandshake = 17
    ApiVersions = 18
    CreateTopics = 19
    DeleteTopics = 20
    DeleteRecords = 21
    InitProducerId = 22
    AddPartitionsToTxn = 24
    AddOffsetsToTxn = 25
    EndTxn = 26
    TxnOffsetCommit = 28
    DescribeAcls = 29
    CreateAcls = 30
    DeleteAcls = 31
    DescribeConfigs = 32
    AlterConfigs = 33
    SaslAuthenticate = 36
    CreatePartitions = 37
    DeleteGroups = 42


# MessageSet/RecordBatch compression attribute bits (Attributes int16)
ATTR_CODEC_MASK = 0x07
ATTR_CODEC_NONE = 0
ATTR_CODEC_GZIP = 1
ATTR_CODEC_SNAPPY = 2
ATTR_CODEC_LZ4 = 3
ATTR_CODEC_ZSTD = 4
ATTR_TIMESTAMP_TYPE = 1 << 3      # 0=CreateTime, 1=LogAppendTime
ATTR_TRANSACTIONAL = 1 << 4
ATTR_CONTROL = 1 << 5

CODEC_NAMES = {ATTR_CODEC_GZIP: "gzip", ATTR_CODEC_SNAPPY: "snappy",
               ATTR_CODEC_LZ4: "lz4", ATTR_CODEC_ZSTD: "zstd"}
CODEC_IDS = {v: k for k, v in CODEC_NAMES.items()}

# RecordBatch (MessageSet v2) header field offsets, relative to batch start
# (reference: RD_KAFKAP_MSGSET_V2_OF_* in src/rdkafka_proto.h).
V2_OF_BaseOffset = 0            # int64
V2_OF_Length = 8                # int32: bytes after this field
V2_OF_PartitionLeaderEpoch = 12  # int32
V2_OF_Magic = 16                # int8 == 2
V2_OF_CRC = 17                  # uint32 crc32c over [Attributes..end]
V2_OF_Attributes = 21           # int16
V2_OF_LastOffsetDelta = 23      # int32
V2_OF_FirstTimestamp = 27       # int64
V2_OF_MaxTimestamp = 35         # int64
V2_OF_ProducerId = 43           # int64
V2_OF_ProducerEpoch = 51        # int16
V2_OF_BaseSequence = 53         # int32
V2_OF_RecordCount = 57          # int32
V2_OF_Records = 61              # first record
V2_HEADER_SIZE = V2_OF_Records

# Legacy MessageSet (MsgVer 0/1) per-message layout
V01_OF_Offset = 0
V01_OF_MessageSize = 8
V01_OF_Crc = 12                 # zlib crc32 over [Magic..end]
V01_OF_Magic = 16
V01_OF_Attributes = 17

# Timestamp types (public API values; reference rdkafka.h timestamp enum)
TSTYPE_NOT_AVAILABLE = 0
TSTYPE_CREATE_TIME = 1
TSTYPE_LOG_APPEND_TIME = 2

# Control record keys (version int16, type int16): abort=0, commit=1
CTRL_ABORT = 0
CTRL_COMMIT = 1

RD_KAFKAP_PARTITIONS_MAX = 100000
UNKNOWN_OFFSET = -1001  # RD_KAFKA_OFFSET_INVALID
OFFSET_BEGINNING = -2
OFFSET_END = -1
OFFSET_STORED = -1000
OFFSET_INVALID = -1001
