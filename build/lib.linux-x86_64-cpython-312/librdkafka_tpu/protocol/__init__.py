"""librdkafka_tpu.protocol"""
