"""Interceptor chains (reference: src/rdkafka_interceptor.c).

Hook points mirror rdkafka_interceptor.h:33-72: on_conf_set, on_new,
on_destroy, on_send, on_acknowledgement, on_consume, on_commit,
on_request_sent, on_thread_start/exit. Plugins (``plugin.library.paths``)
are Python entry points ``module:function`` whose conf_init() registers
interceptors — the same gating boundary the reference uses for codec
providers (src/rdkafka_plugin.c).
"""
from __future__ import annotations

import importlib
from typing import Callable


HOOKS = ("on_conf_set", "on_new", "on_destroy", "on_send",
         "on_acknowledgement", "on_consume", "on_commit",
         "on_request_sent", "on_thread_start", "on_thread_exit")


class InterceptorChain:
    def __init__(self):
        self._hooks: dict[str, list[tuple[str, Callable]]] = {h: [] for h in HOOKS}

    def add(self, name: str, hook: str, fn: Callable) -> None:
        if hook not in self._hooks:
            raise ValueError(f"unknown interceptor hook {hook!r}")
        self._hooks[hook].append((name, fn))

    def _call(self, hook: str, *args):
        for _name, fn in self._hooks[hook]:
            try:
                fn(*args)
            except Exception:
                pass  # interceptor failures must not break the client

    def __getattr__(self, hook):
        if hook in HOOKS:
            return lambda *a: self._call(hook, *a)
        raise AttributeError(hook)

    def __len__(self):
        return sum(len(v) for v in self._hooks.values())


def load_plugins(paths: str, conf) -> InterceptorChain:
    """Load plugin modules listed in plugin.library.paths; each entry is
    ``module`` or ``module:func``; the callable receives (conf, chain) and
    registers interceptors (the conf_init() contract)."""
    chain = conf.get("interceptors") or InterceptorChain()
    for entry in (paths or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        mod_name, _, fn_name = entry.partition(":")
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, fn_name or "conf_init")
        fn(conf, chain)
    return chain
