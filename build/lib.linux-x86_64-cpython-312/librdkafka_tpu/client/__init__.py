"""librdkafka_tpu.client"""
