"""Broker feature negotiation (reference: src/rdkafka_feature.c, 474 LoC).

Maps a broker's advertised ApiVersion ranges to a feature set
(RD_KAFKA_FEATURE_*, rdkafka_feature.h:39-83) that gates what the
client emits: MessageSet version, request versions, ZSTD, idempotence.
When ApiVersions is unsupported (pre-0.10 brokers close the connection
on unknown requests) or disabled (``api.version.request=false``), the
``broker.version.fallback`` property synthesizes an assumed version map
(reference rd_kafka_get_legacy_ApiVersions)."""
from __future__ import annotations

from ..protocol.proto import ApiKey

# feature flags (names follow RD_KAFKA_FEATURE_*)
MSGVER1 = "MSGVER1"                  # magic 1 msgsets (timestamps)
MSGVER2 = "MSGVER2"                  # magic 2 record batches
APIVERSION = "APIVERSION"
BROKER_GROUP_COORDINATOR = "BROKER_GROUP_COORDINATOR"
BROKER_BALANCED_CONSUMER = "BROKER_BALANCED_CONSUMER"
THROTTLETIME = "THROTTLETIME"
OFFSET_TIME = "OFFSET_TIME"
IDEMPOTENT_PRODUCER = "IDEMPOTENT_PRODUCER"
SASL_AUTH_REQ = "SASL_AUTH_REQ"
LZ4 = "LZ4"
ZSTD = "ZSTD"

#: feature → [(api, min_version_required)] (rdkafka_feature.c feature map)
_FEATURE_REQS = {
    MSGVER1: [(ApiKey.Produce, 2), (ApiKey.Fetch, 2)],
    MSGVER2: [(ApiKey.Produce, 3), (ApiKey.Fetch, 4)],
    APIVERSION: [(ApiKey.ApiVersions, 0)],
    BROKER_GROUP_COORDINATOR: [(ApiKey.FindCoordinator, 0)],
    BROKER_BALANCED_CONSUMER: [(ApiKey.FindCoordinator, 0),
                               (ApiKey.OffsetCommit, 1),
                               (ApiKey.OffsetFetch, 1),
                               (ApiKey.JoinGroup, 0),
                               (ApiKey.SyncGroup, 0),
                               (ApiKey.Heartbeat, 0),
                               (ApiKey.LeaveGroup, 0)],
    THROTTLETIME: [(ApiKey.Produce, 1), (ApiKey.Fetch, 1)],
    OFFSET_TIME: [(ApiKey.ListOffsets, 1)],
    IDEMPOTENT_PRODUCER: [(ApiKey.InitProducerId, 0)],
    SASL_AUTH_REQ: [(ApiKey.SaslHandshake, 1),
                    (ApiKey.SaslAuthenticate, 0)],
    LZ4: [(ApiKey.FindCoordinator, 0)],     # >=0.8.3 (like reference)
    ZSTD: [(ApiKey.Produce, 7), (ApiKey.Fetch, 10)],
}


def features_from_api_versions(api_versions: dict[int, int]) -> set[str]:
    """{api_key: max_version} → feature set (rd_kafka_features_check)."""
    out = set()
    for feature, reqs in _FEATURE_REQS.items():
        if all(int(api) in api_versions and api_versions[int(api)] >= minv
               for api, minv in reqs):
            out.add(feature)
    return out


#: broker.version.fallback → assumed {api_key: max_version}
#: (reference rd_kafka_get_legacy_ApiVersions, rdkafka_feature.c)
def fallback_api_versions(version: str) -> dict[int, int]:
    v = _parse_version(version)
    av: dict[int, int] = {}

    def put(api, maxv):
        av[int(api)] = maxv

    # 0.8.x baseline
    put(ApiKey.Produce, 0)
    put(ApiKey.Fetch, 0)
    put(ApiKey.ListOffsets, 0)
    put(ApiKey.Metadata, 0)
    put(ApiKey.OffsetCommit, 0)
    put(ApiKey.OffsetFetch, 0)
    if v >= (0, 8, 3):
        put(ApiKey.FindCoordinator, 0)
        put(ApiKey.OffsetFetch, 1)
    if v >= (0, 9, 0):
        put(ApiKey.Produce, 1)
        put(ApiKey.Fetch, 1)
        put(ApiKey.OffsetCommit, 2)
        put(ApiKey.JoinGroup, 0)
        put(ApiKey.SyncGroup, 0)
        put(ApiKey.Heartbeat, 0)
        put(ApiKey.LeaveGroup, 0)
        put(ApiKey.ListGroups, 0)
        put(ApiKey.DescribeGroups, 0)
    if v >= (0, 10, 0):
        put(ApiKey.Produce, 2)
        put(ApiKey.Fetch, 2)
        put(ApiKey.ApiVersions, 0)
        put(ApiKey.SaslHandshake, 0)
    if v >= (0, 10, 1):
        put(ApiKey.Fetch, 3)
        put(ApiKey.ListOffsets, 1)
        put(ApiKey.JoinGroup, 1)
        put(ApiKey.CreateTopics, 0)
        put(ApiKey.DeleteTopics, 0)
    if v >= (0, 10, 2):
        put(ApiKey.OffsetFetch, 2)
        put(ApiKey.Metadata, 2)
    if v >= (0, 11, 0):
        put(ApiKey.Produce, 3)
        put(ApiKey.Fetch, 4)
        put(ApiKey.InitProducerId, 0)
        put(ApiKey.SaslHandshake, 1)
        put(ApiKey.SaslAuthenticate, 0)
        put(ApiKey.CreatePartitions, 0)
        put(ApiKey.DescribeConfigs, 0)
        put(ApiKey.AlterConfigs, 0)
        put(ApiKey.DeleteGroups, 0)
    if v >= (1, 0, 0):
        put(ApiKey.Metadata, 5)
        put(ApiKey.FindCoordinator, 1)
        put(ApiKey.JoinGroup, 2)
        put(ApiKey.SyncGroup, 1)
        put(ApiKey.Heartbeat, 1)
        put(ApiKey.LeaveGroup, 1)
        put(ApiKey.CreateTopics, 2)
        put(ApiKey.DeleteTopics, 1)
        put(ApiKey.CreatePartitions, 1)
        put(ApiKey.DescribeConfigs, 1)
        put(ApiKey.InitProducerId, 1)
    return av


def _parse_version(s: str) -> tuple:
    parts = []
    for tok in s.strip().split("."):
        digits = "".join(ch for ch in tok if ch.isdigit())
        if not digits:
            break
        parts.append(int(digits))
    while len(parts) < 3:
        parts.append(0)
    return tuple(parts[:3])


def pick_version(api_versions: dict[int, int], api: ApiKey,
                 ours: int) -> int:
    """min(our max, broker max); broker-unknown APIs assume ours."""
    theirs = api_versions.get(int(api))
    return ours if theirs is None else min(ours, theirs)
