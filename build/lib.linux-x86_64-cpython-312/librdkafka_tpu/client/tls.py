"""TLS transport support (reference: src/rdkafka_ssl.c, src/rdkafka_cert.c).

The reference builds one OpenSSL ``SSL_CTX`` per client instance at
``rd_kafka_ssl_ctx_init`` (rdkafka_ssl.c:~1100) from the ``ssl.*``
configuration properties, loading CA bundles, client cert/key pairs and
PKCS#12 keystores (rdkafka_cert.c:~200), then drives the per-connection
handshake from the transport poll loop (rdkafka_transport.c:612-719).

This module is the TPU-rebuild equivalent: ``make_client_ctx(conf)``
constructs a single :class:`ssl.SSLContext` per client from the same
property names; the broker thread drives the non-blocking handshake in
its connection FSM (client/broker.py, state CONNECT).
"""
from __future__ import annotations

import os
import ssl
import tempfile
from typing import Optional

from .errors import Err, KafkaError, KafkaException


def uses_ssl(conf) -> bool:
    return conf.get("security.protocol") in ("ssl", "sasl_ssl")


def make_client_ctx(conf) -> Optional[ssl.SSLContext]:
    """Build the client SSLContext from ``ssl.*`` conf properties.

    Maps the reference's property semantics (rdkafka_conf.c ssl section):
      - ssl.ca.location: CA bundle file or directory; default = system CAs
      - ssl.certificate.location / ssl.key.location / ssl.key.password:
        client cert+key PEM pair
      - ssl.keystore.location / ssl.keystore.password: PKCS#12 keystore
        holding the client key+cert (rdkafka_cert.c PKCS12 path)
      - ssl.cipher.suites: OpenSSL cipher list
      - enable.ssl.certificate.verification: peer verification on/off
      - ssl.endpoint.identification.algorithm: "https" = hostname check
    """
    if not uses_ssl(conf):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)

    verify = conf.get("enable.ssl.certificate.verification")
    algo = conf.get("ssl.endpoint.identification.algorithm")
    # check_hostname must be disabled before verify_mode can be relaxed
    ctx.check_hostname = bool(verify) and algo == "https"
    ctx.verify_mode = ssl.CERT_REQUIRED if verify else ssl.CERT_NONE

    ca = conf.get("ssl.ca.location")
    if ca:
        try:
            if os.path.isdir(ca):
                ctx.load_verify_locations(capath=ca)
            else:
                ctx.load_verify_locations(cafile=ca)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL, f"ssl.ca.location {ca!r}: {e}")
    elif verify:
        ctx.load_default_certs(ssl.Purpose.SERVER_AUTH)

    cert = conf.get("ssl.certificate.location")
    key = conf.get("ssl.key.location")
    if cert:
        try:
            ctx.load_cert_chain(cert, keyfile=key or None,
                                password=conf.get("ssl.key.password") or None)
        except (ssl.SSLError, OSError) as e:
            raise KafkaException(Err._SSL, f"client certificate: {e}")

    ks = conf.get("ssl.keystore.location")
    if ks:
        _load_pkcs12(ctx, ks, conf.get("ssl.keystore.password"))

    ciphers = conf.get("ssl.cipher.suites")
    if ciphers:
        try:
            ctx.set_ciphers(ciphers)
        except ssl.SSLError as e:
            raise KafkaException(Err._SSL, f"ssl.cipher.suites: {e}")
    return ctx


def _load_pkcs12(ctx: ssl.SSLContext, path: str, password: str) -> None:
    """PKCS#12 keystore → client cert chain (rdkafka_cert.c PKCS12 load).

    Python's ssl module cannot ingest PKCS#12 directly; decode with
    `cryptography` and hand the PEM material to the context through a
    transient file (deleted immediately after load).
    """
    try:
        from cryptography.hazmat.primitives.serialization import (
            Encoding, NoEncryption, PrivateFormat, pkcs12)
    except ImportError:
        raise KafkaException(Err._SSL,
                         "ssl.keystore.location requires the 'cryptography' "
                         "package for PKCS#12 decoding")
    try:
        blob = open(path, "rb").read()
        pw = password.encode() if password else None
        pkey, pcert, extra = pkcs12.load_key_and_certificates(blob, pw)
    except Exception as e:
        raise KafkaException(Err._SSL, f"ssl.keystore.location {path!r}: {e}")
    pem = b""
    if pkey is not None:
        pem += pkey.private_bytes(Encoding.PEM, PrivateFormat.PKCS8,
                                  NoEncryption())
    if pcert is not None:
        pem += pcert.public_bytes(Encoding.PEM)
    for c in extra or []:
        pem += c.public_bytes(Encoding.PEM)
    fd, tmp = tempfile.mkstemp(suffix=".pem")
    try:
        os.write(fd, pem)
        os.close(fd)
        ctx.load_cert_chain(tmp)
    finally:
        os.unlink(tmp)


def make_server_ctx(certfile: str, keyfile: str, cafile: str = None,
                    require_client_cert: bool = False) -> ssl.SSLContext:
    """Server-side context for the mock cluster's TLS listener mode."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    if cafile:
        ctx.load_verify_locations(cafile)
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
