"""Partition assignors + consumer-protocol metadata marshalling.

Reference: src/rdkafka_assignor.c (pluggable partition.assignment.strategy,
protocol metadata wire format) with the builtin range
(rdkafka_range_assignor.c) and roundrobin (rdkafka_roundrobin_assignor.c)
strategies; rd_kafka_assignor_run (:283) executes on the elected leader.

Wire formats are the public Kafka "consumer" embedded protocol:
  Subscription: Version i16, Topics [String], UserData Bytes
  Assignment:   Version i16, [Topic String, Partitions [Int32]], UserData
"""
from __future__ import annotations

from typing import Callable

from ..protocol.types import Array, Bytes, Int16, Int32, Schema, String
from ..utils.buf import SegBuf, Slice

SUBSCRIPTION_SCHEMA = Schema(
    ("version", Int16), ("topics", Array(String)), ("user_data", Bytes))
ASSIGNMENT_SCHEMA = Schema(
    ("version", Int16),
    ("topics", Array(Schema(("topic", String),
                            ("partitions", Array(Int32))))),
    ("user_data", Bytes))


def subscription_encode(topics: list[str], user_data: bytes = b"") -> bytes:
    buf = SegBuf()
    SUBSCRIPTION_SCHEMA.write(buf, {"version": 0, "topics": sorted(topics),
                                    "user_data": user_data})
    return buf.as_bytes()


def subscription_decode(data: bytes) -> dict:
    return SUBSCRIPTION_SCHEMA.read(Slice(data))


def assignment_encode(assignment: dict[str, list[int]],
                      user_data: bytes = b"") -> bytes:
    buf = SegBuf()
    ASSIGNMENT_SCHEMA.write(buf, {
        "version": 0,
        "topics": [{"topic": t, "partitions": sorted(ps)}
                   for t, ps in sorted(assignment.items())],
        "user_data": user_data})
    return buf.as_bytes()


def assignment_decode(data: bytes) -> dict[str, list[int]]:
    if not data:
        return {}
    parsed = ASSIGNMENT_SCHEMA.read(Slice(data))
    return {t["topic"]: t["partitions"] for t in parsed["topics"]}


def range_assignor(members: dict[str, list[str]],
                   partitions: dict[str, int]) -> dict[str, dict[str, list[int]]]:
    """Per-topic contiguous ranges (Java RangeAssignor semantics):
    for each topic, sort consumers; first (n_parts % n_consumers) consumers
    get one extra partition."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    topics: dict[str, list[str]] = {}
    for member, subscribed in members.items():
        for t in subscribed:
            topics.setdefault(t, []).append(member)
    for topic, consumers in topics.items():
        nparts = partitions.get(topic, 0)
        if nparts <= 0:
            continue
        consumers = sorted(consumers)
        n = len(consumers)
        per, extra = divmod(nparts, n)
        start = 0
        for i, c in enumerate(consumers):
            cnt = per + (1 if i < extra else 0)
            if cnt:
                out[c][topic] = list(range(start, start + cnt))
            start += cnt
    return out


def roundrobin_assignor(members: dict[str, list[str]],
                        partitions: dict[str, int]) -> dict[str, dict[str, list[int]]]:
    """All (topic, partition) pairs sorted, dealt round-robin to the sorted
    eligible consumers (Java RoundRobinAssignor semantics)."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m in members}
    pairs = []
    for t in sorted(partitions):
        for p in range(partitions[t]):
            pairs.append((t, p))
    consumers = sorted(members)
    i = 0
    for t, p in pairs:
        # find next consumer subscribed to t
        for _ in range(len(consumers)):
            c = consumers[i % len(consumers)]
            i += 1
            if t in members[c]:
                out[c].setdefault(t, []).append(p)
                break
    return out


ASSIGNORS: dict[str, Callable] = {
    "range": range_assignor,
    "roundrobin": roundrobin_assignor,
}
