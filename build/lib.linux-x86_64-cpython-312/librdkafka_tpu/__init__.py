"""librdkafka_tpu — a TPU-native Apache Kafka client framework.

A brand-new implementation with the capabilities of librdkafka v1.3.0
(reference: /root/reference, see SURVEY.md): producer (batched, compressed,
idempotent), simple + balanced consumers, admin client, statistics,
interceptors, and an in-process mock broker cluster — with the hot
MessageSet v2 codec path (per-batch compression + CRC32C) offloadable to
TPU via a JAX/Pallas sidecar selected by ``compression.backend=tpu``.

Layering (bottom → top), mirroring the reference's layer map (SURVEY.md §1):

- ``utils``    — L0/L1: segmented zero-copy buffers, varint, CRC32C, murmur2
- ``ops``      — codec providers: native C++ (ctypes) CPU path, JAX/Pallas TPU path
- ``protocol`` — L4/L6: Kafka wire protocol, MessageSet v2 writer/reader
- ``client``   — L5/L7/L8: broker engine, producer/consumer/admin, config, stats
- ``mock``     — in-process mock broker cluster (brokerless testing)
- ``parallel`` — multi-chip sharded codec offload over a jax.sharding.Mesh
- ``models``   — the flagship batched-codec pipeline (entry point for jit)
"""

__version__ = "0.1.0"
# Wire-compatible with the reference's feature level (rdkafka.h:151,
# RD_KAFKA_VERSION 0x010300ff == v1.3.0).
REFERENCE_VERSION = "1.3.0"

from .client.errors import KafkaError, KafkaException  # noqa: F401
from .client.conf import Conf, TopicConf  # noqa: F401
from .client.producer import Producer  # noqa: F401
from .client.consumer import Consumer  # noqa: F401
from .client.admin import (AdminClient, ConfigEntry, ConfigResource,  # noqa: F401
                           NewPartitions, NewTopic)
from .client.event import Event  # noqa: F401
