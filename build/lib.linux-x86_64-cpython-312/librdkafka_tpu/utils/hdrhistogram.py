"""High Dynamic Range histogram (reference: src/rdhdrhistogram.c, 729
LoC — the C port of Gil Tene's HdrHistogram used for all latency
percentiles in the stats blob, rdkafka.c:1582-1630).

Original implementation of the published HdrHistogram bucketing design:
values are indexed by (bucket, sub-bucket) where each bucket doubles the
value range and sub-buckets give `significant_figures` decimal digits of
relative resolution. Recording is O(1) into a fixed-size counts array;
percentile queries walk the array. No per-sample storage — memory is
constant no matter how many values are recorded (unlike a sample
reservoir, the tail percentiles are exact to the configured resolution).
"""
from __future__ import annotations


class HdrHistogram:
    """Fixed-memory histogram with bounded relative error.

    :param lowest: smallest trackable non-zero value (e.g. 1 µs)
    :param highest: largest trackable value (e.g. 60s in µs)
    :param sigfigs: decimal digits of resolution (1-5)
    """

    __slots__ = ("lowest", "highest", "sigfigs", "unit_magnitude",
                 "sub_bucket_half_count_magnitude", "sub_bucket_count",
                 "sub_bucket_half_count", "sub_bucket_mask", "bucket_count",
                 "counts", "total", "min_v", "max_v", "sum_v",
                 "out_of_range")

    def __init__(self, lowest: int = 1, highest: int = 60_000_000,
                 sigfigs: int = 3):
        if not (1 <= sigfigs <= 5):
            raise ValueError("sigfigs must be 1..5")
        if lowest < 1 or highest < 2 * lowest:
            raise ValueError("need lowest >= 1 and highest >= 2*lowest")
        self.lowest = lowest
        self.highest = highest
        self.sigfigs = sigfigs

        # smallest power of two that gives sigfigs decimal digits of
        # resolution within a single bucket
        largest_single_unit = 2 * (10 ** sigfigs)
        sub_bucket_count_mag = (largest_single_unit - 1).bit_length()
        self.sub_bucket_half_count_magnitude = max(sub_bucket_count_mag - 1, 0)
        self.unit_magnitude = lowest.bit_length() - 1   # floor(log2(lowest))
        self.sub_bucket_count = 1 << (self.sub_bucket_half_count_magnitude + 1)
        self.sub_bucket_half_count = self.sub_bucket_count >> 1
        self.sub_bucket_mask = ((self.sub_bucket_count - 1)
                                << self.unit_magnitude)

        # buckets needed to cover `highest`
        smallest_untrackable = self.sub_bucket_count << self.unit_magnitude
        buckets = 1
        while smallest_untrackable <= highest:
            if smallest_untrackable > (1 << 62):
                buckets += 1
                break
            smallest_untrackable <<= 1
            buckets += 1
        self.bucket_count = buckets

        counts_len = (buckets + 1) * self.sub_bucket_half_count
        self.counts = [0] * counts_len
        self.total = 0
        self.min_v = 0
        self.max_v = 0
        self.sum_v = 0
        self.out_of_range = 0

    # ------------------------------------------------------------ indexing --
    def _bucket_index(self, v: int) -> int:
        # position of the highest set bit above the sub-bucket range
        pow2ceil = (v | self.sub_bucket_mask).bit_length()
        return pow2ceil - self.unit_magnitude - (
            self.sub_bucket_half_count_magnitude + 1)

    def _sub_bucket_index(self, v: int, bucket: int) -> int:
        return v >> (bucket + self.unit_magnitude)

    def _counts_index(self, bucket: int, sub: int) -> int:
        base = (bucket + 1) << self.sub_bucket_half_count_magnitude
        return base + (sub - self.sub_bucket_half_count)

    def _value_from_index(self, idx: int) -> int:
        bucket = (idx >> self.sub_bucket_half_count_magnitude) - 1
        sub = ((idx & (self.sub_bucket_half_count - 1))
               + self.sub_bucket_half_count)
        if bucket < 0:
            bucket = 0
            sub -= self.sub_bucket_half_count
        return sub << (bucket + self.unit_magnitude)

    def _highest_equivalent(self, v: int) -> int:
        bucket = self._bucket_index(v)
        size = 1 << (bucket + self.unit_magnitude)
        lowest_eq = (self._sub_bucket_index(v, bucket)
                     << (bucket + self.unit_magnitude))
        return lowest_eq + size - 1

    # ------------------------------------------------------------- record --
    def record(self, v: int, count: int = 1) -> bool:
        """Record a value; returns False (and counts it out-of-range)
        if untrackable."""
        v = int(v)
        if v < 0 or v > self.highest:
            self.out_of_range += count
            return False
        bucket = self._bucket_index(v)
        sub = self._sub_bucket_index(v, bucket)
        self.counts[self._counts_index(bucket, sub)] += count
        self.total += count
        self.sum_v += v * count
        if self.total == count or v < self.min_v:
            self.min_v = v
        if v > self.max_v:
            self.max_v = v
        return True

    # ------------------------------------------------------------ queries --
    def value_at_percentile(self, pct: float) -> int:
        if self.total == 0:
            return 0
        target = int(pct / 100.0 * self.total + 0.5)
        target = max(1, min(target, self.total))
        running = 0
        for idx, c in enumerate(self.counts):
            if not c:
                continue
            running += c
            if running >= target:
                return min(self._highest_equivalent(
                    self._value_from_index(idx)), self.max_v)
        return self.max_v

    def snapshot(self, pcts) -> tuple[list, float]:
        """One walk over the counts array: values at each percentile of
        the ascending list ``pcts``, plus the stddev. This is what the
        stats emitter uses — eight separate walks per window would stall
        recorders on the hot path."""
        if self.total == 0:
            return [0] * len(pcts), 0.0
        targets = [max(1, min(int(p / 100.0 * self.total + 0.5), self.total))
                   for p in pcts]
        out = [self.max_v] * len(pcts)
        m = self.mean()
        acc = 0.0
        running = 0
        i = 0
        for idx, c in enumerate(self.counts):
            if not c:
                continue
            v = self._value_from_index(idx)
            d = v - m
            acc += d * d * c
            running += c
            while i < len(targets) and running >= targets[i]:
                out[i] = min(self._highest_equivalent(v), self.max_v)
                i += 1
        return out, (acc / self.total) ** 0.5

    def mean(self) -> float:
        return self.sum_v / self.total if self.total else 0.0

    def stddev(self) -> float:
        if not self.total:
            return 0.0
        m = self.mean()
        acc = 0.0
        for idx, c in enumerate(self.counts):
            if not c:
                continue
            d = self._value_from_index(idx) - m
            acc += d * d * c
        return (acc / self.total) ** 0.5

    def reset(self) -> None:
        self.counts = [0] * len(self.counts)
        self.total = 0
        self.min_v = self.max_v = self.sum_v = 0
        self.out_of_range = 0

    @property
    def memsize(self) -> int:
        return len(self.counts) * 8
