"""Offset-based socket buffer helpers (broker transport + mock cluster).

The reference sends straight from segmented buffers via iovecs
(rd_kafka_transport_socket_sendmsg, rdkafka_transport.c:109).  The
Python analog keeps one bytearray per connection and consumes it by
OFFSET: the previous ``del buf[:n]`` pattern memmoved the whole
remaining buffer once per socket chunk (~16MB of GIL-held shifting per
1MB batch).

The memoryview discipline here is load-bearing: a raising ``send()``
pins the traceback — and with it any live buffer export — so the chunk
view must be released in a ``finally`` or a later ``buf.clear()``
raises BufferError.
"""
from __future__ import annotations

import ssl as _ssl
import struct
from typing import Optional

#: consumed-prefix size at which the buffer is compacted even though it
#: has not fully drained (sustained backpressure must not retain every
#: byte ever sent)
COMPACT_THRESHOLD = 1 << 20

_WOULD_BLOCK = (_ssl.SSLWantReadError, _ssl.SSLWantWriteError,
                BlockingIOError, InterruptedError)


def send_from(sock, buf: bytearray,
              off: int) -> tuple[int, bool, Optional[OSError]]:
    """Send buf[off:]; returns (new_off, blocked, error)."""
    err: Optional[OSError] = None
    blocked = False
    mv = memoryview(buf)
    try:
        total = len(mv)
        while off < total:
            chunk = mv[off:]
            try:
                off += sock.send(chunk)
            except _WOULD_BLOCK:
                blocked = True
                break
            except OSError as e:
                err = e
                break
            finally:
                chunk.release()
    finally:
        mv.release()
    return off, blocked, err


def compact_consumed(buf: bytearray, off: int) -> int:
    """Reclaim the consumed prefix; returns the new offset."""
    if off >= len(buf):
        buf.clear()
        return 0
    if off >= COMPACT_THRESHOLD:
        del buf[:off]
        return 0
    return off


def extract_frames(buf: bytearray,
                   max_bytes: Optional[int] = None
                   ) -> tuple[list[bytes], Optional[int]]:
    """Pop every complete 4-byte-length-prefixed frame off the front of
    ``buf`` (ONE compaction per call).  Returns (frames, bad_size):
    bad_size is the offending length when a frame exceeds max_bytes or
    is negative — the caller decides how to die."""
    frames: list[bytes] = []
    off = 0
    blen = len(buf)
    while blen - off >= 4:
        (n,) = struct.unpack_from(">i", buf, off)
        if n < 0 or (max_bytes is not None and n > max_bytes):
            if off:
                del buf[:off]
            return frames, n
        if blen - off < 4 + n:
            break
        frames.append(bytes(buf[off + 4:off + 4 + n]))
        off += 4 + n
    if off:
        del buf[:off]
    return frames, None
