"""Zig-zag varint encoding for MessageSet v2 record framing.

Same wire format as the reference's src/rdvarint.c (rd_uvarint_enc_i64 /
rd_slice_read_varint at src/rdbuf.c:877): protobuf-style base-128 varints,
signed values zig-zag mapped.
"""
from __future__ import annotations


def zigzag(v: int) -> int:
    """Map signed to unsigned: 0,-1,1,-2,... -> 0,1,2,3,..."""
    return (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1


def unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def enc_u64(v: int) -> bytes:
    """Unsigned base-128 varint."""
    out = bytearray()
    v &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_i64(v: int) -> bytes:
    """Signed (zig-zag) varint — the MessageSet v2 record framing encoding."""
    return enc_u64(zigzag(v))


def size_u64(v: int) -> int:
    v &= 0xFFFFFFFFFFFFFFFF
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n


def size_i64(v: int) -> int:
    return size_u64(zigzag(v))


def dec_u64(buf, offset: int = 0) -> tuple[int, int]:
    """Decode unsigned varint; returns (value, bytes_consumed).

    Raises ValueError on truncation or overlong (>10 byte) encoding, the
    same failure contract as rd_slice_read_uvarint's underflow path.
    """
    shift = 0
    val = 0
    i = offset
    end = len(buf)
    while True:
        if i >= end:
            raise ValueError("varint truncated")
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, i - offset
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def dec_i64(buf, offset: int = 0) -> tuple[int, int]:
    u, n = dec_u64(buf, offset)
    return unzigzag(u), n
