"""Partitioner hashes: Java-compatible murmur2, and consistent CRC hashing.

The reference implements murmur2 in src/rdmurmur2.c (unit test vs Java
reference values at rdmurmur2.c:115); the murmur2_random partitioner must
produce the same partition as the Java client for the same key, so the hash
must match org.apache.kafka.common.utils.Utils.murmur2 exactly.
"""
from __future__ import annotations

from .crc import crc32

MURMUR2_SEED = 0x9747B28C
_M = 0x5BD1E995
_MASK = 0xFFFFFFFF


def murmur2(data: bytes) -> int:
    """Java-compatible murmur2 (signed-char reads, seed ^ len init)."""
    n = len(data)
    h = (MURMUR2_SEED ^ n) & _MASK
    i = 0
    while n - i >= 4:
        k = data[i] | (data[i + 1] << 8) | (data[i + 2] << 16) | (data[i + 3] << 24)
        k = (k * _M) & _MASK
        k ^= k >> 24
        k = (k * _M) & _MASK
        h = (h * _M) & _MASK
        h ^= k
        i += 4
    rem = n - i
    # Java reads trailing bytes as *signed* chars; sign-extend accordingly.
    if rem >= 3:
        h ^= (_sext(data[i + 2]) << 16) & _MASK
    if rem >= 2:
        h ^= (_sext(data[i + 1]) << 8) & _MASK
    if rem >= 1:
        h ^= _sext(data[i]) & _MASK
        h = (h * _M) & _MASK
    h ^= h >> 13
    h = (h * _M) & _MASK
    h ^= h >> 15
    return h


def _sext(b: int) -> int:
    return b - 256 if b >= 128 else b


def murmur2_partition(key: bytes, partition_cnt: int) -> int:
    """The murmur2 partitioner mapping: toPositive(murmur2(key)) % cnt."""
    return (murmur2(key) & 0x7FFFFFFF) % partition_cnt


def consistent_partition(key: bytes, partition_cnt: int) -> int:
    """'consistent' partitioner: CRC32 of the key modulo partition count."""
    return crc32(key) % partition_cnt
