"""librdkafka_tpu.utils"""
