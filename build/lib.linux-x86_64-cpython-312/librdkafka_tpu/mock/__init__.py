"""librdkafka_tpu.mock"""
