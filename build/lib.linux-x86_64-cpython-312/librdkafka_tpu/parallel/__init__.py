"""librdkafka_tpu.parallel"""
