"""librdkafka_tpu.ops"""
