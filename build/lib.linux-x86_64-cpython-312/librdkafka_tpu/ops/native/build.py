"""Build the native libraries (g++ → .so), cached by mtime.

Two artifacts:
  _codec.so     — plain shared library reached via ctypes (codec.cpp)
  tk_enqlane.so — CPython extension module (enqlane.cpp; ctypes call
                  overhead would eat the enqueue lane's win)
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "codec.cpp")
SO = os.path.join(_DIR, "_codec.so")
ENQ_SRC = os.path.join(_DIR, "enqlane.cpp")
ENQ_SO = os.path.join(_DIR, "tk_enqlane.so")
_lock = threading.Lock()


def _compile(src: str, so: str, extra: list[str]) -> str:
    if (os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(src)):
        return so
    tmp = so + ".tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *extra, "-o", tmp, src]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def build(force: bool = False) -> str:
    """Compile codec.cpp to a shared library if stale; returns the .so path."""
    with _lock:
        if force and os.path.exists(SO):
            os.remove(SO)
        return _compile(SRC, SO, ["-fvisibility=hidden"])


def build_enqlane(force: bool = False) -> str:
    """Compile the tk_enqlane CPython extension if stale; returns path."""
    with _lock:
        if force and os.path.exists(ENQ_SO):
            os.remove(ENQ_SO)
        inc = sysconfig.get_paths()["include"]
        return _compile(ENQ_SRC, ENQ_SO, ["-I" + inc])


def load_enqlane():
    """Import the tk_enqlane extension module (building if stale)."""
    import importlib.machinery
    import importlib.util

    path = build_enqlane()
    loader = importlib.machinery.ExtensionFileLoader("tk_enqlane", path)
    spec = importlib.util.spec_from_loader("tk_enqlane", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod
