"""Host-side batch packing helpers shared by the device codec kernels.

The lz4 kernel wants RIGHT-padded rows (positions are absolute from the
block start); the crc32c kernel wants LEFT-padded rows (leading zeros are
a no-op under a zero initial register — see ops/crc32c_jax.py).
"""
from __future__ import annotations

import numpy as np


def next_pow2(n: int, lo: int = 64) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _pack(buffers: list[bytes], N: int, left: bool) -> tuple[np.ndarray, np.ndarray]:
    B = len(buffers)
    out = np.zeros((B, N), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, b in enumerate(buffers):
        n = len(b)
        lens[i] = n
        if n:
            arr = np.frombuffer(bytes(b), dtype=np.uint8)
            if left:
                out[i, N - n:] = arr
            else:
                out[i, :n] = arr
    return out, lens


def pad_left(buffers: list[bytes], N: int):
    """Right-aligned rows (leading zeros) — the crc32c kernel layout."""
    return _pack(buffers, N, True)


def pad_right(buffers: list[bytes], N: int):
    """Left-aligned rows (trailing zeros) — the lz4 kernel layout."""
    return _pack(buffers, N, False)
