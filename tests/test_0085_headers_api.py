"""Message headers + misc consumer API e2e (reference: 0085-headers.c /
rdkafka_header.c; watermarks + position from the KafkaConsumer
surface): headers survive the produce -> wire -> consume round trip
(including null values and duplicates), timestamps propagate, and
watermark/position report log positions."""
import time

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.mock.cluster import MockCluster


def test_headers_round_trip_and_position():
    cluster = MockCluster(num_brokers=1, topics={"hdr": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2, "compression.codec": "lz4"})
    ts = 1_680_000_000_123
    try:
        p.produce("hdr", value=b"with-headers", key=b"k", partition=0,
                  timestamp=ts,
                  headers=[("trace-id", b"abc123"),
                           ("null-hdr", None),
                           ("dup", b"first"), ("dup", b"second")])
        p.produce("hdr", value=b"plain", partition=0)
        assert p.flush(10.0) == 0

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "ghdr", "auto.offset.reset": "earliest"})
        c.subscribe(["hdr"])
        got = []
        deadline = time.monotonic() + 15
        while len(got) < 2 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append(m)
        assert len(got) == 2
        m0, m1 = got
        assert m0.value == b"with-headers"
        assert list(m0.headers) == [("trace-id", b"abc123"),
                                    ("null-hdr", None),
                                    ("dup", b"first"), ("dup", b"second")]
        assert m0.timestamp == ts
        assert m1.value == b"plain" and not m1.headers

        # watermarks + position after consuming both
        lo, hi = c.get_watermark_offsets(TopicPartition("hdr", 0))
        assert (lo, hi) == (0, 2)
        pos = c.position([TopicPartition("hdr", 0)])
        assert pos[0].offset == 2
        c.close()
    finally:
        p.close()
        cluster.stop()
