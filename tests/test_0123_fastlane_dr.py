"""Fast-lane delivery reports: dr_msg_cb no longer demotes produce()
to the Message path — records stay in the native arena and materialize
into Message objects at delivery-report time (kafka.dr_msgq →
ArenaBatch.to_messages → tk_enqlane.materialize_arena).

Contract pinned here (reference: rd_kafka_dr_msgq + dr_msg_cb docs):
success DRs carry topic/partition/offset/key/value with per-batch
contiguous offsets; error DRs (message.timeout.ms, purge) carry the
original payloads and the right error codes.
"""
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster


def _mk(conf, cluster, **extra):
    base = {"bootstrap.servers": cluster.bootstrap_servers(),
            "linger.ms": 5}
    base.update(conf)
    base.update(extra)
    return Producer(base)


def test_dr_cb_does_not_demote_fast_lane():
    cluster = MockCluster(num_brokers=1, topics={"fl": 2})
    drs = []
    p = _mk({"dr_msg_cb": lambda e, m: drs.append((e, m))}, cluster)
    try:
        for i in range(50):
            p.produce("fl", value=b"v%03d" % i, key=b"k%03d" % i,
                      partition=i % 2)
        assert p.flush(20.0) == 0
        # the toppars must still be on the arena lane (not demoted)
        for part in (0, 1):
            tp = p.rk._toppars[("fl", part)]
            assert tp.arena_ok, "dr_msg_cb must not demote the fast lane"
        assert len(drs) == 50
        by_part = {0: [], 1: []}
        for e, m in drs:
            assert e is None
            assert m.topic == "fl"
            by_part[m.partition].append(m)
        for part, ms in by_part.items():
            assert len(ms) == 25
            # offsets are per-batch contiguous and strictly increasing
            offs = [m.offset for m in ms]
            assert offs == sorted(offs) and len(set(offs)) == 25
            assert offs[0] == 0 and offs[-1] == 24
        # payloads materialized from the arena, not placeholders
        sent = {(b"k%03d" % i, b"v%03d" % i) for i in range(50)}
        got = {(m.key, m.value) for _e, m in drs}
        assert got == sent
    finally:
        p.close()
        cluster.stop()


def test_timeout_error_drs_carry_payloads():
    """Unsendable fast-lane records expire into error DRs WITH their
    original key/value (arena.expire_records)."""
    drs = []
    p = Producer({"bootstrap.servers": "127.0.0.1:1",   # unreachable
                  "message.timeout.ms": 700,
                  "linger.ms": 5,
                  "topic.metadata.refresh.interval.ms": 100,
                  "dr_msg_cb": lambda e, m: drs.append((e, m))})
    try:
        # route records INTO an arena: requires a known toppar, which
        # needs metadata — unreachable broker keeps them in UA parking
        # (Message path) instead. Seed the toppar directly like the
        # first-sight path would after metadata.
        t = p.rk.get_topic("tt")
        t.partition_cnt = 1
        p.rk.get_toppar("tt", 0)
        for i in range(20):
            p.produce("tt", value=b"x%02d" % i, partition=0)
        tp = p.rk._toppars[("tt", 0)]
        assert tp.arena is not None and len(tp.arena) == 20
        deadline = time.monotonic() + 10
        while len(drs) < 20 and time.monotonic() < deadline:
            p.poll(0.1)
        assert len(drs) == 20
        for e, m in drs:
            assert e is not None and e.code == Err._MSG_TIMED_OUT
            assert m.value.startswith(b"x")
            assert m.topic == "tt" and m.partition == 0
    finally:
        p.rk.conf.set("message.timeout.ms", 300000)
        p.close()


def test_purge_error_drs_carry_payloads():
    drs = []
    p = Producer({"bootstrap.servers": "127.0.0.1:1",
                  "linger.ms": 5,
                  "dr_msg_cb": lambda e, m: drs.append((e, m))})
    try:
        t = p.rk.get_topic("pt")
        t.partition_cnt = 1
        p.rk.get_toppar("pt", 0)
        for i in range(10):
            p.produce("pt", value=b"p%02d" % i, partition=0)
        p.purge(in_queue=True)
        deadline = time.monotonic() + 5
        while len(drs) < 10 and time.monotonic() < deadline:
            p.poll(0.1)
        assert len(drs) == 10
        assert {m.value for _e, m in drs} == {b"p%02d" % i
                                              for i in range(10)}
        assert all(e.code == Err._PURGE_QUEUE for e, _m in drs)
        assert len(p) == 0
    finally:
        p.close()


def test_interceptors_still_demote():
    """on_send must fire per message at produce() time — interceptors
    keep the Message path."""
    from librdkafka_tpu.client.interceptor import InterceptorChain

    cluster = MockCluster(num_brokers=1, topics={"ic": 1})
    sent = []
    chain = InterceptorChain()
    chain.add("t", "on_send", lambda m: sent.append(m))

    p = _mk({}, cluster)
    try:
        assert p.rk._fast_lane          # no interceptors: lane on
    finally:
        p.close()
    p = _mk({"interceptors": chain}, cluster)
    try:
        assert not p.rk._fast_lane      # interceptors: lane off
        p.produce("ic", value=b"v", partition=0)
        assert p.flush(15.0) == 0
        assert len(sent) == 1
    finally:
        p.close()
        cluster.stop()


def test_dr_batch_cb_one_call_per_batch_lazy_payloads():
    """dr_batch_cb (r5): ONE callback per delivered batch with the full
    Message list — the rd_kafka_event_DR message-array contract
    (reference rdkafka_event.c:33) as a direct callback. Messages are
    lazy (key/value materialize on access) and carry contiguous
    offsets, PERSISTED status and error=None on success."""
    from librdkafka_tpu.client.msg import MsgStatus
    cluster = MockCluster(num_brokers=1, topics={"bdr": 1})
    batches = []
    p = _mk({"dr_batch_cb": lambda msgs: batches.append(msgs)}, cluster,
            **{"linger.ms": 20})
    try:
        for i in range(40):
            p.produce("bdr", value=b"v%03d" % i, key=b"k%03d" % i,
                      partition=0)
        assert p.flush(20.0) == 0
        assert sum(len(b) for b in batches) == 40
        assert len(batches) < 40, "callback must batch, not fire per msg"
        seen = []
        for b in batches:
            for m in b:
                assert m.error is None
                assert m.status == MsgStatus.PERSISTED
                assert m.topic == "bdr" and m.partition == 0
                assert m.value == b"v%03d" % len(seen)
                assert m.key == b"k%03d" % len(seen)
                seen.append(m.offset)
        assert seen == list(range(40))      # contiguous batch offsets
    finally:
        p.close()
        cluster.stop()


def test_dr_batch_cb_error_batches():
    """Failed deliveries reach dr_batch_cb with the error stamped on
    every message and the original payloads intact (timeout path)."""
    cluster = MockCluster(num_brokers=1, topics={"bde": 1})
    batches = []
    p = _mk({"dr_batch_cb": lambda msgs: batches.append(msgs)}, cluster,
            **{"message.timeout.ms": 400, "linger.ms": 5})
    try:
        cluster.set_broker_down(1)
        for i in range(5):
            p.produce("bde", value=b"x%d" % i, partition=0)
        deadline = time.monotonic() + 10
        while sum(len(b) for b in batches) < 5 \
                and time.monotonic() < deadline:
            p.poll(0.2)
        got = [m for b in batches for m in b]
        assert len(got) == 5
        for i, m in enumerate(got):
            assert m.error is not None and m.error.code == Err._MSG_TIMED_OUT
            assert m.value == b"x%d" % i
            assert m.offset < 0      # no assigned offset (-1/-1001)
    finally:
        p.close()
        cluster.stop()


def test_dr_batch_cb_composes_with_dr_msg_cb():
    """Both callbacks set: the batch callback fires once per batch AND
    the per-message callback fires per message."""
    cluster = MockCluster(num_brokers=1, topics={"bdc": 1})
    batch_n, msg_n = [0], [0]
    p = _mk({"dr_batch_cb": lambda msgs: batch_n.__setitem__(0, batch_n[0] + len(msgs)),
             "dr_msg_cb": lambda e, m: msg_n.__setitem__(0, msg_n[0] + 1)},
            cluster)
    try:
        for i in range(30):
            p.produce("bdc", value=b"c%d" % i, partition=0)
        assert p.flush(20.0) == 0
        assert batch_n[0] == 30 and msg_n[0] == 30
    finally:
        p.close()
        cluster.stop()
