"""Plugin loading tests (reference: 0066-plugins.cpp + rdkafka_plugin.c:
plugin.library.paths entries are loaded at client creation, their
conf_init() registers interceptors, and the hooks fire on the produce
path)."""
import plugin_fixture

from librdkafka_tpu import Producer


def test_plugin_library_paths_loads_and_hooks_fire():
    before = dict(plugin_fixture.CALLS)
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "plugin.library.paths": "plugin_fixture",
                  "linger.ms": 2})
    assert plugin_fixture.CALLS["conf_init"] == before["conf_init"] + 1
    assert plugin_fixture.CALLS["on_new"] == before["on_new"] + 1
    n = 10
    for i in range(n):
        p.produce("plug", value=b"x%d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    assert plugin_fixture.CALLS["on_send"] >= before["on_send"] + n
    assert (plugin_fixture.CALLS["on_acknowledgement"]
            >= before["on_acknowledgement"] + n)
    # broker requests went out and threads ran under the interceptors
    assert plugin_fixture.CALLS["on_request_sent"] > before["on_request_sent"]
    assert plugin_fixture.CALLS["on_thread_start"] > before["on_thread_start"]
    assert plugin_fixture.CALLS["on_thread_exit"] > before["on_thread_exit"]


def test_plugin_custom_entry_point():
    before = plugin_fixture.CALLS["conf_init"]
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "plugin.library.paths": "plugin_fixture:custom_entry"})
    p.close()
    assert plugin_fixture.CALLS["conf_init"] == before + 100
