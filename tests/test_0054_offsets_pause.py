"""offsets_for_times (reference: 0054-offset_time.cpp,
rd_kafka_offsets_for_times) and pause/resume (0026-era behavior):
timestamp→offset lookup through ListOffsets, and paused partitions stop
fetching until resumed with no message loss."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.mock.cluster import MockCluster


def test_offsets_for_times():
    cluster = MockCluster(num_brokers=1, topics={"oft": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 0})      # one batch per message
    base_ts = 1_600_000_000_000
    try:
        for i in range(5):
            p.produce("oft", value=b"t%d" % i, partition=0,
                      timestamp=base_ts + i * 1000)
            p.flush(10.0)               # separate batches w/ rising ts
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "goft"})
        # Kafka semantics: EARLIEST offset with timestamp >= target
        res = c.offsets_for_times(
            [TopicPartition("oft", 0, base_ts + 1500)], timeout=10)
        assert res[0].error is None and res[0].offset == 2, res[0]
        res = c.offsets_for_times(
            [TopicPartition("oft", 0, base_ts)], timeout=10)
        assert res[0].offset == 0
        res = c.offsets_for_times(
            [TopicPartition("oft", 0, base_ts + 4000)], timeout=10)
        assert res[0].offset == 4
        # beyond the last timestamp: no offset
        res = c.offsets_for_times(
            [TopicPartition("oft", 0, base_ts + 99_000)], timeout=10)
        assert res[0].error is not None or res[0].offset < 0
        c.close()
    finally:
        p.close()
        cluster.stop()


def test_pause_resume_no_loss():
    cluster = MockCluster(num_brokers=1, topics={"pr": 2})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gpr", "auto.offset.reset": "earliest"})
    try:
        for i in range(20):
            p.produce("pr", value=b"a%02d" % i, partition=i % 2)
        assert p.flush(10.0) == 0
        c.subscribe(["pr"])
        got = []
        deadline = time.monotonic() + 20
        while len(got) < 20 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append(m.value)
        assert len(got) == 20

        # pause partition 0, produce to both, only partition 1 arrives
        c.pause([TopicPartition("pr", 0)])
        time.sleep(0.2)
        for i in range(10):
            p.produce("pr", value=b"b%02d" % i, partition=i % 2)
        assert p.flush(10.0) == 0
        paused_got = []
        deadline = time.monotonic() + 4
        while time.monotonic() < deadline:
            m = c.poll(0.25)
            if m is not None and m.error is None:
                paused_got.append((m.partition, m.value))
        assert paused_got and all(part == 1 for part, _ in paused_got), \
            paused_got
        # resume: partition 0's messages arrive with no loss
        c.resume([TopicPartition("pr", 0)])
        resumed = []
        deadline = time.monotonic() + 15
        while len(resumed) < 5 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None and m.partition == 0:
                resumed.append(m.value)
        assert sorted(resumed) == [b"b%02d" % i for i in range(0, 10, 2)]
    finally:
        c.close()
        p.close()
        cluster.stop()
