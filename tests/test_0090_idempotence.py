"""Idempotent producer (EOS v1) integration tests — analogs of the
reference's 0090-idempotence.c and 0094-idempotence_msg_timeout.c:
retriable produce errors and lost responses must yield exactly-once,
in-order logs (PID/epoch/BaseSequence dedup at the broker,
reference src/rdkafka_idempotence.c + rdkafka_msgset_writer.c:397,1288).
"""
import time

from librdkafka_tpu import Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.ops import cpu
from librdkafka_tpu.protocol.msgset import iter_batches, parse_records_v2


def _log_values(cluster, topic, part):
    out = []
    last_seq = None
    for _base, blob in cluster.partition(topic, part).log:
        for info, payload, _full in iter_batches(bytes(blob)):
            if info.codec:
                payload = cpu.lz4_decompress(payload)
            assert info.producer_id >= 1, "idempotent batch must carry PID"
            assert info.base_sequence >= 0
            if last_seq is not None:
                assert info.base_sequence == last_seq, (
                    f"sequence gap: {info.base_sequence} != {last_seq}")
            last_seq = info.base_sequence + info.record_count
            out.extend(r.value for r in parse_records_v2(info, payload))
    return out


def _make_producer(**extra):
    conf = {"bootstrap.servers": "", "test.mock.num.brokers": 1,
            "enable.idempotence": True, "linger.ms": 2,
            "batch.num.messages": 50}
    conf.update(extra)
    return Producer(conf)


def test_idempotent_basic_exactly_once_in_order():
    p = _make_producer()
    n = 1000
    for i in range(n):
        p.produce("eos", value=b"m%05d" % i, partition=0)
    assert p.flush(30.0) == 0
    vals = _log_values(p._rk.mock_cluster, "eos", 0)
    assert vals == [b"m%05d" % i for i in range(n)]
    p.close()


def test_idempotent_retries_no_dup_no_gap():
    """Errors rejected before append: client retries with the SAME
    sequence; log must have no gaps or duplicates and preserve order."""
    p = _make_producer()
    cluster = p._rk.mock_cluster
    from librdkafka_tpu.protocol.proto import ApiKey
    p.produce("eos", value=b"warm", partition=0)
    assert p.flush(30.0) == 0
    # two consecutive rejects (no append), then success
    cluster.push_request_errors(
        ApiKey.Produce, [Err.NOT_LEADER_FOR_PARTITION,
                         Err.LEADER_NOT_AVAILABLE])
    n = 500
    for i in range(n):
        p.produce("eos", value=b"r%05d" % i, partition=0)
    assert p.flush(60.0) == 0
    vals = _log_values(cluster, "eos", 0)
    assert vals == [b"warm"] + [b"r%05d" % i for i in range(n)]
    p.close()


def test_idempotent_lost_response_dedup():
    """Commit-then-lost-response: the retry carries the same BaseSequence,
    the broker answers DUPLICATE_SEQUENCE_NUMBER, and the producer treats
    it as benign success — exactly one copy in the log, DR success."""
    p = _make_producer()
    cluster = p._rk.mock_cluster
    from librdkafka_tpu.protocol.proto import ApiKey
    drs = []
    p._rk.conf.set("dr_msg_cb", lambda err, msg: drs.append(err))
    p.produce("eos", value=b"warm", partition=0)
    assert p.flush(30.0) == 0
    cluster.push_request_errors(ApiKey.Produce, [Err.REQUEST_TIMED_OUT])
    n = 200
    for i in range(n):
        p.produce("eos", value=b"d%05d" % i, partition=0)
    assert p.flush(60.0) == 0
    assert all(e is None for e in drs), [e for e in drs if e][:3]
    vals = _log_values(cluster, "eos", 0)
    assert vals == [b"warm"] + [b"d%05d" % i for i in range(n)]
    p.close()


def test_idempotent_multi_partition_sequences_independent():
    p = _make_producer()
    n = 300
    for i in range(n):
        p.produce("eos", value=b"p%05d" % i, partition=i % 4)
    assert p.flush(30.0) == 0
    cluster = p._rk.mock_cluster
    got = []
    for part in range(4):
        vals = _log_values(cluster, "eos", part)
        assert vals == [b"p%05d" % i for i in range(n) if i % 4 == part]
        got.extend(vals)
    assert len(got) == n
    p.close()


def test_idempotent_head_of_line_gap_is_fatal():
    """A head-of-line sequence gap (no earlier pending batch) is a true
    sequence desynchronization: the rejected batch is POSSIBLY_PERSISTED,
    and resending it under a fresh PID would bypass broker dedup and can
    silently duplicate — so it must be FATAL, not drain+bump (reference:
    rd_kafka_handle_Produce_error, rdkafka_request.c:2173 r==0 branch)."""
    from librdkafka_tpu.client.errors import KafkaException
    p = _make_producer()
    cluster = p._rk.mock_cluster
    dr_errs = []
    p._rk.conf.set("dr_msg_cb", lambda err, msg: dr_errs.append(err))
    p.produce("eos", value=b"warm", partition=0)
    assert p.flush(30.0) == 0
    part = cluster.partition("eos", 0)
    with cluster._lock:
        # roll broker-side expected seq BACKWARD: the next head batch sends
        # base_seq above expected → OUT_OF_ORDER with nothing pending → gap
        for key in list(part.pid_seqs):
            part.pid_seqs[key] = 0
    n = 100
    for i in range(n):
        p.produce("eos", value=b"g%05d" % i, partition=0)
    assert p.flush(60.0) == 0          # everything resolved (via error DRs)
    errs = [e for e in dr_errs if e is not None]
    assert errs, "expected fatal error DRs for the gapped batch"
    assert all(e.code == Err.OUT_OF_ORDER_SEQUENCE_NUMBER for e in errs)
    assert p._rk.fatal_error is not None
    # no duplicates in the broker log: only the warm message + nothing else
    vals = []
    for _base, blob in part.log:
        for info, payload, _full in iter_batches(bytes(blob)):
            vals.extend(r.value for r in parse_records_v2(info, payload))
    assert vals == [b"warm"]
    # the producer is dead: further produce() raises the fatal error
    try:
        p.produce("eos", value=b"after-fatal", partition=0)
        assert False, "produce after fatal error should raise"
    except KafkaException:
        pass
    p.close()


def test_idempotent_partial_batch_lost_response_membership_frozen():
    """Regression (review finding): a linger-expired PARTIAL batch whose
    response is lost must be retried with its original membership — if the
    retry were re-sliced to include newer queued messages, the broker's
    DUPLICATE_SEQUENCE answer would mark never-appended messages as
    delivered and silently lose them."""
    import time as _t
    p = _make_producer(**{"linger.ms": 30, "batch.num.messages": 50})
    cluster = p._rk.mock_cluster
    from librdkafka_tpu.protocol.proto import ApiKey
    drs = []
    p._rk.conf.set("dr_msg_cb", lambda err, msg: drs.append(err))
    p.produce("eos", value=b"warm", partition=0)
    assert p.flush(30.0) == 0
    cluster.push_request_errors(ApiKey.Produce, [Err.REQUEST_TIMED_OUT])
    # 30 msgs -> linger fires a partial batch whose response is "lost"
    for i in range(30):
        p.produce("eos", value=b"a%05d" % i, partition=0)
    _t.sleep(0.12)
    # more messages arrive while the retry is pending
    for i in range(40):
        p.produce("eos", value=b"b%05d" % i, partition=0)
    assert p.flush(60.0) == 0
    assert all(e is None for e in drs)
    vals = _log_values(cluster, "eos", 0)
    assert vals == ([b"warm"] + [b"a%05d" % i for i in range(30)]
                    + [b"b%05d" % i for i in range(40)])
    p.close()
