"""ISSUE 5: flight-recorder tracing + per-stage latency decomposition.

Covers the obs/trace.py tentpole end to end — the e2e acceptance run
(every pipeline stage spanned, governor decision in the span args, the
dump loads as Chrome trace-event JSON), ring semantics (fixed size,
last-N retention, per-thread, refcounted teardown), the three
flight-recorder triggers (fatal error, CRC mismatch path unit,
request timeout), conf-knob set()-time validation, and the offline
summarizer scripts/traceview.py the --smoke overhead gate rides."""
import importlib.util
import json
import os
import threading
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.conf import Conf
from librdkafka_tpu.client.errors import Err, KafkaError, KafkaException
from librdkafka_tpu.obs import trace

HERE = os.path.dirname(os.path.abspath(__file__))


def _load_traceview():
    spec = importlib.util.spec_from_file_location(
        "tk_traceview_test",
        os.path.join(HERE, "..", "scripts", "traceview.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ e2e --
def test_trace_e2e_produce_consume_all_stages(tmp_path):
    """Acceptance: one produce+consume run with trace.enable=true must
    dump spans for every pipeline stage — compress ticket, fan-in
    wait, device launch, readback, fetch CRC verify, decompress,
    deliver — with the governor's route decision visible as span
    args, in a file Perfetto can load (Chrome trace-event JSON)."""
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "trace.enable": True, "trace.ring.events": 16384,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 2, "tpu.governor": False,
                  "tpu.warmup": False, "compression.codec": "lz4",
                  "linger.ms": 10})
    c = None
    try:
        bs = p._rk.mock_cluster.bootstrap_servers()
        # phase 1: a single below-quorum batch -> the engine's fan-in
        # wait (static window, governor off)
        p.produce("tr", value=b"solo", partition=0)
        assert p.flush(120.0) == 0
        # phase 2: four partitions ready in one serve pass -> one
        # at-quorum submission -> device launch + readback
        for i in range(200):
            p.produce("tr", value=b"v%d" % i * 20, partition=i % 4)
        assert p.flush(120.0) == 0
        # consumer mirror: CRC verify + decompress + deliver
        c = Consumer({"bootstrap.servers": bs, "group.id": "g-trace",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True, "trace.enable": True})
        c.subscribe(["tr"])
        got = 0
        deadline = time.monotonic() + 60
        while got < 201 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 201, f"consumed {got}/201"

        path = str(tmp_path / "trace.json")
        n = c.trace_dump(path)          # module-wide: any client dumps
        assert n > 0
        with open(path) as f:
            data = json.load(f)
        # the Perfetto-loadable shape: traceEvents array, ph/ts/pid/tid
        # on every record, X spans carrying dur
        assert isinstance(data["traceEvents"], list)
        evs = data["traceEvents"]
        for e in evs:
            assert {"name", "ph", "pid", "tid"} <= set(e)
            if e["ph"] == "X":
                assert "dur" in e and "ts" in e
        names = {e["name"] for e in evs}
        required = {"enqueue", "batch_assembly", "compress",
                    "crc_ticket", "fanin_wait", "device_launch",
                    "readback", "produce_tx", "ack",
                    "fetch_rx", "crc_verify", "decompress", "deliver"}
        assert required <= names, f"missing spans: {required - names}"
        # governor route decisions ride the launch/serve span args,
        # including the ISSUE 6 dispatch-lane attribution (device id,
        # -1 for a whole-mesh sharded launch)
        launch = next(e for e in evs if e["name"] == "device_launch")
        assert launch["args"]["route"] == "device"
        assert {"explored", "fused", "bucket", "blocks", "device",
                "sharded"} <= set(launch["args"])
        assert launch["args"]["device"] >= -1
        rb = next(e for e in evs if e["name"] == "readback")
        assert "device" in rb["args"]
        # thread metadata present (Perfetto track names)
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in evs)
        # timestamps are sorted (exporter contract)
        ts = [e["ts"] for e in evs if "ts" in e]
        assert ts == sorted(ts)
    finally:
        p.close()
        if c is not None:
            c.close()
    assert not trace.enabled and trace.active_ring_count() == 0


def test_trace_stats_share_instrumentation():
    """The same run feeds the stats decomposition: stage_latency
    windows record real samples and the gauges/fetch_latency fields
    render (the stats half of the ISSUE 5 instrumentation points)."""
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 2, "tpu.governor": False,
                  "tpu.warmup": False, "compression.codec": "lz4",
                  "linger.ms": 10})
    try:
        for i in range(200):
            p.produce("sl", value=b"v%d" % i * 20, partition=i % 4)
        assert p.flush(120.0) == 0
        blob = json.loads(p._rk.stats.emit_json())
        ce = blob["codec_engine"]
        sl = ce["stage_latency"]
        assert sl["launch"]["cnt"] >= 1, sl
        assert sl["submit_wait"]["cnt"] >= 1
        assert sl["reap"]["cnt"] >= 1
        assert set(ce["gauges"]) == {"queue_depth", "inflight_launches",
                                     "fanin_occupancy"}
        b = next(iter(blob["brokers"].values()))
        assert "fetch_latency" in b          # consumer mirror window
    finally:
        p.close()


# ----------------------------------------------------------- ring model --
def test_ring_keeps_last_n_events():
    trace.enable(ring=64)
    try:
        for i in range(200):
            trace.instant("t", f"e{i}")
        ring = trace._local.ring
        evs = ring.snapshot()
        assert len(evs) == 64
        # the LAST 64 survive, oldest first
        assert evs[0][2] == "e136" and evs[-1][2] == "e199"
    finally:
        trace.disable()
    assert not trace.enabled and trace.active_ring_count() == 0


def test_rings_are_per_thread_and_refcounted(tmp_path):
    trace.enable(ring=256)
    trace.enable(ring=256)              # second client's reference
    try:
        trace.instant("t", "main-ev")
        done = threading.Event()

        def worker():
            trace.instant("t", "worker-ev")
            done.set()

        th = threading.Thread(target=worker, name="trace-worker")
        th.start()
        th.join(5)
        assert done.is_set()
        assert trace.active_ring_count() == 2
        path = str(tmp_path / "two.json")
        trace.dump(path)
        evs = json.load(open(path))["traceEvents"]
        tids = {e["tid"] for e in evs if e["ph"] == "i"}
        assert len(tids) == 2
        tnames = {e["args"]["name"] for e in evs if e["ph"] == "M"}
        assert "trace-worker" in tnames
        trace.disable()                 # first release: still enabled
        assert trace.enabled
    finally:
        trace.disable()                 # last release: off, rings freed
    assert not trace.enabled and trace.active_ring_count() == 0


def test_disabled_recording_is_a_noop():
    assert not trace.enabled
    trace.instant("t", "dropped")
    trace.complete("t", "dropped", trace.now())
    assert trace.active_ring_count() == 0


# ------------------------------------------------------- conf validation --
def test_trace_conf_knobs_validate_at_set_time():
    conf = Conf()
    conf.set("trace.enable", "true")
    assert conf.get("trace.enable") is True
    conf.set("trace.ring.events", 4096)
    with pytest.raises(KafkaException, match="power of two"):
        conf.set("trace.ring.events", 1000)
    with pytest.raises(KafkaException, match="outside allowed range"):
        conf.set("trace.ring.events", 32)
    with pytest.raises(KafkaException):
        conf.set("trace.ring.events", 1 << 23)
    conf.set("trace.dump.on.fatal", "false")
    assert conf.get("trace.dump.on.fatal") is False
    # module-level guard mirrors the validator (direct API use)
    with pytest.raises(ValueError):
        trace.enable(ring=100)
    assert not trace.enabled


# -------------------------------------------------------- flight recorder --
def test_flight_record_on_fatal_error(tmp_path):
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "trace.enable": True, "linger.ms": 2})
    old_dir = trace.flight_dir
    trace.flight_dir = str(tmp_path)
    try:
        p.produce("fl", value=b"x", partition=0)
        assert p.flush(30.0) == 0
        p._rk.set_fatal_error(KafkaError(Err._FATAL, "synthetic fatal"))
        path = trace.last_flight_path
        assert path and path.startswith(str(tmp_path))
        assert "fatal" in os.path.basename(path)
        evs = json.load(open(path))["traceEvents"]
        fr = [e for e in evs if e["name"] == "flight_record"]
        assert fr and "fatal" in fr[0]["args"]["reason"]
        assert any(e["name"] == "fatal_error" for e in evs)
    finally:
        trace.flight_dir = old_dir
        p.close()


def test_flight_record_on_request_timeout(tmp_path):
    from librdkafka_tpu.client.broker import Broker, Request
    from librdkafka_tpu.protocol.proto import ApiKey

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "trace.enable": True, "socket.max.fails": 0})
    old_dir, before = trace.flight_dir, trace.last_flight_path
    trace.flight_dir = str(tmp_path)
    try:
        b = Broker(p._rk, 999, "127.0.0.1", 1)     # never started
        try:
            b.waitresp[7] = Request(ApiKey.Metadata, {}, corrid=7,
                                    abs_timeout=time.monotonic() - 1.0)
            b._scan_timeouts(time.monotonic())
            assert b.c_req_timeouts == 1
            path = trace.last_flight_path
            assert path and path != before \
                and path.startswith(str(tmp_path))
            assert "request_timeout" in os.path.basename(path)
            evs = json.load(open(path))["traceEvents"]
            assert any(e["name"] == "request_timeout" for e in evs)
        finally:
            b._wakeup_r.close()
            b._wakeup_w.close()
    finally:
        trace.flight_dir = old_dir
        p.close()


def test_flight_record_bounded_and_gateable(tmp_path):
    # dump.on.fatal=false suppresses entirely
    trace.enable(ring=256, on_fatal=False, dump_dir=str(tmp_path))
    try:
        assert trace.flight_record("nope") is None
    finally:
        trace.disable()
    # bounded per process: FLIGHT_MAX_DUMPS then None
    trace.enable(ring=256, on_fatal=True, dump_dir=str(tmp_path))
    try:
        trace.instant("t", "seed")
        paths = [trace.flight_record(f"r{i}")
                 for i in range(trace.FLIGHT_MAX_DUMPS + 3)]
        made = [x for x in paths if x]
        assert len(made) == trace.FLIGHT_MAX_DUMPS
        assert all(os.path.exists(x) for x in made)
        assert paths[-1] is None
    finally:
        trace.disable()


# -------------------------------------------------------------- tooling --
def test_traceview_summarize_and_render(tmp_path):
    trace.enable(ring=1024)
    try:
        for i in range(20):
            t0 = trace.now()
            time.sleep(0.001 if i != 7 else 0.02)   # one wide outlier
            trace.complete("stage", "work", t0, {"i": i})
        # device-stamped spans (engine launch/readback shape): the
        # summarizer must attribute them per chip (ISSUE 6)
        for dev in (0, 1, -1):
            t0 = trace.now()
            trace.complete("engine", "device_launch", t0,
                           {"device": dev, "sharded": dev == -1})
        trace.instant("stage", "blip")
        path = str(tmp_path / "tv.json")
        trace.dump(path)
    finally:
        trace.disable()
    tv = _load_traceview()
    summary = tv.summarize(tv.load_events(path))
    st = next(s for s in summary["stages"] if s["name"] == "work")
    assert st["cnt"] == 20
    assert st["p50_us"] <= st["p99_us"] <= st["max_us"]
    assert st["max_us"] >= 15_000                   # the outlier
    assert summary["widest"][0]["name"] == "work"
    assert summary["widest"][0]["args"]["i"] == 7
    assert summary["instants"].get("blip") == 1
    devs = {d["device"] for d in summary["by_device"]
            if d["name"] == "device_launch"}
    assert devs == {-1, 0, 1}, summary["by_device"]
    out = tv.render(summary)
    assert "work" in out and "top widest spans" in out
    assert "per-device launch attribution" in out
    # the bare-array form loads too (hand-built dumps)
    alt = str(tmp_path / "arr.json")
    with open(alt, "w") as f:
        json.dump(json.load(open(path))["traceEvents"], f)
    assert tv.summarize(tv.load_events(alt))["stages"]


def test_bench_json_artifact(tmp_path, monkeypatch):
    """bench.py --json <path>: every leg's summary is also written as
    a machine-readable artifact (the BENCH_r*.json trajectory)."""
    spec = importlib.util.spec_from_file_location(
        "tk_bench_test", os.path.join(HERE, "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = str(tmp_path / "leg.json")
    monkeypatch.setattr("sys.argv",
                        ["bench.py", "--smoke", "--json", out])
    bench._emit({"metric": "unit", "value": 1})
    with open(out) as f:
        got = json.load(f)
    # every artifact carries the obs-registry snapshot (ISSUE 20)
    assert got["obs"]["schema"] == 1
    del got["obs"]
    assert got == {"metric": "unit", "value": 1}
    monkeypatch.setattr("sys.argv", ["bench.py", "--smoke"])
    bench._emit({"metric": "unit2"})    # no --json: print only
    with open(out) as f:
        assert json.load(f)["metric"] == "unit"
