"""flush()/DR delivery contract and Consumer._deliver staleness.

The reference's ``rd_kafka_flush`` waits on ``rd_kafka_outq_len``
(rdkafka.c:3905), which counts *undelivered delivery-report ops* — not
just unacked messages.  flush() returning before the DR callback fires
loses the report to a post-flush close; these tests pin the contract.

Delivery (``Consumer._next_pending``) must drop a message when the
partition was seeked/paused since the fetch (version barrier) OR
revoked from the assignment — on group AND simple consumers alike
(reference: rd_kafka_op_version_outdated + fetchq disconnect on
fetch_stop).  The tests seed the fetched-batch queue directly and pull
through the delivery cursor — the same path poll()/consume() take.
"""
import time

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.client.msg import Message
from librdkafka_tpu.mock.cluster import MockCluster


def test_flush_waits_for_dr_delivery():
    """Every DR callback must have fired by the time flush() returns 0."""
    cluster = MockCluster(num_brokers=1, topics={"fdr": 1})
    delivered = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 0,
                  "dr_msg_cb": lambda err, m: delivered.append(m)})
    try:
        # many small rounds: the race window is between the msg_cnt
        # decrement and the DR op being served
        for round_i in range(20):
            sent = 5
            for i in range(sent):
                p.produce("fdr", value=b"x%d.%d" % (round_i, i), partition=0)
            rem = p.flush(10.0)
            assert rem == 0, f"round {round_i}: {rem} outstanding"
            assert len(delivered) == (round_i + 1) * sent, \
                (f"round {round_i}: flush returned before DRs delivered "
                 f"({len(delivered)} != {(round_i + 1) * sent})")
    finally:
        p.close()
        cluster.stop()


def test_deliver_version_stale_simple_consumer():
    """A version-stale message on a simple (group-less) consumer is
    dropped even though the partition is still assigned."""
    cluster = MockCluster(num_brokers=1, topics={"st": 1})
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers()})
    try:
        assert c._rk.cgrp is None
        c.assign([TopicPartition("st", 0)])
        tp = c._assignment[("st", 0)]
        fresh = Message("st", value=b"v", partition=0)
        fresh.offset = 7
        c._pending.append((tp, [fresh], tp.version, fresh.size))
        assert c._next_pending() is fresh
        stale = Message("st", value=b"v", partition=0)
        stale.offset = 8
        c._pending.append((tp, [stale], tp.version - 1, stale.size))
        assert c._next_pending() is None
        # the stale drop must not advance the app offset
        assert tp.app_offset == 8
    finally:
        c.close()
        cluster.stop()


def test_deliver_revoked_partition_dropped():
    """A message from a revoked partition is dropped — with and without
    a consumer group."""
    cluster = MockCluster(num_brokers=1, topics={"rv": 1})
    for conf in ({"bootstrap.servers": cluster.bootstrap_servers()},
                 {"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "grv"}):
        c = Consumer(dict(conf))
        try:
            c.assign([TopicPartition("rv", 0)])
            tp = c._assignment[("rv", 0)]
            ver = tp.version
            m = Message("rv", value=b"v", partition=0)
            m.offset = 0
            c._pending.append((tp, [m], ver, m.size))
            assert c._next_pending() is m
            c.unassign()
            late = Message("rv", value=b"v", partition=0)
            late.offset = 1
            c._pending.append((tp, [late], ver, late.size))
            assert c._next_pending() is None
        finally:
            c.close()
    cluster.stop()


def test_flush_with_event_api_accounts_drs():
    """With no dr callback but DR events enabled, flush() still waits
    for the DR ops to be consumable and queue_poll drains them."""
    cluster = MockCluster(num_brokers=1, topics={"fev": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 0, "enabled_events": "dr"})
    rk = p._rk
    try:
        for i in range(3):
            p.produce("fev", value=b"e%d" % i, partition=0)
        # event mode: flush() must NOT consume the DR events itself —
        # with nothing draining the queue it times out with them intact
        assert p.flush(0.5) > 0
        deadline = time.monotonic() + 10
        got = 0
        while got < 3 and time.monotonic() < deadline:
            ev = rk.queue_poll(0.1)
            if ev is not None and ev.type == "DR":
                got += len(ev.messages())
        assert got == 3
        with rk._msg_cnt_lock:
            assert rk.dr_cnt == 0 and rk.msg_cnt == 0
        assert p.flush(5.0) == 0
    finally:
        p.close()
        cluster.stop()


def test_overlapping_assign_starts_all_partitions():
    """A second assign() that overlaps a pending committed-offset lookup
    must still start every partition's fetcher (the superseded lookup is
    gen-guarded; the new call re-resolves carried-over partitions)."""
    cluster = MockCluster(num_brokers=1, topics={"ov": 2})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(30):
        p.produce("ov", value=b"b%02d" % i, partition=i % 2)
    assert p.flush(10.0) == 0
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gov", "auto.offset.reset": "earliest"})
    c.assign([TopicPartition("ov", 0)])
    c.assign([TopicPartition("ov", 0), TopicPartition("ov", 1)])
    got = 0
    deadline = time.monotonic() + 15
    while got < 30 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got += 1
    c.close()
    p.close()
    cluster.stop()
    assert got == 30, f"only {got}/30 delivered — partition stranded"
