"""Broker-version sweep tier (reference: tests/broker_version_tests.py,
which provisions real Kafka clusters per version via trivup and runs
the client matrix against each).

No real brokers exist here; the mock cluster's ``broker_version``
emulation plays their role — it advertises the version's ApiVersions
set (closing the connection on ApiVersions for <0.10 exactly like real
pre-0.10 brokers), and the full produce→fetch→group path runs against
it for every (version, codec) cell. The interop tier
(test_0200_interop.py) covers the real-binary axis the reference gets
from its Java fixtures.

Run standalone for the full matrix report:
    python tests/test_0114_version_sweep.py
"""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol import proto

VERSIONS = ["0.8.2", "0.9.0", "0.10.0", "0.10.2", "0.11.0", "1.0.0",
            "2.3.0"]
#: expected MessageSet magic on the wire per broker version
MAGIC = {"0.8.2": 0, "0.9.0": 0, "0.10.0": 1, "0.10.2": 1,
         "0.11.0": 2, "1.0.0": 2, "2.3.0": 2}
CODECS = ["none", "gzip"]

# consumer groups arrived with 0.9 (JoinGroup/SyncGroup); 0.8.x uses
# the simple consumer path in the reference — skip group consume there
GROUPLESS = {"0.8.2"}


def _roundtrip(bver: str, codec: str, n: int = 30) -> None:
    cluster = MockCluster(num_brokers=1, topics={"sw": 1},
                          broker_version=bver)
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": bver,
                      "compression.codec": codec, "linger.ms": 5})
        for i in range(n):
            p.produce("sw", value=b"sweep-%03d" % i, key=b"k%d" % i,
                      partition=0)
        assert p.flush(20.0) == 0
        blobs = [b for _o, b in cluster.partition("sw", 0).log]
        assert blobs
        for blob in blobs:
            assert blob[proto.V2_OF_Magic] == MAGIC[bver], \
                f"wrong msgset magic for broker {bver}"
        p.close()

        if bver in GROUPLESS:
            return
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": bver,
                      "group.id": f"gsw-{bver}-{codec}",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["sw"])
        got = []
        deadline = time.monotonic() + 25
        while len(got) < n and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append((m.key, m.value))
        c.close()
        assert sorted(got) == sorted(
            (b"k%d" % i, b"sweep-%03d" % i) for i in range(n))
    finally:
        cluster.stop()


@pytest.mark.parametrize("codec", CODECS)
@pytest.mark.parametrize("bver", VERSIONS)
def test_version_sweep(bver, codec):
    _roundtrip(bver, codec)


if __name__ == "__main__":
    for bver in VERSIONS:
        for codec in CODECS:
            t0 = time.monotonic()
            _roundtrip(bver, codec)
            print(f"{bver:8s} {codec:6s} OK "
                  f"({time.monotonic() - t0:.2f}s)")
