"""Chaos subsystem (ISSUE 7): fault-schedule DSL, cluster controller
hooks (kill/restart/leader+coordinator reassignment), sockem's new
injection modes, and the delivery-invariant oracle.

Tier structure: the unit tests and the two fast deterministic
scenarios run in tier-1; full storms (rolling EOS restarts,
coordinator death, slow-network rebalance) are ``slow``-marked and run
via scripts/chaos.sh (``pytest -m chaos``)."""
import json
import os
import socket
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.chaos import (ChaosScheduler, DeliveryOracle,
                                  OracleViolation, Schedule, broker_kill,
                                  broker_restart, leader_migrate, net)
from librdkafka_tpu.chaos.scenarios import (coordinator_death_midcommit,
                                            fast_kill_restart,
                                            fast_net_flap,
                                            leader_migration_midbatch,
                                            oracle_selftest,
                                            rolling_restart_eos,
                                            slow_network_rebalance)
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.mock.sockem import Sockem
from librdkafka_tpu.protocol.msgset import iter_batches, parse_records_v2


def _log_values(cluster, topic, part):
    vals = []
    for _base, blob in cluster.partition(topic, part).log:
        for info, payload, _full in iter_batches(blob):
            vals += [r.value for r in parse_records_v2(info, payload)]
    return vals


# ===================================================== cluster controller ==
class TestClusterController:
    def test_downed_broker_refuses_connections(self):
        """satellite: a down broker must REFUSE connects (listener
        closed) so clients walk the real connect-retry/backoff path —
        not accept-and-drop."""
        c = MockCluster(num_brokers=2, topics={"t": 1})
        try:
            port = c._ports[1]
            s = socket.create_connection(("127.0.0.1", port), timeout=2)
            s.close()
            c.set_broker_down(1)
            with pytest.raises(ConnectionRefusedError):
                socket.create_connection(("127.0.0.1", port), timeout=2)
            c.set_broker_down(1, down=False)
            # same port after restart: cached client metadata stays valid
            s = socket.create_connection(("127.0.0.1", port), timeout=2)
            s.close()
            assert c._ports[1] == port
        finally:
            c.stop()

    def test_kill_broker_migrates_leadership(self):
        c = MockCluster(num_brokers=3, topics={"t": 6})
        try:
            victims = [p.id for p in c.topics["t"] if p.leader == 2]
            assert victims, "topic layout should give broker 2 leaders"
            v0 = c.metadata_version
            info = c.kill_broker(2)
            assert {m[0:2] for m in info["migrated"]} == \
                {("t", pid) for pid in victims}
            assert all(p.leader != 2 for p in c.topics["t"])
            # the new leader joined the replica set (metadata/isr shows it)
            for p in c.topics["t"]:
                assert p.leader in p.replicas
            assert c.metadata_version > v0
            c.restart_broker(2)
            # leadership does NOT fail back implicitly
            assert all(p.leader != 2 for p in c.topics["t"])
        finally:
            c.stop()

    def test_coordinator_reassignment_skips_dead_brokers(self):
        c = MockCluster(num_brokers=3)
        try:
            base = c.coordinator_for("some-group")
            c.kill_broker(base)
            moved = c.coordinator_for("some-group")
            assert moved != base and moved in c.alive_brokers()
            c.restart_broker(base)
            assert c.coordinator_for("some-group") == base
        finally:
            c.stop()

    def test_new_topic_mid_storm_gets_alive_leader(self):
        c = MockCluster(num_brokers=3)
        try:
            c.kill_broker(1)
            c.create_topic("born-in-storm", 3)
            assert all(p.leader != 1 for p in c.topics["born-in-storm"])
        finally:
            c.stop()

    def test_rolling_restart_leaves_cluster_whole(self):
        c = MockCluster(num_brokers=3, topics={"t": 3})
        try:
            c.rolling_restart(pause_s=0.05)
            assert c.alive_brokers() == [1, 2, 3]
            for b in range(1, 4):
                s = socket.create_connection(("127.0.0.1", c._ports[b]),
                                             timeout=2)
                s.close()
        finally:
            c.stop()


# ============================================================== sockem ==
class TestSockemInjection:
    @pytest.fixture
    def cluster(self):
        c = MockCluster(num_brokers=1, topics={"net": 1})
        yield c
        c.stop()

    def test_partial_writes_still_deliver(self, cluster):
        """max_write chops every frame into tiny sends: the broker and
        client reassembly must still see whole requests/responses."""
        em = Sockem(max_write=7)
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "connect_cb": em.connect_cb, "linger.ms": 2})
        p.produce("net", value=b"x" * 2000, partition=0)
        assert p.flush(15.0) == 0
        assert _log_values(cluster, "net", 0) == [b"x" * 2000]
        p.close()

    def test_tx_drop_partition_then_heal(self, cluster):
        """One-direction partition client->broker: produce stalls while
        dropped, heals live, and idempotence leaves exactly one copy.
        socket.max.fails=0 keeps the half-open connection (a reconnect
        would push the ApiVersions handshake through the same dropped
        link and stall on ITS timeout instead)."""
        em = Sockem()
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "connect_cb": em.connect_cb,
                      "enable.idempotence": True, "linger.ms": 2,
                      "socket.timeout.ms": 600, "socket.max.fails": 0,
                      "retry.backoff.ms": 50,
                      "message.send.max.retries": 50,
                      "message.timeout.ms": 30000})
        p.produce("net", value=b"warm", partition=0)
        assert p.flush(10.0) == 0
        em.set(tx_drop=True)
        p.produce("net", value=b"dropped", partition=0)
        assert p.flush(0.8) == 1, "tx_drop should stall delivery"
        em.set(tx_drop=False)
        assert p.flush(20.0) == 0
        vals = _log_values(cluster, "net", 0)
        assert vals.count(b"dropped") == 1
        p.close()

    def test_rx_drop_loses_response_not_message(self, cluster):
        """Broker->client drop: the request LANDS but its response is
        lost — the retry must dedup broker-side (idempotence), one
        copy in the log."""
        em = Sockem()
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "connect_cb": em.connect_cb,
                      "enable.idempotence": True, "linger.ms": 2,
                      "socket.timeout.ms": 600, "socket.max.fails": 0,
                      "retry.backoff.ms": 50,
                      "message.send.max.retries": 50,
                      "message.timeout.ms": 30000})
        p.produce("net", value=b"warm", partition=0)
        assert p.flush(10.0) == 0
        em.set(rx_drop=True)
        p.produce("net", value=b"half-open", partition=0)
        time.sleep(1.0)          # request delivered, response dropped
        em.set(rx_drop=False)
        assert p.flush(20.0) == 0
        vals = _log_values(cluster, "net", 0)
        assert vals.count(b"half-open") == 1, \
            f"duplicated under rx_drop retry: {vals}"
        p.close()


# ============================================================ schedule ==
class TestSchedule:
    def _storm_schedule(self, seed):
        return (Schedule(seed=seed)
                .at(0.0, broker_kill("any"))
                .at(0.0, leader_migrate("t", "any"))
                .at(0.0, broker_restart())
                .at(0.0, broker_kill("any"))
                .at(0.0, leader_migrate("t", "any"))
                .at(0.0, broker_kill("coordinator:g1"))
                .at(0.0, broker_restart())
                .at(0.0, broker_restart()))

    def _run_once(self, seed):
        c = MockCluster(num_brokers=4, topics={"t": 8})
        try:
            sched = self._storm_schedule(seed)
            chaos = ChaosScheduler(c, min_alive=2)
            chaos.run(sched)            # synchronous: no timing at all
            assert not chaos.errors, chaos.errors
            return chaos.replay_key()
        finally:
            c.stop()

    def test_same_seed_identical_fault_timeline(self):
        """Acceptance criterion: same seed => identical fault timeline
        on replay, including every rng-resolved 'any' target."""
        assert self._run_once(1234) == self._run_once(1234)

    def test_every_expands_and_min_alive_guards(self):
        c = MockCluster(num_brokers=2, topics={"t": 2})
        try:
            sched = Schedule(seed=1).every(0, 0, 4,
                                           lambda: broker_kill("any"))
            assert len(sched.steps) == 4
            chaos = ChaosScheduler(c, min_alive=1)
            chaos.run(sched)
            # first kill lands, the rest are skipped at the quorum floor
            fired = [e for e in chaos.timeline
                     if (e.get("resolved") or {}).get("broker")]
            assert len(fired) == 1
            assert len(c.alive_brokers()) == 1
        finally:
            c.stop()

    def test_net_without_sockem_records_error_not_crash(self):
        c = MockCluster(num_brokers=1)
        try:
            chaos = ChaosScheduler(c)       # no sockem wired
            chaos.run(Schedule(seed=1).at(0, net(delay_ms=5))
                      .at(0, broker_kill(1)))
            assert len(chaos.errors) == 1
            assert "Sockem" in chaos.errors[0]["error"]
            # the storm continued past the failing step
            assert c.alive_brokers() == []
            chaos.heal()
            assert c.alive_brokers() == [1]
        finally:
            c.stop()

    def test_threaded_scheduler_times_steps_and_joins(self):
        c = MockCluster(num_brokers=2, topics={"t": 1})
        try:
            chaos = ChaosScheduler(c)
            chaos.start(Schedule(seed=7)
                        .at(0.05, broker_kill(2))
                        .at(0.25, broker_restart()))
            chaos.join()
            assert [e["action"] for e in chaos.timeline] == \
                ["broker_kill", "broker_restart"]
            assert chaos.timeline[1]["wall"] >= 0.2
            assert c.alive_brokers() == [1, 2]
        finally:
            c.stop()


# ============================================================== oracle ==
class TestOracle:
    def _msg(self, topic, part, off, val):
        class M:
            pass
        m = M()
        m.topic, m.partition, m.offset, m.value = topic, part, off, val
        return m

    def test_clean_ledger_passes(self):
        o = DeliveryOracle()
        for i in range(4):
            o.record_ack("t", 0, i, None, b"v%d" % i)
            o.record_consumed(self._msg("t", 0, i, b"v%d" % i))
        r = o.verify()
        assert r["ok"] and o.missing_count() == 0

    def test_each_invariant_trips(self, tmp_path):
        o = DeliveryOracle(dump_dir=str(tmp_path))
        o.begin_txn("tx-c")
        o.commit_txn("tx-c")
        o.begin_txn("tx-a")
        o.abort_txn("tx-a")
        # committed txn, one of two records lost => lost + torn
        o.record_ack("t", 0, 0, None, b"c0", "tx-c")
        o.record_ack("t", 0, 1, None, b"c1", "tx-c")
        o.record_consumed(self._msg("t", 0, 0, b"c0"))
        # aborted txn leaks a record => aborted_seen
        o.record_ack("t", 1, 0, None, b"a0", "tx-a")
        o.record_consumed(self._msg("t", 1, 0, b"a0"))
        # duplication + reorder on partition 2
        o.record_ack("t", 2, 0, None, b"d0")
        o.record_ack("t", 2, 1, None, b"d1")
        o.record_consumed(self._msg("t", 2, 1, b"d1"))
        o.record_consumed(self._msg("t", 2, 0, b"d0"))
        o.record_consumed(self._msg("t", 2, 0, b"d0"))
        with pytest.raises(OracleViolation) as ei:
            o.verify()
        v = ei.value.report["violations"]
        assert [r["value"] for r in v["lost"]] == ["c1"]
        assert [r["value"] for r in v["aborted_seen"]] == ["a0"]
        assert v["duplicated"] and v["reordered"]
        assert [r["txn"] for r in v["torn_txns"]] == ["tx-c"]
        diff = ei.value.report["diff_path"]
        assert diff and os.path.exists(diff)
        with open(diff) as f:
            on_disk = json.load(f)
        assert on_disk["summary"]["lost"] == 1
        # tracing was off here: no flight dump is possible (scenarios
        # enable it; oracle_selftest asserts the armed path)
        assert ei.value.report["flight_path"] is None

    def test_relaxed_checks_for_at_least_once(self):
        o = DeliveryOracle()
        o.record_ack("t", 0, 0, None, b"x")
        o.record_consumed(self._msg("t", 0, 0, b"x"))
        o.record_consumed(self._msg("t", 0, 0, b"x"))   # redelivery
        with pytest.raises(OracleViolation):
            o.verify()
        r = o.verify(check_duplicates=False, check_order=False)
        assert r["ok"]

    def test_unknown_txn_exempt_from_loss_but_not_atomicity(self):
        o = DeliveryOracle()
        o.begin_txn("tx-u")
        o.unknown_txn("tx-u")
        o.record_ack("t", 0, 0, None, b"u0", "tx-u")
        o.record_ack("t", 0, 1, None, b"u1", "tx-u")
        assert o.verify()["ok"]          # nothing consumed: all-or-nothing ok
        o.record_consumed(self._msg("t", 0, 0, b"u0"))
        with pytest.raises(OracleViolation) as ei:
            o.verify()
        assert ei.value.report["violations"]["torn_txns"]


# =================================================== fast scenarios (t1) ==
@pytest.mark.chaos
class TestFastScenarios:
    def test_fast_kill_restart(self):
        t0 = time.monotonic()
        r = fast_kill_restart()
        assert r["ok"], r["violations"]
        assert not r["errors"] and not r["schedule_errors"]
        kills = [e for e in r["timeline"] if e["action"] == "broker_kill"
                 and (e.get("resolved") or {}).get("broker")]
        assert len(kills) == 1
        assert r["acked"] > 100 and r["consumed"] == r["acked"]
        assert time.monotonic() - t0 < 10, "tier-1 scenario budget blown"

    def test_fast_net_flap(self):
        t0 = time.monotonic()
        r = fast_net_flap()
        assert r["ok"], r["violations"]
        assert not r["errors"] and not r["schedule_errors"]
        assert r["acked"] > 100 and r["consumed"] == r["acked"]
        assert time.monotonic() - t0 < 10, "tier-1 scenario budget blown"

    def test_oracle_selftest_dumps_flight_and_diff(self):
        """Acceptance criterion: an intentionally-broken scenario
        proves a violation produces a flight-recorder dump + oracle
        diff."""
        r = oracle_selftest()
        assert not r["ok"]
        assert r["violations"]["lost"] and r["violations"]["duplicated"]
        assert r["diff_path"] and os.path.exists(r["diff_path"])
        assert r["flight_path"] and os.path.exists(r["flight_path"])
        with open(r["flight_path"]) as f:
            flight = json.load(f)
        names = {e.get("name") for e in flight["traceEvents"]}
        assert "oracle_violation" in names, \
            "flight dump must carry the verdict marker event"


# ======================================================= full storms ==
@pytest.mark.chaos
@pytest.mark.slow
class TestStorms:
    def test_flagship_rolling_restart_eos(self):
        """ISSUE 7 acceptance storm: >=5 rolling kill/restarts under
        sustained transactional produce + read_committed consume; zero
        loss / zero dup / per-partition order / txn atomicity."""
        r = rolling_restart_eos(seed=1)
        assert r["ok"], r["violations"]
        assert r["kills_fired"] >= 5
        assert r["txns"]["committed"] > 10
        assert r["txns"]["aborted"] > 0          # atomicity exercised
        assert r["txns"]["unknown"] == 0
        assert not r["schedule_errors"]

    def test_flagship_replay_same_seed_same_timeline(self):
        """Acceptance criterion at storm scale: same seed => identical
        fault timeline under a real (wall-clock-jittered) run."""
        r1 = rolling_restart_eos(seed=99)
        r2 = rolling_restart_eos(seed=99)
        assert r1["ok"] and r2["ok"]
        assert r1["replay_key"] == r2["replay_key"]

    def test_coordinator_death_midcommit(self):
        r = coordinator_death_midcommit(seed=2)
        assert r["ok"], r["violations"]
        assert r["txns"]["unknown"] == 0
        # at least one kill actually hit the then-coordinator
        assert any(e["action"] == "broker_kill"
                   and (e.get("resolved") or {}).get("broker")
                   for e in r["timeline"])

    def test_leader_migration_midbatch(self):
        r = leader_migration_midbatch(seed=3)
        assert r["ok"], r["violations"]
        migrated = [e for e in r["timeline"]
                    if e["action"] == "leader_migrate"
                    and (e.get("resolved") or {}).get("to")]
        assert len(migrated) >= 6
        assert r["acked"] > 300

    def test_slow_network_rebalance_zero_loss(self):
        r = slow_network_rebalance(seed=4)
        assert r["ok"], r["violations"]
        # at-least-once: duplicates legal, loss is not
        assert not r["violations"]["lost"]
        assert r["consumed"] >= r["acked"]
