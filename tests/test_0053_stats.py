"""Statistics tests (reference: 0053-stats_cb.cpp / 0062-stats_event.c +
rdhdrhistogram.c unittest at :709): HdrHistogram percentile accuracy
against an oracle, rd_avg_t windowed rollover semantics, and the e2e
stats blob carrying the STATISTICS.md latency decomposition
(int_latency, per-broker rtt/outbuf_latency/throttle percentiles)."""
import json
import time

import numpy as np
import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.stats import Avg
from librdkafka_tpu.utils.hdrhistogram import HdrHistogram


class TestHdrHistogram:
    def test_percentiles_vs_numpy(self):
        rng = np.random.default_rng(7)
        for data in (rng.integers(1, 1000, 20000),
                     (rng.lognormal(8, 1.5, 20000)).astype(int) + 1):
            h = HdrHistogram(1, 60_000_000, 3)
            for v in data:
                h.record(int(v))
            for p in (50, 75, 90, 95, 99, 99.99):
                got = h.value_at_percentile(p)
                want = float(np.percentile(data, p, method="inverted_cdf"))
                assert abs(got - want) / max(want, 1) < 0.002, (p, got, want)
            assert h.min_v == data.min() and h.max_v == data.max()
            assert abs(h.mean() - data.mean()) / data.mean() < 0.001
            assert abs(h.stddev() - data.std()) / data.std() < 0.01

    def test_constant_and_edge_values(self):
        h = HdrHistogram(1, 1000, 2)
        for _ in range(100):
            h.record(777)
        assert h.value_at_percentile(50) == h.value_at_percentile(99.99)
        assert abs(h.value_at_percentile(50) - 777) <= 777 * 0.01
        assert h.record(0) is True          # zero is trackable
        assert h.record(5000) is False      # above range
        assert h.record(-1) is False
        assert h.out_of_range == 2
        assert h.min_v == 0

    def test_memory_is_constant(self):
        h = HdrHistogram(1, 60_000_000, 3)
        size0 = h.memsize
        for v in range(1, 200000, 7):
            h.record(v)
        assert h.memsize == size0
        assert h.total == len(range(1, 200000, 7))

    def test_reset(self):
        h = HdrHistogram()
        h.record(42)
        h.reset()
        assert h.total == 0 and h.value_at_percentile(99) == 0


class TestAvg:
    def test_rollover_window_semantics(self):
        a = Avg()
        for v in (100, 200, 300, 400):
            a.add(v)
        w = a.rollover()
        assert w["cnt"] == 4 and w["min"] == 100 and w["max"] == 400
        assert 245 <= w["avg"] <= 255
        assert w["p50"] >= 200 and w["p99"] >= 390 * 0.99
        assert "stddev" in w and "outofrange" in w and "hdrsize" in w
        # windows don't leak into each other
        w2 = a.rollover()
        assert w2["cnt"] == 0 and w2["p99"] == 0


def test_stats_blob_latency_decomposition():
    """e2e: the stats JSON must carry int_latency + per-broker
    rtt/outbuf_latency/throttle with the percentile fields."""
    blobs = []
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 2,
                  "linger.ms": 2, "statistics.interval.ms": 200,
                  "stats_cb": lambda js: blobs.append(json.loads(js))})
    for i in range(300):
        p.produce("st", value=b"v%d" % i, partition=i % 4)
        if i % 50 == 0:
            p.poll(0)
            time.sleep(0.02)
    assert p.flush(15.0) == 0
    deadline = time.monotonic() + 5
    while not blobs and time.monotonic() < deadline:
        p.poll(0.1)
    p.close()
    assert blobs, "no stats emitted"
    # find a blob with traffic recorded
    best = max(blobs, key=lambda b: b["int_latency"]["cnt"])
    il = best["int_latency"]
    assert il["cnt"] > 0
    for f in ("p50", "p75", "p90", "p95", "p99", "p99_99", "stddev",
              "outofrange", "hdrsize"):
        assert f in il
    assert il["min"] <= il["p50"] <= il["p99"] <= il["max"]
    with_rtt = [b for b in blobs
                for br in b["brokers"].values() if br["rtt"]["cnt"] > 0]
    assert with_rtt, "no broker rtt samples recorded"
    br = next(br for br in best["brokers"].values())
    assert "outbuf_latency" in br and "throttle" in br


def test_stats_schema_fields():
    """The emitted blob must carry the STATISTICS.md top-level, broker,
    and partition fields (reference schema: STATISTICS.md:50-150)."""
    import json
    import time as _time

    from librdkafka_tpu import Producer
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"st": 2})
    blobs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "statistics.interval.ms": 100, "linger.ms": 5,
                  "stats_cb": lambda s: blobs.append(json.loads(s))})
    p.produce("st", value=b"schema", partition=0)
    assert p.flush(10.0) == 0
    deadline = _time.monotonic() + 5
    while not blobs and _time.monotonic() < deadline:
        p.poll(0.1)
    p.close()
    cluster.stop()
    assert blobs
    s = blobs[-1]
    for field in ("name", "client_id", "type", "ts", "time", "age",
                  "replyq", "msg_cnt", "msg_size", "msg_max",
                  "msg_size_max", "tx", "tx_bytes", "rx", "rx_bytes",
                  "metadata_cache_cnt", "txmsgs", "rxmsgs", "brokers",
                  "topics"):
        assert field in s, field
    assert s["tx"] > 0 and s["rx"] > 0
    b = next(iter(s["brokers"].values()))
    for field in ("name", "nodeid", "state", "stateage", "connects",
                  "outbuf_cnt", "waitresp_cnt", "tx", "txbytes", "rx",
                  "rxbytes", "req_timeouts", "rtt", "outbuf_latency",
                  "throttle", "fetch_session", "toppars"):
        assert field in b, field
    for field in ("session_id", "epoch", "partitions_sent",
                  "partitions_total", "full_fetches", "resets",
                  "tx_bytes", "rx_bytes"):
        assert field in b["fetch_session"], field
    tp = s["topics"]["st"]["partitions"]["0"]
    for field in ("partition", "leader", "msgq_cnt", "msgq_bytes",
                  "xmit_msgq_cnt", "fetchq_cnt", "fetch_state",
                  "app_offset", "stored_offset", "committed_offset",
                  "hi_offset", "ls_offset", "consumer_lag"):
        assert field in tp, field


def test_stats_blob_eos_txn_state():
    """ISSUE 4: a transactional producer's stats JSON eos blob carries
    the txn FSM snapshot — state, transactional.id, pid/epoch (shared
    with the idempotence layer), registered-partition count, and the
    resolved coordinator."""
    import json as _json
    import time as _time

    from librdkafka_tpu import Producer

    blobs = []
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "transactional.id": "tx-stats", "linger.ms": 2,
                  "statistics.interval.ms": 100,
                  "stats_cb": lambda js: blobs.append(_json.loads(js))})
    try:
        p.init_transactions(30)
        p.begin_transaction()
        p.produce("tx-st", value=b"in-txn", partition=0)
        p.commit_transaction(30)
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            p.poll(0.1)
            if any("eos" in b for b in blobs):
                break
    finally:
        p.close()
    with_eos = [b for b in blobs if "eos" in b]
    assert with_eos, "no stats blob carried eos"
    eos = with_eos[-1]["eos"]
    for field in ("idemp_state", "producer_id", "producer_epoch",
                  "txn_state", "transactional_id",
                  "txn_registered_partitions", "txn_coordinator"):
        assert field in eos, field
    assert eos["transactional_id"] == "tx-stats"
    assert eos["txn_state"] in ("READY", "IN_TXN", "COMMITTING")
    assert eos["producer_id"] >= 0 and eos["producer_epoch"] >= 0
    assert eos["txn_coordinator"] >= 0


#: the window-object keys every `{}`-marked field carries
#: (STATISTICS.md preamble; Avg.rollover / rd_avg_t render)
WINDOW_KEYS = {"min", "max", "avg", "sum", "cnt", "stddev", "hdrsize",
               "outofrange", "p50", "p75", "p90", "p95", "p99", "p99_99"}


def _doc_sections() -> dict:
    """Parse STATISTICS.md into {section: set(field names)}: a section
    is a `## ` heading (keyed by its backticked path, or its lowercased
    title when plain); fields are the backticked tokens in the FIRST
    column of its table rows, `{}` suffix stripped."""
    import os
    import re

    md_path = os.path.join(os.path.dirname(__file__), "..",
                           "STATISTICS.md")
    sections: dict = {}
    cur = None
    with open(md_path) as f:
        for line in f:
            if line.startswith("## "):
                m = re.search(r"`([^`]+)`", line)
                cur = m.group(1) if m else line[3:].strip().lower()
                sections[cur] = set()
            elif cur is not None and line.startswith("|"):
                first = line.split("|")[1]
                for tok in re.findall(r"`([^`]+)`", first):
                    sections[cur].add(tok.strip().rstrip("{}"))
    return sections


def _producer_blob():
    """A transactional tpu-backend producer blob: carries eos AND
    codec_engine (plus brokers/topics with real traffic)."""
    from librdkafka_tpu import Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "transactional.id": "schema-tx",
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 1,
                  "compression.codec": "lz4", "linger.ms": 2})
    try:
        p.init_transactions(30)
        p.begin_transaction()
        for i in range(30):
            p.produce("schema-t", value=b"v%d" % i * 20)
        p.commit_transaction(30)
        return json.loads(p._rk.stats.emit_json())
    finally:
        p.close()


def _consumer_blob():
    """A grouped consumer blob: carries cgrp."""
    from librdkafka_tpu import Consumer

    c = Consumer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "group.id": "schema-g",
                  "auto.offset.reset": "earliest"})
    try:
        c.subscribe(["schema-t"])
        c.poll(0.3)
        return json.loads(c._rk.stats.emit_json())
    finally:
        c.close()


def test_stats_schema_matches_statistics_md():
    """ISSUE 5 satellite: every field documented in STATISTICS.md
    appears in StatsCollector.emit_json() output AND vice versa — the
    doc is executable; an undocumented key or a stale row fails."""
    doc = _doc_sections()
    pb = _producer_blob()
    cb = _consumer_blob()

    # top level: the union of producer (eos, codec_engine) and grouped
    # consumer (cgrp) blobs covers every emittable key
    union = set(pb) | set(cb)
    assert union == doc["top level"], (
        f"undocumented: {sorted(union - doc['top level'])}; "
        f"stale doc rows: {sorted(doc['top level'] - union)}")

    b = next(iter(pb["brokers"].values()))
    assert set(b) == doc["brokers.{name}"], (
        set(b) ^ doc["brokers.{name}"])

    # ISSUE 14: the KIP-227 session snapshot is itself a documented
    # sub-section — schema-checked field for field
    fs = b["fetch_session"]
    want_fs = doc["brokers.{name}.fetch_session"]
    assert set(fs) == want_fs, set(fs) ^ want_fs

    tp = next(iter(pb["topics"].values()))["partitions"]
    part = next(iter(tp.values()))
    want = doc["topics.{topic}.partitions.{partition}"]
    assert set(part) == want, set(part) ^ want

    assert set(cb["cgrp"]) == doc["cgrp"], set(cb["cgrp"]) ^ doc["cgrp"]
    assert set(pb["eos"]) == doc["eos"], set(pb["eos"]) ^ doc["eos"]

    # ISSUE 16: fast-lane engagement counters — producer-only section,
    # bidirectional like the rest (fallback reasons field-for-field)
    assert "arena" not in cb, "arena blob must be producer-only"
    ar = pb["arena"]
    assert set(ar) == doc["arena"], set(ar) ^ doc["arena"]
    assert set(ar["fallback"]) == doc["arena.fallback"], \
        set(ar["fallback"]) ^ doc["arena.fallback"]
    # every one of the 30 transactional produces is visible to the
    # gate: PARTITION_UA + the default consistent_random partitioner
    # counts as auto_partition fallback, and the Python-partitioned
    # messages demote their toppars with reason "partitioner"
    assert ar["fallback"]["auto_partition"] == 30, ar
    assert ar["demoted"].get("partitioner", 0) >= 1, ar

    ce = pb["codec_engine"]
    assert set(ce) == doc["codec_engine"], set(ce) ^ doc["codec_engine"]
    assert set(ce["governor"]) == doc["codec_engine.governor"], \
        set(ce["governor"]) ^ doc["codec_engine.governor"]
    assert set(ce["stage_latency"]) == doc["codec_engine.stage_latency"]
    assert set(ce["gauges"]) == doc["codec_engine.gauges"]

    # ISSUE 17: the device compress route blob — bidirectional like the
    # rest; present (all-zero counters) even while the route is off
    comp = ce["compress"]
    assert set(comp) == doc["codec_engine.compress"], \
        set(comp) ^ doc["codec_engine.compress"]
    assert isinstance(comp["routed"], dict)
    assert set(comp["model"]) == {"cpu_ns_per_byte", "dev_launch_ms"}
    for qrow in comp["qos"].values():
        assert set(qrow) == {"weight", "routed", "shed"}

    # ISSUE 6: the per-device dispatch-lane rows — the engine resolved
    # its lanes (the producer's CRC group reached the launch path), and
    # every row carries exactly the documented fields
    assert ce["devices"], "codec_engine.devices[] empty: no lane resolved"
    for row in ce["devices"]:
        assert set(row) == doc["codec_engine.devices[]"], \
            set(row) ^ doc["codec_engine.devices[]"]
        assert isinstance(row["dev_launch_ms"], dict)

    # ISSUE 20: the unified metrics-registry blob — ALWAYS present on
    # both client types (empty instrument maps while disabled) and
    # bidirectional against its STATISTICS.md section
    for blob in (pb, cb):
        obs = blob["obs"]
        assert set(obs) == doc["obs"], set(obs) ^ doc["obs"]
        assert obs["schema"] == 1
        for m in ("counters", "gauges", "windows"):
            assert isinstance(obs[m], dict)
        if not obs["enabled"]:
            assert not obs["counters"] and not obs["windows"], obs
        for w in obs["windows"].values():
            assert set(w) == WINDOW_KEYS, set(w) ^ WINDOW_KEYS

    # every `{}`-marked window renders the full rd_avg_t field set;
    # stage_latency.launch_dev is a {device id: window} split, its
    # VALUES are windows
    sl = dict(ce["stage_latency"])
    launch_dev = sl.pop("launch_dev")
    for w in (pb["int_latency"], pb["codec_latency"], b["rtt"],
              b["outbuf_latency"], b["throttle"], b["fetch_latency"],
              *sl.values(), *launch_dev.values()):
        assert set(w) == WINDOW_KEYS, set(w) ^ WINDOW_KEYS


def test_stats_emit_safe_during_broker_churn():
    """ISSUE 5 satellite: emit_json() must be safe while the broker set
    mutates concurrently (metadata discovery adds brokers, close reaps
    them) — the emitter snapshots under list(); a 'dict changed size
    during iteration' here would kill the main thread's stats timer."""
    import threading as _th

    from librdkafka_tpu import Producer
    from librdkafka_tpu.client.broker import Broker

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 2,
                  "linger.ms": 2})
    rk = p._rk
    try:
        for i in range(50):
            p.produce("churn-t", value=b"x%d" % i, partition=i % 4)
        errors: list = []
        stop = _th.Event()

        def emitter():
            try:
                while not stop.is_set():
                    blob = json.loads(rk.stats.emit_json())
                    assert "brokers" in blob
            except Exception as e:          # pragma: no cover
                errors.append(e)

        th = _th.Thread(target=emitter)
        th.start()
        try:
            # churn: register/unregister unstarted Broker objects under
            # the same lock metadata discovery uses
            for i in range(150):
                b = Broker(rk, 1000 + i, "127.0.0.1", 1)
                with rk._brokers_lock:
                    rk.brokers[b.nodeid] = b
                with rk._brokers_lock:
                    del rk.brokers[b.nodeid]
                b._wakeup_r.close()
                b._wakeup_w.close()
        finally:
            stop.set()
            th.join(10)
        assert not errors, errors
        assert p.flush(15.0) == 0
    finally:
        p.close()


def test_stats_blob_codec_engine_governor_counters():
    """ISSUE 3: with the tpu backend's async engine live, the stats
    JSON carries a codec_engine section — launch/merge/fallback/warmup
    counters plus the governor's cost-model gauges."""
    import json as _json
    import time as _time

    from librdkafka_tpu import Producer

    blobs = []
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.launch.min.batches": 1,
                  "compression.codec": "lz4", "linger.ms": 2,
                  "statistics.interval.ms": 100,
                  "stats_cb": lambda js: blobs.append(_json.loads(js))})
    try:
        for i in range(50):
            p.produce("gov-st", value=b"v%d" % i * 40)
        assert p.flush(120.0) == 0
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            p.poll(0.1)
            if any("codec_engine" in b for b in blobs):
                break
    finally:
        p.close()
    with_engine = [b for b in blobs if "codec_engine" in b]
    assert with_engine, "no stats blob carried codec_engine"
    ce = with_engine[-1]["codec_engine"]
    for field in ("launches", "jobs", "aggregated", "cpu_fallback_jobs",
                  "warmup_miss_jobs", "warmup_compiled",
                  "routed_cpu_jobs", "explore_routes", "fused_launches",
                  "sharded_launches", "fanin_skips", "fanin_waits",
                  "governor", "devices"):
        assert field in ce, field
    assert ce["jobs"] >= 1, ce
    gov = ce["governor"]
    for field in ("enabled", "warmup", "interarrival_us",
                  "cpu_ns_per_byte", "dev_launch_ms"):
        assert field in gov, field


def test_cgrp_blob_cooperative_fields():
    """ISSUE 12 cross-check: the cgrp blob's rebalance_proto /
    incremental_revokes / stuck_partitions track the live cooperative
    state — a steady cooperative member reports COOPERATIVE, zero
    stuck partitions, and the incremental-revoke counter matches the
    cgrp's own."""
    import time as _time

    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"cb": 2})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 2})
        for i in range(6):
            p.produce("cb", value=b"v%d" % i, partition=i % 2)
        assert p.flush(10) == 0
        p.close()

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "cb-g",
                      "partition.assignment.strategy":
                          "cooperative-sticky",
                      "auto.offset.reset": "earliest"})
        c.subscribe(["cb"])
        got = 0
        deadline = _time.monotonic() + 15
        while got < 6 and _time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got += 1
        assert got == 6
        blob = json.loads(c._rk.stats.emit_json())
        cg = blob["cgrp"]
        assert cg["rebalance_proto"] == "COOPERATIVE"
        assert cg["state"] == "steady"
        assert cg["stuck_partitions"] == 0
        with c._rk.cgrp._lock:
            want = c._rk.cgrp.incremental_revoke_cnt
        assert cg["incremental_revokes"] == want
        # a pre-join producer-side instance reports NONE
        c.close()
    finally:
        cluster.stop()
