"""Producer latency tests (reference: 0055-producer_latency.c): the
linger gate (rdkafka_broker.c:3453-3470) bounds int_latency — low
linger delivers fast; high linger accumulates batches; flush() overrides
linger and sends immediately."""
import json
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.mock.cluster import MockCluster


def _deliver_one(p, topic, timeout=10.0):
    done = []
    p.produce(topic, value=b"lat", partition=0,
              on_delivery=lambda e, m: done.append(time.monotonic()))
    t0 = time.monotonic()
    deadline = t0 + timeout
    while not done and time.monotonic() < deadline:
        p.poll(0.01)
    assert done, "never delivered"
    return done[0] - t0


def test_low_linger_is_fast():
    cluster = MockCluster(num_brokers=1, topics={"lat": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 0})
    try:
        _deliver_one(p, "lat")              # warm connection
        lat = min(_deliver_one(p, "lat") for _ in range(5))
        assert lat < 0.15, f"linger.ms=0 latency {lat*1000:.1f}ms"
    finally:
        p.close()
        cluster.stop()


def test_high_linger_accumulates_then_flush_overrides():
    cluster = MockCluster(num_brokers=1, topics={"lat": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5000, "batch.num.messages": 10000})
    try:
        p.produce("lat", value=b"warm", partition=0)
        assert p.flush(10.0) == 0           # flush sends despite linger
        for i in range(50):
            p.produce("lat", value=b"m%d" % i, partition=0)
        time.sleep(0.4)
        # still lingering: nothing new in the log
        assert cluster.partition("lat", 0).end_offset == 1
        t0 = time.monotonic()
        assert p.flush(10.0) == 0
        assert time.monotonic() - t0 < 2.0, "flush waited for linger"
        assert cluster.partition("lat", 0).end_offset == 51
        # the lingered 50 went out as ONE batch (one wire blob)
        assert len(cluster.partition("lat", 0).log) == 2
    finally:
        p.close()
        cluster.stop()


def test_int_latency_stat_reflects_linger():
    blobs = []
    cluster = MockCluster(num_brokers=1, topics={"lat": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 300, "statistics.interval.ms": 200,
                  "stats_cb": lambda js: blobs.append(json.loads(js))})
    try:
        for i in range(20):
            p.produce("lat", value=b"s%d" % i, partition=0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            p.poll(0.1)
            if any(b["int_latency"]["cnt"] for b in blobs):
                break
        il = next(b["int_latency"] for b in blobs
                  if b["int_latency"]["cnt"])
        # the batch lingered ~300ms before framing
        assert il["max"] >= 250_000, il   # µs
    finally:
        p.close()
        cluster.stop()
