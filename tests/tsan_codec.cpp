/* ThreadSanitizer harness for the native codec layer — the rebuild's
 * analog of the reference's TSAN tier (dev-conf.sh:62-74,
 * tests/Makefile tsan target). codec.cpp owns real concurrency: the
 * *_many entry points fan work over std::thread pools, and the client
 * calls them from broker/codec-worker threads concurrently. This
 * driver exercises those paths under -fsanitize=thread:
 *
 *   - tk_lz4f_compress_many / tk_snappy_compress_many (internal pools)
 *   - tk_lz4f_decompress_many / tk_crc32c_many
 *   - the same entry points called from MULTIPLE app threads at once
 *     (each client instance has several broker threads + a codec
 *     worker sharing the library)
 *
 * Built and run by tests/test_0124_tsan.py; any TSAN report fails.
 */
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int64_t tk_lz4f_bound(int64_t n);
int64_t tk_snappy_bound(int64_t n);
int64_t tk_lz4f_compress(const uint8_t *src, int64_t n, uint8_t *dst,
                         int64_t cap);
int64_t tk_lz4f_decompress(const uint8_t *src, int64_t n, uint8_t *dst,
                           int64_t cap);
void tk_lz4f_compress_many(const uint8_t *base, const int64_t *offs,
                           const int64_t *lens, int n, uint8_t *outbase,
                           const int64_t *out_offs, int64_t *out_lens,
                           int nthreads);
void tk_snappy_compress_many(const uint8_t *base, const int64_t *offs,
                             const int64_t *lens, int n, uint8_t *outbase,
                             const int64_t *out_offs, int64_t *out_lens,
                             int nthreads);
void tk_lz4f_decompress_many(const uint8_t *base, const int64_t *offs,
                             const int64_t *lens, int n, uint8_t *outbase,
                             const int64_t *out_offs,
                             const int64_t *out_caps, int64_t *out_lens,
                             int nthreads);
void tk_crc32c_many(const uint8_t *base, const int64_t *offs,
                    const int64_t *lens, uint32_t *crcs, int n);
uint32_t tk_crc32c(const uint8_t *data, int64_t n, uint32_t seed);
}

static const int NBUF = 16;
static const int64_t BUF = 64 * 1024;

struct Fixture {
    std::vector<uint8_t> base;
    std::vector<int64_t> offs, lens;
    Fixture() : base(NBUF * BUF), offs(NBUF), lens(NBUF) {
        for (int i = 0; i < NBUF; i++) {
            offs[i] = i * BUF;
            lens[i] = BUF;
            for (int64_t j = 0; j < BUF; j++)
                base[i * BUF + j] = (uint8_t)((j * 31 + i * 7) & 0x7F);
        }
    }
};

static int run_round(const Fixture &fx) {
    // one "client instance" worth of concurrent codec work
    int64_t cbound = tk_lz4f_bound(BUF);
    std::vector<uint8_t> cout((size_t)NBUF * cbound);
    std::vector<int64_t> couts(NBUF), coffs(NBUF);
    for (int i = 0; i < NBUF; i++) coffs[i] = i * cbound;
    tk_lz4f_compress_many(fx.base.data(), fx.offs.data(), fx.lens.data(),
                          NBUF, cout.data(), coffs.data(), couts.data(),
                          4);
    // decompress what we compressed (internal pool again)
    std::vector<uint8_t> dout(NBUF * BUF);
    std::vector<int64_t> douts(NBUF), dcaps(NBUF, BUF), doffs(NBUF);
    for (int i = 0; i < NBUF; i++) doffs[i] = i * BUF;
    tk_lz4f_decompress_many(cout.data(), coffs.data(), couts.data(),
                            NBUF, dout.data(), doffs.data(), dcaps.data(),
                            douts.data(), 4);
    for (int i = 0; i < NBUF; i++) {
        if (douts[i] != BUF ||
            memcmp(dout.data() + i * BUF, fx.base.data() + i * BUF, BUF))
            return 1;
    }
    int64_t sbound = tk_snappy_bound(BUF);
    std::vector<uint8_t> sout((size_t)NBUF * sbound);
    std::vector<int64_t> souts(NBUF), soffs(NBUF);
    for (int i = 0; i < NBUF; i++) soffs[i] = i * sbound;
    tk_snappy_compress_many(fx.base.data(), fx.offs.data(),
                            fx.lens.data(), NBUF, sout.data(),
                            soffs.data(), souts.data(), 4);
    std::vector<uint32_t> crcs(NBUF);
    tk_crc32c_many(fx.base.data(), fx.offs.data(), fx.lens.data(),
                   crcs.data(), NBUF);
    for (int i = 0; i < NBUF; i++) {
        if (crcs[i] != tk_crc32c(fx.base.data() + i * BUF, BUF, 0))
            return 2;
    }
    return 0;
}

int main() {
    Fixture fx;
    // several "client" threads concurrently driving the shared library,
    // each spawning its own internal pools — the shape a process with
    // multiple producers/consumers has
    std::vector<std::thread> apps;
    int rc[4] = {0, 0, 0, 0};
    for (int t = 0; t < 4; t++)
        apps.emplace_back([&, t]() {
            for (int r = 0; r < 3 && rc[t] == 0; r++) rc[t] = run_round(fx);
        });
    for (auto &t : apps) t.join();
    for (int t = 0; t < 4; t++)
        if (rc[t]) { std::fprintf(stderr, "round failed: %d\n", rc[t]); return 1; }
    std::printf("TSAN-CODEC-OK\n");
    return 0;
}
