"""Consumer API parity batch: 0022-consume_batch, 0089-max_poll_interval,
0077-compaction (offset gaps in compacted logs)."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.client.msg import Message
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.msgset import MsgsetWriterV2


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"ca": 1})
    yield c
    c.stop()


def _produce(cluster, n, topic="ca"):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(n):
        p.produce(topic, value=b"c%03d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()


def test_consume_batch(cluster):
    """0022-consume_batch: consume(n) returns up to n messages in
    order; a short timeout returns what's available."""
    _produce(cluster, 25)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcb", "auto.offset.reset": "earliest"})
    c.subscribe(["ca"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 25 and time.monotonic() < deadline:
        batch = c.consume(10, timeout=0.5)
        assert len(batch) <= 10
        got += [m for m in batch if m.error is None]
    c.close()
    assert [m.value for m in got] == [b"c%03d" % i for i in range(25)]
    assert [m.offset for m in got] == list(range(25))


def test_max_poll_interval_exceeded(cluster):
    """0089-max_poll_interval: not polling for longer than
    max.poll.interval.ms surfaces _MAX_POLL_EXCEEDED and leaves the
    group; polling again resumes consumption."""
    _produce(cluster, 5)
    errs = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gmp", "auto.offset.reset": "earliest",
                  "max.poll.interval.ms": 1200,
                  "session.timeout.ms": 6000,
                  "error_cb": lambda e: errs.append(e)})
    c.subscribe(["ca"])
    got = 0
    deadline = time.monotonic() + 15
    while got < 5 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got += 1
    assert got == 5
    # stop polling past the interval — the MAIN thread must flag it
    # even with no poll() running (reference: enforced in cgrp serve)
    time.sleep(2.5)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            not any(e.code == Err._MAX_POLL_EXCEEDED for e in errs):
        c.poll(0.1)
    assert any(e.code == Err._MAX_POLL_EXCEEDED for e in errs), errs
    # consumption resumes: the three NEW messages must arrive (old ones
    # may be redelivered first — the leave dropped uncommitted offsets
    # and auto.offset.reset=earliest replays; only the new values prove
    # live consumption after the rejoin)
    _produce2 = [b"post-%d" % i for i in range(3)]
    p2 = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                   "linger.ms": 2})
    for v in _produce2:
        p2.produce("ca", value=v, partition=0)
    assert p2.flush(10.0) == 0
    p2.close()
    seen_new = set()
    deadline = time.monotonic() + 20
    while len(seen_new) < 3 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None and m.value in _produce2:
            seen_new.add(m.value)
    c.close()
    assert seen_new == set(_produce2), \
        f"consumer never resumed after max.poll rejoin ({seen_new})"


def test_compacted_log_offset_gaps(cluster):
    """0077-compaction: a compacted log has non-contiguous offsets; the
    consumer must deliver what exists and advance across the gaps."""
    part = cluster.partition("ca", 0)

    def batch(base, vals):
        msgs = [Message("ca", value=v, partition=0,
                        timestamp=1_690_000_000_000 + i)
                for i, v in enumerate(vals)]
        return MsgsetWriterV2(base_offset=base).build(
            msgs, now_ms=1_690_000_000_000).finalize()

    # offsets 0-2 survive, 3-4 compacted away, 5-6 survive
    with cluster._lock:
        part.log = [(0, batch(0, [b"k0", b"k1", b"k2"])),
                    (5, batch(5, [b"k5", b"k6"]))]
        part.start_offset = 0
        part.end_offset = 7

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcp", "auto.offset.reset": "earliest",
                  "check.crcs": True})
    c.subscribe(["ca"])
    got = []
    deadline = time.monotonic() + 15
    while len(got) < 5 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append((m.offset, m.value))
    c.close()
    assert got == [(0, b"k0"), (1, b"k1"), (2, b"k2"),
                   (5, b"k5"), (6, b"k6")]


def test_consume_connection_close_recovers(cluster):
    """0049-consume_conn_close: the broker connection dies mid-consume;
    the consumer reconnects and finishes the stream without loss."""
    from librdkafka_tpu.mock.sockem import Sockem

    _produce(cluster, 20)
    em = Sockem()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcc", "auto.offset.reset": "earliest",
                  "connect_cb": em.connect_cb,
                  "reconnect.backoff.ms": 50,
                  "fetch.wait.max.ms": 100})
    c.subscribe(["ca"])
    got = []
    deadline = time.monotonic() + 30
    while len(got) < 20 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.offset)
    # kill every connection, THEN produce the second half: delivering
    # it provably requires a fresh connection (the first batch may have
    # been prefetched before the kill)
    assert em.kill_all() > 0, "no live connections to kill"
    _produce(cluster, 20)            # offsets 20-39
    deadline = time.monotonic() + 30
    # count DISTINCT offsets: the post-kill rejoin has no committed
    # offsets and earliest-reset redelivers 0-19 first
    while len(set(got)) < 40 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.offset)
    c.close()
    assert sorted(set(got)) == list(range(40)), \
        f"lost offsets: {sorted(set(range(40)) - set(got))}"


def test_consume_callback_mode(cluster):
    """Callback-based consume (reference rd_kafka_consume_callback +
    consume_cb / consume.callback.max.messages conf rows)."""
    _produce(cluster, 30)
    seen = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gccb", "auto.offset.reset": "earliest",
                  "consume_cb": lambda m: seen.append(m.offset),
                  "consume.callback.max.messages": 10})
    c.subscribe(["ca"])
    total = 0
    deadline = time.monotonic() + 20
    while total < 30 and time.monotonic() < deadline:
        n = c.consume_callback(timeout=0.5)
        assert n <= 10          # consume.callback.max.messages cap
        total += n
    assert total == 30
    assert seen == list(range(30))
    # explicit-arg override beats the conf cap
    _produce(cluster, 5)
    got2 = []
    deadline = time.monotonic() + 20
    while len(got2) < 5 and time.monotonic() < deadline:
        c.consume_callback(timeout=0.5,
                           consume_cb=lambda m: got2.append(m.offset),
                           max_messages=2)
    assert got2 == list(range(30, 35))
    c.close()


def test_consume_callback_requires_cb(cluster):
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gnone"})
    with pytest.raises(Exception):
        c.consume_callback(timeout=0.1)
    c.close()
