"""KIP-392 fetch-from-follower (reference:
tests/0104-fetch_from_follower_mock.c + the preferred_read_replica
handling at rdkafka_broker.c:3921): a v11 Fetch to the leader gets a
redirect to the nominated follower; the consumer moves its fetching
there, keeps producing to the leader, and falls back to the leader when
the follower stops serving."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.proto import ApiKey


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=2, topics={"ff": 1})   # leader = broker 1
    yield c
    c.stop()


def _produce(cluster, n, start=0):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5})
    for i in range(start, start + n):
        p.produce("ff", value=b"ff-%03d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()


def _fetch_brokers(cluster):
    return [b for b, api in cluster.request_log if api == ApiKey.Fetch]


def test_fetch_moves_to_follower_and_back(cluster):
    _produce(cluster, 40)
    cluster.set_follower("ff", 0, 2)

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gff", "auto.offset.reset": "earliest",
                  "client.rack": "rack-b", "fetch.wait.max.ms": 50})
    c.subscribe(["ff"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 40 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.value)
    assert sorted(got) == sorted(b"ff-%03d" % i for i in range(40))
    # the data fetches must have been served by the FOLLOWER
    assert 2 in _fetch_brokers(cluster), "no fetch ever hit the follower"

    # follower withdrawn: NOT_LEADER from broker 2 → revert to leader
    cluster.set_follower("ff", 0, None)
    cluster.request_log.clear()
    _produce(cluster, 20, start=40)
    got2 = []
    deadline = time.monotonic() + 20
    while len(got2) < 20 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got2.append(m.value)
    c.close()
    assert sorted(got2) == sorted(b"ff-%03d" % i for i in range(40, 60))
    assert 1 in _fetch_brokers(cluster), "never reverted to leader fetch"


def test_pre_v11_broker_never_redirects():
    """Against a broker speaking < Fetch v11 the leader serves data
    itself even with a follower nominated (the redirect field does not
    exist on the wire)."""
    cluster = MockCluster(num_brokers=2, topics={"ff": 1},
                          broker_version="0.11.0")
    try:
        cluster.set_follower("ff", 0, 2)
        _produce(cluster, 15)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gff-old",
                      "auto.offset.reset": "earliest",
                      "fetch.wait.max.ms": 50})
        c.subscribe(["ff"])
        got = []
        deadline = time.monotonic() + 15
        while len(got) < 15 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got.append(m.value)
        c.close()
        assert len(got) == 15
        assert 2 not in _fetch_brokers(cluster)
    finally:
        cluster.stop()


def test_producer_keeps_targeting_leader(cluster):
    """Fetch delegation must not move PRODUCE traffic (KIP-392 affects
    consumption only)."""
    cluster.set_follower("ff", 0, 2)
    _produce(cluster, 10)
    produce_brokers = [b for b, api in cluster.request_log
                       if api == ApiKey.Produce]
    assert produce_brokers and set(produce_brokers) == {1}
