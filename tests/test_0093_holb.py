"""Head-of-line blocking tests (reference: 0093-holb.c via sockem): a
slow broker must not block delivery to other brokers — partition
batches are independent (the fan-in axis of the TPU-first design), and
the codec pipeline keeps per-broker reactors isolated."""
import time

from librdkafka_tpu import Producer
from librdkafka_tpu.mock.cluster import MockCluster


def test_slow_broker_does_not_block_fast_broker():
    cluster = MockCluster(num_brokers=2, topics={"holb": 2})
    # partition 0 -> broker 1, partition 1 -> broker 2
    cluster.set_partition_leader("holb", 0, 1)
    cluster.set_partition_leader("holb", 1, 2)
    fast_done = []
    slow_done = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    try:
        # warm both connections
        p.produce("holb", value=b"w0", partition=0,
                  on_delivery=lambda e, m: None)
        p.produce("holb", value=b"w1", partition=1,
                  on_delivery=lambda e, m: None)
        assert p.flush(10.0) == 0

        cluster.set_rtt(1, 2500)          # broker 1 becomes slow
        t0 = time.monotonic()
        for i in range(20):
            p.produce("holb", value=b"s%d" % i, partition=0,
                      on_delivery=lambda e, m, s=t0: slow_done.append(
                          time.monotonic() - s))
            p.produce("holb", value=b"f%d" % i, partition=1,
                      on_delivery=lambda e, m, s=t0: fast_done.append(
                          time.monotonic() - s))
        # the fast broker's deliveries must complete long before the
        # slow broker's injected RTT elapses
        deadline = time.monotonic() + 10
        while len(fast_done) < 20 and time.monotonic() < deadline:
            p.poll(0.05)
        assert len(fast_done) == 20, f"fast partition starved: {len(fast_done)}"
        assert max(fast_done) < 2.0, \
            f"fast deliveries waited on the slow broker: {max(fast_done):.2f}s"
        # slow ones do eventually arrive
        assert p.flush(15.0) == 0
        deadline = time.monotonic() + 5
        while len(slow_done) < 20 and time.monotonic() < deadline:
            p.poll(0.05)
        assert len(slow_done) == 20
        assert max(slow_done) >= 2.0   # they really were delayed
    finally:
        p.close()
        cluster.stop()


def test_close_is_idempotent_and_releases():
    cluster = MockCluster(num_brokers=1, topics={"cl": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    try:
        p.produce("cl", value=b"x", partition=0)
        assert p.flush(10.0) == 0
    finally:
        p.close()
        p.close()                         # second close is a no-op
        cluster.stop()


def test_close_with_pending_messages_flushes_first():
    """Producer.close() flushes outstanding messages (reference
    rd_kafka_destroy after flush contract)."""
    cluster = MockCluster(num_brokers=1, topics={"cl2": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 3000})     # would linger past close
    try:
        for i in range(10):
            p.produce("cl2", value=b"p%d" % i, partition=0)
        p.close()                         # must not abandon the batch
        assert cluster.partition("cl2", 0).end_offset == 10
    finally:
        p.close()
        cluster.stop()
