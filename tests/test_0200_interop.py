"""Interop proof against the REAL reference librdkafka.

The strongest correctness evidence available: the reference C client
(compiled from /root/reference into .refbuild/, see tests/refclient.py)
talks to OUR mock cluster, and OUR client consumes what IT produced —
and vice versa — across every compression codec.  Plus a bit-identical
wire-byte comparison of an uncompressed v2 RecordBatch built from the
same records by both writers (the v2 format pins every byte when
timestamps are pinned; reference writer:
/root/reference/src/rdkafka_msgset_writer.c:653-1288).

All tests skip cleanly when the reference build is absent.
Build it with:  tests/build_reference.sh
"""
import os
import struct
import subprocess
import time

import pytest

import refclient
from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.msg import Message
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol import proto
from librdkafka_tpu.protocol.msgset import MsgsetWriterV2

# Skip ONLY when the reference source tree is absent (a checkout
# without /root/reference) or the user explicitly opted out with
# TK_NO_REFBUILD=1. When the reference exists, the module-scoped
# fixture below auto-builds .refbuild/ (cached) and a FAILED build
# fails this tier loudly — a wire-parity regression must not ship
# behind a silent skip (VERDICT r4 #4).
_REF_DIR = os.environ.get("REFERENCE_DIR", "/root/reference")
pytestmark = pytest.mark.skipif(
    not os.path.isdir(_REF_DIR) or os.environ.get("TK_NO_REFBUILD") == "1",
    reason=f"reference source tree not present ({_REF_DIR}) "
           "or TK_NO_REFBUILD=1")


@pytest.fixture(scope="module", autouse=True)
def _refbuild():
    """Build the reference librdkafka once (cached in the gitignored
    .refbuild/; a few minutes on first run). Build failure FAILS the
    tier — it never skips."""
    if refclient.available():
        return
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "build_reference.sh")
    r = subprocess.run(["sh", script], capture_output=True, text=True,
                       timeout=1800)
    assert r.returncode == 0 and refclient.available(), (
        "reference librdkafka build failed:\n"
        + (r.stderr or r.stdout)[-2000:])


def test_reference_build_available():
    """Fails (never skips) when the reference exists but .refbuild/ is
    absent or broken — the rest of the tier depends on it."""
    assert refclient.available(), (
        "reference librdkafka not built; auto-build failed — run "
        "tests/build_reference.sh and read its error output")

CODECS = ["none", "gzip", "snappy", "lz4", "zstd"]
BASE_TS = 1_690_000_000_000


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"interop": 2})
    yield c
    c.stop()


def _our_consume(cluster, topic, n, timeout=25.0, check_crcs=True):
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "ginterop", "auto.offset.reset": "earliest",
                  "check.crcs": check_crcs})
    c.subscribe([topic])
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c.close()
    return got


def test_ref_perf_producer_to_our_consumer(cluster):
    """(a) reference rdkafka_performance -P → our mock → our Consumer."""
    p = subprocess.run(
        [refclient.PERF_BIN, "-P", "-t", "interop", "-s", "100",
         "-c", "1000", "-b", cluster.bootstrap_servers(),
         "-X", "socket.timeout.ms=3000", "-X", "message.timeout.ms=8000"],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr[-1000:]
    got = _our_consume(cluster, "interop", 1000)
    assert len(got) == 1000
    assert all(len(m.value) == 100 for m in got)


@pytest.mark.parametrize("codec", CODECS)
def test_ref_producer_codecs_to_our_consumer(cluster, codec):
    """Reference producer (each codec) → our consumer content equality.

    This validates our msgset reader + decompressors against compressed
    bytes emitted by the real liblz4/snappy/zlib/zstd paths in the
    reference (rdkafka_msgset_writer.c:943-1108)."""
    rp = refclient.RefProducer(
        cluster.bootstrap_servers(),
        **{"compression.codec": codec, "linger.ms": "30",
           "batch.num.messages": "1000"})
    want = []
    for i in range(200):
        key = b"k%03d" % i
        val = (b"ref-interop-%03d-" % i) * 8
        rp.produce("interop", i % 2, val, key=key,
                   timestamp_ms=BASE_TS + i)
        want.append((i % 2, key, val, BASE_TS + i))
    assert rp.flush() == 0
    rp.close()

    got = _our_consume(cluster, "interop", 200)
    assert len(got) == 200
    got_set = {(m.partition, m.key, m.value, m.timestamp) for m in got}
    assert got_set == set(want)
    # per-partition offset order must be contiguous from 0
    for part in (0, 1):
        offs = [m.offset for m in got if m.partition == part]
        assert offs == sorted(offs)
        assert offs[0] == 0


@pytest.mark.parametrize("codec", CODECS)
def test_our_producer_to_ref_consumer(cluster, codec):
    """(b) our Producer (each codec) → mock → REAL librdkafka consumer.

    The reference's reader (rdkafka_msgset_reader.c:258-530 decompress,
    :982 CRC verify with check.crcs) accepting our wire bytes proves our
    writer + compressors emit spec-conformant MessageSets."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 20, "compression.codec": codec,
                  "batch.num.messages": 500})
    want = []
    for i in range(200):
        key = b"o%03d" % i
        val = (b"our-interop-%03d-" % i) * 8
        p.produce("interop", value=val, key=key, partition=i % 2,
                  timestamp=BASE_TS + i)
        want.append((i % 2, key, val, BASE_TS + i))
    assert p.flush(15.0) == 0
    p.close()

    rc = refclient.RefConsumer(cluster.bootstrap_servers(), "interop",
                               **{"check.crcs": "true"})
    got = []
    for part in (0, 1):
        got += rc.consume(part, sum(1 for w in want if w[0] == part))
    rc.close()
    assert len(got) == 200
    got_set = {(part, key, val, ts) for part, off, key, val, ts in got}
    assert got_set == set(want)


def test_uncompressed_wire_bytes_bit_identical(cluster):
    """(c) For pinned inputs the v2 RecordBatch is fully determined by
    the spec — the reference writer's bytes and ours must be IDENTICAL
    (reference: rdkafka_msgset_writer.c:1230-1288 finalize/CRC)."""
    rp = refclient.RefProducer(
        cluster.bootstrap_servers(),
        **{"linger.ms": "200", "batch.num.messages": "1000"})
    msgs = []
    for i in range(50):
        key = b"key-%02d" % i
        val = b"value-%03d" % i * 3
        rp.produce("interop", 0, val, key=key, timestamp_ms=BASE_TS + 7 * i)
        msgs.append(Message(topic="interop", value=val, key=key,
                            partition=0, timestamp=BASE_TS + 7 * i))
    assert rp.flush() == 0
    rp.close()

    # The reference may split the run into >1 batch (e.g. a first batch
    # dispatched as the broker comes up); mirror its split — each batch
    # is [base, base+count) of our pinned record list and must match
    # byte for byte.
    log = cluster.partition("interop", 0).log
    assert log, "reference produced nothing"
    total = 0
    for base, ref_blob in log:
        count = struct.unpack_from(">i", ref_blob,
                                   proto.V2_OF_RecordCount)[0]
        run = msgs[base:base + count]
        assert run, "batch outside produced range"
        ours = MsgsetWriterV2(base_offset=base).build(
            run, now_ms=BASE_TS).finalize()
        assert ours == ref_blob, (
            "wire bytes differ for batch base=%d count=%d: "
            "ours=%d bytes ref=%d bytes" %
            (base, count, len(ours), len(ref_blob)))
        total += count
    assert total == len(msgs)


def test_ref_idempotent_producer_sequences(cluster):
    """Real librdkafka idempotent producer (InitProducerId + per-batch
    BaseSequence, reference rdkafka_idempotence.c) against our mock's
    sequence bookkeeping, read back by our consumer."""
    rp = refclient.RefProducer(
        cluster.bootstrap_servers(),
        **{"enable.idempotence": "true", "linger.ms": "10",
           "batch.num.messages": "50"})
    for i in range(300):
        rp.produce("interop", i % 2, b"idem-%03d" % i,
                   timestamp_ms=BASE_TS + i)
    assert rp.flush() == 0
    rp.close()

    # the mock recorded a real PID and contiguous sequences
    for part in (0, 1):
        mp = cluster.partition("interop", part)
        assert mp.pid_seqs, "no idempotent sequence state recorded"
        (pid_epoch, next_seq), = mp.pid_seqs.items()
        assert pid_epoch[0] >= 1          # broker-assigned PID
        assert next_seq == sum(1 for i in range(300) if i % 2 == part)

    got = _our_consume(cluster, "interop", 300)
    assert len(got) == 300
    assert {m.value for m in got} == {b"idem-%03d" % i for i in range(300)}


def test_our_producer_to_ref_perf_consumer(cluster):
    """Our producer's wire data consumed by the reference's
    rdkafka_performance -C binary (simple consumer over both
    partitions), count-verified from its stdout."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 10, "compression.codec": "snappy"})
    for i in range(500):
        p.produce("interop", value=b"perfc-%04d" % i, partition=i % 2)
    assert p.flush(15.0) == 0
    p.close()

    r = subprocess.run(
        [refclient.PERF_BIN, "-C", "-t", "interop", "-p", "0", "-p", "1",
         "-b", cluster.bootstrap_servers(), "-o", "beginning",
         "-c", "500", "-X", "socket.timeout.ms=5000"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-800:]
    assert "500 messages" in r.stdout or "500 msgs" in r.stdout, \
        r.stdout[-500:]
