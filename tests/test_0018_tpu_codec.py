"""TPU codec provider equivalence suite (the north-star bit-exactness
harness, SURVEY.md §7 stage 5): device-path lz4 frames and CRC32C must be
byte/bit-identical to the CPU provider, which in turn is oracle-validated
against real liblz4 (test_0017).  Also runs the producer end-to-end with
``compression.backend=tpu`` against the mock cluster and checks the stored
wire bytes equal the CPU backend's.
"""
import os

import numpy as np
import pytest

from librdkafka_tpu.ops import cpu
from librdkafka_tpu.ops.tpu import TpuCodecProvider
from librdkafka_tpu.ops import crc32c_jax, lz4_jax
from librdkafka_tpu.utils.crc import crc32c

from test_0017_codecs import CORPORA, IDS


@pytest.fixture
def tpu_provider():
    # lz4_force=True: this suite exists to prove the DEVICE lz4 encoder
    # is bit-exact.  Production routing (tpu.lz4.force=false, default)
    # keeps lz4 on the native CPU path — see test_lz4_routes_to_cpu.
    # min_transport_mb_s=0: the gate must not silently route these
    # equivalence tests to the CPU provider on slow transport.
    # Function-scoped (warmup=False) so each test's engine is closed
    # before the conftest thread-leak check runs; the expensive XLA
    # compiles live in module-level lru_caches, paid once per process
    # regardless of provider lifetime.
    prov = TpuCodecProvider(min_batches=1, lz4_force=True, warmup=False,
                            min_transport_mb_s=0)
    yield prov
    prov.close()      # stop the async engine's dispatch thread cleanly


def test_lz4_routes_to_cpu_by_default(monkeypatch):
    """backend=tpu must never be slower than cpu: without tpu.lz4.force
    the provider compresses lz4 on the native CPU path (identical
    bytes) and only CRC32C rides the MXU (PERF.md §3 conclusion)."""
    prov = TpuCodecProvider(min_batches=1, warmup=False)

    def boom(bufs):
        raise AssertionError("device lz4 ran without tpu.lz4.force")

    monkeypatch.setattr(prov, "_lz4f_compress_many", boom)
    bufs = [CORPORA["json_like"], CORPORA["near_64k"], b"tiny"]
    assert (prov.compress_many("lz4", bufs)
            == cpu.CpuCodecProvider().compress_many("lz4", bufs))
    # conf plumbing: tpu.lz4.force reaches the provider
    from librdkafka_tpu.client.conf import Conf
    c = Conf()
    c.update({"tpu.lz4.force": True})
    assert c.get("tpu.lz4.force") is True
    assert TpuCodecProvider(min_batches=1, warmup=False,
                            lz4_force=c.get("tpu.lz4.force")).lz4_force


def test_crc_transport_gate(monkeypatch):
    """The adaptive offload gate routes CRC to CPU when the measured
    host->device bandwidth is below tpu.transport.min.mb.s, and keeps
    the device path when it clears (values bit-identical either way)."""
    bufs = [CORPORA["semi"], CORPORA["random_1k"], b"", b"q"]
    want = [crc32c(b) for b in bufs]

    slow = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=100.0)
    slow.transport_mb_s = 2.0                     # a dev-tunnel reading
    import librdkafka_tpu.ops.tpu as tpu_mod
    monkeypatch.setattr(
        tpu_mod, "_crc32c_many_mxu",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("device CRC ran below the transport gate")))
    assert slow.crc32c_many(bufs) == want

    fast = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=100.0)
    fast.transport_mb_s = 10_000.0                # PCIe-class reading
    monkeypatch.setattr(tpu_mod, "_crc32c_many_mxu",
                        crc32c_jax.crc32c_many_mxu)
    assert fast.crc32c_many(bufs) == want
    fast.close()
    # gate disabled: offloads regardless of measured transport
    off = TpuCodecProvider(min_batches=1, warmup=False,
                           min_transport_mb_s=0)
    off.transport_mb_s = 2.0
    assert off.crc32c_many(bufs) == want
    off.close()
    slow.close()


# ------------------------------------------------------------------ crc32c --

def test_crc32c_many_bitexact():
    rng = np.random.default_rng(3)
    bufs = [b"", b"a", b"123456789", bytes(100)] + [
        rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
        for n in [1, 7, 8, 63, 64, 65, 1000, 4096, 65536, 100_001]]
    got = crc32c_jax.crc32c_many(bufs)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]


def test_crc32c_standard_vector():
    # rfc3720 / crc32c.c:388 check value
    assert int(crc32c_jax.crc32c_many([b"123456789"])[0]) == 0xE3069283


def test_crc32c_hw_sw_cross_check():
    """The SSE4.2 3-stream path (tk_crc32c, runtime-detected) must be
    bit-exact vs the software slice-by-8 fold (tk_crc32c_sw) across the
    lane-split thresholds: the 3-lane split engages at n >= 192, lane
    lengths are 8-byte aligned, and the tail folds into lane C — every
    boundary gets randomized coverage, with nonzero initial registers
    (the GF(2) zero-advance stitch must honor them)."""
    import ctypes

    L = cpu.lib()
    L.tk_crc32c_sw.restype = ctypes.c_uint32
    L.tk_crc32c_sw.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.c_uint32]
    rng = np.random.default_rng(42)
    sizes = (list(range(0, 32)) + [63, 64, 65, 127, 128, 129,
             190, 191, 192, 193, 200, 255, 256, 257, 383, 384, 385,
             575, 576, 577, 1000, 4095, 4096, 4097, 65535, 65536,
             65537, 1_000_003])
    for n in sizes:
        buf = rng.integers(0, 256, max(n, 1), dtype=np.uint8).tobytes()[:n]
        for init in (0, 1, 0xFFFFFFFF, int(rng.integers(0, 1 << 32))):
            hw = L.tk_crc32c(buf, n, init)
            sw = L.tk_crc32c_sw(buf, n, init)
            assert hw == sw, (n, hex(init), hex(hw), hex(sw))


def test_crc32c_mxu_bitexact():
    """The one-matmul MXU formulation (64KB blocks + host combine) must
    match the oracle on every size class: sub-block, exact block,
    multi-block with partial tail, empty."""
    rng = np.random.default_rng(5)
    bufs = [b"", b"a", b"123456789", bytes(100)] + [
        rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
        for n in [1, 63, 1000, 65535, 65536, 65537, 200_000]]
    got = crc32c_jax.crc32c_many_mxu(bufs)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]
    assert int(crc32c_jax.crc32c_many_mxu([b"123456789"])[0]) == 0xE3069283


def test_crc32c_mxu_pallas_bitexact():
    """The Pallas fused-bit-plane variant (interpret mode off-TPU)."""
    rng = np.random.default_rng(6)
    bufs = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
            for n in [9, 4096, 65536, 70_000]]
    got = crc32c_jax.crc32c_many_mxu(bufs, pallas=True)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]


# ------------------------------------------------------------------- lz4 ----

@pytest.mark.parametrize("name", IDS)
def test_lz4_block_bitexact(name):
    data = CORPORA[name][:65536]
    got, = lz4_jax.lz4_block_compress_many([data])
    assert got == cpu.lz4_block_compress(data)


def test_lz4_block_batch_mixed_sizes():
    rng = np.random.default_rng(11)
    blocks = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
              for n in [0, 1, 13, 100, 5000, 65536]]
    blocks += [b"z" * int(n) for n in [15, 300, 65536]]
    got = lz4_jax.lz4_block_compress_many(blocks)
    for g, b in zip(got, blocks):
        assert g == cpu.lz4_block_compress(b)


@pytest.mark.parametrize("name", IDS)
def test_lz4_frame_bitexact(tpu_provider, name):
    data = CORPORA[name]
    got, = tpu_provider._lz4f_compress_many([data])
    assert got == cpu.lz4_compress(data)
    assert cpu.lz4_decompress(got, len(data)) == data


def test_compress_many_batched(tpu_provider):
    bufs = [CORPORA["json_like"], CORPORA["over_64k"], b"tiny",
            CORPORA["random_100k"], CORPORA["near_64k"]]
    got = tpu_provider.compress_many("lz4", bufs)
    # the forced device encoder's contract is the DETERMINISTIC spec
    # (the CPU provider's default hot path is the fast parse — same
    # wire format, different bytes)
    want = cpu.lz4f_compress_many(bufs, deterministic=True)
    assert got == want
    # and both decode to the originals
    for g, b in zip(cpu.CpuCodecProvider().compress_many("lz4", bufs),
                    bufs):
        assert cpu.lz4_decompress(g, len(b)) == bytes(b)


@pytest.mark.parametrize("codec", ["gzip", "snappy", "zstd"])
def test_other_codecs_fall_back(tpu_provider, codec):
    if codec == "zstd":
        from conftest import require_zstd
        require_zstd()
    bufs = [CORPORA["json_like"]] * 4
    got = tpu_provider.compress_many(codec, bufs)
    assert tpu_provider.decompress_many(
        codec, got, [len(b) for b in bufs]) == bufs


def test_provider_crc_interface(tpu_provider):
    bufs = [CORPORA["semi"], CORPORA["random_1k"], b"", b"q"]
    assert tpu_provider.crc32c_many(bufs) == [crc32c(b) for b in bufs]


# ------------------------------------------------- async offload engine ----

def _cpu_fallback(bufs, poly):
    prov = cpu.CpuCodecProvider()
    return (prov.crc32c_many(bufs) if poly == "crc32c"
            else prov.crc32_many(bufs))


def test_engine_crc_bitexact():
    """The pipelined engine's CRC path (persistent staging buffers,
    async dispatch, bulk readback, host combine) must be bit-identical
    to the CPU provider for every size class and both polynomials —
    across enough submissions to cycle the staging ring."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine
    from librdkafka_tpu.utils.crc import crc32

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.0005,
                             min_batches=1, cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(7)
        bufs = [b"", b"a", b"123456789", bytes(100)] + [
            rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
            for n in [1, 63, 1000, 65535, 65536, 65537, 200_000]]
        # several rounds so every staging ring slot gets reused with
        # different contents (a stale-buffer bug would surface here)
        for round_ in range(4):
            batch = bufs[round_:] + bufs[:round_]
            got = eng.submit(batch, "crc32c", window=False).result(120)
            assert got.tolist() == [crc32c(b) for b in batch]
        got32 = eng.submit(bufs, "crc32", window=False).result(120)
        assert got32.tolist() == [crc32(b) for b in bufs]
    finally:
        eng.close()


def test_engine_fanin_aggregation_and_quorum_fallback():
    """Below-quorum windowed submissions either merge with concurrent
    jobs into one launch (cross-broker micro-batch aggregation) or, if
    the window expires alone, are served by the CPU fallback — bytes
    identical either way."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.002,
                             min_batches=8, cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(8)
        bufs = [rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
                for _ in range(4)]
        want = [crc32c(b) for b in bufs]
        # alone below quorum: window expires -> CPU fallback
        t = eng.submit(bufs[:2], "crc32c", window=True)
        assert t.result(60).tolist() == want[:2]
        assert eng.stats["cpu_fallback_jobs"] >= 1
        # two concurrent below-quorum submitters merge to meet quorum
        t1 = eng.submit(bufs, "crc32c", window=True)
        t2 = eng.submit(bufs, "crc32c", window=True)
        assert t1.result(60).tolist() == want
        assert t2.result(60).tolist() == want
    finally:
        eng.close()


def test_engine_submit_compute_codec_step():
    """models/codec_step.py driven through the engine's generic compute
    seam: same outputs as the direct step call, via one bulk readback."""
    from librdkafka_tpu.models.codec_step import (batched_codec_step,
                                                  example_inputs,
                                                  pipelined_codec_step)
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, min_batches=1,
                             cpu_fallback=_cpu_fallback)
    try:
        data, lens = example_inputs(1024, 4)
        submit = pipelined_codec_step(eng, 1024, 4)
        out, olen, crcs = submit(data, lens).result(300)
        w_out, w_olen, w_crcs = batched_codec_step(1024, 4)(data, lens)
        assert np.array_equal(out, np.asarray(w_out))
        assert np.array_equal(olen, np.asarray(w_olen))
        assert np.array_equal(crcs, np.asarray(w_crcs))
        # and the CRC lanes are oracle-exact
        assert [int(c) for c in crcs] == [
            crc32c(data[i].tobytes()) for i in range(4)]
    finally:
        eng.close()


def test_engine_host_compute_jobs():
    """submit_compute(host=True) runs a plain host fn on the dispatch
    thread and resolves the ticket with its raw return value (no jax
    readback) — the fetch decompress seam; a raising host fn fails its
    own ticket without killing the engine."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, min_batches=1,
                             cpu_fallback=_cpu_fallback)
    try:
        prov = cpu.CpuCodecProvider()
        payloads = [b"host-job-%d" % i * 40 for i in range(5)]
        comp = prov.compress_many("lz4", payloads)
        t = eng.submit_compute(prov.decompress_many, "lz4", comp,
                               [len(p) for p in payloads], host=True)
        assert t.result(120) == payloads

        def boom():
            raise ValueError("host job failed")

        with pytest.raises(ValueError):
            eng.submit_compute(boom, host=True).result(120)
        # the engine still serves CRC launches after a failed host job
        got = eng.submit([b"123456789"], "crc32c", window=False)
        assert got.result(120).tolist() == [0xE3069283]
    finally:
        eng.close()


def test_engine_close_with_inflight_resolves_every_ticket():
    """close() must drain or fail outstanding tickets deterministically
    (ISSUE 2 satellite): no Ticket.result() may hang forever after
    close() returns — queued jobs drain on a clean exit, and jobs a
    wedged dispatch thread cannot reach are FAILED."""
    import threading
    import time as _time

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    # clean close: queued work drains (results, not errors)
    eng = AsyncOffloadEngine(depth=2, min_batches=1,
                             cpu_fallback=_cpu_fallback)
    tickets = [eng.submit_compute(lambda i=i: (_time.sleep(0.02), i)[1],
                                  host=True) for i in range(8)]
    eng.close()
    for i, t in enumerate(tickets):
        assert t.done(), "ticket left unresolved after close()"
        assert t.result(0) == i
    with pytest.raises(RuntimeError):     # post-close submits refused
        eng.submit([b"x"], "crc32c", window=False)

    # wedged dispatch thread: close(timeout) expires while a host job
    # holds the thread — the job queued BEHIND it must be failed, not
    # left hanging its waiter; the in-flight job itself still completes
    eng2 = AsyncOffloadEngine(depth=1, min_batches=1,
                              cpu_fallback=_cpu_fallback)
    started = threading.Event()

    def wedge():
        started.set()
        _time.sleep(0.8)
        return "wedge-done"

    t_wedge = eng2.submit_compute(wedge, host=True)
    assert started.wait(10)
    t_stuck = eng2.submit_compute(lambda: 2, host=True)
    eng2.close(timeout=0.1)
    with pytest.raises(RuntimeError):
        t_stuck.result(5)
    assert t_wedge.result(5) == "wedge-done"
    eng2._thread.join(5)
    assert not eng2._thread.is_alive()


# ------------------------------------------------ adaptive governor --------

def test_engine_warmup_gate_routes_cpu_then_device():
    """ISSUE 3 tentpole #1: with background warmup on, a launch whose
    bucket kernel is not yet compiled is served by the CPU provider
    (bit-exact, counted as warmup_miss_jobs) instead of stalling the
    dispatch thread behind the XLA compile; once the warmup thread
    readies the bucket, the same shape rides a device launch."""
    import time as _time

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=True,
                             warmup=True, cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(21)
        bufs = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                for n in (5, 3000, 70000)]
        want = [crc32c(b) for b in bufs]
        t0 = _time.perf_counter()
        t = eng.submit(bufs, "crc32c", window=False)
        assert t.result(60).tolist() == want
        first_latency = _time.perf_counter() - t0
        # either the CPU gate served it (the common case — the sweep
        # can't have compiled the bucket this fast) or warmup won an
        # extreme race; both are bit-exact, but neither may stall
        assert (eng.stats["warmup_miss_jobs"] >= 1
                or eng.stats["launches"] >= 1)
        # the bucket the miss requested compiles with priority
        assert eng.warm_wait(64, "crc32c", 180), \
            "warmup never compiled the missed bucket"
        before = eng.stats["launches"]
        assert eng.submit(bufs, "crc32c",
                          window=False).result(60).tolist() == want
        assert eng.stats["launches"] == before + 1, \
            "warmed bucket did not ride a device launch"
        assert first_latency < 30, "first submission stalled on compile"
    finally:
        eng.close()
    # deterministic shutdown covers the warmup thread too
    assert eng._warmup_thread is not None
    assert not eng._warmup_thread.is_alive()


def test_engine_fused_multipoly_single_launch():
    """ISSUE 3 tentpole #4: crc32c and legacy-crc32 jobs popped
    together fuse into ONE padded launch with per-row Q-matrix/term
    selection — half the launches of the per-poly split — and each
    row's checksum is bit-exact for ITS polynomial."""
    import zlib

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.1, min_batches=4,
                             governor=True, warmup=False,
                             cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(22)
        bufs_c = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                  for n in (900, 70000)]
        bufs_l = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                  for n in (4096, 17)]
        t1 = eng.submit(bufs_c, "crc32c", window=True)
        t2 = eng.submit(bufs_l, "crc32", window=True)
        assert t1.result(300).tolist() == [crc32c(b) for b in bufs_c]
        assert t2.result(300).tolist() == [
            zlib.crc32(b) & 0xFFFFFFFF for b in bufs_l]
        assert eng.stats["fused_launches"] == 1, eng.stats
        assert eng.stats["launches"] == 1, eng.stats
    finally:
        eng.close()


def test_engine_adaptive_fanin_sheds_window_at_low_rate():
    """ISSUE 3 tentpole #3: the fan-in wait is sized from the
    submission inter-arrival EWMA with tpu.pipeline.fanin.us as the
    cap — once the governor observes a mean inter-arrival beyond the
    cap (nothing will merge), below-quorum jobs dispatch immediately
    instead of paying the window latency."""
    import time as _time

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.3, min_batches=8,
                             governor=True, warmup=False,
                             cpu_fallback=_cpu_fallback)
    try:
        bufs = [b"low-rate" * 64]
        want = [crc32c(bufs[0])]
        last = None
        for _ in range(3):
            t0 = _time.perf_counter()
            t = eng.submit(bufs, "crc32c", window=True)
            assert t.result(30).tolist() == want
            last = _time.perf_counter() - t0
            _time.sleep(0.45)        # inter-arrival >> the 0.3s cap
        assert eng.stats["fanin_skips"] >= 1, eng.stats
        assert last < 0.15, f"still paying the window: {last:.3f}s"
    finally:
        eng.close()


def test_engine_cost_model_routes_and_explores():
    """ISSUE 3 tentpole #2: with both model sides measured, at-quorum
    groups go to the predicted-faster side (min_batches stays a hard
    floor), and periodic exploration keeps the unpicked side's
    estimate fresh — every route bit-exact."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0, min_batches=2,
                             governor=True, warmup=False,
                             cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(23)
        bufs = [rng.integers(0, 256, 2048, dtype=np.uint8).tobytes()
                for _ in range(2)]
        want = [crc32c(b) for b in bufs]
        # seed the device estimate (unknown estimates prefer device,
        # exactly the static policy)...
        assert eng.submit(bufs, "crc32c",
                          window=False).result(120).tolist() == want
        # ...and the CPU estimate via a below-floor group
        assert eng.submit(bufs[:1], "crc32c",
                          window=False).result(60).tolist() == want[:1]
        assert eng.stats["cpu_fallback_jobs"] >= 1
        g = eng.governor
        # model peeks via the locked snapshot (the lockset sweep
        # convicts lock-free EWMA reads against the dispatch thread)
        snap0 = g.snapshot()
        assert snap0["dev_launch_ms"] and \
            snap0["cpu_ns_per_byte"] is not None
        # the jax-CPU "device" launch costs ms; the native CPU provider
        # runs 2KB in µs — the model must route these groups to CPU now
        routed = 0
        for _ in range(8):
            assert eng.submit(bufs, "crc32c",
                              window=False).result(60).tolist() == want
            routed = eng.stats["routed_cpu_jobs"]
        assert routed >= 1, eng.stats
        # exploration provably flips some decisions over enough rounds
        for _ in range(2 * g.EXPLORE_EVERY):
            assert eng.submit(bufs, "crc32c",
                              window=False).result(60).tolist() == want
        assert eng.stats["explore_routes"] >= 1, eng.stats
        snap = eng.governor_snapshot()
        assert snap["cpu_ns_per_byte"] is not None
        assert snap["dev_launch_ms"]
    finally:
        eng.close()


def test_engine_close_races_warmup_and_fanin_window():
    """ISSUE 3 satellite: close() during an in-flight warmup compile
    joins the warmup thread deterministically (the conftest leak
    fixture watches it by name), and close() racing an open fan-in
    window interrupts the wait — the parked below-quorum job resolves
    instead of sitting out the window."""
    import time as _time

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    # close immediately after start: the warmup thread is almost
    # certainly inside its first compile — close() must still drain
    eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=True,
                             warmup=True, cpu_fallback=_cpu_fallback)
    t = eng.submit([b"racing-warmup"], "crc32c", window=False)
    eng.close()
    assert t.result(5).tolist() == [crc32c(b"racing-warmup")]
    assert not eng._warmup_thread.is_alive()
    assert not eng._thread.is_alive()

    # fan-in window race: a 2s window must not delay close()
    eng2 = AsyncOffloadEngine(depth=2, fanin_window_s=2.0,
                              min_batches=64, governor=False,
                              warmup=False, cpu_fallback=_cpu_fallback)
    t = eng2.submit([b"racing-fanin"], "crc32c", window=True)
    _time.sleep(0.05)            # let the dispatch thread park
    t0 = _time.monotonic()
    eng2.close()
    assert _time.monotonic() - t0 < 1.5, "close() sat out the window"
    assert t.result(5).tolist() == [crc32c(b"racing-fanin")]
    assert not eng2._thread.is_alive()


# ------------------------------------------- mesh-sharded dispatch lanes --

def test_engine_mesh_bitexact_across_device_counts():
    """ISSUE 6 tentpole: the same CRC workload must produce identical
    checksums with tpu.mesh.devices at 1 (the pre-mesh single lane), 2,
    and 0 (all 8 virtual devices) — across staging-ring reuse rounds
    and both polynomials.  Sharding only moves WHERE each block's CRC
    runs, never the result."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine
    from librdkafka_tpu.utils.crc import crc32

    rng = np.random.default_rng(26)
    bufs = [b"", b"a", b"123456789", bytes(100)] + [
        rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
        for n in [1, 63, 1000, 65535, 65536, 65537, 200_000]]
    want_c = [crc32c(b) for b in bufs]
    want_l = [crc32(b) for b in bufs]
    for nd in (1, 2, 0):
        eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                                 warmup=False, mesh_devices=nd,
                                 cpu_fallback=_cpu_fallback)
        try:
            for round_ in range(3):
                batch = bufs[round_:] + bufs[:round_]
                got = eng.submit(batch, "crc32c",
                                 window=False).result(300)
                assert got.tolist() == want_c[round_:] + want_c[:round_]
            got32 = eng.submit(bufs, "crc32", window=False).result(300)
            assert got32.tolist() == want_l
            lanes = eng._lanes
            assert len(lanes) == (nd if nd else 8)
            if nd != 1:
                # whole-to-one-lane least-loaded pick spreads cold
                # lanes first: 4 sequential launches land on >1 chip
                assert sum(1 for ln in lanes if ln.launches) >= 2, \
                    [(ln.dev_id, ln.launches) for ln in lanes]
        finally:
            eng.close()


def test_engine_mesh_sharded_launch_bitexact_and_counted():
    """A group spanning a mesh multiple (>= SHARD_MIN_ROWS blocks per
    device) splits across every chip via shard_map: checksums stay
    oracle-exact, the launch counts as sharded, every lane records it,
    and the per-device stats rows carry the split."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=False,
                             warmup=False, mesh_devices=2,
                             cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(27)
        # 17 full 64KB blocks >= 2 devices * SHARD_MIN_ROWS(8)
        bufs = [rng.integers(0, 256, 65536, dtype=np.uint8).tobytes()
                for _ in range(16)] + [b"tail-block" * 7]
        want = [crc32c(b) for b in bufs]
        got = eng.submit(bufs, "crc32c", window=False).result(300)
        assert got.tolist() == want
        assert eng.stats["sharded_launches"] >= 1, eng.stats
        rows = eng.devices_snapshot()
        assert len(rows) == 2
        for row in rows:
            # a sharded launch records on every participating lane
            assert row["launches"] >= 1, rows
            assert row["blocks"] >= 1, rows
        # the shard pseudo-lane drained (nothing left in flight)
        assert eng._shard_lane is not None
        assert not eng._shard_lane.inflight
    finally:
        eng.close()
    # engine close released the compiled shard_map steps (the mesh
    # module's close-time hook; the conftest fixture asserts this too)
    from librdkafka_tpu.parallel.mesh import step_cache_count
    assert step_cache_count() == 0


def test_engine_mesh_governor_explore_and_fanin_skip_bitexact():
    """ISSUE 6 satellite: the governor's explore and adaptive fan-in
    paths stay bit-exact when dispatch is mesh-sharded — exploration
    flips routes with per-(device, bucket) EWMAs live, and the low-rate
    fan-in shed dispatches below-quorum jobs immediately."""
    import time as _time

    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, fanin_window_s=0.3, min_batches=2,
                             governor=True, warmup=False,
                             mesh_devices=0, cpu_fallback=_cpu_fallback)
    try:
        rng = np.random.default_rng(28)
        bufs = [rng.integers(0, 256, 2048, dtype=np.uint8).tobytes()
                for _ in range(2)]
        want = [crc32c(b) for b in bufs]
        # seed the device side (multiple lanes get measured: cold
        # chips sort first in the least-loaded pick)...
        for _ in range(4):
            assert eng.submit(bufs, "crc32c",
                              window=False).result(300).tolist() == want
        # ...and the CPU side via a below-floor group
        assert eng.submit(bufs[:1], "crc32c",
                          window=False).result(60).tolist() == want[:1]
        g = eng.governor
        snap0 = g.snapshot()
        assert snap0["dev_launch_ms"] and \
            snap0["cpu_ns_per_byte"] is not None
        # per-device EWMAs: >1 device measured (locked per-device view)
        assert len([d for d in range(8)
                    if g.device_launch_ms(d)]) >= 2
        # exploration provably flips some decisions over enough rounds
        for _ in range(2 * g.EXPLORE_EVERY):
            assert eng.submit(bufs, "crc32c",
                              window=False).result(60).tolist() == want
        assert eng.stats["explore_routes"] >= 1, eng.stats
        # the stats blob's governor view is the best-device collapse
        snap = eng.governor_snapshot()
        assert snap["dev_launch_ms"]
        # fan-in skip at low rate: below-quorum windowed jobs dispatch
        # immediately once the inter-arrival EWMA exceeds the cap
        last = None
        for _ in range(3):
            t0 = _time.perf_counter()
            t = eng.submit(bufs[:1], "crc32c", window=True)
            assert t.result(60).tolist() == want[:1]
            last = _time.perf_counter() - t0
            _time.sleep(0.45)
        assert eng.stats["fanin_skips"] >= 1, eng.stats
        assert last < 0.15, f"still paying the window: {last:.3f}s"
    finally:
        eng.close()


def test_engine_close_racing_warmup_on_device_k():
    """ISSUE 6 satellite: close() racing the warmup sweep while it
    compiles on a NON-default device must still drain deterministically
    — per-lane in-flight launches resolve, both threads join, and the
    compiled shard-step cache is released."""
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine

    eng = AsyncOffloadEngine(depth=2, min_batches=1, governor=True,
                             warmup=True, mesh_devices=0,
                             cpu_fallback=_cpu_fallback)
    try:
        # jump a device-7 bucket to the front of the sweep and wait for
        # it: the race now provably closes mid-sweep on device k
        eng._request_warm(("kernel", 64, "crc32c", 7))
        assert eng.warm_wait(64, "crc32c", timeout=300, device=7), \
            "warmup never compiled the device-7 bucket"
        t = eng.submit([b"racing-mesh-warmup" * 200], "crc32c",
                       window=False)
    finally:
        eng.close()
    assert t.result(5).tolist() == [crc32c(b"racing-mesh-warmup" * 200)]
    assert not eng._warmup_thread.is_alive()
    assert not eng._thread.is_alive()
    for ln in eng._all_lanes():
        assert not ln.inflight, "lane left launches in flight"
    from librdkafka_tpu.parallel.mesh import step_cache_count
    assert step_cache_count() == 0


def _wire_build(provider, ticketed: bool) -> bytes:
    """Deterministic multi-batch msgset build (writer-level, so wire
    bytes are timing-independent): mixed batch sizes, one spanning
    enough 64KB blocks to take the sharded route on a 2-lane mesh."""
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record

    now = 1_700_000_000_000
    rng = np.random.default_rng(29)
    batches = [
        [Record(key=b"k%d" % i, value=(b"mesh-%d " % i) * 30,
                timestamp=now + i) for i in range(16)],
        [Record(key=None, value=rng.integers(
            0, 256, 70_000, dtype=np.uint8).tobytes(),
            timestamp=now) for _ in range(18)],   # ~18+ 64KB blocks
        [Record(key=b"solo", value=b"x", timestamp=now)],
    ]
    wires = []
    for msgs in batches:
        w = MsgsetWriterV2(codec="lz4")
        w.build(msgs, now)
        blob = provider.compress_many("lz4", [w.records_bytes])[0]
        if len(blob) >= len(w.records_bytes):
            blob, w.codec = None, None
        region = w.assemble(blob)
        if ticketed:
            t = provider.crc32c_submit([region])
            assert t is not None
            crc = int(t.result(300)[0])
        else:
            crc = int(provider.crc32c_many([region])[0])
        wires.append(w.patch_crc(crc))
    return b"".join(wires)


def test_mesh_produce_wire_bitexact_across_device_counts():
    """ISSUE 6 satellite: the same produce workload assembles
    bit-identical msgset wire bytes (CRCs included) with
    tpu.mesh.devices at 1, 2, and all — every route vs the CPU
    provider's build."""
    want = _wire_build(cpu.CpuCodecProvider(), ticketed=False)
    for nd in (1, 2, 0):
        prov = TpuCodecProvider(min_batches=1, warmup=False,
                                min_transport_mb_s=0, mesh_devices=nd)
        try:
            assert _wire_build(prov, ticketed=True) == want, \
                f"wire bytes diverged at mesh_devices={nd}"
        finally:
            prov.close()


def test_provider_pipelined_crc_bitexact(tpu_provider):
    """TpuCodecProvider's async submit seam resolves to the same values
    as the synchronous interface and the oracle."""
    bufs = [CORPORA["semi"], CORPORA["random_1k"], b"", b"q",
            CORPORA["near_64k"], CORPORA["over_64k"]]
    want = [crc32c(b) for b in bufs]
    ticket = tpu_provider.crc32c_submit(bufs)
    assert ticket is not None
    assert ticket.result(120).tolist() == want
    assert tpu_provider.crc32c_many(bufs) == want


def test_provider_submit_declines_below_gate():
    """A closed transport gate returns None from crc32c_submit so the
    caller stays on the synchronous CPU path (no engine thread spun)."""
    prov = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=100.0)
    prov.transport_mb_s = 2.0
    assert prov.crc32c_submit([b"x" * 100]) is None
    assert prov._engine is None
    # pipeline disabled: sync route only
    off = TpuCodecProvider(min_batches=1, warmup=False,
                           min_transport_mb_s=0, pipeline_depth=0)
    assert off.crc32c_submit([b"x" * 100]) is None


class _SlowTicket:
    def __init__(self, values, delay):
        import threading as _t
        self._ev = _t.Event()
        self._values = values
        _t.Timer(delay, self._ev.set).start()

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        self._ev.wait(timeout)
        return self._values


class _SlowProvider:
    """Fake device provider: every CRC batch resolves after ``delay``
    seconds, asynchronously — models a device round-trip without jax."""

    def __init__(self, delay=0.2):
        self.delay = delay
        self.outstanding = 0
        self.max_outstanding = 0
        self._cpu = cpu.CpuCodecProvider()
        import threading as _t
        self._lock = _t.Lock()

    def compress_many(self, codec, bufs, level=-1):
        return self._cpu.compress_many(codec, bufs, level)

    def crc32c_submit(self, regions):
        vals = np.asarray(self._cpu.crc32c_many([bytes(r) for r in regions]),
                          dtype=np.uint32)
        with self._lock:
            self.outstanding += 1
            self.max_outstanding = max(self.max_outstanding,
                                       self.outstanding)
        t = _SlowTicket(vals, self.delay)

        def _done():
            with self._lock:
                self.outstanding -= 1
        import threading as _t
        _t.Timer(self.delay, _done).start()
        return t

    def crc32c_many(self, bufs):
        import time as _t
        _t.sleep(self.delay)
        return self._cpu.crc32c_many(bufs)


def test_codec_worker_overlaps_slow_provider():
    """The codec worker must NOT block for the device round-trip: with a
    fake provider whose CRC resolves 200 ms after submission, N jobs
    must overlap (>=2 tickets concurrently in flight) and finish in far
    less than N * delay — the r5 loop serialized them."""
    import threading
    import time as _time
    from types import SimpleNamespace

    from librdkafka_tpu.client.broker import CodecWorker
    from librdkafka_tpu.client.msg import Message
    from librdkafka_tpu.client.queue import OpQueue
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2

    delay = 0.2
    prov = _SlowProvider(delay)
    rk = SimpleNamespace(
        interceptors=None,
        codec_provider=prov,
        codec_pipeline_depth=4,
        topic_conf_for=lambda t: {"compression.level": -1})
    worker = CodecWorker(rk)
    broker = SimpleNamespace(ops=OpQueue("fake-broker-ops"))
    tp = SimpleNamespace(topic="t", partition=0)

    def job(i):
        msgs = []
        for k in range(4):
            m = Message("t", value=b"v%d-%d" % (i, k) * 50)
            msgs.append(m)
        w = MsgsetWriterV2(codec=None)
        w.build(msgs, 1700000000000 + i)
        return [(tp, msgs, w)]

    njobs = 4
    t0 = _time.perf_counter()
    for i in range(njobs):
        worker.submit(broker, job(i), _time.monotonic(), 0)
    done = []
    deadline = _time.monotonic() + 10
    while len(done) < njobs and _time.monotonic() < deadline:
        op = broker.ops.pop(0.2)
        if op is not None:
            done.append(op)
    elapsed = _time.perf_counter() - t0
    worker.stop()
    worker.join(5)
    assert len(done) == njobs
    # overlap proof: >=2 device round-trips in flight at once, and the
    # wall clock beats strict serialization (njobs * delay = 0.8s) by a
    # wide margin
    assert prov.max_outstanding >= 2, prov.max_outstanding
    assert worker.inflight_hwm >= 2, worker.inflight_hwm
    assert elapsed < njobs * delay * 0.8, elapsed
    # results arrive in submission order with correct wire bytes
    for i, op in enumerate(done):
        kind, results, _ts, _pe = op.payload
        assert kind == "codec_done"
        (tp_r, msgs_r, wire, exc) = results[0]
        assert exc is None
        assert wire is not None and len(wire) > 61


# ------------------------------------------------------------- e2e produce --

def _produce_consume(backend: str, n: int = 300):
    from librdkafka_tpu import Producer, Consumer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": backend,
                  "tpu.launch.min.batches": 1,
                  "compression.codec": "lz4", "linger.ms": 5,
                  "batch.num.messages": 100})
    vals = [("payload-%05d" % i).encode() * 8 for i in range(n)]
    for i, v in enumerate(vals):
        p.produce("tpu-e2e", value=v, key=b"k%d" % i)
    # generous timeout: first device launches pay one-time jit compiles
    assert p.flush(120.0) == 0
    cluster = p._rk.mock_cluster
    # read raw stored wire blobs before shutting the producer down
    blobs = [bytes(blob)
             for part in range(len(cluster.topics["tpu-e2e"]))
             for _base, blob in cluster.partition("tpu-e2e", part).log]

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "g-tpu-e2e", "auto.offset.reset": "earliest"})
    c.subscribe(["tpu-e2e"])
    got = []
    import time
    deadline = time.time() + 15
    while len(got) < n and time.time() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.value)
    c.close()
    p.close()
    return blobs, sorted(got)


def test_e2e_tpu_backend_roundtrip_and_wire_equal():
    blobs_tpu, got_tpu = _produce_consume("tpu")
    blobs_cpu, got_cpu = _produce_consume("cpu")
    want = sorted(("payload-%05d" % i).encode() * 8 for i in range(300))
    assert got_tpu == want
    assert got_cpu == want
    # batching boundaries aren't guaranteed identical across runs (timing-
    # dependent), but every stored blob must be a CRC-valid v2 batch whose
    # lz4 frame decodes; compare the decoded record payload streams.
    from librdkafka_tpu.protocol import proto
    from librdkafka_tpu.protocol.msgset import (iter_batches,
                                                parse_records_v2,
                                                verify_crc_v2)

    def payloads(blobs):
        out = []
        for b in blobs:
            for info, payload, full in iter_batches(b):
                assert verify_crc_v2(info, full)
                if info.codec:
                    assert info.codec == "lz4"
                    payload = cpu.lz4_decompress(payload)
                out.extend(r.value for r in parse_records_v2(info, payload))
        return sorted(out)

    assert payloads(blobs_tpu) == payloads(blobs_cpu) == want


# --------------------------------------------------------- transactions --

def test_txn_batch_wire_bitexact_cpu_vs_ticketed_tpu():
    """ISSUE 4: a transactional RecordBatch (attr bit + pid + epoch +
    base sequence) must come out bit-identical whether its compress/CRC
    phases run on the CPU provider or ride the TPU provider's ticketed
    async seam — same writer, same wire.  Production routing (no
    lz4_force): lz4 compresses on the shared native path either way;
    the CRC is what crosses the offload seam."""
    from librdkafka_tpu.protocol import proto
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record

    tpu_provider = TpuCodecProvider(min_batches=1, warmup=False,
                                    min_transport_mb_s=0)
    now = 1_700_000_000_000
    msgs = [Record(key=b"k%d" % i, value=(b"txn-%d " % i) * 30,
                   timestamp=now + i) for i in range(16)]

    def build(provider, ticketed: bool) -> bytes:
        w = MsgsetWriterV2(producer_id=7, producer_epoch=3,
                           base_sequence=0, transactional=True,
                           codec="lz4")
        w.build(msgs, now)
        blob = provider.compress_many("lz4", [w.records_bytes])[0]
        if len(blob) >= len(w.records_bytes):
            blob, w.codec = None, None
        region = w.assemble(blob)
        if ticketed:
            t = provider.crc32c_submit([region])
            assert t is not None
            crc = int(t.result(120)[0])
        else:
            crc = int(provider.crc32c_many([region])[0])
        return w.patch_crc(crc)

    try:
        want = build(cpu.CpuCodecProvider(), ticketed=False)
        got = build(tpu_provider, ticketed=True)
    finally:
        tpu_provider.close()
    assert got == want
    attrs = int.from_bytes(
        want[proto.V2_OF_Attributes:proto.V2_OF_Attributes + 2], "big")
    assert attrs & proto.ATTR_TRANSACTIONAL
    pid = int.from_bytes(
        want[proto.V2_OF_ProducerId:proto.V2_OF_ProducerId + 8], "big")
    assert pid == 7


def test_txn_e2e_wire_equal_cpu_vs_tpu_backend():
    """End-to-end: the same committed transaction produced through the
    cpu and tpu backends stores CRC-valid transactional batches whose
    decoded record streams are identical, each followed by a COMMIT
    control record."""
    from librdkafka_tpu import Producer
    from librdkafka_tpu.protocol.msgset import (iter_batches,
                                                parse_records_v2,
                                                verify_crc_v2)

    def produce(backend: str):
        p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                      "transactional.id": f"tx-wire-{backend}",
                      "compression.backend": backend,
                      "tpu.launch.min.batches": 1,
                      "tpu.transport.min.mb.s": 0,
                      "compression.codec": "lz4", "linger.ms": 5,
                      "batch.num.messages": 100})
        try:
            p.init_transactions(60)
            p.begin_transaction()
            for i in range(200):
                p.produce("txw", value=("txn-%05d" % i).encode() * 8,
                          partition=0)
            p.commit_transaction(120)
            part = p._rk.mock_cluster.partition("txw", 0)
            return [bytes(b) for _base, b in part.log]
        finally:
            p.close()

    def decode(blobs):
        data, markers = [], 0
        for b in blobs:
            for info, payload, full in iter_batches(b):
                assert verify_crc_v2(info, full)
                assert info.is_transactional
                if info.is_control:
                    markers += 1
                    continue
                if info.codec:
                    payload = cpu.lz4_decompress(payload)
                data.extend(r.value for r in parse_records_v2(info, payload))
        return sorted(data), markers

    data_cpu, markers_cpu = decode(produce("cpu"))
    data_tpu, markers_tpu = decode(produce("tpu"))
    want = sorted(("txn-%05d" % i).encode() * 8 for i in range(200))
    assert data_cpu == data_tpu == want
    assert markers_cpu == markers_tpu == 1


def test_txn_abort_with_inflight_codec_tickets_drains():
    """Abort racing the codec pipeline: batches whose compress/CRC
    tickets are still in flight on the offload engine must fail-or-
    drain deterministically — the abort completes, the dispatch thread
    never wedges (conftest's engine-leak fixture enforces the clean
    close), and the producer remains usable for the next txn."""
    from librdkafka_tpu import Producer
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "transactional.id": "tx-drain",
                  "compression.backend": "tpu",
                  "tpu.launch.min.batches": 1,
                  "tpu.transport.min.mb.s": 0,
                  "compression.codec": "lz4", "linger.ms": 1,
                  "batch.num.messages": 50})
    try:
        p.init_transactions(60)
        p.begin_transaction()
        for i in range(500):
            p.produce("txd", value=(b"v%d " % i) * 50, partition=0)
        # no flush: batches are mid-pipeline when the abort lands
        p.abort_transaction(180)
        assert p.rk.txnmgr.state == "READY"
        p.begin_transaction()
        p.produce("txd", value=b"after-abort", partition=0)
        p.commit_transaction(60)
        part = p._rk.mock_cluster.partition("txd", 0)
        # whatever drained before the abort is capped by an ABORT
        # marker; the follow-up txn ends with data + COMMIT marker
        from librdkafka_tpu.protocol.msgset import read_batch_header
        from librdkafka_tpu.utils.buf import Slice
        infos = [read_batch_header(Slice(bytes(b)))
                 for _base, b in part.log]
        assert infos, "follow-up txn produced nothing"
        assert infos[-1].is_control        # COMMIT marker tail
        assert all(i.is_transactional for i in infos)
    finally:
        p.close()
