"""TPU codec provider equivalence suite (the north-star bit-exactness
harness, SURVEY.md §7 stage 5): device-path lz4 frames and CRC32C must be
byte/bit-identical to the CPU provider, which in turn is oracle-validated
against real liblz4 (test_0017).  Also runs the producer end-to-end with
``compression.backend=tpu`` against the mock cluster and checks the stored
wire bytes equal the CPU backend's.
"""
import os

import numpy as np
import pytest

from librdkafka_tpu.ops import cpu
from librdkafka_tpu.ops.tpu import TpuCodecProvider
from librdkafka_tpu.ops import crc32c_jax, lz4_jax
from librdkafka_tpu.utils.crc import crc32c

from test_0017_codecs import CORPORA, IDS


@pytest.fixture(scope="module")
def tpu_provider():
    # lz4_force=True: this suite exists to prove the DEVICE lz4 encoder
    # is bit-exact.  Production routing (tpu.lz4.force=false, default)
    # keeps lz4 on the native CPU path — see test_lz4_routes_to_cpu.
    # min_transport_mb_s=0: the gate must not silently route these
    # equivalence tests to the CPU provider on slow transport.
    return TpuCodecProvider(min_batches=1, lz4_force=True,
                            min_transport_mb_s=0)


def test_lz4_routes_to_cpu_by_default(monkeypatch):
    """backend=tpu must never be slower than cpu: without tpu.lz4.force
    the provider compresses lz4 on the native CPU path (identical
    bytes) and only CRC32C rides the MXU (PERF.md §3 conclusion)."""
    prov = TpuCodecProvider(min_batches=1, warmup=False)

    def boom(bufs):
        raise AssertionError("device lz4 ran without tpu.lz4.force")

    monkeypatch.setattr(prov, "_lz4f_compress_many", boom)
    bufs = [CORPORA["json_like"], CORPORA["near_64k"], b"tiny"]
    assert (prov.compress_many("lz4", bufs)
            == cpu.CpuCodecProvider().compress_many("lz4", bufs))
    # conf plumbing: tpu.lz4.force reaches the provider
    from librdkafka_tpu.client.conf import Conf
    c = Conf()
    c.update({"tpu.lz4.force": True})
    assert c.get("tpu.lz4.force") is True
    assert TpuCodecProvider(min_batches=1, warmup=False,
                            lz4_force=c.get("tpu.lz4.force")).lz4_force


def test_crc_transport_gate(monkeypatch):
    """The adaptive offload gate routes CRC to CPU when the measured
    host->device bandwidth is below tpu.transport.min.mb.s, and keeps
    the device path when it clears (values bit-identical either way)."""
    bufs = [CORPORA["semi"], CORPORA["random_1k"], b"", b"q"]
    want = [crc32c(b) for b in bufs]

    slow = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=100.0)
    slow.transport_mb_s = 2.0                     # a dev-tunnel reading
    import librdkafka_tpu.ops.tpu as tpu_mod
    monkeypatch.setattr(
        tpu_mod, "_crc32c_many_mxu",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("device CRC ran below the transport gate")))
    assert slow.crc32c_many(bufs) == want

    fast = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=100.0)
    fast.transport_mb_s = 10_000.0                # PCIe-class reading
    monkeypatch.setattr(tpu_mod, "_crc32c_many_mxu",
                        crc32c_jax.crc32c_many_mxu)
    assert fast.crc32c_many(bufs) == want
    # gate disabled: offloads regardless of measured transport
    off = TpuCodecProvider(min_batches=1, warmup=False,
                           min_transport_mb_s=0)
    off.transport_mb_s = 2.0
    assert off.crc32c_many(bufs) == want


# ------------------------------------------------------------------ crc32c --

def test_crc32c_many_bitexact():
    rng = np.random.default_rng(3)
    bufs = [b"", b"a", b"123456789", bytes(100)] + [
        rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
        for n in [1, 7, 8, 63, 64, 65, 1000, 4096, 65536, 100_001]]
    got = crc32c_jax.crc32c_many(bufs)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]


def test_crc32c_standard_vector():
    # rfc3720 / crc32c.c:388 check value
    assert int(crc32c_jax.crc32c_many([b"123456789"])[0]) == 0xE3069283


def test_crc32c_hw_sw_cross_check():
    """The SSE4.2 3-stream path (tk_crc32c, runtime-detected) must be
    bit-exact vs the software slice-by-8 fold (tk_crc32c_sw) across the
    lane-split thresholds: the 3-lane split engages at n >= 192, lane
    lengths are 8-byte aligned, and the tail folds into lane C — every
    boundary gets randomized coverage, with nonzero initial registers
    (the GF(2) zero-advance stitch must honor them)."""
    import ctypes

    L = cpu.lib()
    L.tk_crc32c_sw.restype = ctypes.c_uint32
    L.tk_crc32c_sw.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.c_uint32]
    rng = np.random.default_rng(42)
    sizes = (list(range(0, 32)) + [63, 64, 65, 127, 128, 129,
             190, 191, 192, 193, 200, 255, 256, 257, 383, 384, 385,
             575, 576, 577, 1000, 4095, 4096, 4097, 65535, 65536,
             65537, 1_000_003])
    for n in sizes:
        buf = rng.integers(0, 256, max(n, 1), dtype=np.uint8).tobytes()[:n]
        for init in (0, 1, 0xFFFFFFFF, int(rng.integers(0, 1 << 32))):
            hw = L.tk_crc32c(buf, n, init)
            sw = L.tk_crc32c_sw(buf, n, init)
            assert hw == sw, (n, hex(init), hex(hw), hex(sw))


def test_crc32c_mxu_bitexact():
    """The one-matmul MXU formulation (64KB blocks + host combine) must
    match the oracle on every size class: sub-block, exact block,
    multi-block with partial tail, empty."""
    rng = np.random.default_rng(5)
    bufs = [b"", b"a", b"123456789", bytes(100)] + [
        rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
        for n in [1, 63, 1000, 65535, 65536, 65537, 200_000]]
    got = crc32c_jax.crc32c_many_mxu(bufs)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]
    assert int(crc32c_jax.crc32c_many_mxu([b"123456789"])[0]) == 0xE3069283


def test_crc32c_mxu_pallas_bitexact():
    """The Pallas fused-bit-plane variant (interpret mode off-TPU)."""
    rng = np.random.default_rng(6)
    bufs = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
            for n in [9, 4096, 65536, 70_000]]
    got = crc32c_jax.crc32c_many_mxu(bufs, pallas=True)
    assert [int(x) for x in got] == [crc32c(b) for b in bufs]


# ------------------------------------------------------------------- lz4 ----

@pytest.mark.parametrize("name", IDS)
def test_lz4_block_bitexact(name):
    data = CORPORA[name][:65536]
    got, = lz4_jax.lz4_block_compress_many([data])
    assert got == cpu.lz4_block_compress(data)


def test_lz4_block_batch_mixed_sizes():
    rng = np.random.default_rng(11)
    blocks = [rng.integers(0, 256, int(n), dtype=np.uint8).tobytes()
              for n in [0, 1, 13, 100, 5000, 65536]]
    blocks += [b"z" * int(n) for n in [15, 300, 65536]]
    got = lz4_jax.lz4_block_compress_many(blocks)
    for g, b in zip(got, blocks):
        assert g == cpu.lz4_block_compress(b)


@pytest.mark.parametrize("name", IDS)
def test_lz4_frame_bitexact(tpu_provider, name):
    data = CORPORA[name]
    got, = tpu_provider._lz4f_compress_many([data])
    assert got == cpu.lz4_compress(data)
    assert cpu.lz4_decompress(got, len(data)) == data


def test_compress_many_batched(tpu_provider):
    bufs = [CORPORA["json_like"], CORPORA["over_64k"], b"tiny",
            CORPORA["random_100k"], CORPORA["near_64k"]]
    got = tpu_provider.compress_many("lz4", bufs)
    # the forced device encoder's contract is the DETERMINISTIC spec
    # (the CPU provider's default hot path is the fast parse — same
    # wire format, different bytes)
    want = cpu.lz4f_compress_many(bufs, deterministic=True)
    assert got == want
    # and both decode to the originals
    for g, b in zip(cpu.CpuCodecProvider().compress_many("lz4", bufs),
                    bufs):
        assert cpu.lz4_decompress(g, len(b)) == bytes(b)


def test_other_codecs_fall_back(tpu_provider):
    bufs = [CORPORA["json_like"]] * 4
    for codec in ("gzip", "snappy", "zstd"):
        got = tpu_provider.compress_many(codec, bufs)
        assert tpu_provider.decompress_many(
            codec, got, [len(b) for b in bufs]) == bufs


def test_provider_crc_interface(tpu_provider):
    bufs = [CORPORA["semi"], CORPORA["random_1k"], b"", b"q"]
    assert tpu_provider.crc32c_many(bufs) == [crc32c(b) for b in bufs]


# ------------------------------------------------------------- e2e produce --

def _produce_consume(backend: str, n: int = 300):
    from librdkafka_tpu import Producer, Consumer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": backend,
                  "tpu.launch.min.batches": 1,
                  "compression.codec": "lz4", "linger.ms": 5,
                  "batch.num.messages": 100})
    vals = [("payload-%05d" % i).encode() * 8 for i in range(n)]
    for i, v in enumerate(vals):
        p.produce("tpu-e2e", value=v, key=b"k%d" % i)
    # generous timeout: first device launches pay one-time jit compiles
    assert p.flush(120.0) == 0
    cluster = p._rk.mock_cluster
    # read raw stored wire blobs before shutting the producer down
    blobs = [bytes(blob)
             for part in range(len(cluster.topics["tpu-e2e"]))
             for _base, blob in cluster.partition("tpu-e2e", part).log]

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "g-tpu-e2e", "auto.offset.reset": "earliest"})
    c.subscribe(["tpu-e2e"])
    got = []
    import time
    deadline = time.time() + 15
    while len(got) < n and time.time() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.value)
    c.close()
    p.close()
    return blobs, sorted(got)


def test_e2e_tpu_backend_roundtrip_and_wire_equal():
    blobs_tpu, got_tpu = _produce_consume("tpu")
    blobs_cpu, got_cpu = _produce_consume("cpu")
    want = sorted(("payload-%05d" % i).encode() * 8 for i in range(300))
    assert got_tpu == want
    assert got_cpu == want
    # batching boundaries aren't guaranteed identical across runs (timing-
    # dependent), but every stored blob must be a CRC-valid v2 batch whose
    # lz4 frame decodes; compare the decoded record payload streams.
    from librdkafka_tpu.protocol import proto
    from librdkafka_tpu.protocol.msgset import (iter_batches,
                                                parse_records_v2,
                                                verify_crc_v2)

    def payloads(blobs):
        out = []
        for b in blobs:
            for info, payload, full in iter_batches(b):
                assert verify_crc_v2(info, full)
                if info.codec:
                    assert info.codec == "lz4"
                    payload = cpu.lz4_decompress(payload)
                out.extend(r.value for r in parse_records_v2(info, payload))
        return sorted(out)

    assert payloads(blobs_tpu) == payloads(blobs_cpu) == want
