"""Widened fast-lane eligibility (ISSUE 16): native murmur2
auto-partition, explicit timestamps, and record headers ride the
zero-Python-per-message arena lane.

Pinned here:
- murmur2 parity sweep: the native hash (tk_enqlane / rd_murmur2
  analog) is bit-exact vs the Python partitioner for empty, NUL-laden,
  sign-extension-sensitive and long keys across partition counts;
- keyed-run demotion regression: a mixed keyed/unkeyed murmur2_random
  run must not scramble partition routing (unkeyed records take the
  Python random partitioner and demote THEIR toppar only);
- wire bit-exactness: the fused run-native build equals the pure-Python
  writer byte for byte across headers x timestamps x idempotence x
  codec combinations;
- DR/demotion fidelity: timestamps and headers survive materialization
  out of the arena (delivery reports, demotion drains, expiry).
"""
import itertools
import time

import numpy as np
import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.arena import _mod, decode_hblob, encode_headers
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.ops.cpu import CpuCodecProvider
from librdkafka_tpu.ops.packing import iter_run_records
from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record
from librdkafka_tpu.utils.hash import murmur2_partition

NOW_MS = 1722900000123


def _native():
    m = _mod()
    if m is None or not hasattr(m, "murmur2_partition"):
        pytest.skip("tk_enqlane unavailable")
    return m


# ------------------------------------------------------- murmur2 parity --

KEY_SWEEP = [
    b"",                                   # empty key (murmur2 of b"")
    b"\x00", b"\x00\x00\x00\x00",          # NUL-containing
    b"key", b"kafka-key", b"a" * 3,        # trailing-byte paths (1..3)
    bytes(range(256)),                     # every byte value
    b"\x7f\x80\xff\x01",                   # sign-extension sensitive
    b"\x80" * 7, b"\xff" * 9,              # negative signed chars
    b"k" * 1000, b"\xfe\xdc\xba" * 333,    # long keys
    "héllo-wörld".encode(), "キー".encode(),  # multibyte utf-8
]
CNT_SWEEP = [1, 2, 3, 7, 16, 100, 12345]


def test_murmur2_native_parity_sweep():
    m = _native()
    for key, cnt in itertools.product(KEY_SWEEP, CNT_SWEEP):
        assert m.murmur2_partition(key, cnt) == murmur2_partition(key, cnt), \
            (key[:16], cnt)
    # randomized fuzz on top of the fixed sweep
    rng = np.random.default_rng(16)
    for _ in range(300):
        key = rng.integers(0, 256, int(rng.integers(0, 64)),
                           dtype=np.uint8).tobytes()
        for cnt in (3, 12, 31):
            assert (m.murmur2_partition(key, cnt)
                    == murmur2_partition(key, cnt)), (key, cnt)


def test_murmur2_partitioner_none_key_semantics():
    """The 'murmur2' partitioner hashes a None/empty key as b'' (the
    confluent semantics partitioner_fn pins) — the native lane must
    route a keyless produce to the same partition."""
    m = _native()
    for cnt in CNT_SWEEP:
        assert m.murmur2_partition(b"", cnt) == murmur2_partition(b"", cnt)


# ------------------------------------------- end-to-end auto-partition --

def test_auto_partition_routes_like_python_partitioner():
    """PARTITION_UA + partitioner=murmur2: every record lands on the
    partition the Python partitioner would pick, and the lane stays
    engaged (no demotions)."""
    cluster = MockCluster(num_brokers=1, topics={"ap": 5})
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5,
                  "dr_msg_cb": lambda e, mm: drs.append((e, mm))})
    p.set_topic_conf("ap", {"partitioner": "murmur2"})
    try:
        p.rk.get_topic("ap")
        deadline = time.monotonic() + 5
        while (p.rk.topics["ap"].partition_cnt <= 0
               and time.monotonic() < deadline):
            p.poll(0.05)
        keys = [b"k-%03d" % i for i in range(120)] + [b"", None]
        for k in keys:
            p.produce("ap", value=b"v", key=k)
        assert p.flush(20.0) == 0
        assert len(drs) == len(keys)
        for e, mm in drs:
            assert e is None
            assert mm.partition == murmur2_partition(mm.key or b"", 5)
        assert p.rk._demote_reasons == {}, p.rk._demote_reasons
        ctrs = p.rk._lane.counters()
        # everything after the per-toppar first sights ran natively
        assert ctrs["engaged"] >= len(keys) - 6, ctrs
    finally:
        p.close()
        cluster.stop()


def test_murmur2_random_mixed_keyed_unkeyed_routing():
    """Keyed-run demotion regression: with murmur2_random, unkeyed
    records fall back to the Python random partitioner (demoting only
    the toppars they land on) while keyed records keep murmur2 routing
    — the mixed run must not scramble keyed partition assignment."""
    cluster = MockCluster(num_brokers=1, topics={"mr": 4})
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5,
                  "dr_msg_cb": lambda e, mm: drs.append((e, mm))})
    p.set_topic_conf("mr", {"partitioner": "murmur2_random"})
    try:
        p.rk.get_topic("mr")
        deadline = time.monotonic() + 5
        while (p.rk.topics["mr"].partition_cnt <= 0
               and time.monotonic() < deadline):
            p.poll(0.05)
        for i in range(200):
            if i % 5 == 0:
                p.produce("mr", value=b"u%03d" % i)         # unkeyed
            else:
                p.produce("mr", value=b"v%03d" % i, key=b"k%03d" % i)
        assert p.flush(20.0) == 0
        assert len(drs) == 200
        for e, mm in drs:
            assert e is None
            if mm.key:      # keyed: murmur2 routing, bit-exact
                assert mm.partition == murmur2_partition(mm.key, 4), \
                    (mm.key, mm.partition)
        # unkeyed records demote via the Python random partitioner
        assert set(p.rk._demote_reasons) <= {"partitioner"}, \
            p.rk._demote_reasons
        ctrs = p.rk._lane.counters()
        assert ctrs["fallback"]["auto_partition"] >= 40, ctrs
    finally:
        p.close()
        cluster.stop()


# ---------------------------------------------------- wire bit-exactness --

def _run_from(recs):
    """Arena-run descriptor (ArenaBatch layout) for logical records."""
    parts, klens, vlens, tss, hbufs, hlens = [], [], [], [], [], []
    for k, v, ts, hdrs in recs:
        klens.append(-1 if k is None else len(k))
        vlens.append(-1 if v is None else len(v))
        if k is not None:
            parts.append(k)
        if v is not None:
            parts.append(v)
        tss.append(ts)
        hb = encode_headers(hdrs) if hdrs else b""
        hbufs.append(hb)
        hlens.append(len(hb))
    ts_any = any(tss)
    h_any = any(hlens)
    return (b"".join(parts),
            np.array(klens, np.int32).tobytes(),
            np.array(vlens, np.int32).tobytes(),
            np.array(tss, np.int64).tobytes() if ts_any else None,
            b"".join(hbufs) if h_any else None,
            np.array(hlens, np.int32).tobytes() if h_any else None)


def _combo_records(with_hdrs, with_ts):
    recs = []
    for i in range(7):
        k = b"k%d" % i if i % 2 == 0 else None
        v = (b"v" * (i * 13 + 1)) if i != 3 else None
        ts = (NOW_MS - 500 + i * 37) if (with_ts and i % 3 != 1) else 0
        hdrs = ([("hk%d" % i, b"hv%d" % i), ("null", None), ("", b"")]
                if (with_hdrs and i % 2 == 0) else ())
        recs.append((k, v, ts, hdrs))
    return recs


CODEC_ID = {"none": 0, "snappy": 2, "lz4": 3}


@pytest.mark.parametrize("codec", ["none", "lz4", "snappy"])
@pytest.mark.parametrize("idem", [False, True])
@pytest.mark.parametrize("with_ts", [False, True])
@pytest.mark.parametrize("with_hdrs", [False, True])
def test_wire_bit_identical_fast_vs_slow(with_hdrs, with_ts, idem, codec):
    m = _native()
    if not hasattr(m, "build_batch"):
        pytest.skip("fused builder unavailable")
    prov = CpuCodecProvider()
    recs = _combo_records(with_hdrs, with_ts)
    pid, epoch, seq = (1234, 7, 99) if idem else (-1, -1, -1)
    # slow path: pure-Python framer + writer + provider codec/CRC
    msgs = [Record(key=k, value=v, timestamp=ts if ts else -1, headers=h)
            for k, v, ts, h in recs]
    w = MsgsetWriterV2(producer_id=pid, producer_epoch=epoch,
                       base_sequence=seq,
                       codec=None if codec == "none" else codec)
    w._build_py(msgs, NOW_MS)
    comp = None
    if codec != "none":
        c = prov.compress_many(codec, [w.records_bytes])[0]
        if len(c) < len(w.records_bytes):
            comp = c
        else:
            w.codec = None
    region = w.assemble(comp)
    slow = w.patch_crc(int(prov.crc32c_many([region])[0]))
    # fast path: ONE fused native call off the run descriptor
    base, kl, vl, tsb, hb, hlb = _run_from(recs)
    fast = m.build_batch(base, kl, vl, len(recs), NOW_MS, pid, epoch,
                         seq, CODEC_ID[codec], 0, tsb, hb, hlb)
    assert bytes(fast) == slow


def test_run_descriptor_walk_round_trips():
    """iter_run_records (ops/packing.py) inverts the descriptor: the
    inspection seam the wire gates rely on must see exactly the logical
    records that went in."""
    recs = _combo_records(True, True)
    base, kl, vl, tsb, hb, hlb = _run_from(recs)
    walked = list(iter_run_records(base, kl, vl, len(recs), tsb, hb, hlb))
    assert len(walked) == len(recs)
    for (k, v, ts, hdrs), (wk, wv, wts, whb) in zip(recs, walked):
        assert wk == k and wv == v and wts == ts
        assert (decode_hblob(whb) if whb else []) == list(hdrs)


def test_headers_blob_codec_round_trip():
    cases = [
        [],
        [("a", b"1")],
        [("key", None), ("", b""), ("utf8-ключ", b"\x00\xff")],
        [("h%d" % i, b"v" * i) for i in range(40)],
    ]
    for hdrs in cases:
        blob = encode_headers(hdrs)
        assert blob is not None
        assert decode_hblob(blob) == [(k, v) for k, v in hdrs]
    # ineligible shapes return None (fast lane falls back, not crash)
    assert encode_headers([("k", "str-not-bytes")]) is None
    assert encode_headers([(1, b"v")]) is None
    assert encode_headers("not-a-seq-of-pairs") is None


# ----------------------------------------------- materialization fidelity --

def test_dr_carries_timestamps_and_headers():
    cluster = MockCluster(num_brokers=1, topics={"drw": 1})
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5,
                  "dr_msg_cb": lambda e, mm: drs.append((e, mm))})
    try:
        hdrs = [("trace", b"abc"), ("nil", None)]
        for i in range(30):
            p.produce("drw", value=b"v%02d" % i, partition=0,
                      timestamp=NOW_MS + i, headers=hdrs)
        assert p.flush(20.0) == 0
        tp = p.rk._toppars[("drw", 0)]
        assert tp.arena_ok, "widened shapes must not demote"
        assert len(drs) == 30
        for i, (e, mm) in enumerate(sorted(drs, key=lambda x: x[1].offset)):
            assert e is None
            assert mm.value == b"v%02d" % i
            assert mm.timestamp == NOW_MS + i
            assert list(mm.headers) == hdrs
    finally:
        p.close()
        cluster.stop()


def test_demotion_drain_preserves_ts_and_headers():
    """An arena holding widened records demotes into Messages with
    timestamps and headers intact (FIFO preserved)."""
    p = Producer({"bootstrap.servers": "127.0.0.1:1", "linger.ms": 5})
    try:
        t = p.rk.get_topic("dm")
        t.partition_cnt = 1
        p.rk.get_toppar("dm", 0)
        hdrs = [("h", b"x")]
        for i in range(10):
            p.produce("dm", value=b"w%d" % i, partition=0,
                      timestamp=NOW_MS + i, headers=hdrs)
        tp = p.rk._toppars[("dm", 0)]
        assert tp.arena is not None and len(tp.arena) == 10
        p.rk._demote(tp, "ineligible")
        assert not tp.arena_ok
        assert len(tp.msgq) == 10
        for i, mm in enumerate(tp.msgq):
            assert mm.value == b"w%d" % i
            assert mm.timestamp == NOW_MS + i
            assert list(mm.headers) == hdrs
        assert p.rk._demote_reasons.get("ineligible") == 1
    finally:
        p.rk.purge(in_queue=True)
        p.close()


def test_expiry_drs_carry_ts_and_headers():
    drs = []
    p = Producer({"bootstrap.servers": "127.0.0.1:1",
                  "message.timeout.ms": 600, "linger.ms": 5,
                  "dr_msg_cb": lambda e, mm: drs.append((e, mm))})
    try:
        t = p.rk.get_topic("ex")
        t.partition_cnt = 1
        p.rk.get_toppar("ex", 0)
        hdrs = [("why", b"expired")]
        for i in range(5):
            p.produce("ex", value=b"e%d" % i, partition=0,
                      timestamp=NOW_MS + i, headers=hdrs)
        deadline = time.monotonic() + 10
        while len(drs) < 5 and time.monotonic() < deadline:
            p.poll(0.1)
        assert len(drs) == 5
        for i, (e, mm) in enumerate(drs):
            assert e is not None
            assert mm.value == b"e%d" % i
            assert mm.timestamp == NOW_MS + i
            assert list(mm.headers) == hdrs
    finally:
        p.rk.conf.set("message.timeout.ms", 300000)
        p.close()


def test_consume_round_trip_widened():
    """Produce (headers + explicit ts + murmur2 auto-partition, fast
    lane engaged) then consume: the app sees exactly what was sent."""
    cluster = MockCluster(num_brokers=1, topics={"rt": 3})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5})
    p.set_topic_conf("rt", {"partitioner": "murmur2"})
    sent = {}
    try:
        p.rk.get_topic("rt")
        deadline = time.monotonic() + 5
        while (p.rk.topics["rt"].partition_cnt <= 0
               and time.monotonic() < deadline):
            p.poll(0.05)
        for i in range(90):
            key = b"rk%03d" % i
            hdrs = [("seq", b"%d" % i)] if i % 2 else ()
            ts = NOW_MS + i if i % 3 else 0
            p.produce("rt", value=b"rv%03d" % i, key=key,
                      timestamp=ts, headers=hdrs)
            sent[key] = (b"rv%03d" % i, ts, list(hdrs))
        assert p.flush(20.0) == 0
        assert p.rk._demote_reasons == {}
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "rtg",
                      "auto.offset.reset": "earliest"})
        c.subscribe(["rt"])
        got = {}
        deadline = time.monotonic() + 20
        while len(got) < 90 and time.monotonic() < deadline:
            mm = c.poll(0.2)
            if mm and not mm.error:
                got[mm.key] = mm
        c.close()
        assert len(got) == 90
        for key, (val, ts, hdrs) in sent.items():
            mm = got[key]
            assert mm.value == val
            assert mm.partition == murmur2_partition(key, 3)
            if ts:
                assert mm.timestamp == ts
            assert list(mm.headers) == hdrs
    finally:
        p.close()
        cluster.stop()
