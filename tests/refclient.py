"""ctypes binding to the *reference* librdkafka.so, for interop tests only.

The reference tree is compiled into ``.refbuild/`` (gitignored) by
``tests/build_reference.sh`` (or manually: ``configure && make libs`` in a
copy of ``/root/reference``).  When the shared object is absent every
interop test skips cleanly.

This is deliberately a minimal surface — enough to (a) produce records
with pinned timestamps/keys/values through the real C client
(rd_kafka_producev, /root/reference/src/rdkafka.h:1145) and (b) consume
them back with the legacy simple-consumer API (rd_kafka_consume_batch,
rdkafka.h:3097), so tests can prove that wire bytes produced by the
reference are readable by our client and vice versa.
"""
from __future__ import annotations

import ctypes
import os
from ctypes import (POINTER, Structure, byref, c_char_p, c_int, c_int32,
                    c_int64, c_size_t, c_ssize_t, c_void_p, create_string_buffer)

REFBUILD = os.path.join(os.path.dirname(__file__), "..", ".refbuild")
SO_PATH = os.path.abspath(os.path.join(REFBUILD, "src", "librdkafka.so.1"))
PERF_BIN = os.path.abspath(
    os.path.join(REFBUILD, "examples", "rdkafka_performance"))


def available() -> bool:
    return os.path.exists(SO_PATH)


class rd_kafka_message_t(Structure):
    _fields_ = [
        ("err", c_int),
        ("rkt", c_void_p),
        ("partition", c_int32),
        ("payload", c_void_p),
        ("len", c_size_t),
        ("key", c_void_p),
        ("key_len", c_size_t),
        ("offset", c_int64),
        ("_private", c_void_p),
    ]


_lib = None

# rd_kafka_vtype_t (rdkafka.h:937-953)
VTYPE_END = 0
VTYPE_TOPIC = 1
VTYPE_PARTITION = 3
VTYPE_VALUE = 4
VTYPE_KEY = 5
VTYPE_MSGFLAGS = 7
VTYPE_TIMESTAMP = 8

MSG_F_COPY = 0x2

RD_KAFKA_PRODUCER = 0
RD_KAFKA_CONSUMER = 1

PARTITION_UA = -1
OFFSET_BEGINNING = -2


def lib():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(SO_PATH)
        _lib.rd_kafka_conf_new.restype = c_void_p
        _lib.rd_kafka_conf_set.argtypes = [c_void_p, c_char_p, c_char_p,
                                           c_char_p, c_size_t]
        _lib.rd_kafka_new.restype = c_void_p
        _lib.rd_kafka_new.argtypes = [c_int, c_void_p, c_char_p, c_size_t]
        _lib.rd_kafka_producev.restype = c_int
        _lib.rd_kafka_flush.argtypes = [c_void_p, c_int]
        _lib.rd_kafka_flush.restype = c_int
        _lib.rd_kafka_poll.argtypes = [c_void_p, c_int]
        _lib.rd_kafka_destroy.argtypes = [c_void_p]
        _lib.rd_kafka_topic_new.restype = c_void_p
        _lib.rd_kafka_topic_new.argtypes = [c_void_p, c_char_p, c_void_p]
        _lib.rd_kafka_topic_destroy.argtypes = [c_void_p]
        _lib.rd_kafka_consume_start.argtypes = [c_void_p, c_int32, c_int64]
        _lib.rd_kafka_consume_start.restype = c_int
        _lib.rd_kafka_consume_stop.argtypes = [c_void_p, c_int32]
        _lib.rd_kafka_consume_batch.argtypes = [
            c_void_p, c_int32, c_int, POINTER(POINTER(rd_kafka_message_t)),
            c_size_t]
        _lib.rd_kafka_consume_batch.restype = c_ssize_t
        _lib.rd_kafka_message_destroy.argtypes = [
            POINTER(rd_kafka_message_t)]
        _lib.rd_kafka_message_timestamp.argtypes = [
            POINTER(rd_kafka_message_t), POINTER(c_int)]
        _lib.rd_kafka_message_timestamp.restype = c_int64
        _lib.rd_kafka_err2str.restype = c_char_p
        _lib.rd_kafka_last_error.restype = c_int
    return _lib


def _mk_handle(ctype: int, conf: dict[str, str]) -> c_void_p:
    L = lib()
    c = L.rd_kafka_conf_new()
    errstr = create_string_buffer(512)
    for k, v in conf.items():
        res = L.rd_kafka_conf_set(c, k.encode(), str(v).encode(),
                                  errstr, 512)
        if res != 0:
            raise RuntimeError(f"conf_set {k}: {errstr.value.decode()}")
    rk = L.rd_kafka_new(ctype, c, errstr, 512)
    if not rk:
        raise RuntimeError(f"rd_kafka_new: {errstr.value.decode()}")
    return rk


class RefProducer:
    """The real librdkafka producer, driven via ctypes."""

    def __init__(self, bootstrap: str, **extra_conf: str):
        conf = {"bootstrap.servers": bootstrap,
                "socket.timeout.ms": "5000",
                "message.timeout.ms": "10000",
                **extra_conf}
        self.rk = _mk_handle(RD_KAFKA_PRODUCER, conf)

    def produce(self, topic: str, partition: int, value: bytes,
                key: bytes | None = None, timestamp_ms: int | None = None):
        L = lib()
        args: list = [
            c_int(VTYPE_TOPIC), c_char_p(topic.encode()),
            c_int(VTYPE_PARTITION), c_int32(partition),
            c_int(VTYPE_MSGFLAGS), c_int(MSG_F_COPY),
            c_int(VTYPE_VALUE), c_char_p(value), c_size_t(len(value)),
        ]
        if key is not None:
            args += [c_int(VTYPE_KEY), c_char_p(key), c_size_t(len(key))]
        if timestamp_ms is not None:
            args += [c_int(VTYPE_TIMESTAMP), c_int64(timestamp_ms)]
        args += [c_int(VTYPE_END)]
        err = L.rd_kafka_producev(c_void_p(self.rk), *args)
        if err != 0:
            raise RuntimeError(
                f"producev: {L.rd_kafka_err2str(err).decode()}")

    def flush(self, timeout_ms: int = 10000) -> int:
        return lib().rd_kafka_flush(c_void_p(self.rk), timeout_ms)

    def close(self):
        if self.rk:
            lib().rd_kafka_destroy(c_void_p(self.rk))
            self.rk = None


class RefConsumer:
    """The real librdkafka simple consumer (consume_start/consume_batch)."""

    def __init__(self, bootstrap: str, topic: str, **extra_conf: str):
        conf = {"bootstrap.servers": bootstrap,
                "socket.timeout.ms": "5000",
                **extra_conf}
        self.rk = _mk_handle(RD_KAFKA_CONSUMER, conf)
        self.rkt = lib().rd_kafka_topic_new(
            c_void_p(self.rk), topic.encode(), None)
        self._started: set[int] = set()

    def consume(self, partition: int, n: int, timeout_ms: int = 10000):
        """Consume up to n messages; returns list of
        (partition, offset, key|None, value, timestamp_ms)."""
        import time
        L = lib()
        if partition not in self._started:
            if L.rd_kafka_consume_start(c_void_p(self.rkt), partition,
                                        OFFSET_BEGINNING) == -1:
                err = L.rd_kafka_last_error()
                raise RuntimeError(
                    f"consume_start: {L.rd_kafka_err2str(err).decode()}")
            self._started.add(partition)
        out = []
        msgs = (POINTER(rd_kafka_message_t) * n)()
        deadline = time.monotonic() + timeout_ms / 1000.0
        while len(out) < n and time.monotonic() < deadline:
            cnt = L.rd_kafka_consume_batch(
                c_void_p(self.rkt), partition, 1000,
                ctypes.cast(msgs, POINTER(POINTER(rd_kafka_message_t))),
                n - len(out))
            for i in range(max(cnt, 0)):
                m = msgs[i].contents
                if m.err == 0:
                    key = (ctypes.string_at(m.key, m.key_len)
                           if m.key else None)
                    val = (ctypes.string_at(m.payload, m.len)
                           if m.payload else b"")
                    tstype = c_int(0)
                    ts = L.rd_kafka_message_timestamp(msgs[i], byref(tstype))
                    out.append((partition, m.offset, key, val, ts))
                L.rd_kafka_message_destroy(msgs[i])
        return out

    def close(self):
        L = lib()
        for p in self._started:
            L.rd_kafka_consume_stop(c_void_p(self.rkt), p)
        if self.rkt:
            L.rd_kafka_topic_destroy(c_void_p(self.rkt))
            self.rkt = None
        if self.rk:
            L.rd_kafka_destroy(c_void_p(self.rk))
            self.rk = None
