"""Multi-chip codec scale-out over a virtual 8-device mesh: shard_compress
must match the single-device oracles bit-for-bit (lz4 blocks) and
value-for-value (crc32c), including when B is not a mesh multiple (pad
rows must not pollute results or the psum'd byte counter)."""
import numpy as np

from librdkafka_tpu.ops import cpu
from librdkafka_tpu.parallel.mesh import make_mesh, shard_compress
from librdkafka_tpu.utils.crc import crc32c


def test_shard_compress_matches_oracles():
    mesh = make_mesh(8)
    rng = np.random.default_rng(23)
    blocks = [b"hello world, this is a test buffer",
              rng.integers(0, 256, 5000, dtype=np.uint8).tobytes(),
              b"z" * 10000, b"", b"x"]          # B=5, not a multiple of 8
    outs, crcs, total = shard_compress(mesh, blocks)
    for got, b in zip(outs, blocks):
        assert got == cpu.lz4_block_compress(b)
    assert [int(c) for c in crcs] == [crc32c(b) for b in blocks]
    assert total == sum(len(o) for o in outs)


def test_shard_compress_full_multiple():
    mesh = make_mesh(8)
    blocks = [(b"msg-%d " % i) * 200 for i in range(16)]
    outs, crcs, total = shard_compress(mesh, blocks)
    assert [int(c) for c in crcs] == [crc32c(b) for b in blocks]
    assert outs == [cpu.lz4_block_compress(b) for b in blocks]
    assert total == sum(len(o) for o in outs)
