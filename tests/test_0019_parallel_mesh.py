"""Multi-chip codec scale-out over a virtual 8-device mesh: shard_compress
must match the single-device oracles bit-for-bit (lz4 blocks) and
value-for-value (crc32c), including when B is not a mesh multiple (pad
rows must not pollute results or the psum'd byte counter)."""
import numpy as np
import pytest

from librdkafka_tpu.ops import cpu
from librdkafka_tpu.parallel.mesh import (make_mesh, release_step_cache,
                                          shard_compress,
                                          step_cache_count)
from librdkafka_tpu.utils.crc import crc32c


@pytest.fixture(autouse=True)
def _release_compiled_steps():
    """Direct mesh tests compile sharded steps outside any engine or
    provider, so the close-time hook never fires for them — release
    here so the conftest leak fixture's step-cache assertion holds."""
    yield
    release_step_cache()


def test_shard_compress_matches_oracles():
    mesh = make_mesh(8)
    rng = np.random.default_rng(23)
    blocks = [b"hello world, this is a test buffer",
              rng.integers(0, 256, 5000, dtype=np.uint8).tobytes(),
              b"z" * 10000, b"", b"x"]          # B=5, not a multiple of 8
    outs, crcs, total = shard_compress(mesh, blocks)
    for got, b in zip(outs, blocks):
        assert got == cpu.lz4_block_compress(b)
    assert [int(c) for c in crcs] == [crc32c(b) for b in blocks]
    assert total == sum(len(o) for o in outs)


def test_shard_compress_full_multiple():
    mesh = make_mesh(8)
    blocks = [(b"msg-%d " % i) * 200 for i in range(16)]
    outs, crcs, total = shard_compress(mesh, blocks)
    assert [int(c) for c in crcs] == [crc32c(b) for b in blocks]
    assert outs == [cpu.lz4_block_compress(b) for b in blocks]
    assert total == sum(len(o) for o in outs)


def test_shard_compress_empty_blocks():
    """ISSUE 6 satellite: zero blocks must short-circuit (shard_map
    cannot partition zero rows) without touching the step cache."""
    mesh = make_mesh(2)
    outs, crcs, total = shard_compress(mesh, [])
    assert outs == [] and total == 0 and len(crcs) == 0
    outs, crcs, total = shard_compress(mesh, [], with_crc=False)
    assert outs == [] and crcs is None and total == 0
    assert step_cache_count() == 0


def test_step_cache_bounded_lru():
    """ISSUE 6 satellite: the compiled-step cache is a bounded LRU —
    inserts past the cap evict least-recently-USED (a get refreshes),
    and release_step_cache() empties it (the engine/provider close-time
    hook the conftest leak fixture asserts)."""
    from librdkafka_tpu.parallel import mesh as m

    release_step_cache()
    try:
        for i in range(m._STEP_CACHE_MAX):
            m._step_cache_put(("t", i), i)
        assert step_cache_count() == m._STEP_CACHE_MAX
        m._step_cache_get(("t", 0))             # refresh: 0 is now MRU
        m._step_cache_put(("t", "overflow"), -1)
        assert step_cache_count() == m._STEP_CACHE_MAX
        assert m._step_cache_get(("t", 0)) == 0          # survived
        assert m._step_cache_get(("t", 1)) is None       # LRU evicted
        assert m._step_cache_get(("t", "overflow")) == -1
    finally:
        release_step_cache()
    assert step_cache_count() == 0


def test_step_cache_caches_compiled_steps():
    """A real shard_compress populates the cache (so the bound and the
    release hook actually govern compiled executables, not just the
    test doubles above)."""
    mesh = make_mesh(2)
    shard_compress(mesh, [b"payload" * 64] * 4)
    assert step_cache_count() > 0
    release_step_cache()
    assert step_cache_count() == 0
