"""Regex subscription tests (reference: rdkafka_pattern.c + rdregex.c;
behavior of `^`-prefixed topics in rd_kafka_subscribe): pattern
subscriptions match against the full cluster topic list, newly created
matching topics trigger a rebalance and get consumed, and non-matching
topics are ignored."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"bench-a": 1, "other": 1},
                    auto_create_topics=False)
    yield c
    c.stop()


def _consume_until(c, want, timeout=25):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < want and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append((m.topic, m.value))
    return got


def test_regex_matches_existing_and_new_topics(cluster):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("bench-a", value=b"a1", partition=0)
    p.produce("other", value=b"x1", partition=0)
    assert p.flush(10.0) == 0

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "rgx", "auto.offset.reset": "earliest",
                  # fast periodic full refresh so new topics are seen
                  "topic.metadata.refresh.interval.ms": 400})
    c.subscribe(["^bench-.*"])

    got = _consume_until(c, 1)
    assert got == [("bench-a", b"a1")], got

    # create a new matching topic AFTER subscription: the pattern must
    # pick it up on the next metadata refresh and rebalance onto it
    cluster.create_topic("bench-b", 1)
    p.produce("bench-b", value=b"b1", partition=0)
    assert p.flush(10.0) == 0
    got = _consume_until(c, 1)
    assert got == [("bench-b", b"b1")], got

    # non-matching topic traffic is never delivered
    p.produce("other", value=b"x2", partition=0)
    assert p.flush(10.0) == 0
    assert _consume_until(c, 1, timeout=2) == []
    c.close()
    p.close()


def test_mixed_literal_and_regex(cluster):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("bench-a", value=b"a", partition=0)
    p.produce("other", value=b"o", partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "rgx2", "auto.offset.reset": "earliest",
                  "topic.metadata.refresh.interval.ms": 400})
    c.subscribe(["other", "^bench-.*"])
    got = _consume_until(c, 2)
    assert sorted(got) == [("bench-a", b"a"), ("other", b"o")]
    c.close()


def test_bad_regex_raises(cluster):
    from librdkafka_tpu import KafkaException
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "rgx3"})
    with pytest.raises(KafkaException):
        c.subscribe(["^ben[ch-"])
    c.close()
