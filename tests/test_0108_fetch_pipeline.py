"""Fetch pipelining (reference: rd_kafka_broker_fetch_toppars,
rdkafka_broker.c:4279 — the fetch pipe stays full): up to
``fetch.num.inflight`` FetchRequests may be outstanding per broker over
disjoint partition sets, instead of serializing one Fetch per round
trip.  With RTT injected on the mock broker, overlapping Fetches are
observable directly on the broker's in-flight counter and in total
consumption latency."""
import time

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster


def _fill(cluster, topic, parts, per_part):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(per_part):
        for part in range(parts):
            p.produce(topic, value=b"m%03d.%d" % (i, part), partition=part)
    assert p.flush(30.0) == 0
    p.close()


def test_fetch_pipeline_overlaps_under_rtt():
    """While one Fetch is waiting out the injected RTT, partitions that
    turn fetchable afterwards are fetched by a SECOND in-flight request
    — the in-flight counter must be observed above 1."""
    cluster = MockCluster(num_brokers=1, topics={"fp": 4})
    try:
        _fill(cluster, "fp", 4, 25)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gfp", "auto.offset.reset": "earliest",
                      # tiny queue budget: partitions become fetchable
                      # again one drained batch at a time
                      "queued.min.messages": 1,
                      "fetch.wait.max.ms": 10})
        c.subscribe(["fp"])
        rk = c._rk
        cluster.set_rtt(1, 150)
        got = 0
        max_inflight = 0
        deadline = time.monotonic() + 40
        while got < 100 and time.monotonic() < deadline:
            m = c.poll(0.05)
            for b in list(rk.brokers.values()):
                max_inflight = max(max_inflight, b.fetch_inflight_cnt)
            if m is not None and m.error is None:
                got += 1
        cluster.set_rtt(1, 0)
        assert got == 100, got
        assert max_inflight >= 2, \
            f"no Fetch overlap observed (max in-flight {max_inflight})"
        c.close()
    finally:
        cluster.stop()


def test_fetch_disjoint_partition_sets():
    """A partition never appears in two outstanding Fetches: offsets
    advance strictly (no duplicate deliveries) while pipelining under
    RTT."""
    cluster = MockCluster(num_brokers=1, topics={"fd": 4})
    try:
        _fill(cluster, "fd", 4, 25)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gfd", "auto.offset.reset": "earliest",
                      "queued.min.messages": 1,
                      "fetch.wait.max.ms": 10})
        c.subscribe(["fd"])
        cluster.set_rtt(1, 60)
        seen: dict[int, list] = {0: [], 1: [], 2: [], 3: []}
        deadline = time.monotonic() + 40
        total = 0
        while total < 100 and time.monotonic() < deadline:
            m = c.poll(0.05)
            if m is not None and m.error is None:
                seen[m.partition].append(m.offset)
                total += 1
        cluster.set_rtt(1, 0)
        assert total == 100, total
        for part, offs in seen.items():
            assert offs == sorted(set(offs)), \
                f"partition {part}: duplicate/unordered offsets {offs[:10]}"
            assert offs == list(range(25)), f"partition {part}: {offs}"
        c.close()
    finally:
        cluster.stop()


def test_fetch_num_inflight_cap():
    """fetch.num.inflight=1 restores strict serialization."""
    cluster = MockCluster(num_brokers=1, topics={"fc": 4})
    try:
        _fill(cluster, "fc", 4, 10)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gfc", "auto.offset.reset": "earliest",
                      "fetch.num.inflight": 1,
                      "queued.min.messages": 1,
                      "fetch.wait.max.ms": 10})
        c.subscribe(["fc"])
        rk = c._rk
        cluster.set_rtt(1, 50)
        got = 0
        max_inflight = 0
        deadline = time.monotonic() + 40
        while got < 40 and time.monotonic() < deadline:
            m = c.poll(0.05)
            for b in list(rk.brokers.values()):
                max_inflight = max(max_inflight, b.fetch_inflight_cnt)
            if m is not None and m.error is None:
                got += 1
        cluster.set_rtt(1, 0)
        assert got == 40, got
        assert max_inflight <= 1, max_inflight
        c.close()
    finally:
        cluster.stop()


def test_fetch_codec_tickets_overlap_partitions():
    """ISSUE 2 tentpole: with an async provider whose tickets resolve
    ~80 ms after submission, the broker must keep MULTIPLE partitions'
    codec phases in flight concurrently (the _PendingFetch FIFO) —
    total consumption wall-clock beats strict per-partition
    serialization and >=2 tickets are observed outstanding at once."""
    import threading

    import numpy as np

    from librdkafka_tpu import Consumer
    from librdkafka_tpu.ops.cpu import CpuCodecProvider

    class _TimerTicket:
        def __init__(self, values, delay):
            self._ev = threading.Event()
            self._values = values
            threading.Timer(delay, self._ev.set).start()

        def done(self):
            return self._ev.is_set()

        def result(self, timeout=None):
            if not self._ev.wait(timeout):
                raise TimeoutError("timer ticket")
            return self._values

    class _TimerProvider:
        """CRC/decompress tickets resolve after ``delay`` —
        models the engine's device round trip without jax."""

        def __init__(self, delay=0.08):
            self._cpu = CpuCodecProvider()
            self.delay = delay
            self.outstanding = 0
            self.hwm = 0
            self._lock = threading.Lock()

        def _ticket(self, values):
            with self._lock:
                self.outstanding += 1
                self.hwm = max(self.hwm, self.outstanding)
            t = _TimerTicket(values, self.delay)

            def _done():
                with self._lock:
                    self.outstanding -= 1
            threading.Timer(self.delay, _done).start()
            return t

        def crc32c_submit(self, bufs):
            return self._ticket(np.asarray(
                self._cpu.crc32c_many([bytes(b) for b in bufs]),
                dtype=np.uint32))

        def decompress_submit(self, codec, bufs, size_hints=None):
            return self._ticket(self._cpu.decompress_many(
                codec, [bytes(b) for b in bufs], size_hints))

        def __getattr__(self, name):
            return getattr(self._cpu, name)

    cluster = MockCluster(num_brokers=1, topics={"fo": 4})
    try:
        _fill(cluster, "fo", 4, 25)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gfo", "auto.offset.reset": "earliest",
                      "check.crcs": True,
                      "fetch.wait.max.ms": 10})
        prov = _TimerProvider()
        c._rk.codec_provider = prov
        c.subscribe(["fo"])
        got = 0
        deadline = time.monotonic() + 30
        while got < 100 and time.monotonic() < deadline:
            m = c.poll(0.05)
            if m is not None and m.error is None:
                got += 1
        c.close()
        assert got == 100, got
        assert prov.hwm >= 2, \
            f"no codec-phase overlap observed (hwm {prov.hwm})"
    finally:
        cluster.stop()


def test_deferred_fetch_survives_seek():
    """r5 flow control: with a tiny queued.max.messages.kbytes budget
    every response parks in the broker's deferred queue. A seek() while
    entries are parked must not deliver stale offsets (version barrier)
    nor lose the stream — delivery resumes exactly at the seek point
    and stays gapless."""
    import time

    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.client.consumer import TopicPartition
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"dfs": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5, "compression.codec": "lz4"})
    for i in range(3000):
        p.produce("dfs", value=b"m%05d" % i, partition=0)
    assert p.flush(30.0) == 0
    p.close()

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gdfs", "auto.offset.reset": "earliest",
                  "check.crcs": True,
                  "queued.max.messages.kbytes": 1})   # park everything
    c.subscribe(["dfs"])
    got = 0
    deadline = time.monotonic() + 30
    while got < 100 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got += 1
    assert got == 100
    c.seek(TopicPartition("dfs", 0, 50))
    seq = []
    deadline = time.monotonic() + 45
    while len(seq) < 500 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            seq.append(m.offset)
    c.close()
    cluster.stop()
    assert seq[:1] == [50], seq[:5]
    assert seq == list(range(50, 50 + len(seq))), "gap/dup after seek"
    assert len(seq) == 500


def test_close_with_deferred_entries_is_clean():
    """Closing mid-stream with fetch responses parked in the deferred
    queue releases their in-flight claims and returns promptly."""
    import time

    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"dfc": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5, "compression.codec": "lz4"})
    for i in range(3000):
        p.produce("dfc", value=b"c%05d" % i, partition=0)
    assert p.flush(30.0) == 0
    p.close()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gdfc", "auto.offset.reset": "earliest",
                  "queued.max.messages.kbytes": 1})
    c.subscribe(["dfc"])
    got = 0
    deadline = time.monotonic() + 30
    while got < 50 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got += 1
    assert got == 50
    t0 = time.monotonic()
    c.close()
    assert time.monotonic() - t0 < 10.0
    cluster.stop()
