"""Device-side batch compression (ISSUE 17): the fused compress→CRC
route must be bit-identical to the deterministic CPU encoder on EVERY
route the engine can take — device launch, governor CPU re-route,
warmup miss, QoS shed — at writer level (idempotent + headers included)
and end-to-end through the producer; plus the governor's per-topic QoS
model and the hot-topic-flood isolation smoke.

Extends the test_0018 harness one layer up: test_0018 proves the lz4
KERNEL bit-exact; this file proves the PIPELINE around it (staging
rings, fused CRC readback, FrameBlob batch-CRC folding, routing)."""
import time

import numpy as np
import pytest

from librdkafka_tpu.ops import cpu, lz4_jax
from librdkafka_tpu.ops.packing import FrameBlob, lz4f_frame
from librdkafka_tpu.ops.tpu import TpuCodecProvider
from librdkafka_tpu.utils.crc import crc32c

from test_0017_codecs import CORPORA

#: the ISSUE-17 size sweep: empty / 1B / 100B / 1KB / 64KB boundary /
#: multi-block / incompressible
def _sweep():
    rng = np.random.default_rng(135)
    return [
        b"",
        b"Z",
        bytes(CORPORA["json_like"][:100]),
        b"kv-pair " * 128,                       # ~1KB compressible
        CORPORA["near_64k"],                     # straddles a block
        CORPORA["over_64k"],                     # multi-block frame
        rng.integers(0, 256, 3000, dtype=np.uint8).tobytes(),  # incompr.
    ]


def _det(bufs):
    """The oracle: the native deterministic (TPU-greedy insert-all)
    encoder — bit-exact with the device kernel by construction."""
    return cpu.lz4f_compress_many([bytes(b) for b in bufs],
                                  deterministic=True)


def _cpu_crc_fallback(bufs, poly):
    prov = cpu.CpuCodecProvider()
    return (prov.crc32c_many(bufs) if poly == "crc32c"
            else prov.crc32_many(bufs))


def _mk_engine(**kw):
    from librdkafka_tpu.ops.engine import AsyncOffloadEngine
    kw.setdefault("depth", 2)
    kw.setdefault("min_batches", 1)
    kw.setdefault("cpu_fallback", _cpu_crc_fallback)
    kw.setdefault("cpu_compress_fallback", _det)
    kw.setdefault("warmup", False)
    return AsyncOffloadEngine(**kw)


@pytest.fixture
def dev_provider():
    # the device compress route, transport gate open; warmup off so
    # each test's engine closes before the conftest leak check
    prov = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=0, compress_device=True)
    yield prov
    prov.close()


# ------------------------------------------------------- FrameBlob unit --

def test_frameblob_region_crc_folds_exactly():
    """region_crc(prefix) must equal a byte-for-byte crc32c over
    prefix + frame — the writer patches the v2 batch CRC without ever
    re-scanning the frame the device produced."""
    rng = np.random.default_rng(1)
    raws = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (100, 65536, 7)]
    bodies = []
    for raw in raws:
        comp = cpu.lz4_block_compress(raw)
        bodies.append((comp, crc32c(comp), raw, crc32c(raw)))
    blob = lz4f_frame(bodies)
    assert isinstance(blob, FrameBlob)
    for prefix in (b"", b"hdr", b"\x00" * 61):
        assert blob.region_crc(prefix) == crc32c(prefix + bytes(blob))
    # and the assembled frame is the deterministic encoder's frame
    # when built from ITS blocks (store-raw rule included)


def test_lz4f_frame_empty_matches_native():
    assert bytes(lz4f_frame([])) == _det([b""])[0]


# ------------------------------------------------ engine device route ----

def test_engine_device_frames_bitexact_sweep():
    """The full ISSUE-17 sweep through submit_compress: staging rings,
    fused kernel, FrameBlob reassembly — frames byte-equal to the
    deterministic encoder, across ring-reuse rounds, and the fused CRC
    parts fold to the true crc32c of each frame."""
    eng = _mk_engine()
    try:
        sweep = _sweep()
        for round_ in range(3):
            batch = sweep[round_:] + sweep[:round_]
            got = eng.submit_compress(batch, window=False).result(300)
            want = _det(batch)
            assert [bytes(f) for f in got] == want, f"round {round_}"
            for f, src in zip(got, batch):
                assert f.region_crc() == crc32c(bytes(f))
                assert cpu.lz4_decompress(bytes(f), len(src)) == bytes(src)
        snap = eng.compress_snapshot()
        assert snap["launches"] >= 1 and snap["fused_crc"] >= 1, snap
        assert snap["bytes_in"] > 0 and snap["bytes_out"] > 0, snap
        assert any(v["device"] for v in snap["routed"].values()), snap
    finally:
        eng.close()
    assert lz4_jax.device_kernel_count() == 0


def test_engine_compress_below_quorum_serves_cpu_bitexact():
    """A group under min_batches is served on the deterministic CPU
    encoder (counted, never window-stalled) — same bytes as the device
    route by construction."""
    eng = _mk_engine(min_batches=4)
    try:
        bufs = [b"below-quorum " * 50]
        got = eng.submit_compress(bufs, window=False).result(60)
        assert [bytes(f) for f in got] == _det(bufs)
        assert eng.compress_stats["cpu_jobs"] >= 1
        assert eng.compress_stats["launches"] == 0
    finally:
        eng.close()


def test_engine_compress_governor_routes_and_explores():
    """The compress cost model mirrors the CRC one: with both sides
    measured, the jax-CPU 'device' launch (ms) loses to the native
    encoder (ns/byte) and at-quorum groups re-route to CPU; periodic
    exploration keeps the device estimate fresh — every route
    bit-exact."""
    eng = _mk_engine(min_batches=2, governor=True, fanin_window_s=0)
    try:
        rng = np.random.default_rng(2)
        bufs = [rng.integers(0, 256, 2048, dtype=np.uint8).tobytes(),
                b"governed " * 200]
        want = _det(bufs)
        # seed the device estimate (unknown model prefers device)...
        assert [bytes(f) for f in
                eng.submit_compress(bufs, window=False).result(300)] \
            == want
        # ...and the CPU estimate via a below-floor group
        assert [bytes(f) for f in
                eng.submit_compress(bufs[:1],
                                    window=False).result(60)] == want[:1]
        assert eng.compress_stats["cpu_jobs"] >= 1
        model = eng.governor.compress_models()
        assert model["cpu_ns_per_byte"] is not None
        assert model["dev_launch_ms"]
        routed = 0
        for _ in range(8):
            assert [bytes(f) for f in
                    eng.submit_compress(bufs,
                                        window=False).result(60)] == want
            routed = eng.compress_stats["routed_cpu_jobs"]
        assert routed >= 1, dict(eng.compress_stats)
        for _ in range(2 * eng.governor.EXPLORE_EVERY):
            assert [bytes(f) for f in
                    eng.submit_compress(bufs,
                                        window=False).result(60)] == want
        assert eng.compress_stats["explore_routes"] >= 1, \
            dict(eng.compress_stats)
        snap = eng.compress_snapshot()
        assert any(v["cpu"] for v in snap["routed"].values()), snap
    finally:
        eng.close()


def test_engine_compress_warm_gate_routes_cpu_then_device():
    """With background warmup on, a bucket whose fused kernel is still
    compiling is served by the deterministic CPU encoder (counted as
    warmup_miss_jobs) instead of stalling the dispatch thread; once
    warm, the same shape rides a device launch."""
    eng = _mk_engine(warmup=True)
    try:
        bufs = [b"warm-gate " * 80]              # ~800B -> N=1024, B=8
        want = _det(bufs)
        t0 = time.perf_counter()
        assert [bytes(f) for f in
                eng.submit_compress(bufs, window=False).result(60)] \
            == want
        first_latency = time.perf_counter() - t0
        assert (eng.compress_stats["warmup_miss_jobs"] >= 1
                or eng.compress_stats["launches"] >= 1)
        assert eng.lz4_warm_wait(8, 1024, 180), \
            "warmup never compiled the missed lz4 bucket"
        before = eng.compress_stats["launches"]
        assert [bytes(f) for f in
                eng.submit_compress(bufs, window=False).result(60)] \
            == want
        assert eng.compress_stats["launches"] == before + 1, \
            "warmed lz4 bucket did not ride a device launch"
        assert first_latency < 30, "first submission stalled on compile"
    finally:
        eng.close()
    assert lz4_jax.device_kernel_count() == 0


def test_engine_close_with_inflight_compress_resolves_tickets():
    """close() racing queued compress jobs: every ticket resolves
    (result or error), nothing hangs — the shutdown sweep covers lz4
    launches exactly like CRC ones."""
    eng = _mk_engine()
    bufs = [b"drain " * 100] * 3
    tickets = [eng.submit_compress(bufs, window=False) for _ in range(4)]
    eng.close()
    for t in tickets:
        assert t.done(), "compress ticket left unresolved after close()"
        try:
            out = t.result(0)
        except RuntimeError:
            continue                  # failed-by-shutdown is acceptable
        assert [bytes(f) for f in out] == _det(bufs)
    assert lz4_jax.device_kernel_count() == 0


# ------------------------------------------------------ governor QoS -----

def test_governor_qos_shed_model():
    """shed_topics: only under saturation, only over-share topics
    (byte share > 1.5x weight share), never the whole set, tracked
    per topic in qos_snapshot."""
    from librdkafka_tpu.ops.engine import _Governor
    g = _Governor(True, 0.0)
    # bulk hogs 99% of recent bytes with 3% of the weight
    g.note_topics([("bulk", 0.25, 990_000), ("lat", 8.0, 10_000)])
    assert g.shed_topics(saturated=False) == set()
    shed = g.shed_topics(saturated=True)
    assert shed == {"bulk"}, shed
    g.note_qos(("bulk",), shed=True)
    g.note_qos(("lat",), shed=False)
    snap = g.qos_snapshot()
    assert snap["bulk"] == {"weight": 0.25, "routed": 0, "shed": 1}
    assert snap["lat"]["routed"] == 1 and snap["lat"]["shed"] == 0
    # a single topic is never shed (nothing to isolate it FROM)
    g2 = _Governor(True, 0.0)
    g2.note_topics([("only", 1.0, 500_000)])
    assert g2.shed_topics(saturated=True) == set()
    # balanced topics: no one exceeds 1.5x their fair share
    g3 = _Governor(True, 0.0)
    g3.note_topics([("a", 1.0, 100_000), ("b", 1.0, 100_000)])
    assert g3.shed_topics(saturated=True) == set()
    # disabled governor never sheds
    g4 = _Governor(False, 0.0)
    g4.note_topics([("bulk", 0.25, 990_000), ("lat", 8.0, 10_000)])
    assert g4.shed_topics(saturated=True) == set()


def test_engine_shed_serves_overshare_topic_on_cpu_bitexact():
    """An over-share topic's jobs divert to the deterministic CPU
    encoder when every lane is saturated — same bytes, counted as
    shed_jobs, never shedding the whole group."""
    eng = _mk_engine(governor=True)
    try:
        # real launch first so the lanes exist and _lanes_ready is set
        init = [b"lane-init " * 60]
        assert [bytes(f) for f in
                eng.submit_compress(init, window=False).result(300)] \
            == _det(init)
        # make the governor see bulk as an extreme over-share topic,
        # and force the saturation read (instead of racing real
        # launches against the depth limit)
        eng.governor.note_topics([("bulk", 0.25, 10_000_000),
                                  ("lat", 8.0, 1_000)])
        eng._inflight_total = lambda: 10**9
        bulk = [b"\xa5" * 4000]
        lat = [b"latency " * 100]
        t_b = eng.submit_compress(bulk, qos=[("bulk", 0.25)],
                                  window=True)
        t_l = eng.submit_compress(lat, qos=[("lat", 8.0)], window=True)
        assert [bytes(f) for f in t_b.result(120)] == _det(bulk)
        assert [bytes(f) for f in t_l.result(120)] == _det(lat)
        snap = eng.compress_snapshot()
        # bulk diverted (when the two jobs shared a dispatch pop);
        # either way every byte is exact and lat was never shed
        assert snap["qos"].get("lat", {}).get("shed", 0) == 0, snap
    finally:
        # un-forge the saturation read: the dispatch loop's shutdown
        # condition polls it, a forever-huge count would hang close()
        eng.__dict__.pop("_inflight_total", None)
        eng.close()


def test_qos_weight_conf_roundtrip():
    """topic.qos.weight: a topic-scope float row with range validation,
    reaching the broker's writer phase via topic_conf_for."""
    from librdkafka_tpu.client.conf import Conf, TopicConf
    from librdkafka_tpu.client.errors import KafkaException

    tc = TopicConf()
    assert tc.get("topic.qos.weight") == 1.0
    tc.set("topic.qos.weight", "8.5")
    assert tc.get("topic.qos.weight") == 8.5
    with pytest.raises(KafkaException):
        tc.set("topic.qos.weight", 0.0)          # below vmin
    with pytest.raises(KafkaException):
        tc.set("topic.qos.weight", 1e6)          # above vmax
    # global-conf fallthrough routes the topic-only name to the
    # default topic conf (the reference's fallthrough behavior)
    c = Conf()
    c.set("topic.qos.weight", 2.5)
    assert c.get("default_topic_conf").get("topic.qos.weight") == 2.5


# ------------------------------------------------- provider + writer -----

def test_provider_compress_submit_routes(dev_provider):
    """accepts_qos is declared, lz4 rides the device route, non-lz4
    codecs stay host jobs, and with compress_device off lz4 is a host
    job too — every ticket bit-exact for its own route's contract."""
    assert getattr(dev_provider, "accepts_qos", False) is True
    bufs = [b"route-check " * 60]
    t = dev_provider.compress_submit("lz4", bufs,
                                     qos=[("t", 2.0)])
    assert t is not None
    got = t.result(300)
    assert [bytes(f) for f in got] == _det(bufs)
    assert isinstance(got[0], FrameBlob)
    # non-lz4: host-job route (CpuCodecProvider semantics)
    t2 = dev_provider.compress_submit("gzip", bufs, qos=[("t", 2.0)])
    assert t2.result(60) == cpu.CpuCodecProvider().compress_many(
        "gzip", bufs)
    # device route off: lz4 host job returns the provider's
    # compress_many bytes (the native fast parse), not FrameBlobs
    host = TpuCodecProvider(min_batches=1, warmup=False,
                            min_transport_mb_s=0)
    try:
        t3 = host.compress_submit("lz4", bufs, qos=[("t", 1.0)])
        out = t3.result(60)
        assert out == host.compress_many("lz4", bufs)
        assert not isinstance(out[0], FrameBlob)
    finally:
        host.close()


def _writer_wire(blob_source, msgs, now, *, idemp=False) -> bytes:
    """Writer-level build: compress via ``blob_source``, patch the CRC
    the way broker._assemble_and_submit_crc does (FrameBlob fold vs
    full-region scan) — wire bytes must agree between sources."""
    from librdkafka_tpu.protocol.msgset import MsgsetWriterV2

    kw = dict(producer_id=9, producer_epoch=2,
              base_sequence=100) if idemp else {}
    w = MsgsetWriterV2(codec="lz4", **kw)
    w.build(msgs, now)
    blob = blob_source(w.records_bytes)
    if blob is not None and len(blob) >= len(w.records_bytes):
        blob, w.codec = None, None
    region = w.assemble(blob)
    if isinstance(blob, FrameBlob):
        crc = blob.region_crc(bytes(region[:len(region) - len(blob)]))
    else:
        crc = crc32c(bytes(region))
    return w.patch_crc(crc)


@pytest.mark.parametrize("idemp", [False, True], ids=["plain", "idemp"])
def test_wire_bitexact_device_vs_cpu_with_headers(dev_provider, idemp):
    """The tentpole gate at writer level: identical MessageSet v2 wire
    bytes (CRC included) whether the lz4 frame + CRC came from the
    fused device route or the deterministic CPU encoder — across the
    size sweep, with record headers, plain and idempotent."""
    from librdkafka_tpu.protocol.msgset import Record

    now = 1_700_000_000_000
    for payload in _sweep():
        msgs = [Record(key=b"k%d" % i, value=bytes(payload),
                       timestamp=now + i,
                       headers=[("h1", b"v1"), ("trace", b"\x00\x01")])
                for i in range(3)]

        def dev(records_bytes):
            t = dev_provider.compress_submit("lz4", [records_bytes],
                                             qos=[("sweep", 1.0)])
            assert t is not None
            return t.result(300)[0]

        def cpu_det(records_bytes):
            return _det([records_bytes])[0]

        got = _writer_wire(dev, msgs, now, idemp=idemp)
        want = _writer_wire(cpu_det, msgs, now, idemp=idemp)
        assert got == want, f"wire diverged for {len(payload)}B payload"


# ----------------------------------------------------------- e2e ---------

def test_e2e_device_route_roundtrip_and_stats():
    """Producer with tpu.compress.device=true: the produce path shows
    device compress launches > 0 (the acceptance counter), the stored
    batches decode to the produced payloads through a CRC-checking
    consumer, and the per-topic QoS tallies surface in stats."""
    import json

    from librdkafka_tpu import Consumer, Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.compress.device": True,
                  "tpu.launch.min.batches": 1,
                  "tpu.governor": False, "tpu.warmup": False,
                  "compression.codec": "lz4", "linger.ms": 5})
    n = 50
    vals = [(b"payload-%04d-" % i) * 40 for i in range(n)]
    try:
        for i, v in enumerate(vals):
            p.produce("devtp", value=v, key=b"k%d" % i)
        assert p.flush(120.0) == 0
        blob = json.loads(p._rk.stats.emit_json())
        comp = blob["codec_engine"]["compress"]
        assert comp["launches"] >= 1, comp
        assert comp["fused_crc"] >= 1, comp
        assert comp["bytes_in"] > 0 and comp["bytes_out"] > 0, comp
        assert comp["qos"]["devtp"]["routed"] >= 1, comp
        bs = p._rk.mock_cluster.bootstrap_servers()
        c = Consumer({"bootstrap.servers": bs, "group.id": "g-dev",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["devtp"])
        got = {}
        deadline = time.time() + 30
        while len(got) < n and time.time() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None:
                got[bytes(m.key)] = bytes(m.value)
        c.close()
        assert len(got) == n, len(got)
        for i, v in enumerate(vals):
            assert got[b"k%d" % i] == v
    finally:
        p.close()
    assert lz4_jax.device_kernel_count() == 0


def test_hot_topic_flood_qos_isolation():
    """The ISSUE-17 acceptance scenario as a tier-1 smoke: zipf bulk
    flood vs a weight-8 latency topic — flooded p99 within the bound,
    every latency message acked, bulk still progressing."""
    from librdkafka_tpu.chaos.scenarios import hot_topic_flood

    t0 = time.monotonic()
    r = hot_topic_flood(17, flood_s=1.5)
    assert r["ok"], r
    assert r["latency_acked"] == r["latency_sent"], r
    assert r["bulk_acked"] > 0, r
    assert r["qos"]["qos-latency"]["weight"] == 8.0, r
    assert time.monotonic() - t0 < 60, "flood smoke budget blown"
