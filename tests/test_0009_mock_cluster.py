"""Mock cluster smoke tests via raw sockets (reference: 0009-mock_cluster.c):
the mock must act as a protocol oracle — produced wire bytes come back from
Fetch verbatim (modulo the broker's BaseOffset patch)."""
import socket
import struct

import pytest

from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.ops import cpu
from librdkafka_tpu.protocol import apis, proto
from librdkafka_tpu.protocol.msgset import (MsgsetWriterV2, Record,
                                            iter_batches, parse_records_v2,
                                            verify_crc_v2)
from librdkafka_tpu.protocol.proto import ApiKey
from librdkafka_tpu.client.errors import Err

NOW = 1_690_000_000_000


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=3, topics={"t1": 4})
    yield c
    c.stop()


class RawClient:
    """Minimal blocking protocol client for oracle tests."""

    def __init__(self, host_port: str):
        host, port = host_port.split(":")
        self.sock = socket.create_connection((host, int(port)), timeout=5)
        self.corrid = 0

    def call(self, api: ApiKey, body: dict) -> dict:
        self.corrid += 1
        self.sock.sendall(apis.build_request(api, self.corrid, "raw", body))
        hdr = self._recvn(4)
        (n,) = struct.unpack(">i", hdr)
        payload = self._recvn(n)
        corrid, resp = apis.parse_response(api, payload)
        assert corrid == self.corrid
        return resp

    def _recvn(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("eof")
            out += chunk
        return out

    def close(self):
        self.sock.close()


def broker_client(cluster, broker_id) -> RawClient:
    addr = cluster.bootstrap_servers().split(",")[broker_id - 1]
    return RawClient(addr)


def test_apiversions_and_metadata(cluster):
    c = broker_client(cluster, 1)
    try:
        vers = c.call(ApiKey.ApiVersions, {})
        assert vers["error_code"] == 0
        keys = {v["api_key"] for v in vers["api_versions"]}
        assert int(ApiKey.Produce) in keys and int(ApiKey.Fetch) in keys

        md = c.call(ApiKey.Metadata, {"topics": ["t1"]})
        assert len(md["brokers"]) == 3
        t = md["topics"][0]
        assert t["topic"] == "t1" and len(t["partitions"]) == 4
    finally:
        c.close()


def produce_fetch_roundtrip(cluster, codec):
    # find partition 0's leader
    part = cluster.partition("t1", 0)
    c = broker_client(cluster, part.leader)
    try:
        msgs = [Record(key=b"k%d" % i, value=b"payload-%d-" % i + b"z" * 100,
                       timestamp=NOW + i) for i in range(17)]
        w = MsgsetWriterV2(codec=codec)
        compress = (lambda b: cpu.CODECS[codec][0](b)) if codec else None
        wire = w.write_batch(msgs, NOW, compress)

        resp = c.call(ApiKey.Produce, {
            "transactional_id": None, "acks": -1, "timeout": 5000,
            "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "records": wire}]}]})
        pres = resp["topics"][0]["partitions"][0]
        assert pres["error_code"] == 0
        assert pres["base_offset"] == 0

        fresp = c.call(ApiKey.Fetch, {
            "replica_id": -1, "max_wait_time": 1000, "min_bytes": 1,
            "max_bytes": 1 << 20, "isolation_level": 1,
            "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "fetch_offset": 0, "max_bytes": 1 << 20}]}]})
        fpart = fresp["topics"][0]["partitions"][0]
        assert fpart["error_code"] == 0
        assert fpart["high_watermark"] == 17
        # ORACLE: fetched bytes == produced bytes (BaseOffset was 0 already)
        assert fpart["records"] == wire

        info, payload, full = next(iter_batches(fpart["records"]))
        assert verify_crc_v2(info, full)
        if info.codec:
            payload = cpu.CODECS[info.codec][1](payload, 0)
        recs = parse_records_v2(info, payload)
        assert [r.value for r in recs] == [m.value for m in msgs]
        assert [r.offset for r in recs] == list(range(17))
    finally:
        c.close()


@pytest.mark.parametrize("codec", [None, "lz4", "snappy", "gzip", "zstd"])
def test_produce_fetch_wire_oracle(cluster, codec):
    if codec == "zstd":
        from conftest import require_zstd
        require_zstd()
    produce_fetch_roundtrip(cluster, codec)


def test_base_offset_patching(cluster):
    part = cluster.partition("t1", 1)
    c = broker_client(cluster, part.leader)
    try:
        for batch_i in range(3):
            w = MsgsetWriterV2()
            wire = w.write_batch([Record(value=b"b%d-%d" % (batch_i, j))
                                  for j in range(5)], NOW)
            resp = c.call(ApiKey.Produce, {
                "transactional_id": None, "acks": -1, "timeout": 5000,
                "topics": [{"topic": "t1", "partitions": [
                    {"partition": 1, "records": wire}]}]})
            assert resp["topics"][0]["partitions"][0]["base_offset"] == batch_i * 5

        fresp = c.call(ApiKey.Fetch, {
            "replica_id": -1, "max_wait_time": 100, "min_bytes": 1,
            "max_bytes": 1 << 20, "isolation_level": 1,
            "topics": [{"topic": "t1", "partitions": [
                {"partition": 1, "fetch_offset": 5, "max_bytes": 1 << 20}]}]})
        blob = fresp["topics"][0]["partitions"][0]["records"]
        offs = []
        for info, payload, full in iter_batches(blob):
            assert verify_crc_v2(info, full)  # CRC survives offset patching
            offs.extend(r.offset for r in parse_records_v2(info, payload))
        assert offs == list(range(5, 15))
    finally:
        c.close()


def test_error_injection_and_leader_error(cluster):
    part = cluster.partition("t1", 0)
    non_leader = part.leader % 3 + 1
    c = broker_client(cluster, non_leader)
    try:
        wire = MsgsetWriterV2().write_batch([Record(value=b"x")], NOW)
        resp = c.call(ApiKey.Produce, {
            "transactional_id": None, "acks": -1, "timeout": 5000,
            "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "records": wire}]}]})
        assert (resp["topics"][0]["partitions"][0]["error_code"]
                == Err.NOT_LEADER_FOR_PARTITION.wire)

        cluster.push_request_errors(ApiKey.ListOffsets,
                                    [Err.REQUEST_TIMED_OUT])
        r1 = c.call(ApiKey.ListOffsets, {
            "replica_id": -1, "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "timestamp": -1}]}]})
        assert (r1["topics"][0]["partitions"][0]["error_code"]
                == Err.REQUEST_TIMED_OUT.wire)
        r2 = c.call(ApiKey.ListOffsets, {
            "replica_id": -1, "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "timestamp": -1}]}]})
        assert r2["topics"][0]["partitions"][0]["error_code"] == 0
    finally:
        c.close()


def test_idempotent_sequence_checks(cluster):
    part = cluster.partition("t1", 2)
    c = broker_client(cluster, part.leader)
    try:
        pid = c.call(ApiKey.InitProducerId,
                     {"transactional_id": None,
                      "transaction_timeout_ms": 60000})
        assert pid["error_code"] == 0 and pid["producer_id"] >= 1

        def produce(seq):
            w = MsgsetWriterV2(producer_id=pid["producer_id"],
                               producer_epoch=pid["producer_epoch"],
                               base_sequence=seq)
            wire = w.write_batch([Record(value=b"s%d" % seq)], NOW)
            r = c.call(ApiKey.Produce, {
                "transactional_id": None, "acks": -1, "timeout": 5000,
                "topics": [{"topic": "t1", "partitions": [
                    {"partition": 2, "records": wire}]}]})
            return r["topics"][0]["partitions"][0]["error_code"]

        assert produce(0) == 0
        assert produce(1) == 0
        assert produce(1) == Err.DUPLICATE_SEQUENCE_NUMBER.wire   # replay
        assert produce(5) == Err.OUT_OF_ORDER_SEQUENCE_NUMBER.wire  # gap
        assert produce(2) == 0
    finally:
        c.close()


def test_group_join_sync_single_member(cluster):
    coord = cluster.coordinator_for("g1")
    c = broker_client(cluster, coord)
    try:
        fc = c.call(ApiKey.FindCoordinator, {"key": "g1", "key_type": 0})
        assert fc["error_code"] == 0 and fc["node_id"] == coord

        j = c.call(ApiKey.JoinGroup, {
            "group_id": "g1", "session_timeout": 10000,
            "rebalance_timeout": 3000, "member_id": "",
            "group_instance_id": None,
            "protocol_type": "consumer",
            "protocols": [{"name": "range", "metadata": b"MD"}]})
        assert j["error_code"] == 0
        assert j["leader_id"] == j["member_id"]
        assert j["members"][0]["metadata"] == b"MD"

        s = c.call(ApiKey.SyncGroup, {
            "group_id": "g1", "generation_id": j["generation_id"],
            "member_id": j["member_id"],
            "assignments": [{"member_id": j["member_id"],
                             "assignment": b"ASSIGN"}]})
        assert s["error_code"] == 0 and s["assignment"] == b"ASSIGN"

        h = c.call(ApiKey.Heartbeat, {
            "group_id": "g1", "generation_id": j["generation_id"],
            "member_id": j["member_id"]})
        assert h["error_code"] == 0

        c.call(ApiKey.OffsetCommit, {
            "group_id": "g1", "generation_id": j["generation_id"],
            "member_id": j["member_id"], "retention_time": -1,
            "topics": [{"topic": "t1", "partitions": [
                {"partition": 0, "offset": 42, "metadata": None}]}]})
        of = c.call(ApiKey.OffsetFetch, {
            "group_id": "g1",
            "topics": [{"topic": "t1", "partitions": [0, 1]}]})
        parts = {p["partition"]: p["offset"]
                 for p in of["topics"][0]["partitions"]}
        assert parts == {0: 42, 1: -1}
    finally:
        c.close()


def test_admin_ops(cluster):
    c = broker_client(cluster, 1)
    try:
        r = c.call(ApiKey.CreateTopics, {
            "topics": [{"topic": "newt", "num_partitions": 2,
                        "replication_factor": 1, "replica_assignment": [],
                        "configs": []}],
            "timeout": 1000, "validate_only": False})
        assert r["topics"][0]["error_code"] == 0
        r2 = c.call(ApiKey.CreateTopics, {
            "topics": [{"topic": "newt", "num_partitions": 2,
                        "replication_factor": 1, "replica_assignment": [],
                        "configs": []}],
            "timeout": 1000, "validate_only": False})
        assert r2["topics"][0]["error_code"] == Err.TOPIC_ALREADY_EXISTS.wire

        r3 = c.call(ApiKey.CreatePartitions, {
            "topics": [{"topic": "newt", "count": 5, "assignment": None}],
            "timeout": 1000, "validate_only": False})
        assert r3["topics"][0]["error_code"] == 0
        md = c.call(ApiKey.Metadata, {"topics": ["newt"]})
        assert len(md["topics"][0]["partitions"]) == 5

        r4 = c.call(ApiKey.DeleteTopics, {"topics": ["newt"], "timeout": 100})
        assert r4["topics"][0]["error_code"] == 0
    finally:
        c.close()
