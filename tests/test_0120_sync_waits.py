"""Sync request/response paths must block on condvars, not sleep-poll.

Round-2/3 verdicts flagged sleep-polled waits in commit/committed/
offsets_for_times/list_topics/flush; they now ride SyncReply /
metadata_wait / the outq condvar (the reference's replyq-pop pattern,
rd_kafka_q_serve, rdkafka_queue.c:431). This test grep-enforces that
they stay gone — the same style of proof as test_0110's zero-dead-rows.
"""
import pathlib
import re
import threading
import time

import pytest

from librdkafka_tpu.mock.cluster import MockCluster

CLIENT = pathlib.Path(__file__).parent.parent / "librdkafka_tpu" / "client"

# The only time.sleep allowed in client/: broker.py's crash-recovery
# backoff after an unexpected serve exception (not a request/response
# wait — it rate-limits a broken broker thread's restart loop).
ALLOWED = {"broker.py": 1}


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=2, topics={"t0120": 2, "t0120f": 1})
    yield c
    c.stop()


def test_no_sleep_poll_in_client():
    found = {}
    for py in sorted(CLIENT.glob("*.py")):
        n = len(re.findall(r"time\.sleep\(", py.read_text()))
        if n:
            found[py.name] = n
    assert found == ALLOWED, (
        f"sleep-polling crept back into client/: {found} "
        f"(allowed: {ALLOWED})")


def test_commit_wakes_without_poll_period(cluster):
    """A synchronous commit returns as soon as the reply arrives (condvar
    wake), well under the old 5ms-poll-ladder ceiling."""
    from librdkafka_tpu import Consumer, Producer

    bs = cluster.bootstrap_servers()
    p = Producer({"bootstrap.servers": bs})
    for i in range(10):
        p.produce("t0120", value=b"m%d" % i, partition=0)
    assert p.flush(5) == 0
    c = Consumer({"bootstrap.servers": bs, "group.id": "g0120",
                  "auto.offset.reset": "earliest",
                  "enable.auto.commit": False})
    c.subscribe(["t0120"])
    got = 0
    deadline = time.monotonic() + 15
    while got < 10 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m and not m.error:
            got += 1
    assert got == 10
    t0 = time.monotonic()
    res = c.commit(asynchronous=False)
    dt = time.monotonic() - t0
    assert res, "commit returned no offsets"
    # condvar wake: the bound here is one mock-broker round trip, not a
    # whole poll ladder; generous cap for a loaded host
    assert dt < 2.0, f"sync commit took {dt:.3f}s"
    committed = c.committed(res, timeout=5.0)
    by_part = {tp.partition: tp.offset for tp in committed}
    assert by_part[0] == 10
    c.close()
    p.close()


def test_flush_event_mode_wakes(cluster):
    """flush() in DR-event mode returns promptly once another thread
    drains the DR events (the condvar path, not the 10ms sleep)."""
    from librdkafka_tpu import Producer

    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "enabled_events": ["dr"]})
    for i in range(50):
        p.produce("t0120f", value=b"x" * 100, partition=0)

    stop = threading.Event()

    def drain():
        while not stop.is_set():
            p.rk.queue_poll(0.05)

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    try:
        left = p.flush(10)
        assert left == 0
    finally:
        stop.set()
        t.join(2)
    p.close()
