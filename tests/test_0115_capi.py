"""C ABI binding tier: build libtkafka.so (cffi embedding), compile the
C smoke program against tkafka.h, and run a full produce→consume round
trip driven from C — the rebuild's counterpart of the reference's
second-language binding (src-cpp/rdkafkacpp.h over src/rdkafka.h)."""
import os
import subprocess
import sys
import sysconfig

import pytest

from librdkafka_tpu.capi import build_capi

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def libtkafka():
    try:
        so = build_capi.build()
    except Exception as e:
        pytest.skip(f"capi build unavailable: {e}")
    return so


def test_c_program_round_trip(libtkafka):
    exe = os.path.join(build_capi.HERE, "capi_smoke")
    src = os.path.join(HERE, "capi_smoke.c")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    subprocess.run(
        ["gcc", "-O1", "-o", exe, src,
         "-I", build_capi.HERE,
         "-L", build_capi.HERE, "-ltkafka",
         f"-Wl,-rpath,{build_capi.HERE}",
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True)
    env = dict(os.environ)
    # the embedded interpreter must see the repo package
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([exe], capture_output=True, text=True, timeout=120,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "CAPI-OK" in r.stdout and "all pass" in r.stdout


def test_header_is_self_contained(libtkafka):
    """tkafka.h must compile standalone under -std=c99."""
    src = os.path.join(build_capi.HERE, "_hdrcheck.c")
    with open(src, "w") as f:
        f.write('#include "tkafka.h"\nint main(void){return 0;}\n')
    try:
        subprocess.run(
            ["gcc", "-std=c99", "-fsyntax-only", "-I", build_capi.HERE,
             src],
            check=True, capture_output=True)
    finally:
        os.unlink(src)


def test_cpp_wrapper_round_trip(libtkafka):
    """The C++ RAII wrapper (tkafka.hpp, the src-cpp/rdkafkacpp.h
    analog): compile examples/cpp_client.cpp with g++ and run the full
    produce->consume round trip — DeliveryReportCb, EventCb (stats),
    raw-byte headers, commit/committed."""
    exe = os.path.join(build_capi.HERE, "cpp_client")
    src = os.path.join(os.path.dirname(HERE), "examples", "cpp_client.cpp")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-o", exe, src,
         "-I", build_capi.HERE,
         "-L", build_capi.HERE, "-ltkafka",
         f"-Wl,-rpath,{build_capi.HERE}",
         f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([exe], capture_output=True, text=True, timeout=120,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "CPP-OK" in r.stdout
