"""Legacy MsgVer0/1 per-message CRC verification (reference:
src/rdcrc32.c zlib-poly CRC + rdkafka_msgset_reader.c v0/v1 parse):
batched through the provider's crc32_many — CPU (zlib) or the one-
matmul MXU GF(2) kernel (poly-agnostic) — and wired into the fetch
phase-B verify like the v2 CRC32C path."""
import time
import zlib

import numpy as np
import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.ops.cpu import CpuCodecProvider
from librdkafka_tpu.ops.crc32c_jax import crc32_many_mxu
from librdkafka_tpu.ops.tpu import TpuCodecProvider
from librdkafka_tpu.protocol.msgset import iter_legacy_crc_regions


def test_crc32_mxu_bit_exact_vs_zlib():
    rng = np.random.default_rng(11)
    bufs = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (0, 1, 13, 255, 4096, 65536, 65537, 150000)]
    got = [int(x) for x in crc32_many_mxu(bufs)]
    assert got == [zlib.crc32(b) & 0xFFFFFFFF for b in bufs]


def test_provider_crc32_many_parity():
    rng = np.random.default_rng(12)
    bufs = [rng.integers(0, 256, 400, dtype=np.uint8).tobytes()
            for _ in range(9)]
    cpu = CpuCodecProvider().crc32_many(bufs)
    prov = TpuCodecProvider(min_batches=1, min_transport_mb_s=0)
    # first call serves from CPU while the device kernel warms in the
    # background; wait for the route to open, then exercise it
    first = prov.crc32_many(bufs)
    deadline = time.monotonic() + 120
    while not prov._crc32_ready and time.monotonic() < deadline:
        time.sleep(0.1)
    assert prov._crc32_ready, "crc32 device kernel never became ready"
    tpu = prov.crc32_many(bufs)
    # the async mirror resolves to the same values through the engine
    ticket = prov.crc32_submit(bufs)
    assert ticket is not None
    got_async = ticket.result(120).tolist()
    prov.close()
    assert first == cpu
    assert cpu == tpu == got_async == \
        [zlib.crc32(b) & 0xFFFFFFFF for b in bufs]


def test_crc32_submit_rides_device_engine():
    """ISSUE 3 satellite: with the engine warmup landed, crc32_submit
    rides _jit_mxu(poly='crc32') end to end — submissions enter the
    engine immediately (the warmup gate serves from the CPU provider,
    bit-exact, until the bucket kernel compiles) instead of returning
    None for unconditional CPU service; once the bucket is warm the
    same shape is a device launch, bit-exact on zlib-poly CRCs."""
    rng = np.random.default_rng(14)
    bufs = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (5, 700, 4096, 70000)]
    want = [zlib.crc32(b) & 0xFFFFFFFF for b in bufs]
    prov = TpuCodecProvider(min_batches=1, min_transport_mb_s=0,
                            warmup=False, engine_warmup=True)
    try:
        t = prov.crc32_submit(bufs)
        assert t is not None, \
            "crc32_submit fell back to CPU service with warmup on"
        assert t.result(120).tolist() == want
        eng = prov._engine
        assert eng.warm_wait(64, "crc32", 180), \
            "engine warmup never compiled the crc32 bucket"
        before = eng.stats["launches"]
        t2 = prov.crc32_submit(bufs)
        assert t2.result(120).tolist() == want
        assert eng.stats["launches"] == before + 1, \
            "warmed crc32 bucket did not ride a device launch"
    finally:
        prov.close()


def _legacy_cluster(bver="0.10.0"):
    return MockCluster(num_brokers=1, topics={"old": 1},
                       broker_version=bver)


def _produce_legacy(cluster, n=20, bver="0.10.0"):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "broker.version.fallback": bver, "linger.ms": 5})
    for i in range(n):
        p.produce("old", value=b"legacy-%02d" % i, partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_iter_legacy_crc_regions_matches_stored():
    cluster = _legacy_cluster()
    try:
        _produce_legacy(cluster)
        blobs = [b for _o, b in cluster.partition("old", 0).log]
        n = 0
        for blob in blobs:
            for off, crc, region in iter_legacy_crc_regions(blob):
                assert zlib.crc32(region) & 0xFFFFFFFF == crc
                n += 1
        assert n == 20
    finally:
        cluster.stop()


def test_corrupted_legacy_message_rejected():
    cluster = _legacy_cluster()
    try:
        _produce_legacy(cluster)
        part = cluster.partition("old", 0)
        base, blob = part.log[0]
        corrupt = bytearray(blob)
        corrupt[-2] ^= 0xFF              # flip a payload bit
        part.log[0] = (base, bytes(corrupt))

        errs = []
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": "0.10.0",
                      "group.id": "glegcrc",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True,
                      "error_cb": lambda e: errs.append(e)})
        c.subscribe(["old"])
        got = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not errs:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append(m)
        c.close()
        assert any(e.code == Err._BAD_MSG for e in errs), errs
        assert not got, "corrupted legacy message must not be delivered"
    finally:
        cluster.stop()


def test_clean_legacy_passes_check_crcs():
    cluster = _legacy_cluster()
    try:
        _produce_legacy(cluster)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": "0.10.0",
                      "group.id": "glegok",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["old"])
        got = []
        deadline = time.monotonic() + 20
        while len(got) < 20 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append(m.value)
        c.close()
        assert sorted(got) == sorted(b"legacy-%02d" % i for i in range(20))
    finally:
        cluster.stop()
