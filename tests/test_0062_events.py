"""Event API + background queue tests (reference: rdkafka_event.c typed
events + 0062-stats_event.c; background thread rdkafka_background.c:109):
queue_poll returns typed events as an alternative to callback dispatch,
and background_event_cb serves DR/STATS/ERROR events from a dedicated
thread with no app polling at all."""
import json
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.event import (EVENT_DR, EVENT_ERROR, EVENT_LOG,
                                         EVENT_STATS)


def test_queue_poll_typed_dr_events():
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 2, "enabled_events": "dr"})
    for i in range(5):
        p.produce("ev", value=b"e%d" % i, partition=0)
    # drain DR events via queue_poll instead of poll()+callback
    got = []
    deadline = time.monotonic() + 10
    while len(got) < 5 and time.monotonic() < deadline:
        ev = p.rk.queue_poll(0.2)
        if ev is None:
            continue
        if ev.type == EVENT_DR:
            got.extend(ev.messages())
    assert len(got) == 5
    assert all(m.error is None for m in got)
    assert sorted(m.value for m in got) == [b"e%d" % i for i in range(5)]
    p.close()


def test_background_event_thread_serves_without_polling():
    events = []
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 2, "statistics.interval.ms": 150,
                  "background_event_cb": lambda ev: events.append(ev)})
    for i in range(10):
        p.produce("bg", value=b"b%d" % i, partition=0)
    # NO poll() calls at all: the background thread must deliver
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        drs = [m for e in events if e.type == EVENT_DR for m in e.messages()]
        stats = [e for e in events if e.type == EVENT_STATS]
        if len(drs) >= 10 and stats:
            break
        time.sleep(0.05)
    p.close()
    drs = [m for e in events if e.type == EVENT_DR for m in e.messages()]
    stats = [e for e in events if e.type == EVENT_STATS]
    assert len(drs) == 10, f"background DRs: {len(drs)}"
    assert stats and json.loads(stats[0].stats())["type"] == "producer"


def test_error_event_type():
    events = []
    p = Producer({"bootstrap.servers": "127.0.0.1:1",  # nothing listening
                  "message.timeout.ms": 1200,
                  "background_event_cb": lambda ev: events.append(ev)})
    p.produce("never", value=b"x", partition=0)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if any(e.type == EVENT_DR for e in events):
            break
        time.sleep(0.05)
    p.close()
    dr = [m for e in events if e.type == EVENT_DR for m in e.messages()]
    assert dr and dr[0].error is not None


def test_io_event_fd_wakeup():
    """0040-io_event: with io_event_enable(fd), every op landing on the
    app-facing queue writes the payload byte to the fd, so an app can
    select() on it alongside its own fds (reference
    rd_kafka_queue_io_event_enable, rdkafka_queue.h:294)."""
    import os
    import select as _select

    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.mock.cluster import MockCluster

    cluster = MockCluster(num_brokers=1, topics={"ioe": 1})
    try:
        r, w = os.pipe()
        os.set_blocking(w, False)
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 2,
                      "dr_msg_cb": lambda e, m: None})
        p.io_event_enable(w, b"D")
        p.produce("ioe", value=b"x", partition=0)
        ready, _, _ = _select.select([r], [], [], 10.0)
        assert ready, "no io-event for the DR op"
        assert os.read(r, 16)[:1] == b"D"
        p.flush(10.0)
        p.close()

        r2, w2 = os.pipe()
        os.set_blocking(w2, False)
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gioe",
                      "auto.offset.reset": "earliest"})
        c.io_event_enable(w2, b"M")
        c.subscribe(["ioe"])
        ready, _, _ = _select.select([r2], [], [], 15.0)
        assert ready, "no io-event for the fetch op"
        assert b"M" in os.read(r2, 64)
        m = c.poll(5.0)
        assert m is not None
        c.close()
        for fd in (r, w, r2, w2):
            os.close(fd)
    finally:
        cluster.stop()
