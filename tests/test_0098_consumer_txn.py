"""read_committed / aborted-transaction filtering tests (reference:
0098-consumer-txn.cpp, driven by the TransactionProducerCli Java
fixture; reader logic rdkafka_msgset_reader.c:1050-1120 + :1442-1560).
v1.3.0 has no transactional PRODUCER — the consumer-side contract is
what matters: transactional batches listed in aborted_transactions must
be invisible under isolation.level=read_committed, control records are
never delivered, and read_uncommitted sees everything. The transactional
wire data is synthesized directly into the mock log, playing the role of
the reference's Java fixture."""
import struct
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol import proto
from librdkafka_tpu.protocol.msgset import MsgsetWriterV2, Record, crc32c


def _batch(msgs, *, base_offset, pid=-1, transactional=False,
           control=False, ctrl_type=None):
    """Build a v2 batch blob (optionally transactional/control)."""
    now = 1_700_000_000_000
    if control:
        msgs = [Record(offset=0, timestamp=now,
                       key=struct.pack(">hh", 0, ctrl_type), value=b"")]
    w = MsgsetWriterV2(base_offset=base_offset, producer_id=pid,
                       transactional=transactional)
    blob = bytearray(w.write_batch(msgs, now))
    if control:
        # flip the CONTROL attr bit and re-CRC (the writer has no
        # control mode — control batches are broker-generated)
        attrs = struct.unpack_from(">h", blob, proto.V2_OF_Attributes)[0]
        struct.pack_into(">h", blob, proto.V2_OF_Attributes,
                         attrs | proto.ATTR_CONTROL)
        struct.pack_into(">I", blob, proto.V2_OF_CRC,
                         crc32c(bytes(blob[proto.V2_OF_Attributes:])))
    return bytes(blob)


def _recs(vals, ts=1_700_000_000_000):
    return [Record(offset=i, timestamp=ts, key=None, value=v)
            for i, v in enumerate(vals)]


@pytest.fixture
def txn_cluster():
    """A partition log with: committed txn (pid 9), aborted txn (pid 7),
    plain batch — plus the control records a broker would write."""
    c = MockCluster(num_brokers=1, topics={"txn": 1})
    part = c.partition("txn", 0)
    part.append(_batch(_recs([b"plain-0", b"plain-1"]), base_offset=0))
    part.append(_batch(_recs([b"committed-0", b"committed-1"]),
                       base_offset=2, pid=9, transactional=True))
    part.append(_batch([], base_offset=4, pid=9, transactional=True,
                       control=True, ctrl_type=proto.CTRL_COMMIT))
    part.append(_batch(_recs([b"aborted-0", b"aborted-1", b"aborted-2"]),
                       base_offset=5, pid=7, transactional=True))
    part.append(_batch([], base_offset=8, pid=7, transactional=True,
                       control=True, ctrl_type=proto.CTRL_ABORT))
    part.append(_batch(_recs([b"tail-0"]), base_offset=9))
    # mock must report the aborted range for read_committed fetches;
    # last_offset = the ABORT marker so resumed fetches past it don't
    # re-apply the range
    part.aborted = [{"producer_id": 7, "first_offset": 5,
                     "last_offset": 8}]
    yield c
    c.stop()


def _consume_all(cluster, isolation):
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": f"g-{isolation}",
                  "auto.offset.reset": "earliest",
                  "isolation.level": isolation})
    c.subscribe(["txn"])
    got = []
    deadline = time.monotonic() + 15
    idle = 0
    while time.monotonic() < deadline and idle < 8:
        m = c.poll(0.25)
        if m is not None and m.error is None:
            got.append(m.value)
            idle = 0
        else:
            idle += 1
    c.close()
    return got


def test_read_committed_filters_aborted(txn_cluster):
    got = _consume_all(txn_cluster, "read_committed")
    assert got == [b"plain-0", b"plain-1", b"committed-0", b"committed-1",
                   b"tail-0"], got


def test_read_uncommitted_sees_everything_but_control(txn_cluster):
    got = _consume_all(txn_cluster, "read_uncommitted")
    assert got == [b"plain-0", b"plain-1", b"committed-0", b"committed-1",
                   b"aborted-0", b"aborted-1", b"aborted-2", b"tail-0"], got
