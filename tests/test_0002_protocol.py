"""Wire-protocol schema round-trips: every API's request/response must
survive build→parse through the shared declarative schemas."""
import pytest

from librdkafka_tpu.protocol import apis
from librdkafka_tpu.protocol.proto import ApiKey
from librdkafka_tpu.utils.buf import Slice


def frame_strip(b: bytes) -> bytes:
    import struct
    (n,) = struct.unpack(">i", b[:4])
    assert n == len(b) - 4
    return b[4:]


def test_request_header_roundtrip():
    wire = apis.build_request(ApiKey.Metadata, 77, "cid", {"topics": None})
    hdr, body = apis.parse_request(frame_strip(wire))
    assert hdr == {"api_key": 3, "api_version": 4, "correlation_id": 77,
                   "client_id": "cid"}
    # v4: the omitted KIP-204 flag serializes via the schema default
    assert body == {"topics": None, "allow_auto_topic_creation": True}


SAMPLES = {
    ApiKey.ApiVersions: ({}, {
        "error_code": 0,
        "api_versions": [{"api_key": 0, "min_version": 0, "max_version": 7}]}),
    ApiKey.Metadata: ({"topics": ["t1", "t2"],
                       "allow_auto_topic_creation": False}, {
        "throttle_time_ms": 0,
        "brokers": [{"node_id": 1, "host": "localhost", "port": 9092,
                     "rack": None}],
        "cluster_id": "mockCluster", "controller_id": 1,
        "topics": [{"error_code": 0, "topic": "t1", "is_internal": False,
                    "partitions": [{"error_code": 0, "partition": 0,
                                    "leader": 1, "replicas": [1],
                                    "isr": [1]}]}]}),
    ApiKey.Produce: ({"transactional_id": None, "acks": -1, "timeout": 5000,
                      "topics": [{"topic": "t", "partitions": [
                          {"partition": 0, "records": b"\x01\x02"}]}]},
                     {"topics": [{"topic": "t", "partitions": [
                         {"partition": 0, "error_code": 0, "base_offset": 12,
                          "log_append_time": -1}]}],
                      "throttle_time_ms": 0}),
    # v11 shape: the session/epoch/rack/log_start_offset fields are
    # spelled out because parse returns them (builders may omit them —
    # the schema defaults cover that, proven by the version-sweep test)
    ApiKey.Fetch: ({"replica_id": -1, "max_wait_time": 100, "min_bytes": 1,
                    "max_bytes": 1 << 20, "isolation_level": 1,
                    "session_id": 0, "session_epoch": -1,
                    "forgotten_topics": [], "rack_id": "",
                    "topics": [{"topic": "t", "partitions": [
                        {"partition": 0, "current_leader_epoch": -1,
                         "fetch_offset": 0, "log_start_offset": -1,
                         "max_bytes": 1 << 20}]}]},
                   {"throttle_time_ms": 0, "error_code": 0,
                    "session_id": 0,
                    "topics": [{"topic": "t", "partitions": [
                        {"partition": 0, "error_code": 0,
                         "high_watermark": 10, "last_stable_offset": 10,
                         "log_start_offset": -1,
                         "aborted_transactions": [
                             {"producer_id": 1, "first_offset": 4}],
                         "preferred_read_replica": -1,
                         "records": b"RECORDS"}]}]}),
    ApiKey.ListOffsets: ({"replica_id": -1, "topics": [
                             {"topic": "t", "partitions": [
                                 {"partition": 0, "timestamp": -1}]}]},
                         {"topics": [{"topic": "t", "partitions": [
                             {"partition": 0, "error_code": 0,
                              "timestamp": -1, "offset": 33}]}]}),
    ApiKey.FindCoordinator: ({"key": "grp", "key_type": 0},
                             {"throttle_time_ms": 0, "error_code": 0,
                              "error_message": None, "node_id": 2,
                              "host": "h", "port": 1234}),
    ApiKey.JoinGroup: ({"group_id": "g", "session_timeout": 10000,
                        "rebalance_timeout": 30000, "member_id": "",
                        "group_instance_id": "node-7",
                        "protocol_type": "consumer",
                        "protocols": [{"name": "range", "metadata": b"md"}]},
                       {"throttle_time_ms": 0, "error_code": 0,
                        "generation_id": 1, "protocol": "range",
                        "leader_id": "m1", "member_id": "m1",
                        "members": [{"member_id": "m1",
                                     "group_instance_id": None,
                                     "metadata": b"md"}]}),
    ApiKey.SyncGroup: ({"group_id": "g", "generation_id": 1,
                        "member_id": "m1",
                        "assignments": [{"member_id": "m1",
                                         "assignment": b"as"}]},
                       {"throttle_time_ms": 0, "error_code": 0,
                        "assignment": b"as"}),
    ApiKey.Heartbeat: ({"group_id": "g", "generation_id": 1,
                        "member_id": "m1"},
                       {"throttle_time_ms": 0, "error_code": 0}),
    ApiKey.LeaveGroup: ({"group_id": "g", "member_id": "m1"},
                        {"throttle_time_ms": 0, "error_code": 0}),
    ApiKey.OffsetCommit: ({"group_id": "g", "generation_id": 1,
                           "member_id": "m", "retention_time": -1,
                           "topics": [{"topic": "t", "partitions": [
                               {"partition": 0, "offset": 5,
                                "metadata": None}]}]},
                          {"topics": [{"topic": "t", "partitions": [
                              {"partition": 0, "error_code": 0}]}]}),
    ApiKey.OffsetFetch: ({"group_id": "g", "topics": [
                             {"topic": "t", "partitions": [0, 1]}]},
                         {"topics": [{"topic": "t", "partitions": [
                             {"partition": 0, "offset": 3, "metadata": None,
                              "error_code": 0}]}]}),
    ApiKey.SaslHandshake: ({"mechanism": "PLAIN"},
                           {"error_code": 0, "mechanisms": ["PLAIN", "SCRAM-SHA-256"]}),
    ApiKey.SaslAuthenticate: ({"auth_bytes": b"\x00user\x00pass"},
                              {"error_code": 0, "error_message": None,
                               "auth_bytes": b""}),
    ApiKey.InitProducerId: ({"transactional_id": None,
                             "transaction_timeout_ms": 60000},
                            {"throttle_time_ms": 0, "error_code": 0,
                             "producer_id": 7, "producer_epoch": 0}),
    ApiKey.AddPartitionsToTxn: ({"transactional_id": "tx1",
                                 "producer_id": 7, "producer_epoch": 0,
                                 "topics": [{"topic": "t",
                                             "partitions": [0, 2]}]},
                                {"throttle_time_ms": 0,
                                 "results": [{"topic": "t", "partitions": [
                                     {"partition": 0, "error_code": 0},
                                     {"partition": 2, "error_code": 0}]}]}),
    ApiKey.AddOffsetsToTxn: ({"transactional_id": "tx1", "producer_id": 7,
                              "producer_epoch": 0, "group_id": "g"},
                             {"throttle_time_ms": 0, "error_code": 0}),
    ApiKey.EndTxn: ({"transactional_id": "tx1", "producer_id": 7,
                     "producer_epoch": 0, "committed": True},
                    {"throttle_time_ms": 0, "error_code": 0}),
    ApiKey.TxnOffsetCommit: ({"transactional_id": "tx1", "group_id": "g",
                              "producer_id": 7, "producer_epoch": 0,
                              "topics": [{"topic": "t", "partitions": [
                                  {"partition": 0, "offset": 5,
                                   "metadata": None}]}]},
                             {"throttle_time_ms": 0,
                              "topics": [{"topic": "t", "partitions": [
                                  {"partition": 0, "error_code": 0}]}]}),
    ApiKey.CreateTopics: ({"topics": [{"topic": "nt", "num_partitions": 3,
                                       "replication_factor": 1,
                                       "replica_assignment": [],
                                       "configs": [{"name": "x",
                                                    "value": "y"}]}],
                           "timeout": 1000, "validate_only": False},
                          {"throttle_time_ms": 0,
                           "topics": [{"topic": "nt", "error_code": 0,
                                       "error_message": None}]}),
    ApiKey.DeleteTopics: ({"topics": ["t"], "timeout": 100},
                          {"throttle_time_ms": 0,
                           "topics": [{"topic": "t", "error_code": 0}]}),
    ApiKey.CreatePartitions: ({"topics": [{"topic": "t", "count": 6,
                                           "assignment": None}],
                               "timeout": 100, "validate_only": False},
                              {"throttle_time_ms": 0,
                               "topics": [{"topic": "t", "error_code": 0,
                                           "error_message": None}]}),
    ApiKey.DescribeConfigs: ({"resources": [{"resource_type": 2,
                                             "resource_name": "t",
                                             "config_names": None}],
                              "include_synonyms": False},
                             {"throttle_time_ms": 0,
                              "resources": [{"error_code": 0,
                                             "error_message": None,
                                             "resource_type": 2,
                                             "resource_name": "t",
                                             "entries": [
                                  {"name": "retention.ms", "value": "100",
                                   "read_only": False, "source": 5,
                                   "sensitive": False, "synonyms": []}]}]}),
    ApiKey.AlterConfigs: ({"resources": [{"resource_type": 2,
                                          "resource_name": "t",
                                          "entries": [{"name": "a",
                                                       "value": "b"}]}],
                           "validate_only": False},
                          {"throttle_time_ms": 0,
                           "resources": [{"error_code": 0,
                                          "error_message": None,
                                          "resource_type": 2,
                                          "resource_name": "t"}]}),
    ApiKey.DescribeGroups: ({"groups": ["g"]},
                            {"groups": [{"error_code": 0, "group_id": "g",
                                         "state": "Stable",
                                         "protocol_type": "consumer",
                                         "protocol": "range",
                                         "members": [
                                  {"member_id": "m", "client_id": "c",
                                   "client_host": "/1.2.3.4",
                                   "metadata": b"", "assignment": b""}]}]}),
    ApiKey.ListGroups: ({}, {"error_code": 0,
                             "groups": [{"group_id": "g",
                                         "protocol_type": "consumer"}]}),
    ApiKey.DeleteGroups: ({"groups": ["g"]},
                          {"throttle_time_ms": 0,
                           "results": [{"group_id": "g", "error_code": 0}]}),
}


@pytest.mark.parametrize("api", list(SAMPLES), ids=lambda a: a.name)
def test_api_roundtrip(api):
    req_body, resp_body = SAMPLES[api]
    wire = apis.build_request(api, 5, "c", req_body)
    hdr, parsed_req = apis.parse_request(frame_strip(wire))
    assert parsed_req == req_body
    wire2 = apis.build_response(api, 5, resp_body)
    corrid, parsed_resp = apis.parse_response(api, frame_strip(wire2))
    assert corrid == 5
    assert parsed_resp == resp_body


def test_all_apis_have_samples():
    assert set(SAMPLES) == set(apis.APIS), "every API needs a round-trip test"
