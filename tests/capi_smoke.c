/* C smoke test for libtkafka.so (tests/test_0115_capi.py compiles and
 * runs this): produce 50 records through the embedded framework into
 * its in-process mock cluster, then consume them back — a full wire
 * round trip driven entirely from C, the role src-cpp/ plays for the
 * reference. */
#include <stdio.h>
#include <string.h>
#include "tkafka.h"

int main(void) {
    char errstr[512];
    tk_handle_t p = tk_producer_new(
        "{\"bootstrap.servers\": \"\", \"test.mock.num.brokers\": 1,"
        " \"linger.ms\": 5, \"compression.codec\": \"lz4\"}",
        errstr, sizeof(errstr));
    if (!p) { fprintf(stderr, "producer_new: %s\n", errstr); return 1; }

    char payload[64], key[16];
    for (int i = 0; i < 50; i++) {
        snprintf(payload, sizeof(payload), "c-api-message-%03d", i);
        snprintf(key, sizeof(key), "k%d", i);
        if (tk_produce(p, "ctopic", i % 2, key, strlen(key),
                       payload, strlen(payload)) != 0) {
            fprintf(stderr, "produce %d failed\n", i);
            return 1;
        }
    }
    if (tk_flush(p, 30000) != 0) { fprintf(stderr, "flush\n"); return 1; }

    char bootstrap[256];
    if (tk_mock_bootstrap(p, bootstrap, sizeof(bootstrap)) <= 0) {
        fprintf(stderr, "mock_bootstrap\n");
        return 1;
    }

    char conf[512];
    snprintf(conf, sizeof(conf),
             "{\"bootstrap.servers\": \"%s\", \"group.id\": \"gc\","
             " \"auto.offset.reset\": \"earliest\","
             " \"check.crcs\": true}", bootstrap);
    tk_handle_t c = tk_consumer_new(conf, errstr, sizeof(errstr));
    if (!c) { fprintf(stderr, "consumer_new: %s\n", errstr); return 1; }
    if (tk_subscribe(c, "ctopic") != 0) { return 1; }

    int got = 0, polls = 0;
    long long key_sum = 0;
    while (got < 50 && polls++ < 600) {
        tk_msg_t m;
        int r = tk_consumer_poll(c, 100, &m);
        if (r < 0) { fprintf(stderr, "poll error\n"); return 1; }
        if (r == 1) {
            if (m.err == 0) {
                if (strncmp(m.payload, "c-api-message-", 14) != 0) {
                    fprintf(stderr, "bad payload\n");
                    return 1;
                }
                key_sum += m.key_len;
                got++;
            }
            tk_msg_free(&m);
        }
    }
    tk_destroy(c);
    tk_destroy(p);
    if (got != 50) { fprintf(stderr, "got %d/50\n", got); return 1; }
    printf("CAPI-OK %d messages, key bytes %lld\n", got, key_sum);
    return 0;
}
