/* C smoke test for libtkafka.so (tests/test_0115_capi.py compiles and
 * runs this): the full client lifecycle driven from C — the role
 * src-cpp/ plays for the reference (surface: src/rdkafka.h).
 *
 *   1. admin: create a topic
 *   2. produce with headers + timestamp + per-message opaque, DR
 *      callback trampoline counting deliveries
 *   3. arena-layout batch produce (rd_kafka_produce_batch analog)
 *   4. consume: headers arrive; commit (sync)
 *   5. reopen the group and RESUME from the committed offset
 *   6. seek + committed introspection; admin: delete the topic
 */
#include <stdio.h>
#include <string.h>
#include <stdlib.h>
#include "tkafka.h"

static long long dr_ok = 0, dr_err = 0, dr_opaque_sum = 0;

static void on_dr(long long opaque, int err, int32_t partition,
                  int64_t offset) {
    (void)partition; (void)offset;
    if (err == 0) { dr_ok++; dr_opaque_sum += opaque; }
    else dr_err++;
}

static int stats_seen = 0;
static void on_stats(const char *json_str) {
    if (json_str && strstr(json_str, "\"brokers\"")) stats_seen++;
}

static int log_seen = 0;
static void on_log(int level, const char *fac, const char *msg) {
    (void)level; (void)fac; (void)msg;
    log_seen++;
}

int main(void) {
    char errstr[512];
    tk_handle_t p = tk_producer_new(
        "{\"bootstrap.servers\": \"\", \"test.mock.num.brokers\": 1,"
        " \"linger.ms\": 5, \"compression.codec\": \"lz4\","
        " \"statistics.interval.ms\": 100}",
        errstr, sizeof(errstr));
    if (!p) { fprintf(stderr, "producer_new: %s\n", errstr); return 1; }

    /* --- 0. observability callbacks + per-property conf ------------- */
    if (tk_set_stats_cb(p, on_stats) != 0) {
        fprintf(stderr, "set_stats_cb\n"); return 1;
    }
    if (tk_set_log_cb(p, on_log) != 0) {
        fprintf(stderr, "set_log_cb\n"); return 1;
    }
    if (tk_conf_set(p, "linger.ms", "10") != 0) {
        fprintf(stderr, "conf_set\n"); return 1;
    }
    char cv[64];
    if (tk_conf_get(p, "linger.ms", cv, sizeof cv) <= 0
        || strncmp(cv, "10", 2) != 0) {
        fprintf(stderr, "conf_get linger.ms = %s\n", cv); return 1;
    }
    if (tk_conf_set(p, "no.such.property", "x") == 0) {
        fprintf(stderr, "conf_set accepted junk\n"); return 1;
    }

    /* --- 1. admin: create the topic over the wire ------------------- */
    if (tk_create_topic(p, "ctopic", 2, 10000) != 0) {
        fprintf(stderr, "create_topic failed\n"); return 1;
    }

    /* --- 2. produce with headers/timestamp/opaque + DR callback ----- */
    if (tk_set_dr_cb(p, on_dr) != 0) { fprintf(stderr, "set_dr_cb\n"); return 1; }
    const char *hn[3] = {"source", "seq", "bin"};
    static const char binval[3] = {'\0', (char)0xff, 'x'};
    char payload[64], key[16], seqv[16];
    for (int i = 0; i < 25; i++) {
        snprintf(payload, sizeof(payload), "c-api-message-%03d", i);
        snprintf(key, sizeof(key), "k%d", i);
        snprintf(seqv, sizeof(seqv), "%d", i);
        const char *hv[3] = {"capi-smoke", seqv, binval};
        size_t hl[3] = {strlen("capi-smoke"), strlen(seqv), 3};
        if (tk_produce2(p, "ctopic", i % 2, key, strlen(key),
                        payload, strlen(payload),
                        0 /* timestamp: now */, hn, hv, hl, 3,
                        (long long)i /* opaque */) != 0) {
            fprintf(stderr, "produce2 %d failed\n", i); return 1;
        }
    }

    /* --- 3. arena-layout batch produce ------------------------------ */
    /* 25 records "batch-####" with null keys, partition 0 */
    char base[25 * 16];
    int32_t klens[25], vlens[25];
    size_t off = 0;
    for (int i = 0; i < 25; i++) {
        int n = snprintf(base + off, 16, "batch-%04d", i);
        klens[i] = -1;
        vlens[i] = n;
        off += (size_t)n;
    }
    long long nb = tk_produce_batch(p, "ctopic", 0, base, klens, vlens, 25);
    if (nb != 25) { fprintf(stderr, "produce_batch %lld/25\n", nb); return 1; }

    if (tk_flush(p, 30000) != 0) { fprintf(stderr, "flush\n"); return 1; }
    if (dr_ok != 25 || dr_err != 0 || dr_opaque_sum != 25 * 24 / 2) {
        fprintf(stderr, "dr counts: ok=%lld err=%lld opq=%lld\n",
                dr_ok, dr_err, dr_opaque_sum);
        return 1;
    }
    if (tk_outq_len(p) != 0) { fprintf(stderr, "outq != 0\n"); return 1; }

    char bootstrap[256];
    if (tk_mock_bootstrap(p, bootstrap, sizeof(bootstrap)) <= 0) {
        fprintf(stderr, "mock_bootstrap\n"); return 1;
    }

    /* --- 4. consume 30 of 50; verify headers; sync-commit ----------- */
    char conf[512];
    snprintf(conf, sizeof(conf),
             "{\"bootstrap.servers\": \"%s\", \"group.id\": \"gc\","
             " \"auto.offset.reset\": \"earliest\","
             " \"enable.auto.commit\": false,"
             " \"check.crcs\": true}", bootstrap);
    tk_handle_t c = tk_consumer_new(conf, errstr, sizeof(errstr));
    if (!c) { fprintf(stderr, "consumer_new: %s\n", errstr); return 1; }
    if (tk_subscribe(c, "ctopic") != 0) return 1;

    int got = 0, with_headers = 0, bin_ok = 0, polls = 0;
    while (got < 30 && polls++ < 600) {
        tk_msg_t m;
        int r = tk_consumer_poll(c, 100, &m);
        if (r < 0) { fprintf(stderr, "poll error\n"); return 1; }
        if (r == 1) {
            if (m.err == 0) {
                got++;
                /* first-class header arrays: raw bytes, no escaping */
                for (int i = 0; i < m.hdr_cnt; i++) {
                    if (strcmp(m.hdr_names[i], "source") == 0
                        && m.hdr_val_lens[i] == strlen("capi-smoke")
                        && memcmp(m.hdr_vals[i], "capi-smoke",
                                  m.hdr_val_lens[i]) == 0)
                        with_headers++;
                    if (strcmp(m.hdr_names[i], "bin") == 0
                        && m.hdr_val_lens[i] == 3
                        && memcmp(m.hdr_vals[i], binval, 3) == 0)
                        bin_ok++;
                }
            }
            tk_msg_free(&m);
        }
    }
    if (got != 30) { fprintf(stderr, "phase4 got %d/30\n", got); return 1; }
    if (with_headers == 0) { fprintf(stderr, "no headers seen\n"); return 1; }
    if (bin_ok == 0) {
        fprintf(stderr, "binary header did not round-trip raw\n"); return 1;
    }
    if (tk_commit(c, 0) != 0) { fprintf(stderr, "commit\n"); return 1; }

    long long c0 = tk_committed(c, "ctopic", 0, 5000);
    long long c1 = tk_committed(c, "ctopic", 1, 5000);
    /* negative = no committed offset for that partition */
    long long csum = (c0 > 0 ? c0 : 0) + (c1 > 0 ? c1 : 0);
    if (csum != 30) {
        fprintf(stderr, "committed %lld+%lld != 30\n", c0, c1); return 1;
    }
    if (c0 < 0) c0 = 0;
    tk_destroy(c);

    /* --- 5. reopen the same group: must RESUME at committed --------- */
    tk_handle_t c2 = tk_consumer_new(conf, errstr, sizeof(errstr));
    if (!c2) { fprintf(stderr, "consumer_new2: %s\n", errstr); return 1; }
    if (tk_subscribe(c2, "ctopic") != 0) return 1;
    int rest = 0; polls = 0;
    long long min_off_p0 = 1 << 30;
    while (rest < 20 && polls++ < 600) {
        tk_msg_t m;
        int r = tk_consumer_poll(c2, 100, &m);
        if (r == 1) {
            if (m.err == 0) {
                rest++;
                if (m.partition == 0 && m.offset < min_off_p0)
                    min_off_p0 = m.offset;
            }
            tk_msg_free(&m);
        }
    }
    if (rest != 20) { fprintf(stderr, "resume got %d/20\n", rest); return 1; }
    if (min_off_p0 < c0) {
        fprintf(stderr, "resumed below committed (%lld < %lld)\n",
                min_off_p0, c0);
        return 1;
    }

    /* --- 6. seek back and re-read one; then admin delete ------------ */
    if (tk_seek(c2, "ctopic", 0, 0) != 0) { fprintf(stderr, "seek\n"); return 1; }
    int reread = 0; polls = 0;
    while (!reread && polls++ < 600) {
        tk_msg_t m;
        int r = tk_consumer_poll(c2, 100, &m);
        if (r == 1) {
            if (m.err == 0 && m.partition == 0 && m.offset == 0) reread = 1;
            tk_msg_free(&m);
        }
    }
    if (!reread) { fprintf(stderr, "seek re-read failed\n"); return 1; }

    /* --- 7. introspection & offset queries --------------------------- */
    char vbuf[64], ebuf[64];
    if (tk_version(vbuf, sizeof vbuf) <= 0) {
        fprintf(stderr, "tk_version\n"); return 1;
    }
    if (tk_err2str(0, ebuf, sizeof ebuf) <= 0) {
        fprintf(stderr, "tk_err2str\n"); return 1;
    }
    int64_t lo = -1, hi = -1;
    if (tk_query_watermark_offsets(c2, "ctopic", 0, &lo, &hi, 10000) != 0) {
        fprintf(stderr, "watermarks failed\n"); return 1;
    }
    if (lo != 0 || hi <= 0) {
        fprintf(stderr, "watermarks lo=%lld hi=%lld\n",
                (long long)lo, (long long)hi);
        return 1;
    }
    long long earliest = tk_offsets_for_times(c2, "ctopic", 0, 0, 10000);
    if (earliest != 0) {
        fprintf(stderr, "offsets_for_times(ts=0) = %lld\n", earliest);
        return 1;
    }
    long long pos = tk_position(c2, "ctopic", 0);
    if (pos < 1) {   /* consumed offset 0 again after the seek */
        fprintf(stderr, "position = %lld\n", pos); return 1;
    }
    if (tk_pause(c2, "ctopic", 0) != 0 || tk_resume(c2, "ctopic", 0) != 0) {
        fprintf(stderr, "pause/resume failed\n"); return 1;
    }
    char mbuf[8192];
    if (tk_metadata_json(c2, mbuf, sizeof mbuf, 10000) <= 0
        || !strstr(mbuf, "ctopic")) {
        fprintf(stderr, "metadata_json: %s\n", mbuf); return 1;
    }
    char cbuf[16384];
    if (tk_conf_dump_json(c2, cbuf, sizeof cbuf) <= 0
        || !strstr(cbuf, "group.id")) {
        fprintf(stderr, "conf_dump_json failed\n"); return 1;
    }
    if (tk_purge(p, 1, 0) != 0) {
        fprintf(stderr, "purge failed\n"); return 1;
    }

    /* --- 8. r5 surface: stats cb, configs admin, group admin --------- */
    /* stats.interval=100ms: tk_poll serves the stats op -> C callback */
    for (int i = 0; i < 50 && !stats_seen; i++) tk_poll(p, 100);
    if (!stats_seen) { fprintf(stderr, "stats callback never fired\n"); return 1; }

    char dbuf[8192];
    if (tk_describe_configs(p, 2 /* TOPIC */, "ctopic",
                            dbuf, sizeof dbuf, 10000) <= 0
        || dbuf[0] != '{') {
        fprintf(stderr, "describe_configs: %s\n", dbuf); return 1;
    }
    if (tk_alter_configs(p, 2, "ctopic",
                         "{\"retention.bytes\": \"123456\"}", 10000) != 0) {
        fprintf(stderr, "alter_configs failed\n"); return 1;
    }
    if (tk_describe_configs(p, 2, "ctopic", dbuf, sizeof dbuf, 10000) <= 0
        || !strstr(dbuf, "123456")) {
        fprintf(stderr, "altered config not visible: %s\n", dbuf); return 1;
    }
    if (tk_create_partitions(p, "ctopic", 4, 10000) != 0) {
        fprintf(stderr, "create_partitions failed\n"); return 1;
    }
    char gbuf[8192];
    if (tk_list_groups(p, gbuf, sizeof gbuf, 10000) <= 0
        || !strstr(gbuf, "gc")) {
        fprintf(stderr, "list_groups: %s\n", gbuf); return 1;
    }
    if (tk_describe_group(p, "gc", gbuf, sizeof gbuf, 10000) <= 0
        || !strstr(gbuf, "state")) {
        fprintf(stderr, "describe_group: %s\n", gbuf); return 1;
    }
    tk_destroy(c2);
    /* group now memberless: delete it, then it no longer lists */
    if (tk_delete_group(p, "gc", 10000) != 0) {
        fprintf(stderr, "delete_group failed\n"); return 1;
    }
    if (tk_list_groups(p, gbuf, sizeof gbuf, 10000) > 0
        && strstr(gbuf, "\"gc\"")) {
        fprintf(stderr, "group still listed after delete: %s\n", gbuf);
        return 1;
    }

    if (tk_delete_topic(p, "ctopic", 10000) != 0) {
        fprintf(stderr, "delete_topic failed\n"); return 1;
    }
    tk_destroy(p);
    printf("CAPI-OK produce2+rawheaders+dr=%lld batch=%lld consume+commit+"
           "resume+seek+admin+watermarks+times+position+pause+metadata+"
           "confdump+purge+stats=%d+configs+groups v=%s all pass\n",
           dr_ok, nb, stats_seen, vbuf);
    return 0;
}
