"""Purge API tests (reference: rd_kafka_purge + 0086-purge.c): in-queue
purge drains every queue tier (msgq, xmit, frozen retry batches, UA
parking) with _PURGE_QUEUE DRs; in-flight purge abandons outstanding
ProduceRequests with _PURGE_INFLIGHT DRs and flush() returns."""
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.mock.sockem import Sockem


def test_purge_in_queue_covers_all_tiers():
    drs = []
    cluster = MockCluster(num_brokers=1, topics={"pq": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 60000,      # park in msgq forever
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    try:
        for i in range(10):
            p.produce("pq", value=b"q%d" % i, partition=0)
        p.produce("unknown-topic-parked", value=b"ua")   # UA parking
        time.sleep(0.3)
        p.purge(in_queue=True, in_flight=False)
        assert p.flush(10.0) == 0
        deadline = time.monotonic() + 5
        while len(drs) < 11 and time.monotonic() < deadline:
            p.poll(0.1)           # purge DRs arrive via the reply queue
        errs = [e for e in drs if e is not None]
        assert len(errs) >= 10
        assert all(e.code == Err._PURGE_QUEUE for e in errs[:10])
        assert p._rk.msg_cnt == 0 and p._rk.msg_bytes == 0
    finally:
        p.close()
        cluster.stop()


def test_purge_in_flight():
    """Choke the network so a ProduceRequest is stuck in flight, purge,
    and verify _PURGE_INFLIGHT DRs + fast flush return."""
    drs = []
    em = Sockem()
    cluster = MockCluster(num_brokers=1, topics={"pf": 1})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb, "linger.ms": 2,
                  "message.timeout.ms": 120000,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    try:
        p.produce("pf", value=b"warm", partition=0)
        assert p.flush(10.0) == 0
        em.set(rate_bps=2000)             # responses crawl
        for i in range(5):
            p.produce("pf", value=b"f%d" % i * 200, partition=0)
        time.sleep(0.6)                   # request now in flight
        t0 = time.monotonic()
        p.purge(in_queue=True, in_flight=True)
        assert p.flush(10.0) == 0
        assert time.monotonic() - t0 < 5.0, "flush blocked despite purge"
        deadline = time.monotonic() + 5
        while len(drs) < 6 and time.monotonic() < deadline:
            p.poll(0.1)
        errs = [e for e in drs if e is not None]
        assert errs, "no purge DRs delivered"
        assert all(e.code in (Err._PURGE_QUEUE, Err._PURGE_INFLIGHT)
                   for e in errs)
        assert any(e.code == Err._PURGE_INFLIGHT for e in errs), \
            "no in-flight purge happened"
    finally:
        p.close()
        cluster.stop()
        em.kill_all()
