"""SASL GSSAPI provider tests (reference: rdkafka_sasl_cyrus.c:1-645).

No KDC is available here, so the GSS *mechanism* is a scripted context
injected through GssapiClient's ctx_factory; what these tests pin down
is everything the client itself owns: the RFC 4752 token relay, the
security-layer negotiation bytes, the authzid from sasl.kerberos.*
conf, the hostbased service name, and the fail-fast gate when
python-gssapi is absent.
"""
import struct

import pytest

from librdkafka_tpu.client.conf import Conf
from librdkafka_tpu.client.errors import KafkaException
from librdkafka_tpu.client.sasl import (GssapiClient, gssapi_available,
                                        validate_mechanism)

# Recorded-shape vectors: opaque context tokens (contents arbitrary —
# GSS tokens are opaque to SASL; the framing around them is what must
# be exact).
TOK_AP_REQ = b"\x60\x82\x01\x23APREQ-token-bytes"
TOK_AP_REP = b"\x6f\x81\x99APREP-token-bytes"
SSF_NONE_1MB = bytes([0x01, 0x10, 0x00, 0x00])   # layer NONE, max 1MB


class ScriptedCtx:
    """Stand-in GSS security context with the python-gssapi surface the
    provider uses: step/complete/unwrap/wrap."""

    class _Wrapped:
        def __init__(self, message):
            self.message = message

    def __init__(self, service, host, ssf_plain=SSF_NONE_1MB):
        self.service = service
        self.host = host
        self.ssf_plain = ssf_plain
        self.complete = False
        self.steps = 0
        self.wrapped_out = None

    def step(self, tok):
        self.steps += 1
        if self.steps == 1:
            assert tok is None
            return TOK_AP_REQ
        # second step consumes AP-REP, completes, no output token
        assert tok == TOK_AP_REP
        self.complete = True
        return None

    def unwrap(self, data):
        assert data == b"WRAPPED[" + self.ssf_plain + b"]"
        return self._Wrapped(self.ssf_plain)

    def wrap(self, data, encrypt):
        assert encrypt is False
        self.wrapped_out = data
        return self._Wrapped(b"WRAPPED[" + data + b"]")


class _RkStub:
    def __init__(self, **conf):
        self.conf = Conf()
        self.conf.update({"security.protocol": "sasl_plaintext",
                          "sasl.mechanisms": "PLAIN", **conf})


def make_client(**conf):
    rk = _RkStub(**conf)
    ctxs = []

    def factory(service, host):
        c = ScriptedCtx(service, host)
        ctxs.append(c)
        return c

    cli = GssapiClient(rk, "broker1.example.com", ctx_factory=factory)
    return cli, ctxs[0]


def test_token_relay_and_security_layer_exchange():
    cli, ctx = make_client(
        **{"sasl.kerberos.principal": "client@EXAMPLE.COM"})
    # phase 1: context establishment
    assert cli.first_message() == TOK_AP_REQ
    assert cli.step(TOK_AP_REP) == b""       # AP-REP consumed, no token
    assert ctx.complete
    # phase 2: server's wrapped [bitmask|max]; client answers wrapped
    # [LAYER_NONE << 24] with an EMPTY authzid (authorize as the
    # authenticated principal — what the reference's cyrus provider
    # sends; a mismatched authzid is rejected by the broker)
    out = cli.step(b"WRAPPED[" + SSF_NONE_1MB + b"]")
    assert out == b"WRAPPED[" + struct.pack(">I", 0x01000000) + b"]"
    assert ctx.wrapped_out == struct.pack(">I", 0x01000000)
    # phase 3: done — outcome arrives via error_code
    assert cli.step(b"") is None


def test_hostbased_service_name_from_conf():
    cli, ctx = make_client(
        **{"sasl.kerberos.service.name": "brokersvc"})
    assert ctx.service == "brokersvc"
    assert ctx.host == "broker1.example.com"


def test_default_service_name_is_kafka():
    cli, ctx = make_client()
    assert ctx.service == "kafka"


def test_server_without_layer_none_is_rejected():
    rk = _RkStub()
    ctx_holder = []

    def factory(service, host):
        c = ScriptedCtx(service, host,
                        ssf_plain=bytes([0x04, 0, 0x40, 0]))  # conf only
        ctx_holder.append(c)
        return c

    cli = GssapiClient(rk, "h", ctx_factory=factory)
    cli.first_message()
    cli.step(TOK_AP_REP)
    with pytest.raises(KafkaException, match="security layer"):
        cli.step(b"WRAPPED[" + bytes([0x04, 0, 0x40, 0]) + b"]")


def test_malformed_ssf_token_is_rejected():
    cli, ctx = make_client()
    cli.first_message()
    cli.step(TOK_AP_REP)
    ctx.ssf_plain = b"\x01\x00"          # 2 bytes, want 4
    with pytest.raises(KafkaException, match="malformed"):
        cli.step(b"WRAPPED[" + b"\x01\x00" + b"]")


@pytest.mark.skipif(gssapi_available(),
                    reason="python-gssapi installed: gate inactive")
def test_fail_fast_without_python_gssapi():
    """Without the gssapi package, selecting GSSAPI must fail at client
    creation (reference: a build without WITH_SASL_CYRUS rejects it in
    rd_kafka_sasl_select_provider)."""
    conf = Conf()
    conf.update({"security.protocol": "sasl_plaintext",
                 "sasl.mechanisms": "GSSAPI"})
    with pytest.raises(KafkaException, match="python-gssapi"):
        validate_mechanism(conf)


def test_render_conf_template():
    from librdkafka_tpu.client.sasl import render_conf_template
    conf = Conf()
    conf.update({"sasl.kerberos.keytab": "/etc/krb.keytab",
                 "sasl.kerberos.principal": "svc@REALM"})
    out = render_conf_template(
        conf, 'kinit -t "%{sasl.kerberos.keytab}" -k '
              '%{sasl.kerberos.principal} %{no.such.prop}')
    assert out == 'kinit -t "/etc/krb.keytab" -k svc@REALM '


def test_kinit_cmd_runs_at_creation_and_on_timer(tmp_path, monkeypatch):
    """The reference runs sasl.kerberos.kinit.cmd at client creation and
    every min.time.before.relogin ms (rdkafka_sasl_cyrus.c:193-260). A
    fake command records invocations; GSSAPI availability is stubbed so
    the mechanism passes validation without a real KDC."""
    import time as _time

    import librdkafka_tpu.client.sasl as sasl_mod

    marker = tmp_path / "kinit-calls"
    monkeypatch.setattr(sasl_mod, "gssapi_available", lambda: True)
    from librdkafka_tpu import Producer
    p = Producer({"bootstrap.servers": "127.0.0.1:1",
                  "security.protocol": "sasl_plaintext",
                  "sasl.mechanisms": "GSSAPI",
                  "sasl.kerberos.principal": "tester@X",
                  "sasl.kerberos.kinit.cmd":
                      f'echo run-%{{sasl.kerberos.principal}} >> {marker}',
                  "sasl.kerberos.min.time.before.relogin": 200})
    try:
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            if marker.exists() and \
                    len(marker.read_text().splitlines()) >= 2:
                break
            _time.sleep(0.05)
        lines = marker.read_text().splitlines()
        # once at creation + at least one timed refresh, with the
        # %{...} template rendered
        assert len(lines) >= 2
        assert all(l == "run-tester@X" for l in lines)
    finally:
        p.close()
