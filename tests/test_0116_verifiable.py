"""Verifiable-client protocol (reference:
examples/kafkatest_verifiable_client.cpp — the ducktape system-test
client): both modes run as real subprocesses against a standalone mock
broker process, and the emitted JSON protocol lines are validated."""
import json
import os
import select
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT = os.path.join(REPO, "examples", "verifiable_client.py")


@pytest.fixture(scope="module")
def mock_proc():
    child = subprocess.Popen(
        [sys.executable, "-m", "librdkafka_tpu.mock.standalone",
         "--brokers", "1", "--topic", "vt:2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO)
    # guard the address read: a hung child must fail the fixture, not
    # block the whole pytest session
    ready, _, _ = select.select([child.stdout], [], [], 30)
    bs = child.stdout.readline().strip() if ready else ""
    if not bs:
        child.kill()
        pytest.fail("standalone mock failed to start: "
                    + (child.stderr.read() or "")[-500:])
    yield bs
    child.kill()


def _run(args, timeout=90):
    r = subprocess.run(
        [sys.executable, CLIENT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stderr[-800:]
    return [json.loads(line) for line in r.stdout.splitlines()
            if line.strip()]


def test_verifiable_producer(mock_proc):
    lines = _run(["--producer", "--topic", "vt", "--max-messages", "300",
                  "--bootstrap-server", mock_proc])
    names = [l["name"] for l in lines]
    assert names[0] == "startup_complete"
    assert names[-1] == "shutdown_complete"
    acks = [l for l in lines if l["name"] == "producer_send_success"]
    assert len(acks) == 300
    assert {a["topic"] for a in acks} == {"vt"}
    tool = next(l for l in lines if l["name"] == "tool_data")
    assert tool["sent"] == tool["acked"] == 300


def test_verifiable_consumer(mock_proc):
    lines = _run(["--consumer", "--topic", "vt", "--max-messages", "300",
                  "--group-id", "vg", "--bootstrap-server", mock_proc,
                  "--commit-interval-ms", "300"])
    names = [l["name"] for l in lines]
    assert names[0] == "startup_complete"
    assert names[-1] == "shutdown_complete"
    assert "partitions_assigned" in names
    consumed = [l for l in lines if l["name"] == "records_consumed"]
    assert consumed and consumed[-1]["_totcount"] == 300
    # per-partition min/max offsets must be coherent
    for batch in consumed:
        for p in batch["partitions"]:
            assert 0 <= p["minOffset"] <= p["maxOffset"]
    commits = [l for l in lines if l["name"] == "offsets_committed"]
    assert commits and all(c["success"] for c in commits)


def test_verifiable_two_consumer_rebalance(mock_proc):
    """The ducktape scenario the protocol exists for: a second consumer
    joins the same group mid-stream — both sides emit the rebalance
    protocol events and the partition set splits disjointly."""
    import time

    def read_until(proc, name, timeout=60):
        """Read protocol lines from proc until `name` appears."""
        lines = []
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(json.loads(line))
            if lines[-1]["name"] == name:
                return lines
        raise AssertionError(
            f"never saw {name}: {[l['name'] for l in lines]}")

    _run(["--producer", "--topic", "vt", "--max-messages", "400",
          "--bootstrap-server", mock_proc])
    c1 = c2 = None
    try:
        c1 = subprocess.Popen(
            [sys.executable, CLIENT, "--consumer", "--topic", "vt",
             "--group-id", "vreb", "--bootstrap-server", mock_proc,
             "--commit-interval-ms", "300"],
            stdout=subprocess.PIPE, text=True, cwd=REPO)
        # deterministic: wait for c1's FIRST assignment before c2 joins
        l1 = read_until(c1, "partitions_assigned")
        c2 = subprocess.Popen(
            [sys.executable, CLIENT, "--consumer", "--topic", "vt",
             "--group-id", "vreb", "--bootstrap-server", mock_proc,
             "--commit-interval-ms", "300"],
            stdout=subprocess.PIPE, text=True, cwd=REPO)
        # the join must revoke c1's assignment and re-assign both sides
        l1 += read_until(c1, "partitions_revoked")
        l1 += read_until(c1, "partitions_assigned")
        l2 = read_until(c2, "partitions_assigned")
        c1.terminate()
        c2.terminate()
        out1, _ = c1.communicate(timeout=30)
        out2, _ = c2.communicate(timeout=30)
        l1 += [json.loads(x) for x in out1.splitlines() if x.strip()]
        l2 += [json.loads(x) for x in out2.splitlines() if x.strip()]
    finally:
        for proc in (c1, c2):
            if proc is not None and proc.poll() is None:
                proc.kill()
    n1 = [x["name"] for x in l1]
    n2 = [x["name"] for x in l2]
    assert n1[-1] == "shutdown_complete" and n2[-1] == "shutdown_complete"
    # after the rebalance each holds ONE of the two partitions
    last1 = [x for x in l1 if x["name"] == "partitions_assigned"][-1]
    last2 = [x for x in l2 if x["name"] == "partitions_assigned"][-1]
    p1 = {(p["topic"], p["partition"]) for p in last1["partitions"]}
    p2 = {(p["topic"], p["partition"]) for p in last2["partitions"]}
    assert p1 and p2 and not (p1 & p2), (p1, p2)
