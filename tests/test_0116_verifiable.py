"""Verifiable-client protocol (reference:
examples/kafkatest_verifiable_client.cpp — the ducktape system-test
client): both modes run as real subprocesses against a standalone mock
broker process, and the emitted JSON protocol lines are validated."""
import json
import os
import select
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT = os.path.join(REPO, "examples", "verifiable_client.py")


@pytest.fixture(scope="module")
def mock_proc():
    child = subprocess.Popen(
        [sys.executable, "-m", "librdkafka_tpu.mock.standalone",
         "--brokers", "1", "--topic", "vt:2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO)
    # guard the address read: a hung child must fail the fixture, not
    # block the whole pytest session
    ready, _, _ = select.select([child.stdout], [], [], 30)
    bs = child.stdout.readline().strip() if ready else ""
    if not bs:
        child.kill()
        pytest.fail("standalone mock failed to start: "
                    + (child.stderr.read() or "")[-500:])
    yield bs
    child.kill()


def _run(args, timeout=90):
    r = subprocess.run(
        [sys.executable, CLIENT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stderr[-800:]
    return [json.loads(line) for line in r.stdout.splitlines()
            if line.strip()]


def test_verifiable_producer(mock_proc):
    lines = _run(["--producer", "--topic", "vt", "--max-messages", "300",
                  "--bootstrap-server", mock_proc])
    names = [l["name"] for l in lines]
    assert names[0] == "startup_complete"
    assert names[-1] == "shutdown_complete"
    acks = [l for l in lines if l["name"] == "producer_send_success"]
    assert len(acks) == 300
    assert {a["topic"] for a in acks} == {"vt"}
    tool = next(l for l in lines if l["name"] == "tool_data")
    assert tool["sent"] == tool["acked"] == 300


def test_verifiable_consumer(mock_proc):
    lines = _run(["--consumer", "--topic", "vt", "--max-messages", "300",
                  "--group-id", "vg", "--bootstrap-server", mock_proc,
                  "--commit-interval-ms", "300"])
    names = [l["name"] for l in lines]
    assert names[0] == "startup_complete"
    assert names[-1] == "shutdown_complete"
    assert "partitions_assigned" in names
    consumed = [l for l in lines if l["name"] == "records_consumed"]
    assert consumed and consumed[-1]["_totcount"] == 300
    # per-partition min/max offsets must be coherent
    for batch in consumed:
        for p in batch["partitions"]:
            assert 0 <= p["minOffset"] <= p["maxOffset"]
    commits = [l for l in lines if l["name"] == "offsets_committed"]
    assert commits and all(c["success"] for c in commits)
