"""ISSUE 10: Eraser-style lockset race detector (analysis/races.py) +
seeded schedule explorer (analysis/interleave.py) + the shared-state
lint rule.

Covers: the lockset state machine (virgin/exclusive/shared/
shared-modified, read-shared no-report, write race with both stacks,
refinement by intersection), the zero-cost-when-disabled descriptor
contract, the shared container wrappers, SchedFuzzer determinism
(same seed => same replay_key => same per-thread interleaving trace),
a planted race a plain run misses but a seeded schedule reproduces,
lint pos/neg/pragma fixtures, the ``analysis.races`` conf knob, and
regressions for the unguarded-access fixes the sweep surfaced
(fetchq accounting, governor EWMAs, stats counters, flush flag).
"""
import sys
import threading
import time

import pytest

from librdkafka_tpu.analysis import interleave, lockdep, races
from librdkafka_tpu.analysis.lint import lint_source
from librdkafka_tpu.analysis.locks import new_lock
from librdkafka_tpu.analysis.races import (
    Guarded, shared, shared_counter, shared_dict, shared_list)


# ---------------------------------------------------- fixture classes --
class _Cell:
    v = shared("t0130.cell.v")

    def __init__(self):
        self.v = 0


class _RelaxedCell:
    v = shared("t0130.relaxed.v", relaxed=True)

    def __init__(self):
        self.v = 0


class _SlotCell:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0


races.register_slots(_SlotCell, "v", prefix="t0130.slot")


class _Plant:
    counter = shared("t0130.plant.counter")

    def __init__(self):
        self.counter = 0


def _run_threads(*targets):
    ths = [threading.Thread(target=fn, name=f"t0130-{i}", daemon=True)
           for i, fn in enumerate(targets)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert not any(t.is_alive() for t in ths)


def _var(st, name):
    vs = [v for v in st.vars.values() if v.var == name]
    assert vs, f"variable {name} never tracked"
    return vs[-1]


# ------------------------------------------------- state machine unit --
def test_disabled_marker_resolves_to_plain_attribute():
    """The zero-cost contract: disabled, the class carries NO
    descriptor — the attribute is a plain instance-dict slot."""
    if races.enabled:
        pytest.skip("detector enabled for this session (--races)")
    assert "v" not in _Cell.__dict__
    c = _Cell()
    assert c.__dict__["v"] == 0
    c.v += 1
    assert c.v == 1
    # slotted: the original member_descriptor is in place
    assert not isinstance(_SlotCell.__dict__["v"], Guarded)


def test_enable_installs_and_disable_restores():
    races.enable()
    try:
        assert isinstance(_Cell.__dict__["v"], Guarded)
        assert isinstance(_SlotCell.__dict__["v"], Guarded)
        c = _Cell()
        c.v = 7
        s = _SlotCell()
        s.v = 9
        assert c.v == 7 and s.v == 9
    finally:
        races.disable()
    if not races.enabled:
        assert "v" not in _Cell.__dict__
        assert not isinstance(_SlotCell.__dict__["v"], Guarded)
    # values survive the uninstall (state lives in the instance)
    assert c.v == 7 and s.v == 9


def test_exclusive_single_thread_no_report():
    races.enable()
    try:
        with races.scope() as st:
            c = _Cell()
            for _ in range(5):
                c.v += 1
            vs = _var(st, "t0130.cell.v")
            assert vs.state == "exclusive"
            assert races.clean()
    finally:
        races.disable()


def test_read_shared_no_report():
    """Owner initializes; a second thread only READS — the classic
    read-shared pattern stays in 'shared' and never reports."""
    races.enable()
    try:
        with races.scope() as st:
            c = _Cell()
            c.v = 41
            out = []
            _run_threads(lambda: out.append(c.v),
                         lambda: out.append(c.v))
            vs = _var(st, "t0130.cell.v")
            assert vs.state == "shared"
            assert races.clean()
            assert out == [41, 41]
    finally:
        races.disable()


def test_unguarded_write_race_reported_with_both_stacks():
    races.enable()
    try:
        with races.scope() as st:
            c = _Cell()

            def a():
                for _ in range(3):
                    c.v += 1

            def b():
                for _ in range(3):
                    c.v += 1

            _run_threads(a, b)
            vs = _var(st, "t0130.cell.v")
            assert vs.state == "shared_modified"
            rep = races.report()
            assert not races.clean(rep)
            r = [x for x in rep["races"]
                 if x["var"] == "t0130.cell.v"][0]
            assert r["kind"] == "empty_lockset_write"
            # both access stacks: the racing write's and the other
            # thread's first access
            assert "test_0130_races" in r["stack"]
            assert r["other_stacks"], r
            assert any("test_0130_races" in o["stack"]
                       for o in r["other_stacks"])
            assert len(r["threads"]) >= 2
    finally:
        races.disable()


def test_consistently_locked_writes_clean():
    races.enable()
    try:
        with races.scope() as st:
            lk = new_lock("t0130.lock")
            c = _Cell()

            def w():
                for _ in range(10):
                    with lk:
                        c.v += 1

            _run_threads(w, w)
            vs = _var(st, "t0130.cell.v")
            assert vs.state == "shared_modified"
            assert vs.lockset, "candidate set must retain the lock"
            assert races.clean()
    finally:
        races.disable()


def test_refinement_by_intersection():
    """A holds L1+L2, B holds only L2: C(v) refines to {L2} (no
    report); a later write holding neither empties it -> report."""
    races.enable()
    try:
        with races.scope():
            l1, l2 = new_lock("t0130.l1"), new_lock("t0130.l2")
            c = _Cell()

            def a():
                with l1:
                    with l2:
                        c.v += 1

            def b():
                with l2:
                    c.v += 1

            _run_threads(a, b)
            assert races.clean(), races.report()["races"]

            def naked():
                c.v += 1

            _run_threads(naked)
            rep = races.report()
            assert not races.clean(rep)
            assert rep["races"][0]["var"] == "t0130.cell.v"
    finally:
        races.disable()


def test_relaxed_reported_separately_never_fails():
    races.enable()
    try:
        with races.scope():
            c = _RelaxedCell()

            def w():
                for _ in range(3):
                    c.v += 1

            _run_threads(w, w)
            rep = races.report()
            assert races.clean(rep)          # relaxed never fails
            assert any(r["var"] == "t0130.relaxed.v"
                       for r in rep["relaxed_races"])
    finally:
        races.disable()


def test_report_once_per_variable():
    races.enable()
    try:
        with races.scope():
            c = _Cell()

            def w():
                for _ in range(50):
                    c.v += 1

            _run_threads(w, w)
            rep = races.report()
            hits = [r for r in rep["races"]
                    if r["var"] == "t0130.cell.v"]
            assert len(hits) == 1
    finally:
        races.disable()


# ----------------------------------------------------- containers -----
def test_shared_containers_disabled_are_plain():
    if races.enabled:
        pytest.skip("detector enabled for this session (--races)")
    assert type(shared_list("x")) is list
    assert type(shared_dict("x")) is dict
    c = shared_counter("x")
    c.add(2)
    assert c.value == 2


def test_shared_list_append_race_and_locked_clean():
    races.enable()
    try:
        with races.scope():
            lst = shared_list("t0130.list")

            def w():
                for i in range(5):
                    lst.append(i)

            _run_threads(w, w)
            rep = races.report()
            assert any(r["var"] == "t0130.list" for r in rep["races"])
        with races.scope():
            lk = new_lock("t0130.list_lock")
            lst2 = shared_list("t0130.list2")

            def w2():
                for i in range(5):
                    with lk:
                        lst2.append(i)

            _run_threads(w2, w2)
            assert races.clean(), races.report()["races"]
    finally:
        races.disable()


def test_shared_dict_and_counter_record_writes():
    races.enable()
    try:
        with races.scope():
            d = shared_dict("t0130.dict")
            cn = shared_counter("t0130.counter")

            def w():
                for i in range(5):
                    d[i] = i
                    cn.add()

            _run_threads(w, w)
            rep = races.report()
            racy = {r["var"] for r in rep["races"]}
            assert "t0130.dict" in racy and "t0130.counter" in racy
            assert cn.value <= 10
    finally:
        races.disable()


# ------------------------------------------------- schedule explorer --
def _fuzz_workload(fz, name, n=200):
    def body():
        for _ in range(n):
            fz.maybe_yield("p")
    t = threading.Thread(target=body, name=name, daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive()


def test_schedfuzzer_determinism_same_seed_same_trace():
    f1 = interleave.SchedFuzzer(1234, preemption_bound=20)
    f2 = interleave.SchedFuzzer(1234, preemption_bound=20)
    assert f1.replay_key() == f2.replay_key()
    for fz in (f1, f2):
        _fuzz_workload(fz, "fz-a")
        _fuzz_workload(fz, "fz-b")
    assert f1.trace_for("fz-a") == f2.trace_for("fz-a")
    assert f1.trace_for("fz-b") == f2.trace_for("fz-b")
    assert f1.trace_for("fz-a"), "no preemption ever fired"
    # a different seed explores a different schedule
    f3 = interleave.SchedFuzzer(99, preemption_bound=20)
    assert f3.replay_key() != f1.replay_key()
    _fuzz_workload(f3, "fz-a")
    assert f3.trace_for("fz-a") != f1.trace_for("fz-a")
    # from_key rebuilds the exact fuzzer (the replay contract)
    f4 = interleave.SchedFuzzer.from_key(f1.replay_key())
    _fuzz_workload(f4, "fz-a")
    assert f4.trace_for("fz-a") == f1.trace_for("fz-a")


def _plant_run(n, fuzzer=None):
    """Two named threads each += 1 the planted counter n times.
    Returns (final value, races_report)."""
    with races.scope():
        p = _Plant()
        ev = threading.Event()

        def body():
            ev.wait(5)
            for _ in range(n):
                p.counter += 1

        ths = [threading.Thread(target=body, name=f"plant-{c}",
                                daemon=True) for c in "ab"]
        for t in ths:
            t.start()
        if fuzzer is not None:
            interleave.install(fuzzer)
        try:
            ev.set()
            for t in ths:
                t.join(60)
        finally:
            interleave.uninstall()
        assert not any(t.is_alive() for t in ths)
        return p.counter, races.report()


def test_planted_race_detected_by_lockset_and_schedule():
    """The acceptance shape: a straight (plain-scheduler) run leaves
    the planted lost-update latent — the value stays correct — but the
    lockset detector still convicts it; a seeded schedule makes the
    SAME bug manifest as an actually-wrong value, deterministically
    replayable via its replay_key."""
    n = 400
    races.enable()
    try:
        # plain run: raise the GIL switch interval so the scheduler
        # cannot preempt mid-RMW — the bug stays latent.  A loaded
        # host can still preempt at a blocking boundary and manifest
        # the lost update anyway (seen ~5% on a busy 1-core CI), so
        # retry a few times for a latent run; the detector must
        # convict it on EVERY attempt regardless.
        for _attempt in range(3):
            old_si = sys.getswitchinterval()
            sys.setswitchinterval(5.0)
            try:
                val, rep = _plant_run(n)
            finally:
                sys.setswitchinterval(old_si)
            assert any(r["var"] == "t0130.plant.counter"
                       for r in rep["races"]), \
                "lockset detector must convict the latent race"
            if val == 2 * n:
                break
        assert val == 2 * n, "plain run was supposed to miss the race"

        # seeded schedule: preemptions inside the get->set window make
        # the lost update real, twice, with one replay_key
        results = []
        for _ in range(2):
            fz = interleave.SchedFuzzer(7, preemption_bound=80, p=0.2)
            val, rep = _plant_run(n, fuzzer=fz)
            assert any(r["var"] == "t0130.plant.counter"
                       for r in rep["races"])
            results.append((val, fz.replay_key(),
                            fz.trace_for("plant-a")))
        (v1, k1, tr1), (v2, k2, tr2) = results
        assert v1 < 2 * n and v2 < 2 * n, \
            f"seeded schedule failed to reproduce the lost update " \
            f"({v1}, {v2} vs {2*n})"
        assert k1 == k2, "same seed must give the same replay_key"
        assert tr1 == tr2, "same seed must give the same per-thread trace"
    finally:
        races.disable()


def test_yield_points_quiescent_without_fuzzer():
    assert not interleave.active
    interleave.maybe_yield("nothing-installed")   # must be a no-op


# ------------------------------------------------------- lint rule ----
_LINT_POS = '''
from ..analysis.locks import new_lock

class Racy:
    def __init__(self):
        self._lock = new_lock("x.y")
        self.table = {}
'''

_LINT_NEG = '''
from ..analysis.locks import new_lock
from ..analysis.races import shared

class Fine:
    table = shared("x.table")

    def __init__(self):
        self._lock = new_lock("x.y")
        self.table = {}
'''

_LINT_SLOTS = '''
from ..analysis.races import register_slots
import threading

class SlotFine:
    __slots__ = ("q",)
    def __init__(self):
        self.t = threading.Thread(target=None, name="x")

register_slots(SlotFine, "q")
'''

_LINT_PRAGMA = '''
from ..analysis.locks import new_lock

class Judged:  # lint: ok shared-state
    """why: no mutable state outlives __init__."""
    def __init__(self):
        self._lock = new_lock("x.y")
'''


def test_lint_shared_state_positive():
    fs = lint_source(_LINT_POS, "client/fake.py")
    assert any(f.rule == "shared-state" and "Racy" in f.msg
               for f in fs), fs


def test_lint_shared_state_negative_decl_and_slots():
    assert not [f for f in lint_source(_LINT_NEG, "client/fake.py")
                if f.rule == "shared-state"]
    assert not [f for f in lint_source(_LINT_SLOTS, "mock/fake.py")
                if f.rule == "shared-state"]


def test_lint_shared_state_pragma_and_scope():
    assert not [f for f in lint_source(_LINT_PRAGMA, "client/fake.py")
                if f.rule == "shared-state"]
    # out of the lockdep-scoped layers: no finding
    assert not [f for f in lint_source(_LINT_POS, "obs/fake.py")
                if f.rule == "shared-state"]


def test_lint_package_clean():
    from librdkafka_tpu.analysis.lint import lint_package
    assert [str(f) for f in lint_package()] == []


# ------------------------------------------------ conf knob + e2e -----
def test_conf_knob_roundtrip():
    from librdkafka_tpu import Producer
    was = races.enabled
    with races.scope():
        p = Producer({"bootstrap.servers": "",
                      "test.mock.num.brokers": 1,
                      "analysis.races": True, "linger.ms": 1})
        try:
            assert races.enabled
            assert lockdep.enabled, "races implies lockdep"
            p.produce("races-knob", value=b"x", partition=0)
            assert p.flush(30) == 0
        finally:
            p.close()
        assert races.enabled == was
        rep = races.report()
        assert rep["accesses"] > 0
        assert races.clean(rep), rep["races"]


def test_e2e_produce_consume_sweep_clean():
    """Regression for every ISSUE-10 unguarded-access fix at once
    (fetchq accounting under kafka.toppar, stats counters under
    stats.counters, flush flag under kafka.msg_cnt, engine warmup
    bump, OpQueue wakeup publish): a ticketed produce + CRC-checked
    consume under the detector must end with zero strict findings."""
    from librdkafka_tpu import Consumer, Producer
    with races.scope():
        races.enable()
        c = None
        try:
            p = Producer({"bootstrap.servers": "",
                          "test.mock.num.brokers": 1,
                          "compression.backend": "tpu",
                          "tpu.transport.min.mb.s": 0,
                          "tpu.launch.min.batches": 2,
                          "tpu.governor": False, "tpu.warmup": False,
                          "compression.codec": "lz4", "linger.ms": 5,
                          "statistics.interval.ms": 100})
            try:
                bs = p._rk.mock_cluster.bootstrap_servers()
                for i in range(120):
                    p.produce("races-e2e", value=b"v%d" % i * 10,
                              partition=i % 4)
                assert p.flush(60) == 0
                stats_blob = p._rk.stats.emit_json()
                c = Consumer({"bootstrap.servers": bs,
                              "group.id": "races-e2e",
                              "auto.offset.reset": "earliest",
                              "check.crcs": True})
                c.subscribe(["races-e2e"])
                got = 0
                deadline = time.monotonic() + 45
                while got < 120 and time.monotonic() < deadline:
                    m = c.poll(0.2)
                    if m is not None and m.error is None:
                        got += 1
                assert got == 120
            finally:
                p.close()
                if c is not None:
                    c.close()
            rep = races.report()
            assert races.clean(rep), races.format_report(rep)
            # the resurrected txmsgs counter (the sweep also found it
            # was never bumped): acked count lands in the stats blob
            import json
            assert json.loads(stats_blob)["txmsgs"] == 120
        finally:
            races.disable()


def test_governor_ewma_lock_regression():
    """The flagship sweep finding: governor EWMAs are RMW'd from the
    dispatch thread while the stats emitter snapshots — all under
    engine.governor now; concurrent hammering must stay clean."""
    from librdkafka_tpu.ops.engine import _Governor
    with races.scope():
        races.enable()
        try:
            g = _Governor(True, 0.0005)
            stop = threading.Event()

            def model():
                i = 0
                while not stop.is_set() and i < 3000:
                    g.note_cpu(1000, 0.0001)
                    g.note_device(128, 0.0002, dev=i % 2)
                    g.route(128, 4096)
                    g.note_submit(time.monotonic())
                    i += 1

            def reader():
                for _ in range(300):
                    g.snapshot()
                    g.device_launch_ms(0)
                stop.set()

            _run_threads(model, reader)
            rep = races.report()
            assert races.clean(rep), races.format_report(rep)
            snap = g.snapshot()
            assert snap["cpu_ns_per_byte"] is not None
        finally:
            races.disable()


def test_fetchq_accounting_exact_under_contention():
    """Direct regression for the fetchq_cnt/fetchq_bytes lost-update:
    concurrent locked increments and clamped decrements must land on
    the exact expected value (the old bare RMW lost updates)."""
    from librdkafka_tpu.client.partition import Toppar
    tp = Toppar("t", 0)
    n = 2000

    def enq():
        for _ in range(n):
            with tp.lock:
                tp.fetchq_cnt += 1
                tp.fetchq_bytes += 10

    def drain():
        done = 0
        while done < n:
            with tp.lock:
                if tp.fetchq_cnt > 0:
                    fc = tp.fetchq_cnt - 1
                    tp.fetchq_cnt = fc if fc > 0 else 0
                    fb = tp.fetchq_bytes - 10
                    tp.fetchq_bytes = fb if fb > 0 else 0
                    done += 1

    _run_threads(enq, drain)
    assert tp.fetchq_cnt == 0 and tp.fetchq_bytes == 0


def test_races_cli_sweep_shape():
    """python -m librdkafka_tpu.analysis races wiring: the module
    resolves the command and the runner exposes the schedule seeds."""
    from librdkafka_tpu.analysis import __main__ as cli
    from librdkafka_tpu.analysis import stress
    assert cli.main(["bogus"]) == 2
    assert len(stress.SCHEDULE_SEEDS) >= 2
