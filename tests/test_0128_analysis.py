"""Analyzer tier (ISSUE 8): the lockdep runtime checker and the
project-invariant lint — the tooling itself must be tested, or the
gate it implements is hope with extra steps.

Covers: a deliberately introduced AB/BA inversion reported with BOTH
acquisition stacks, held-across-blocking detection, RLock re-entrancy
(and condvar waits over it) never flagged, same-class distinct-instance
nesting flagged, the ``analysis.lockdep`` conf knob wiring through a
real produce round trip (clean graph + released refcount), one
positive + one negative fixture per lint rule, pragma suppression, and
a clean lint run over the real package (the scripts/check.sh gate).
"""
import threading

from librdkafka_tpu.analysis import lint, lockdep, locks


# ===================================================== lockdep runtime ==
def test_abba_inversion_caught_with_both_stacks():
    with lockdep.scope():
        lockdep.enable()
        try:
            a = lockdep.DepLock("t.A")
            b = lockdep.DepLock("t.B")

            def fwd():
                with a:
                    with b:
                        pass

            th = threading.Thread(target=fwd, name="abba-fwd")
            th.start()
            th.join()
            with b:            # the inversion, safely sequenced
                with a:
                    pass
            rep = lockdep.report()
        finally:
            lockdep.disable()
    pairs = [c for c in rep["cycles"]
             if c["kind"] == "inconsistent_order"]
    assert len(pairs) == 1, rep["cycles"]
    c = pairs[0]
    assert set(c["path"]) == {"t.A", "t.B"}
    # both edges present, each carrying the acquisition stack that
    # created it, attributed to the right thread
    assert len(c["edges"]) == 2
    assert {e["thread"] for e in c["edges"]} == {"abba-fwd",
                                                 "MainThread"}
    for e in c["edges"]:
        assert "test_0128" in e["stack"], e
        assert ("fwd" in e["stack"]) or ("test_abba" in e["stack"])
    # the human rendering names the pair and includes the stacks
    txt = lockdep.format_report(rep)
    assert "inconsistent_order" in txt and "t.A" in txt
    assert not lockdep.clean(rep)


def test_held_across_blocking_detected_and_exonerated():
    with lockdep.scope():
        lockdep.enable()
        try:
            lk = lockdep.DepLock("t.blk")
            lockdep.note_blocking("t.sock.recv")   # nothing held: fine
            with lk:
                lockdep.note_blocking("t.sock.recv")
            rep = lockdep.report()
        finally:
            lockdep.disable()
    assert len(rep["blocking"]) == 1, rep["blocking"]
    v = rep["blocking"][0]
    assert v["lock"] == "t.blk" and v["call"] == "t.sock.recv"
    assert "test_0128" in v["stack"]
    assert not lockdep.clean(rep)


def test_rlock_reentrancy_never_flagged():
    with lockdep.scope():
        lockdep.enable()
        try:
            r = lockdep.DepRLock("t.R")
            with r:
                with r:                 # re-entrant: NOT an edge
                    with r:
                        pass
            rep = lockdep.report()
        finally:
            lockdep.disable()
    assert rep["cycles"] == [], rep["cycles"]
    assert lockdep.clean(rep)


def test_same_class_distinct_instances_flagged():
    # two instances of one lock class nested = the two-threads/two-
    # instances/opposite-order deadlock shape (kernel lockdep flags
    # this unless explicitly annotated as ordered nesting)
    with lockdep.scope():
        lockdep.enable()
        try:
            a = lockdep.DepLock("t.same")
            b = lockdep.DepLock("t.same")
            with a:
                with b:
                    pass
            rep = lockdep.report()
        finally:
            lockdep.disable()
    kinds = {c["kind"] for c in rep["cycles"]}
    assert kinds == {"self_order"}, rep["cycles"]


def test_condition_wait_releases_the_held_set():
    with lockdep.scope():
        lockdep.enable()
        try:
            cv = lockdep.DepCondition("t.cv")
            entered = threading.Event()
            done = threading.Event()

            def waiter():
                with cv:
                    entered.set()
                    cv.wait(timeout=5.0)
                done.set()

            th = threading.Thread(target=waiter, name="cv-waiter")
            th.start()
            assert entered.wait(5.0)
            # if wait() had NOT released through the wrapper, this
            # acquire would park until the waiter's timeout
            with cv:
                cv.notify()
            assert done.wait(5.0)
            th.join(5.0)
            rep = lockdep.report()
        finally:
            lockdep.disable()
    assert lockdep.clean(rep), lockdep.format_report(rep)


def test_condition_over_rlock_full_release_at_depth():
    # the txnmgr pattern: Condition over an RLock, wait() at recursion
    # depth 2 must fully release (stdlib _release_save) and restore
    with lockdep.scope():
        lockdep.enable()
        try:
            rl = lockdep.DepRLock("t.cvR")
            cv = lockdep.DepCondition("t.cvR", rl)
            entered = threading.Event()
            done = threading.Event()

            def waiter():
                with rl:
                    with rl:            # depth 2
                        with cv:        # depth 3, same lock
                            entered.set()
                            cv.wait(timeout=5.0)
                done.set()

            th = threading.Thread(target=waiter, name="cvR-waiter")
            th.start()
            assert entered.wait(5.0)
            with cv:
                cv.notify_all()
            assert done.wait(5.0)
            th.join(5.0)
            rep = lockdep.report()
        finally:
            lockdep.disable()
    assert lockdep.clean(rep), lockdep.format_report(rep)


def test_forwarded_queue_len_holds_one_lock_only():
    """Regression (found by the pytest --lockdep sweep, PR 8): len()
    of a forwarded OpQueue used to take the destination's lock while
    still holding its own — a same-class nested hold (queue.opq
    self-order) that a forwarding cycle would turn into a deadlock.
    The fwd pointer is now read under the lock and the destination
    measured after it drops."""
    from librdkafka_tpu.client.queue import Op, OpQueue, OpType
    with lockdep.scope():
        lockdep.enable()
        try:
            a, b = OpQueue("a"), OpQueue("b")
            a.forward_to(b)
            a.push(Op(OpType.BROKER_WAKEUP))
            assert len(a) == 1 == len(b)
            rep = lockdep.report()
        finally:
            lockdep.disable()
    assert lockdep.clean(rep), lockdep.format_report(rep)


def test_factory_plain_when_disabled_instrumented_when_enabled():
    import pytest
    if lockdep.enabled:
        pytest.skip("session runs under --lockdep; the disabled-mode "
                    "half is covered by the default tier-1 run")
    assert type(locks.new_lock("t.x")) is type(threading.Lock())
    assert isinstance(locks.new_rlock("t.x"), type(threading.RLock()))
    assert isinstance(locks.new_cond("t.x"), threading.Condition)
    with lockdep.scope():
        lockdep.enable()
        try:
            assert isinstance(locks.new_lock("t.x"), lockdep.DepLock)
            assert isinstance(locks.new_rlock("t.x"), lockdep.DepRLock)
            assert isinstance(locks.new_cond("t.x"),
                              lockdep.DepCondition)
        finally:
            lockdep.disable()


def test_client_knob_instruments_and_releases():
    """analysis.lockdep=true wires the whole client through DepLocks:
    a real produce round trip over the mock must leave a populated,
    CLEAN graph (this is the tier-1 shadow of the scripts/check.sh
    stress gate) and close() must drop the checker reference."""
    from librdkafka_tpu import Producer
    with lockdep.scope():
        base = lockdep._enable_count
        p = Producer({"bootstrap.servers": "",
                      "test.mock.num.brokers": 1,
                      "analysis.lockdep": True, "linger.ms": 1})
        try:
            assert lockdep.enabled
            for i in range(100):
                p.produce("ld-knob", value=b"v%d" % i, partition=i % 2)
            assert p.flush(30.0) == 0
        finally:
            p.close()
        assert lockdep._enable_count == base
        rep = lockdep.report()
    assert rep["acquisitions"] > 100
    assert rep["classes"] >= 4          # kafka/queue/toppar/broker...
    # the lock-order graph snapshot: the discipline the stress pass
    # verified stays acyclic — any new inversion fails HERE first
    assert lockdep.clean(rep), lockdep.format_report(rep)


# ========================================================== lint rules ==
def _rules(findings):
    return [f.rule for f in findings]


def test_lint_sleep_poll():
    bad = "import time\nwhile True:\n    time.sleep(0.1)\n"
    assert _rules(lint.lint_source(bad, "client/x.py")) == ["sleep-poll"]
    # same code outside client/: not this rule's scope
    assert lint.lint_source(bad, "ops/x.py") == []
    # non-loop sleep in client/ is allowed (startup delays etc.)
    assert lint.lint_source("import time\ntime.sleep(0.1)\n",
                            "client/x.py") == []
    # pragma suppression with a reason
    ok = ("import time\nwhile True:\n"
          "    time.sleep(0.1)  # lint: ok sleep-poll\n")
    assert lint.lint_source(ok, "client/x.py") == []


def test_lint_conf_prop():
    src = ('PROPERTIES = [\n'
           '    _p("x.ms", GLOBAL, "int", 5, "doc"),\n'
           ']\n')
    fs = lint.lint_source(src, "client/conf.py", doc_names={"x.ms"})
    assert _rules(fs) == ["conf-prop"] and "vmin" in fs[0].msg
    good = ('PROPERTIES = [\n'
            '    _p("x.ms", GLOBAL, "int", 5, "doc", vmin=0, vmax=9),\n'
            '    _p("y.ms", GLOBAL, "int", 5, "Alias.", alias="x.ms"),\n'
            ']\n')
    assert lint.lint_source(good, "client/conf.py",
                            doc_names={"x.ms", "y.ms"}) == []
    # documented nowhere -> the doc-row finding
    fs = lint.lint_source(good, "client/conf.py", doc_names={"x.ms"})
    assert _rules(fs) == ["conf-prop"] and "CONFIGURATION.md" in fs[0].msg
    # the rule only applies to conf.py
    assert lint.lint_source(src, "client/other.py") == []


def test_lint_trace_guard():
    bad = "_trace.instant('a', 'b')\n"
    assert _rules(lint.lint_source(bad, "client/x.py")) == ["trace-guard"]
    good = "if _trace.enabled:\n    _trace.instant('a', 'b')\n"
    assert lint.lint_source(good, "client/x.py") == []
    # guard-variable form (the engine's t0 pattern)
    gv = ("def f():\n"
          "    t0 = _trace.now() if _trace.enabled else 0\n"
          "    if t0:\n"
          "        _trace.complete('a', 'b', t0)\n")
    assert lint.lint_source(gv, "ops/x.py") == []
    # guard ATTRIBUTE form (broker.py's self.t_crc_ns pattern)
    ga = ("class P:\n"
          "    def s(self):\n"
          "        if _trace.enabled:\n"
          "            self.t0 = _trace.now()\n"
          "    def f(self):\n"
          "        if self.t0:\n"
          "            _trace.complete('a', 'b', self.t0)\n")
    assert lint.lint_source(ga, "client/x.py") == []
    # trace.py itself is exempt (it IS the tracer)
    assert lint.lint_source(bad.replace("_trace", "trace"),
                            "obs/trace.py") == []


def test_lint_bare_except():
    bad = "try:\n    f()\nexcept:\n    pass\n"
    assert _rules(lint.lint_source(bad, "utils/x.py")) == ["bare-except"]
    good = "try:\n    f()\nexcept Exception:\n    pass\n"
    assert lint.lint_source(good, "utils/x.py") == []


def test_lint_chaos_random():
    bad = "import random\nx = random.random()\n"
    assert _rules(lint.lint_source(bad, "chaos/x.py")) == ["chaos-random"]
    # the seeded-Random constructor is exactly what the schedule does
    good = "import random\nrng = random.Random(7)\nx = rng.random()\n"
    assert lint.lint_source(good, "chaos/x.py") == []
    # outside chaos/ the rule does not apply (sockem jitter is mock/)
    assert lint.lint_source(bad, "mock/x.py") == []


def test_lint_thread_name():
    bad = "import threading\nt = threading.Thread(target=f)\n"
    assert _rules(lint.lint_source(bad, "ops/x.py")) == ["thread-name"]
    good = "import threading\nt = threading.Thread(target=f, name='x')\n"
    assert lint.lint_source(good, "ops/x.py") == []
    # subclass form: super().__init__ must forward a name
    # the class-line pragma isolates the thread-name rule: a Thread
    # subclass with no declared state also trips shared-state (ISSUE
    # 10), which has its own fixtures in test_0130
    sub_bad = ("import threading\n"
               "class P(threading.Thread):  # lint: ok shared-state\n"
               "    def __init__(self):\n"
               "        super().__init__(daemon=True)\n")
    assert _rules(lint.lint_source(sub_bad, "mock/x.py")) == ["thread-name"]
    assert lint.lint_source(
        sub_bad.replace("daemon=True", "daemon=True, name='p'"),
        "mock/x.py") == []


def test_lint_manual_acquire():
    bad = "lk.acquire()\ntry:\n    f()\nfinally:\n    lk.release()\n"
    assert _rules(lint.lint_source(bad, "client/x.py")) == \
        ["manual-acquire"]
    assert lint.lint_source("with lk:\n    f()\n", "client/x.py") == []
    # lockdep's wrappers ARE the acquire implementation — exempt
    assert lint.lint_source(bad, "analysis/lockdep.py") == []


def test_lint_lock_factory():
    bad = "import threading\nlk = threading.Lock()\n"
    for scoped in ("client/x.py", "mock/x.py", "chaos/x.py",
                   "ops/engine.py", "ops/tpu.py"):
        assert _rules(lint.lint_source(bad, scoped)) == ["lock-factory"], \
            scoped
    # out-of-scope layers may keep plain primitives (module-level
    # import-time locks: obs/trace.py, parallel/mesh.py, utils)
    assert lint.lint_source(bad, "obs/x.py") == []
    assert lint.lint_source(bad, "ops/crc32c_jax.py") == []
    good = "lk = new_lock('x')\n"
    assert lint.lint_source(good, "client/x.py") == []


def test_lint_clean_over_real_package():
    findings = lint.lint_package()
    assert findings == [], "\n".join(str(f) for f in findings)
