"""Plugin module for test_0066_plugins (the analog of the reference's
tests/plugin_test shared object loaded via plugin.library.paths): the
conf_init() contract receives (conf, chain) and registers interceptors."""

CALLS = {"conf_init": 0, "on_send": 0, "on_acknowledgement": 0,
         "on_new": 0, "on_request_sent": 0, "on_thread_start": 0,
         "on_thread_exit": 0}


def conf_init(conf, chain):
    CALLS["conf_init"] += 1
    chain.add("plugin_fixture", "on_new",
              lambda rk: CALLS.__setitem__("on_new", CALLS["on_new"] + 1))
    chain.add("plugin_fixture", "on_send",
              lambda msg: CALLS.__setitem__("on_send", CALLS["on_send"] + 1))
    chain.add("plugin_fixture", "on_acknowledgement",
              lambda msg: CALLS.__setitem__(
                  "on_acknowledgement", CALLS["on_acknowledgement"] + 1))
    chain.add("plugin_fixture", "on_request_sent",
              lambda *a: CALLS.__setitem__(
                  "on_request_sent", CALLS["on_request_sent"] + 1))
    chain.add("plugin_fixture", "on_thread_start",
              lambda *a: CALLS.__setitem__(
                  "on_thread_start", CALLS["on_thread_start"] + 1))
    chain.add("plugin_fixture", "on_thread_exit",
              lambda *a: CALLS.__setitem__(
                  "on_thread_exit", CALLS["on_thread_exit"] + 1))


def custom_entry(conf, chain):
    CALLS["conf_init"] += 100
