"""Network-shaping fault tests via the sockem shim (reference:
tests/sockem.c interposed through socket_cb/connect_cb; test patterns
from 0075-retry.c and 0088-produce_metadata_timeout.c): latency and
bandwidth shaping on live connections, and — the critical one —
killing a connection mid-ProduceRequest and proving the idempotent
producer retries without duplication."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.mock.sockem import Sockem
from librdkafka_tpu.protocol.msgset import iter_batches, parse_records_v2


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"net": 1})
    yield c
    c.stop()


def _log_values(cluster, topic="net", part=0):
    vals = []
    for base, blob in cluster.partition(topic, part).log:
        for info, payload, full in iter_batches(blob):
            vals += [r.value for r in parse_records_v2(info, payload)]
    return vals


def test_latency_injection_slows_but_delivers(cluster):
    em = Sockem(delay_ms=0)
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb, "linger.ms": 2})
    p.produce("net", value=b"fast", partition=0)
    assert p.flush(10.0) == 0
    assert em.connect_count >= 1

    em.set(delay_ms=400)
    t0 = time.monotonic()
    p.produce("net", value=b"slow", partition=0)
    assert p.flush(15.0) == 0
    took = time.monotonic() - t0
    assert took >= 0.4, f"delay not applied ({took:.3f}s)"
    assert _log_values(cluster) == [b"fast", b"slow"]
    p.close()


def test_rate_limit_paces_transfer(cluster):
    em = Sockem(rate_bps=40000)
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb, "linger.ms": 2,
                  "compression.codec": "none"})
    payload = b"x" * 40000            # ~1 second at 40 kB/s
    t0 = time.monotonic()
    p.produce("net", value=payload, partition=0)
    assert p.flush(20.0) == 0
    took = time.monotonic() - t0
    assert took >= 0.8, f"rate cap not applied ({took:.3f}s)"
    p.close()


def test_kill_mid_produce_retries_without_duplication(cluster):
    """Throttle the link so the ProduceRequest is mid-transfer, kill the
    connection, and verify the idempotent producer redelivers exactly
    once after reconnect (reference 0075-retry.c + sockem kill)."""
    em = Sockem()
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb,
                  "enable.idempotence": True,
                  "linger.ms": 5, "retry.backoff.ms": 50,
                  "message.send.max.retries": 20,
                  "message.timeout.ms": 30000})
    # warm connection + PID assignment
    p.produce("net", value=b"warm", partition=0)
    assert p.flush(10.0) == 0

    # choke the pipe so the next request crawls, then cut it mid-flight
    em.set(rate_bps=30000)
    n = 100
    for i in range(n):
        p.produce("net", value=(b"m%03d-" % i) * 100, partition=0)  # ~600B
    time.sleep(0.6)                  # request is mid-transfer now
    killed = em.kill_all()
    assert killed >= 1, "nothing was in flight to kill"
    em.set(rate_bps=0)               # restore full speed for the retry

    assert p.flush(30.0) == 0
    vals = _log_values(cluster)
    body = [v for v in vals if v != b"warm"]
    assert len(body) == n, f"expected {n}, log has {len(body)}"
    assert len(set(body)) == n, "duplicated messages in the log"
    p.close()


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_request_timeout_retry_no_duplicate(cluster, backend):
    """The reference 0075-retry.c shape: 2 s of injected latency makes
    the in-flight ProduceRequest overshoot the request timeout
    (socket.timeout.ms — the client-side budget; the topic's
    request.timeout.ms is the broker-side wait), the client times it
    out and retries, and after the latency clears the retry succeeds
    with NO duplicate: whichever copy lands second is deduped broker-
    side via the idempotent (pid, epoch, seq) check.  Runs on both the
    sync CPU codec path and the ticketed offload-engine path — retry
    semantics must be identical."""
    em = Sockem()
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb,
                  "enable.idempotence": True,
                  "compression.backend": backend,
                  "compression.codec": "lz4",
                  "linger.ms": 2,
                  "socket.timeout.ms": 1000, "socket.max.fails": 0,
                  "retry.backoff.ms": 100,
                  "message.send.max.retries": 20,
                  "message.timeout.ms": 30000})
    # warm connection + PID assignment at full speed
    p.produce("net", value=b"warm", partition=0)
    assert p.flush(10.0) == 0

    em.set(delay_ms=2000)
    p.produce("net", value=b"timeout-victim", partition=0)
    time.sleep(1.4)          # > socket.timeout.ms: the timeout fired
    brokers = list(p.rk.brokers.values())
    assert sum(b.c_req_timeouts for b in brokers) >= 1, \
        "request should have timed out under 2s latency"
    em.set(delay_ms=0)
    assert p.flush(20.0) == 0

    vals = _log_values(cluster)
    assert vals.count(b"timeout-victim") == 1, \
        f"retry duplicated the message: {vals}"
    # the broker really saw the request more than once (original +
    # timed-out retry), i.e. success came from a retry + dedup, not
    # from a lucky slow first attempt
    from librdkafka_tpu.protocol.proto import ApiKey
    n_produce = sum(1 for _b, api in cluster.request_log
                    if api == int(ApiKey.Produce))
    assert n_produce >= 3, f"expected warm + original + retry, saw {n_produce}"
    p.close()


@pytest.mark.chaos
def test_kill9_during_produce_backoff_and_dedup():
    """ISSUE 9 retry shape, out-of-process: SIGKILL the REAL broker
    process mid-produce.  While the port is unbound the client must
    walk the jittered reconnect.backoff.ms schedule
    (client/broker.py _update_reconnect_backoff: -25%..+50% jitter,
    base doubling, reconnect.backoff.max.ms cap) — and once the
    process respawns, exactly one copy of every message survives
    broker-side idempotent (pid, epoch, seq) dedup."""
    from librdkafka_tpu.mock.external import ClusterHandle, pid_alive

    base_ms, max_ms = 200, 1500
    h = ClusterHandle(brokers=1, topics={"net": 1})
    p = None
    c = None
    try:
        p = Producer({"bootstrap.servers": h.bootstrap_servers(),
                      "enable.idempotence": True, "linger.ms": 2,
                      "reconnect.backoff.ms": base_ms,
                      "reconnect.backoff.max.ms": max_ms,
                      "socket.timeout.ms": 2000, "socket.max.fails": 0,
                      "retry.backoff.ms": 50,
                      "message.send.max.retries": 200,
                      "message.timeout.ms": 60000})
        # warm connection + PID assignment
        p.produce("net", value=b"warm", partition=0)
        assert p.flush(15.0) == 0

        n = 40
        for i in range(n):
            p.produce("net", value=b"k%03d" % i, partition=0)
        p.poll(0)                       # some batches now in flight
        pid = h.broker_pids[1]
        r = h.kill9(1)
        assert r["exit"] == -9 and not pid_alive(pid), \
            "broker process must be SIGKILLed dead"

        # dead window: connects hit ECONNREFUSED and every failure
        # re-arms the jittered backoff schedule
        time.sleep(2.2)
        h.restart_broker(1)
        assert p.flush(60.0) == 0

        brokers = [b for b in p.rk.brokers.values() if b.nodeid >= 0]
        hist = [d for _ts, d in brokers[0].reconnect_history]
        assert len(hist) >= 2, \
            f"expected repeated backoff decisions, saw {hist}"
        lo, hi = 0.75 * base_ms / 1000.0, max_ms / 1000.0
        assert all(lo <= d <= hi * 1.0001 for d in hist), \
            f"backoff outside jitter/cap bounds: {hist}"
        # the base doubles under consecutive failures, so later
        # delays must grow beyond the first round's jitter ceiling
        assert max(hist) > base_ms / 1000.0 * 1.5001 or \
            max(hist) == pytest.approx(hi, rel=1e-6), \
            f"no backoff growth across the dead window: {hist}"

        # exactly one copy of each message (broker-side dedup), read
        # back through a real consumer — the external log is in
        # another process
        c = Consumer({"bootstrap.servers": h.bootstrap_servers(),
                      "group.id": "g-kill9",
                      "auto.offset.reset": "earliest"})
        c.subscribe(["net"])
        got = []
        deadline = time.monotonic() + 30
        while len(got) < n + 1 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append(bytes(m.value))
        body = [v for v in got if v != b"warm"]
        assert sorted(body) == sorted(b"k%03d" % i for i in range(n)), \
            f"loss or duplication across kill9: {len(body)}/{n}"
    finally:
        if p is not None:
            p.close()
        if c is not None:
            c.close()
        h.stop()


def test_connection_kill_recovery_consumer(cluster):
    """Consumer side: kill the connection between fetches; the consumer
    reconnects and resumes from its offsets without message loss."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(30):
        p.produce("net", value=b"c%d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    em = Sockem()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "connect_cb": em.connect_cb,
                  "group.id": "gsock", "auto.offset.reset": "earliest",
                  "session.timeout.ms": 30000})
    c.subscribe(["net"])
    got = []
    deadline = time.monotonic() + 30
    killed = False
    while len(got) < 30 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m.value)
        if len(got) >= 10 and not killed:
            killed = True
            em.kill_all()
    assert killed
    assert sorted(got) == sorted(b"c%d" % i for i in range(30)), \
        f"got {len(got)}"
    c.close()
