"""Deferred-fetch claim discipline + close() teardown guards (ISSUE 1
satellites): a partition migrated off a broker must get its
``fetch_in_flight`` claim released even while the old broker's
queued-bytes budget is exhausted (its new leader is otherwise blocked
by an undrained backlog), and close() must not rip shared structures
out from under a broker thread that failed to join."""
import threading
import time
from collections import deque
from types import SimpleNamespace

from librdkafka_tpu.client.broker import Broker, Request
from librdkafka_tpu.protocol.proto import ApiKey


class _FakeTp:
    def __init__(self, name, part=0, qbytes=0):
        self.topic = name
        self.partition = part
        self.fetch_in_flight = True
        # the budget reads now snapshot under the toppar lock (ISSUE
        # 10 fetchq-accounting fix), so the shell needs one
        self.lock = threading.Lock()
        self.fetchq_bytes = qbytes


def _fake_broker(budget_kb: int = 0) -> Broker:
    """A Broker shell with just the state _serve_deferred_fetch needs —
    no socket, no thread."""
    b = Broker.__new__(Broker)
    b.name = "fake:0/1"
    b.rk = SimpleNamespace(
        conf=SimpleNamespace(
            get=lambda k: {"queued.max.messages.kbytes": budget_kb}[k]),
        fetch_pipeline_depth=2,
        log=lambda *a, **k: None)
    b.toppars = set()
    b._fetch_deferred = deque()
    b._fetch_pending = deque()
    # the budget walk is O(active) since ISSUE 14 — the shell's active
    # set is simply whatever the broker owns
    b.rk.active_toppars = lambda: list(b.toppars)
    return b


def test_migrated_partition_released_despite_exhausted_budget():
    """Budget 0 (every drain returns immediately): the migrated
    partition's claim must still be released, while the owned
    partition's entry stays parked AND claimed."""
    b = _fake_broker(budget_kb=0)
    owned = _FakeTp("owned")
    migrated = _FakeTp("migrated")
    b.toppars = {owned}
    b._fetch_deferred.extend([
        (migrated, {}, None, 0, 0),
        (owned, {}, None, 0, 0),
    ])
    b._serve_deferred_fetch()
    assert migrated.fetch_in_flight is False
    assert owned.fetch_in_flight is True
    assert len(b._fetch_deferred) == 1
    assert b._fetch_deferred[0][0] is owned


def test_owned_partition_processed_when_budget_allows():
    from librdkafka_tpu.client.broker import _PendingFetch

    b = _fake_broker(budget_kb=1024)
    owned = _FakeTp("owned")
    migrated = _FakeTp("migrated")
    b.toppars = {owned}
    begun, finished = [], []
    b._begin_fetch_partition = \
        lambda entry: (begun.append(entry[0]), _PendingFetch(entry))[1]
    b._finish_fetch_partition = \
        lambda pend: finished.append(pend.entry[0])
    b._fetch_deferred.extend([
        (migrated, {}, None, 0, 0),
        (owned, {}, None, 0, 0),
    ])
    b._serve_deferred_fetch()
    assert begun == [owned]
    assert finished == [owned]
    assert owned.fetch_in_flight is False
    assert migrated.fetch_in_flight is False
    assert not b._fetch_deferred
    assert not b._fetch_pending


def test_close_leaves_stuck_broker_structures_alone():
    """close() only reaps a broker's buffers/queues when its thread
    really exited: a stuck thread still owns them (clearing under it
    races the serve loop)."""
    from librdkafka_tpu import Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 2})
    p.produce("guard", value=b"x", partition=0)
    assert p.flush(10.0) == 0
    rk = p._rk
    with rk._brokers_lock:
        brokers = list(rk.brokers.values())
    # wedge one broker's serve loop: it never processes ops, so the
    # TERMINATE op close() pushes is never seen and the join times out
    stuck = brokers[0]
    stuck._serve = lambda: time.sleep(0.05)
    time.sleep(0.3)               # let any in-progress serve pass drain
    stuck._rbuf += b"sentinel"
    stuck.waitresp[999999] = Request(ApiKey.Metadata, {})
    try:
        p.close()
        assert stuck.thread.is_alive()
        # the stuck broker kept its structures...
        assert bytes(stuck._rbuf).endswith(b"sentinel")
        assert 999999 in stuck.waitresp
        # ...while cleanly-exited brokers were reaped
        for b in brokers[1:]:
            if not b.thread.is_alive():
                assert not b.waitresp
    finally:
        stuck.terminate = True    # let the wedged thread exit
        stuck.thread.join(5)


class _Evil:
    """Deque stand-in whose iteration raises like a mutated deque."""

    def __iter__(self):
        raise RuntimeError("deque mutated during iteration")

    def clear(self):
        raise RuntimeError("deque mutated during iteration")


def test_broker_exit_deferred_release_survives_concurrent_clear():
    """The thread-exit deferred-release loop is guarded: a concurrent
    clear (close() racing a stuck exit path) mutating the deque must
    not raise out of _thread_main."""
    conf = {"reconnect.backoff.ms": 100}
    rk = SimpleNamespace(conf=SimpleNamespace(get=lambda k: conf[k]),
                         interceptors=None,
                         dbg=lambda *a, **k: None,
                         log=lambda *a, **k: None)
    b = Broker(rk, 1, "localhost", 1)
    b.terminate = True
    b._fetch_deferred = _Evil()
    b._thread_main()          # must return cleanly, not raise
