"""produce_batch per-message error parity (reference:
rd_kafka_produce_batch sets rkmessages[i].err, rdkafka_msg.c:478):
a mixed batch must report which messages failed and why, not silently
drop them."""
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"t0121": 2})
    yield c
    c.stop()


def test_produce_batch_per_message_errors(cluster):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "message.max.bytes": 1000})
    msgs = [
        {"value": b"ok-1", "partition": 0},
        {"value": b"x" * 2000, "partition": 0},        # oversize
        {"value": b"ok-2", "key": b"k", "partition": 1},
        {"value": b"x" * 5000, "partition": 1},        # oversize
        {"value": b"ok-3", "partition": 0},
    ]
    n = p.produce_batch("t0121", msgs)
    assert n == 3
    assert "error" not in msgs[0]
    assert msgs[1]["error"].code == Err.MSG_SIZE_TOO_LARGE
    assert "error" not in msgs[2]
    assert msgs[3]["error"].code == Err.MSG_SIZE_TOO_LARGE
    assert "error" not in msgs[4]
    assert p.flush(10) == 0
    p.close()


def test_produce_batch_queue_full():
    # tiny queue: overflow must surface _QUEUE_FULL per message, and the
    # count must reflect only the enqueued ones.  No broker: nothing
    # drains the queue mid-batch.
    p = Producer({"bootstrap.servers": "127.0.0.1:1",
                  "queue.buffering.max.messages": 5,
                  "message.timeout.ms": 100})
    msgs = [{"value": b"v%d" % i, "partition": 0} for i in range(8)]
    n = p.produce_batch("t0121q", msgs)
    assert n == 5
    errs = [m.get("error") for m in msgs]
    assert [e.code for e in errs if e] == [Err._QUEUE_FULL] * 3
    p.purge(in_queue=True)
    p.flush(2)
    p.close()
