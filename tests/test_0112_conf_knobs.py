"""Behavioral tests for conf knobs wired in round 3 (VERDICT r2 weak
#6): socket.max.fails, queue.buffering.backpressure.threshold,
allow.auto.create.topics, log.queue / log.thread.name,
message.copy.max.bytes, group.protocol.type."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"kn": 2})
    yield c
    c.stop()


def test_socket_max_fails_forces_reconnect(cluster):
    """Consecutive request timeouts reach socket.max.fails → the broker
    connection is torn down and re-established (reference:
    rkb_req_timeouts handling in rdkafka_broker.c)."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "socket.timeout.ms": 400,
                  "socket.max.fails": 2,
                  "message.timeout.ms": 8000,
                  "retries": 100, "retry.backoff.ms": 50})

    def total_connects():
        rk = p._rk
        return sum(b.c_connects for b in
                   list(rk.brokers.values()) + list(rk._bootstrap))

    # establish the connection cleanly first
    p.produce("kn", value=b"warm", partition=0)
    assert p.flush(10.0) == 0
    base = total_connects()
    # now every response is delayed past socket.timeout.ms: two
    # consecutive request timeouts must tear the connection down
    cluster.set_rtt(1, 4000)
    p.produce("kn", value=b"x", partition=0)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and total_connects() <= base:
        time.sleep(0.05)
    assert total_connects() > base, \
        "no reconnect after socket.max.fails timeouts"
    cluster.set_rtt(1, 0)
    p.flush(10.0)
    p.close()


def test_backpressure_threshold_batches_harder():
    """On a rate-limited socket (sockem), untransmitted requests back up
    in the write buffer; threshold=1 must then pause MessageSet
    formation → strictly fewer, larger batches than an effectively-
    disabled threshold under identical load."""
    import socket as _socket

    from librdkafka_tpu.mock.sockem import Sockem

    counts = {}
    for thresh in (1, 1000000):
        c = MockCluster(num_brokers=1, topics={"bp": 1})
        # slow proxy + tiny client send buffer: the socket genuinely
        # backs up, so untransmitted requests sit in the broker's write
        # buffer where the threshold can see them
        em = Sockem(rate_bps=24 * 1024)

        def connect_cb(host, port, timeout, _em=em):
            s = _em.connect_cb(host, port, timeout)
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, 4096)
            return s

        p = Producer({"bootstrap.servers": c.bootstrap_servers(),
                      "connect_cb": connect_cb,
                      "queue.buffering.backpressure.threshold": thresh,
                      "linger.ms": 0, "batch.num.messages": 10000,
                      "message.timeout.ms": 60000})
        # pace the app thread so the serve loop runs many times during
        # the burst — without backpressure that means many small
        # requests piling into the choked socket
        for i in range(300):
            p.produce("bp", value=b"y" * 100, partition=0)
            if i % 10 == 9:
                time.sleep(0.002)
        assert p.flush(60.0) == 0
        counts[thresh] = len(c.partition("bp", 0).log)
        p.close()
        c.stop()
    # with backpressure the producer must coalesce into FEWER requests
    assert counts[1] < counts[1000000], counts


def test_allow_auto_create_topics_consumer(cluster):
    """Consumer metadata for an unknown topic must NOT auto-create it
    unless allow.auto.create.topics=true (KIP-204, Metadata v4 flag)."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "g-no-create",
                  "allow.auto.create.topics": False})
    c.subscribe(["kn-nocreate"])
    for _ in range(20):
        c.poll(0.1)
        if "kn-nocreate" in cluster.topics:
            break
    assert "kn-nocreate" not in cluster.topics
    c.close()

    c2 = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                   "group.id": "g-create",
                   "allow.auto.create.topics": True})
    c2.subscribe(["kn-docreate"])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and "kn-docreate" not in cluster.topics:
        c2.poll(0.1)
    assert "kn-docreate" in cluster.topics
    c2.close()


def test_producer_metadata_always_allows_auto_create(cluster):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 1})
    p.produce("kn-prod-new", value=b"z")
    assert p.flush(10.0) == 0
    assert "kn-prod-new" in cluster.topics
    p.close()


def test_log_queue_and_thread_name(cluster):
    """log.queue=true: logs arrive as LOG events from the app queue,
    tagged [thrd:...] when log.thread.name=true."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "log.queue": True, "log.thread.name": True,
                  "log_level": 7})
    p._rk.log("INFO", "queued line")
    logs = []
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not logs:
        ev = p._rk.queue_poll(0.1)
        if ev is not None and ev.type == "LOG":
            logs.append(ev.log())
    assert logs, "no LOG event on the app queue with log.queue=true"
    level, fac, msg = logs[0]
    assert level == "INFO" and fac == "rdkafka"
    assert "[thrd:" in msg and msg.endswith("queued line")
    p.close()

    # log.thread.name=false: no prefix; log.queue=false: direct log_cb
    seen = []
    p2 = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                   "log.thread.name": False, "log_level": 7,
                   "log_cb": lambda lvl, fac, m: seen.append(m)})
    p2._rk.log("INFO", "direct line")
    assert seen == ["direct line"]
    p2.close()


def test_message_copy_max_bytes_lane_routing(cluster):
    """Payloads above message.copy.max.bytes skip the arena copy and
    take the reference-holding Message path; both deliver."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "message.copy.max.bytes": 64, "linger.ms": 2})
    small = b"s" * 10
    big = b"B" * 4096
    p.produce("kn", value=small, partition=0)   # arena lane
    p.produce("kn", value=big, partition=0)     # Message path (referenced)
    assert p.flush(10.0) == 0
    blobs = b"".join(blob for _, blob in cluster.partition("kn", 0).log)
    assert small in blobs and big in blobs
    p.close()


def test_tpu_knob_validation_at_set_time(tmp_path):
    """ISSUE 3 satellite: every tpu.* knob fails at Conf.set() time
    with a clear error — negative/zero depths, bad bools, an unusable
    compile-cache path — never at first launch."""
    from librdkafka_tpu.client.conf import Conf
    from librdkafka_tpu.client.errors import KafkaException

    bad = [
        ("tpu.pipeline.depth", -1),          # negative depth
        ("tpu.pipeline.depth", 99),          # above range
        ("tpu.fetch.pipeline.depth", 0),     # zero is not a valid depth
        ("tpu.fetch.pipeline.depth", -3),
        ("tpu.pipeline.fanin.us", -1),       # negative window
        ("tpu.pipeline.fanin.us", 10**9),    # absurd window
        ("tpu.launch.min.batches", 0),       # quorum floor is >= 1
        ("tpu.warmup", "definitely"),        # not a bool
        ("tpu.governor", "perhaps"),
        # compile cache: parent directory must exist
        ("tpu.compile.cache.dir",
         str(tmp_path / "no-such-parent" / "deeper" / "cache")),
    ]
    for name, value in bad:
        with pytest.raises(KafkaException) as ei:
            Conf().set(name, value)
        assert name in str(ei.value) or "Expected" in str(ei.value), \
            (name, str(ei.value))
    # a file is not a usable cache directory
    somefile = tmp_path / "a-file"
    somefile.write_text("x")
    with pytest.raises(KafkaException):
        Conf().set("tpu.compile.cache.dir", str(somefile))

    # valid values round-trip, including the documented 'disabled'
    # zeros and a creatable (not-yet-existing) cache dir
    c = Conf()
    c.set("tpu.pipeline.depth", 0)           # 0 = engine disabled
    c.set("tpu.pipeline.fanin.us", 0)        # 0 = dispatch immediately
    c.set("tpu.warmup", False)
    c.set("tpu.governor", "true")
    c.set("tpu.compile.cache.dir", str(tmp_path / "cache"))
    assert c.get("tpu.governor") is True
    assert c.get("tpu.warmup") is False
    assert c.get("tpu.compile.cache.dir").endswith("cache")
    existing = tmp_path / "have"
    existing.mkdir()
    c.set("tpu.compile.cache.dir", str(existing))


def test_tpu_governor_knobs_reach_provider():
    """Conf plumbing: tpu.governor / tpu.warmup / tpu.compile.cache.dir
    reach the TpuCodecProvider the client constructs."""
    from librdkafka_tpu import Producer

    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "compression.backend": "tpu",
                  "tpu.transport.min.mb.s": 0,
                  "tpu.governor": False, "tpu.warmup": False})
    try:
        prov = p._rk.codec_provider
        assert prov.governor is False
        assert prov.engine_warmup is False
        assert prov.compile_cache_dir is None
    finally:
        p.close()


def test_group_protocol_type_on_wire(cluster):
    """group.protocol.type feeds JoinGroup's protocol_type field — the
    mock group records what the client sent."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gpt",
                  "group.protocol.type": "myproto"})
    c.subscribe(["kn"])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        c.poll(0.1)
        grp = cluster.groups.get("gpt")
        if grp is not None and grp.protocol_type:
            break
    grp = cluster.groups.get("gpt")
    assert grp is not None and grp.protocol_type == "myproto"
    c.close()
