"""Message timeout + unknown partition delivery-failure tests — analogs
of the reference's 0094-idempotence_msg_timeout.c and the
rd_kafka_broker_toppar_msgq_scan path (rdkafka_broker.c:3093): every
queue — msgq, xmit_msgq AND frozen retry batches — must expire within
message.timeout.ms, flush() must return, and producing to a partition
that does not exist must fail fast (UNKNOWN_PARTITION,
rdkafka_msg.c partitioning path) instead of parking forever.
"""
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.protocol.proto import ApiKey


def _producer(**extra):
    conf = {"bootstrap.servers": "", "test.mock.num.brokers": 1,
            "linger.ms": 2, "batch.num.messages": 50}
    conf.update(extra)
    return Producer(conf)


def test_unknown_partition_fails_parked_messages():
    """Messages produced to out-of-range partitions before metadata
    arrives get _UNKNOWN_PARTITION error DRs once the real partition
    count is known — they must not park until message.timeout.ms."""
    drs = []
    p = _producer()
    p._rk.conf.set("dr_msg_cb", lambda err, msg: drs.append((err, msg)))
    # partition 99 >> mock default of 4; produced before metadata arrives
    p.produce("nopart", value=b"x", partition=99)
    assert p.flush(10.0) == 0, "flush must drain via the error DR"
    errs = [e for e, _ in drs if e is not None]
    assert len(errs) == 1 and errs[0].code == Err._UNKNOWN_PARTITION
    p.close()


def test_unknown_partition_fails_fast_when_count_known():
    p = _producer()
    p.produce("t", value=b"ok", partition=0)
    assert p.flush(10.0) == 0
    with pytest.raises(KafkaException) as ei:
        p.produce("t", value=b"x", partition=99)
    assert ei.value.error.code == Err._UNKNOWN_PARTITION
    # the failed produce must not leak queue accounting
    assert p._rk.msg_cnt == 0
    p.close()


def test_msg_timeout_expires_retry_batches_broker_down():
    """Kill the mock broker mid-produce with retries pending: ALL
    messages — including frozen retry batches — get _MSG_TIMED_OUT DRs
    within message.timeout.ms and flush() returns (reference scans all
    queues, rdkafka_broker.c:3093)."""
    drs = []
    p = _producer(**{"message.timeout.ms": 2500,
                     "enable.idempotence": True,
                     "message.send.max.retries": 10000,
                     # long backoff: the frozen retry batch is still
                     # parked in tp.retry_batches when the broker dies
                     "retry.backoff.ms": 1000})
    p._rk.conf.set("dr_msg_cb", lambda err, msg: drs.append(err))
    cluster = p._rk.mock_cluster
    p.produce("tmo", value=b"warm", partition=0)
    assert p.flush(10.0) == 0
    # force a retriable produce error, then take the broker down so the
    # frozen retry batch can never resend
    cluster.push_request_errors(ApiKey.Produce, [Err.REQUEST_TIMED_OUT])
    for i in range(20):
        p.produce("tmo", value=b"m%d" % i, partition=0)
    time.sleep(0.2)             # let the first send + error happen
    cluster.set_broker_down(1)
    t0 = time.monotonic()
    assert p.flush(30.0) == 0, "flush must return once messages expire"
    took = time.monotonic() - t0
    assert took < 15.0, f"flush took {took:.1f}s; retry batches not scanned?"
    errs = [e for e in drs if e is not None]
    assert len(errs) == 20
    assert all(e.code == Err._MSG_TIMED_OUT for e in errs)
    cluster.set_broker_down(1, False)
    p.close()


def test_retry_backoff_is_honored():
    """A failed batch must not burn retries instantly: with
    retry.backoff.ms=200 and 3 consecutive injected errors, delivery
    takes >= ~3 backoffs (ADVICE: enqueue_retry_batch previously resent
    on the very next serve tick)."""
    p = _producer(**{"retry.backoff.ms": 200,
                     "message.send.max.retries": 10})
    cluster = p._rk.mock_cluster
    p.produce("bk", value=b"warm", partition=0)
    assert p.flush(10.0) == 0
    cluster.push_request_errors(
        ApiKey.Produce, [Err.REQUEST_TIMED_OUT] * 3)
    t0 = time.monotonic()
    p.produce("bk", value=b"retry-me", partition=0)
    assert p.flush(15.0) == 0
    took = time.monotonic() - t0
    assert took >= 0.55, f"delivered in {took*1000:.0f}ms — backoff ignored"
    p.close()
