"""Fleet subsystem (ISSUE 11): multi-process client traffic simulator
+ environment fault library.

Tier structure: traffic-shape/plan/verb/merge unit tests and the relay
brownout protocol run plain in tier-1; everything that launches real
worker/broker OS processes is ``fleet``-marked (the fast scenarios
stay tier-1 — scripts/fleet.sh is the tier's runner); the ≥24-worker
flagship storm is ``slow``.
"""
import json
import os
import selectors
import socket
import subprocess
import sys
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.chaos.oracle import DeliveryOracle, OracleViolation
from librdkafka_tpu.chaos.schedule import (ChaosScheduler, Schedule,
                                           env_brownout,
                                           env_brownout_clear, env_eio,
                                           env_eio_clear, env_rlimit,
                                           env_skew)
from librdkafka_tpu.fleet.traffic import (Pacer, PartitionPicker,
                                          TrafficPlan, ZipfSampler,
                                          bursts, diurnal, flat,
                                          hot_partitions, rate_at, stack,
                                          zipf)
from librdkafka_tpu.mock.cluster import MockCluster

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RELAY = os.path.join(_PKG, "librdkafka_tpu", "mock", "_relay.py")
_WORKER = os.path.join(_PKG, "librdkafka_tpu", "fleet", "_worker.py")


# ================================================== traffic shapes ==
class TestTrafficShapes:
    def test_rate_at_catalog(self):
        assert rate_at(flat(42), 999) == 42
        d = diurnal(10, 30, 6.0)
        assert rate_at(d, 0.0) == pytest.approx(10.0)
        assert rate_at(d, 3.0) == pytest.approx(30.0)   # mid-period peak
        assert rate_at(d, 6.0) == pytest.approx(10.0)
        b = bursts(5, 50, 2.0, duty=0.25)
        assert rate_at(b, 0.1) == 50                    # inside burst
        assert rate_at(b, 0.6) == 5                     # quiet
        assert rate_at(b, 2.3) == 50                    # next period
        s = stack(flat(10), bursts(0, 20, 2.0, 0.25))
        assert rate_at(s, 0.1) == 30 and rate_at(s, 1.0) == 10
        with pytest.raises(ValueError):
            rate_at({"kind": "nope"}, 0)

    def test_zipf_sampler_deterministic_and_skewed(self):
        import random
        draws1 = [ZipfSampler(zipf(20, 1.3), random.Random(7)).rank()
                  for _ in range(1)]
        s1 = ZipfSampler(zipf(20, 1.3), random.Random(7))
        s2 = ZipfSampler(zipf(20, 1.3), random.Random(7))
        seq1 = [s1.rank() for _ in range(500)]
        seq2 = [s2.rank() for _ in range(500)]
        assert seq1 == seq2                      # same seed, same keys
        assert draws1[0] == seq1[0]
        # rank 0 must be the hottest key by a wide margin
        assert seq1.count(0) > seq1.count(10) and seq1.count(0) > 50
        assert all(0 <= r < 20 for r in seq1)

    def test_hot_partition_picker(self):
        import random
        pk = PartitionPicker(8, hot_partitions(8, 3, 0.7),
                             random.Random(3))
        picks = [pk.pick() for _ in range(500)]
        assert picks.count(3) > 250              # ~70% + uniform share
        assert set(picks) <= set(range(8))
        uni = PartitionPicker(4, None, random.Random(3))
        assert set(uni.pick() for _ in range(200)) == set(range(4))

    def test_pacer_tracks_rate_and_caps_bursts(self):
        p = Pacer(flat(100.0))
        assert p.take(0.0) == 0                  # first call only arms
        total = sum(p.take(0.0 + i * 0.05) for i in range(1, 21))
        assert 80 <= total <= 105                # ~100 msgs over 1s
        p2 = Pacer(flat(1000.0))
        p2.take(0.0)
        assert p2.take(60.0) <= Pacer.BURST_CAP  # stall != flood

    def test_plan_deterministic_and_json_shippable(self):
        def mk(seed):
            return TrafficPlan(seed, producers=3, groups=2, group_size=2,
                               partitions=8,
                               shape=stack(diurnal(8, 30, 6.0),
                                           bursts(0, 25, 2.0, 0.3)),
                               keys=zipf(100, 1.2),
                               hot_partition_weight=0.6)
        a, b, c = mk(5), mk(5), mk(6)
        assert a.replay_key() == b.replay_key()
        assert a.replay_key() != c.replay_key()
        assert a.workers == 7 and a.n_groups == 2
        # every spec must survive the wire (the worker line protocol)
        assert json.loads(json.dumps(a.specs)) == a.specs
        names = [s["name"] for s in a.specs]
        assert len(set(names)) == len(names)
        # producers carry seeded per-worker phase jitter (desync)
        phases = {p2["shape"]["parts"][0]["phase"]
                  for p2 in a.specs if p2["role"] == "producer"}
        assert len(phases) == 3


# ============================================= env fault verbs (unit) ==
class _StubCluster:
    """Target-resolution surface + fault-call recorder for verb unit
    tests (the external rig's shape, no processes)."""

    def __init__(self, n=4):
        self.n = n
        self.controller_id = 1
        self.calls = []

    def alive_brokers(self):
        return list(range(1, self.n + 1))

    def coordinator_for(self, key):
        return 2

    def partition(self, topic, part):
        class _P:
            leader = 3
        return _P()

    def set_storage_error(self, b, on=True):
        self.calls.append(("eio", b, on))

    def set_clock_skew(self, b, ms=0.0):
        self.calls.append(("skew", b, ms))

    def set_rlimit(self, b, nbytes):
        self.calls.append(("rlimit", b, nbytes))

    def brownout(self, b, **knobs):
        self.calls.append(("brownout", b, knobs))

    def clear_brownout(self, b):
        self.calls.append(("brownout_clear", b))


class TestEnvVerbs:
    def test_replay_deterministic_and_fields_in_key(self):
        def run_once(seed):
            c = _StubCluster(4)
            chaos = ChaosScheduler(c, min_alive=1)
            chaos.run(Schedule(seed=seed)
                      .at(0, env_eio("any"))
                      .at(0, env_skew(-1500.0, "any"))
                      .at(0, env_rlimit(64 << 20, "any"))
                      .at(0, env_brownout("any", rx_drop=True,
                                          tx_delay_ms=40.0))
                      .at(0, env_brownout_clear())
                      .at(0, env_eio_clear()))
            assert not chaos.errors, chaos.errors
            return chaos.replay_key()
        k1, k2 = run_once(99), run_once(99)
        assert k1 == k2
        assert k1 != run_once(100)
        flat_items = [kv for _i, _t, _a, res in k1 for kv in res]
        for want in ("skew_ms", "rlim_bytes", "rx_drop", "tx_delay_ms"):
            assert any(k == want for k, _v in flat_items), \
                f"{want} missing from replay key: {k1}"

    def test_targets_and_fifo_clear(self):
        c = _StubCluster(4)
        chaos = ChaosScheduler(c, min_alive=1)
        chaos.run(Schedule(seed=1)
                  .at(0, env_eio(3))
                  .at(0, env_eio("coordinator:g"))
                  .at(0, env_eio_clear())          # FIFO: heals 3 first
                  .at(0, env_skew(2000.0, "controller"))
                  .at(0, env_brownout("leader:t:0", tx_drop=True)))
        assert not chaos.errors, chaos.errors
        assert ("eio", 3, True) in c.calls and ("eio", 2, True) in c.calls
        assert c.calls.index(("eio", 3, False)) > \
            c.calls.index(("eio", 2, True))
        assert ("skew", 1, 2000.0) in c.calls
        assert any(k == "brownout" and b == 3 and kn["tx_drop"]
                   for k, b, *rest in c.calls for kn in rest)
        assert chaos.ctx.eio == [2] and chaos.ctx.browned == [3]

    def test_quorum_floor_counts_env_faulted_brokers(self):
        c = _StubCluster(3)
        chaos = ChaosScheduler(c, min_alive=2)
        chaos.run(Schedule(seed=5)
                  .at(0, env_eio("any"))
                  .at(0, env_eio("any"))            # would leave 1 < 2
                  .at(0, env_brownout("any", rx_drop=True)))
        fired = [e for e in chaos.timeline
                 if (e.get("resolved") or {}).get("broker") is not None]
        skipped = [e for e in chaos.timeline
                   if (e.get("resolved") or {}).get("skipped")]
        assert len(fired) == 1 and len(skipped) == 2
        assert all(e["resolved"]["skipped"] == "min_alive"
                   for e in skipped)

    def test_heal_lifts_every_env_fault(self):
        c = _StubCluster(4)
        chaos = ChaosScheduler(c, min_alive=1)
        chaos.run(Schedule(seed=2)
                  .at(0, env_eio("any"))
                  .at(0, env_skew(500.0, "any"))
                  .at(0, env_rlimit(32 << 20, "any"))
                  .at(0, env_brownout("any", rx_delay_ms=100.0)))
        assert not chaos.errors, chaos.errors
        chaos.heal()
        assert not chaos.ctx.eio and not chaos.ctx.skewed
        assert not chaos.ctx.rlimited and not chaos.ctx.browned
        heals = [x for x in c.calls
                 if x[0] == "eio" and x[2] is False
                 or x[0] == "skew" and x[2] == 0.0
                 or x[0] == "rlimit" and x[2] == 0
                 or x[0] == "brownout_clear"]
        assert len(heals) == 4, c.calls

    def test_inprocess_eio_stalls_then_heals(self):
        """KAFKA_STORAGE_ERROR window on the in-process storage plane:
        produce stalls (retriable), heals to exactly one copy."""
        c = MockCluster(num_brokers=1, topics={"t": 1})
        p = None
        try:
            p = Producer({"bootstrap.servers": c.bootstrap_servers(),
                          "linger.ms": 2, "enable.idempotence": True,
                          "retry.backoff.ms": 50,
                          "message.send.max.retries": 200,
                          "message.timeout.ms": 30000})
            p.produce("t", b"warm", partition=0)
            assert p.flush(10.0) == 0
            c.set_storage_error(None, True)
            assert c.storage_error_brokers() == [1]
            p.produce("t", b"during-eio", partition=0)
            assert p.flush(1.0) == 1, "produce must stall during EIO"
            c.set_storage_error(None, False)
            assert p.flush(20.0) == 0
            blobs = b"".join(blob for _b, blob in c.partition("t", 0).log)
            assert blobs.count(b"during-eio") == 1   # no dup after retry
        finally:
            if p is not None:
                p.close()
            c.stop()

    def test_inprocess_clock_skew(self):
        c = MockCluster(num_brokers=2, topics={"t": 1})
        try:
            c.set_clock_skew(1, -60000.0)
            true_ms = time.time() * 1000.0
            assert c.broker_clock_ms(1) == pytest.approx(
                true_ms - 60000.0, abs=2000)
            assert c.broker_clock_ms(2) == pytest.approx(
                true_ms, abs=2000)
            assert c.clock_skews() == {1: -60000.0}
            c.set_clock_skew(1, 0.0)
            assert c.clock_skews() == {}
        finally:
            c.stop()


# ================================================ relay brownout ==
class _RelayRig:
    """A live _relay.py subprocess fronting a plain TCP upstream."""

    def __init__(self):
        self.up_ls = socket.socket()
        self.up_ls.bind(("127.0.0.1", 0))
        self.up_ls.listen(4)
        self.proc = subprocess.Popen(
            [sys.executable, _RELAY, "--broker-id", "1", "--port", "0",
             "--upstream", "127.0.0.1:%d" % self.up_ls.getsockname()[1]],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        hs = json.loads(self.proc.stdout.readline())
        self.port = hs["port"]
        self.client = socket.create_connection(("127.0.0.1", self.port),
                                               timeout=5)
        self.upstream, _ = self.up_ls.accept()
        self.client.settimeout(2.0)
        self.upstream.settimeout(2.0)

    def set(self, **knobs) -> dict:
        line = json.dumps({"set": knobs}).encode() + b"\n"
        self.proc.stdin.write(line)
        self.proc.stdin.flush()
        return json.loads(self.proc.stdout.readline())

    def close(self):
        for s in (self.client, self.upstream, self.up_ls):
            try:
                s.close()
            except OSError:
                pass
        try:
            self.proc.stdin.close()      # EOF => relay exits
        except OSError:
            pass
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)


class TestRelayBrownout:
    def test_asymmetric_drop_and_delay_live_settable(self):
        rig = _RelayRig()
        try:
            # baseline: both directions flow
            rig.client.sendall(b"tx1")
            assert rig.upstream.recv(16) == b"tx1"
            rig.upstream.sendall(b"rx1")
            assert rig.client.recv(16) == b"rx1"

            # rx_drop: broker->client silently discarded, tx unaffected
            ack = rig.set(rx_drop=True)
            assert ack["ok"] and ack["knobs"]["rx_drop"] is True
            rig.upstream.sendall(b"dropped")
            with pytest.raises(socket.timeout):
                rig.client.recv(16)
            rig.client.sendall(b"tx2")           # asymmetric: tx alive
            assert rig.upstream.recv(16) == b"tx2"
            rig.set(rx_drop=False)
            rig.upstream.sendall(b"rx2")         # healed (drop is loss)
            assert rig.client.recv(16) == b"rx2"

            # tx_delay_ms: client->broker latency, measured
            rig.set(tx_delay_ms=250)
            t0 = time.monotonic()
            rig.client.sendall(b"slow")
            assert rig.upstream.recv(16) == b"slow"
            assert time.monotonic() - t0 >= 0.2
            ack = rig.set(tx_delay_ms=0)
            assert ack["knobs"] == {"rx_drop": False, "tx_drop": False,
                                    "rx_delay_ms": 0.0,
                                    "tx_delay_ms": 0.0}
            t0 = time.monotonic()
            rig.client.sendall(b"fast")
            assert rig.upstream.recv(16) == b"fast"
            assert time.monotonic() - t0 < 0.2
        finally:
            rig.close()


# ============================================ oracle fleet-merge (unit) ==
class TestLedgerMergeUnit:
    def _merged(self):
        o = DeliveryOracle()
        now = time.monotonic()
        o.record_acks([("t", 0, i, None, "p00-%08d" % i, None, now + i)
                       for i in range(5)])
        o.record_consumed_rows([("t", 0, i, "p00-%08d" % i)
                                for i in range(5)])
        return o

    def test_clean_merge_verifies(self):
        o = self._merged()
        r = o.verify(check_duplicates=False, check_order=False)
        assert r["ok"] and r["acked"] == 5 and r["consumed"] == 5
        assert o.missing_count() == 0

    def test_tampered_worker_ledger_raises_with_json_diff(self, tmp_path):
        """ISSUE 11 acceptance: a tampered worker ledger must raise
        OracleViolation carrying the JSON diff."""
        o = DeliveryOracle(dump_dir=str(tmp_path))
        now = time.monotonic()
        o.record_acks([("t", 0, i, None, "p00-%08d" % i, None, now)
                       for i in range(5)])
        rows = [("t", 0, i, "p00-%08d" % i) for i in range(5)]
        rows.pop(2)                              # lose one mid-stream
        o.record_consumed_rows(rows)
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_duplicates=False, check_order=False)
        rep = ei.value.report
        assert rep["violations"]["lost"][0]["value"] == "p00-00000002"
        assert rep["diff_path"] and os.path.exists(rep["diff_path"])
        diff = json.load(open(rep["diff_path"]))
        assert diff["summary"]["lost"] == 1

    def test_worker_ts_feeds_recovery_clock(self):
        o = DeliveryOracle()
        o.record_acks([("t", 0, 0, None, "v0", None, 123.0)])
        o.record_ack("t", 0, 1, None, "v1")
        with o._lock:
            assert o.acked_ts[0] == 123.0
            assert o.acked_ts[1] > 1000.0        # arrival-stamped


# =========================================== worker spawn protocol ==
class TestWorkerProtocol:
    def test_handshake_is_package_free_and_stop_exits(self):
        """The worker must hand-shake BEFORE importing the package
        (spawn cost contract) and exit 0 on an immediate stop."""
        env = dict(os.environ)
        env["PYTHONPATH"] = _PKG
        proc = subprocess.Popen(
            [sys.executable, _WORKER], stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        try:
            sel = selectors.DefaultSelector()
            sel.register(proc.stdout.fileno(), selectors.EVENT_READ)
            assert sel.select(timeout=10), "handshake timeout"
            sel.close()
            hs = json.loads(proc.stdout.readline())
            assert hs["ready"] and hs["pid"] == proc.pid
            proc.stdin.write(b'{"cmd":"stop"}\n')
            proc.stdin.flush()
            assert proc.wait(timeout=10) == 0
        finally:
            proc.kill()
            proc.wait(timeout=5)
            proc.stdin.close()
            proc.stdout.close()


# ============================================ fleet runs (processes) ==
@pytest.mark.fleet
class TestFleetRuns:
    def test_fleet_smoke_kill9_and_metrics(self):
        from librdkafka_tpu.fleet.scenarios import fleet_smoke
        t0 = time.monotonic()
        r = fleet_smoke()
        assert r["ok"], r
        assert r["workers"] == 4
        assert not r["errors"] and not r["schedule_errors"]
        kills = r["pids_killed"]
        assert kills and all(e["verified_dead"] for e in kills), \
            "fleet SIGKILL must be pid-verified"
        assert r["kills_fired"] == 1
        fm = r["fleet_metrics"]
        assert fm["acked_total"] > 50
        assert fm["fleet_msgs_s"] and fm["fleet_msgs_s"] > 0
        assert fm["client_p99_ms_max"] is not None
        # producers report per-client latency histograms (a worker that
        # got no ack inside its window under extreme host load legally
        # reports none — delivery is still judged by the oracle)
        assert fm["client_p99_ms"]
        assert set(fm["client_p99_ms"]) <= {"p00", "p01"}
        # at-least-once across the kill: every ack delivered, dups legal
        assert r["consumed_by_group"][0] >= fm["acked_total"]
        m = r["storm_metrics"]
        assert m["kills"] == 1
        assert m["recovery_ms"]["unrecovered"] == 0
        assert r["converged_s"] is not None
        assert time.monotonic() - t0 < 35, "fleet fast-tier budget blown"

    def test_fleet_replay_key_identical_across_rigs(self):
        """ACCEPTANCE: same seed ⇒ identical fleet replay_key across
        two SEPARATELY LAUNCHED rigs — fresh supervisor, fresh broker
        relays, fresh worker processes; the plan digest and every
        rng-resolved fault target must replay."""
        from librdkafka_tpu.fleet.scenarios import FleetRun
        from librdkafka_tpu.chaos.schedule import proc_kill9, proc_restart

        def run_once(seed):
            run = FleetRun(seed=seed, brokers=2, partitions=2,
                           producers=1, groups=1, group_size=1,
                           shape=flat(120.0), duration_s=1.2,
                           drain_s=20.0, converge_s=15.0)
            sched = (Schedule(seed=seed)
                     .at(0.5, proc_kill9("any"))
                     .at(0.9, proc_restart()))
            r = run.run(sched)
            assert r["ok"], r
            return r["replay_key"]
        k1, k2 = run_once(4747), run_once(4747)
        assert k1 == k2
        plan_key, sched_key = k1
        assert len(plan_key) == 16
        assert any(a == "proc_kill9" for _i, _t, a, _r in sched_key)

    def test_fleet_tampered_ledger_trips_merged_oracle(self):
        """A worker ledger tampered after the merge must raise
        OracleViolation through the real fleet run path."""
        from librdkafka_tpu.fleet.scenarios import FleetRun

        def _tamper(oracles):
            o = oracles[0]
            with o._lock:
                if len(o.consumed) >= 2:
                    o.consumed.pop()
        run = FleetRun(seed=49, brokers=1, partitions=2,
                       producers=1, groups=1, group_size=1,
                       shape=flat(150.0), duration_s=1.2,
                       drain_s=15.0, converge_s=15.0)
        with pytest.raises(OracleViolation) as ei:
            run.run(None, tamper=_tamper)
        rep = ei.value.report
        assert rep["violations"]["lost"]
        assert rep["diff_path"], "violation must carry the JSON diff"


@pytest.mark.fleet
@pytest.mark.slow
class TestFleetFlagship:
    def test_flagship_fleet_storm(self):
        """ISSUE 11 acceptance storm: ≥24 worker processes (16
        producers + 2 consumer groups × 4) under diurnal+burst traffic
        with hot-partition skew, sustaining ≥3 pid-verified SIGKILLs,
        one asymmetric brownout and one EIO window — per-group merged
        oracle clean (zero acked loss, coverage exact)."""
        from librdkafka_tpu.fleet.scenarios import fleet_storm
        r = fleet_storm()
        assert r["ok"], r.get("group_reports")
        assert r["workers"] >= 24
        assert r["kills_fired"] >= 3
        kills = r["pids_killed"]
        assert len(kills) >= 3
        assert all(e["verified_dead"] and e["exit"] == -9 for e in kills)
        assert len({e["pid"] for e in kills}) == len(kills)
        assert any(e["rx_drop"] for e in r["brownouts"])
        assert any(e["on"] for e in r["eio_windows"])
        assert not r["schedule_errors"]
        # fan-out: BOTH groups delivered the whole acked set
        assert len(r["group_reports"]) == 2
        assert all(g["ok"] for g in r["group_reports"])
        assert all(n >= r["acked"] for n in r["consumed_by_group"])
        fm = r["fleet_metrics"]
        assert fm["fleet_msgs_s"] > 0
        assert fm["client_p99_ms_max"] is not None
        assert r["storm_metrics"]["kills"] == 3
        assert r["storm_metrics"]["recovery_ms"]["unrecovered"] == 0


# ====================================================== CLI + bench ==
class TestCliAndBench:
    def test_cli_list(self):
        import io
        from contextlib import redirect_stdout
        from librdkafka_tpu.fleet.__main__ import main
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert main(["--list"]) == 0
        out = buf.getvalue()
        for name in ("fleet_mini", "fleet_smoke", "fleet_storm"):
            assert name in out
        assert "loss,group" in out

    def test_fleet_bench_emits_aggregate_schema(self):
        """bench.py --fleet artifact contract (cheap static check —
        the full leg runs the flagship): aggregate msgs/s, per-client
        p99, storm kill count and recovery p50/p99 at top level."""
        import ast
        src = open(os.path.join(_PKG, "bench.py")).read()
        tree = ast.parse(src)
        fn = next(n for n in ast.walk(tree)
                  if isinstance(n, ast.FunctionDef)
                  and n.name == "fleet_bench")
        keys = {getattr(k, "value", None)
                for n in ast.walk(fn) if isinstance(n, ast.Dict)
                for k in n.keys}
        for want in ("fleet_msgs_s", "client_p99_ms_max", "storm_kills",
                     "recovery_p50_ms", "recovery_p99_ms"):
            assert want in keys, f"fleet_bench must emit {want!r}"
        # and the mini --smoke leg exists
        assert "fleet_mini" in src and "--fleet" in src
