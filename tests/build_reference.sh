#!/bin/sh
# Build the reference librdkafka (from /root/reference, read-only) into
# the gitignored .refbuild/ tree so the interop tier
# (tests/test_0200_interop.py) can run against the real C client.
set -e
REPO="$(cd "$(dirname "$0")/.." && pwd)"
REF="${REFERENCE_DIR:-/root/reference}"
DST="$REPO/.refbuild"

if [ -e "$DST/src/librdkafka.so.1" ]; then
    echo "reference already built at $DST"
    exit 0
fi
mkdir -p "$DST"
cp -r "$REF"/* "$DST"/
cd "$DST"
./configure
make -j"$(nproc)" libs
make -C examples rdkafka_performance
echo "reference built: $DST/src/librdkafka.so.1"
