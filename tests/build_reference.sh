#!/bin/sh
# Build the reference librdkafka (from /root/reference, read-only) into
# the gitignored .refbuild/ tree so the interop tier
# (tests/test_0200_interop.py) can run against the real C client.
set -e
REPO="$(cd "$(dirname "$0")/.." && pwd)"
REF="${REFERENCE_DIR:-/root/reference}"
DST="$REPO/.refbuild"

if [ -e "$DST/src/librdkafka.so.1" ]; then
    echo "reference already built at $DST"
    exit 0
fi
BUILD="$DST-build"      # transient (gitignored); removed on ANY exit —
                        # reference source copies must never persist
rm -rf "$BUILD" && mkdir -p "$BUILD"
trap 'rm -rf "$BUILD"' EXIT
cp -r "$REF"/* "$BUILD"/
cd "$BUILD"
./configure
make -j"$(nproc)" libs
make -C examples rdkafka_performance
# keep only the built artifacts: the interop tier needs just these, and
# keeping reference SOURCE copies inside the repo tree is off-limits
mkdir -p "$DST/src" "$DST/examples"
cp "$BUILD/src/librdkafka.so.1" "$DST/src/"
cp "$BUILD/examples/rdkafka_performance" "$DST/examples/"
echo "reference built: $DST/src/librdkafka.so.1"
