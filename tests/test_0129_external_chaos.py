"""Out-of-process chaos tier (ISSUE 9): supervised multi-process mock
cluster, process-fault schedule verbs (SIGKILL / SIGSTOP brownouts),
consumer-group oracle invariants, and the pid-leak contract.

Tier structure: unit tests + the in-process verb mapping run plain in
tier-1; everything that launches real broker subprocesses is ``chaos``
-marked (fast ones stay tier-1); the flagship SIGKILL-EOS storm and
the big group-churn storm are ``slow``; the multi-minute endurance
storm is ``soak`` (scripts/chaos.sh --soak)."""
import io
import json
import socket
import time
from contextlib import redirect_stdout

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.chaos import (ChaosScheduler, DeliveryOracle,
                                  OracleViolation, Schedule, proc_cont,
                                  proc_kill9, proc_pause, proc_restart)
from librdkafka_tpu.chaos.scenarios import (SCENARIOS,
                                            external_kill9_eos,
                                            fast_external_kill9,
                                            fast_group_churn,
                                            fast_session_kill9,
                                            group_churn_coordinator_storm,
                                            soak_kill9_txn_storm)
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.mock.external import (ClusterHandle,
                                          active_subprocess_pids,
                                          pid_alive)


# ========================================== in-process proc-fault verbs ==
class TestInProcessProcVerbs:
    def test_pause_freezes_and_resume_heals(self):
        """pause_broker is the SIGSTOP analog: connects still succeed
        (no ECONNREFUSED — the listener stays bound) but nothing is
        served; resume flushes what queued."""
        c = MockCluster(num_brokers=1, topics={"t": 1})
        p = None
        try:
            p = Producer({"bootstrap.servers": c.bootstrap_servers(),
                          "linger.ms": 2, "enable.idempotence": True,
                          "socket.timeout.ms": 2000,
                          "socket.max.fails": 0,
                          "retry.backoff.ms": 50,
                          "message.send.max.retries": 100,
                          "message.timeout.ms": 30000})
            p.produce("t", b"warm", partition=0)
            assert p.flush(10.0) == 0
            c.pause_broker(1)
            assert c.paused_brokers() == [1]
            # frozen broker still ACCEPTS (kernel backlog), unlike down
            s = socket.create_connection(
                ("127.0.0.1", c._ports[1]), timeout=2)
            s.close()
            p.produce("t", b"frozen", partition=0)
            assert p.flush(0.8) == 1, "produce must stall while frozen"
            c.resume_broker(1)
            assert c.paused_brokers() == []
            assert p.flush(20.0) == 0
            vals = [v for _b, blob in c.partition("t", 0).log
                    for v in [blob]]
            assert len(vals) >= 2
        finally:
            if p is not None:
                p.close()
            c.stop()

    def test_kill9_alias_and_scheduler_heal_resumes_paused(self):
        c = MockCluster(num_brokers=4, topics={"t": 4})
        try:
            info = c.kill9(2)           # same controller reaction
            assert info["broker"] == 2 and 2 not in c.alive_brokers()
            c.restart_broker(2)

            chaos = ChaosScheduler(c, min_alive=1)
            chaos.run(Schedule(seed=5)
                      .at(0, proc_pause("any"))
                      .at(0, proc_kill9("any"))
                      .at(0, proc_pause("any")))
            assert len(chaos.ctx.paused) == 2
            assert len(chaos.ctx.killed) == 1
            chaos.heal()
            assert not chaos.ctx.paused and not chaos.ctx.killed
            assert c.paused_brokers() == []
            assert c.alive_brokers() == [1, 2, 3, 4]
        finally:
            c.stop()

    def test_proc_verbs_replay_deterministic_in_process(self):
        def run_once(seed):
            c = MockCluster(num_brokers=4, topics={"t": 4})
            try:
                chaos = ChaosScheduler(c, min_alive=2)
                chaos.run(Schedule(seed=seed)
                          .at(0, proc_pause("any"))
                          .at(0, proc_kill9("any"))
                          .at(0, proc_cont())
                          .at(0, proc_kill9("coordinator:g-x"))
                          .at(0, proc_restart())
                          .at(0, proc_restart()))
                assert not chaos.errors, chaos.errors
                return chaos.replay_key()
            finally:
                c.stop()
        assert run_once(77) == run_once(77)

    def test_pause_respects_quorum_floor(self):
        c = MockCluster(num_brokers=2, topics={"t": 2})
        try:
            chaos = ChaosScheduler(c, min_alive=1)
            chaos.run(Schedule(seed=3)
                      .at(0, proc_pause("any"))
                      .at(0, proc_pause("any")))
            fired = [e for e in chaos.timeline
                     if (e.get("resolved") or {}).get("broker")]
            assert len(fired) == 1, \
                "second pause must skip at the responsive-quorum floor"
            chaos.heal()
        finally:
            c.stop()


# ====================================================== oracle: groups ==
class TestOracleGroupInvariants:
    def _seed_clean_group(self, o: DeliveryOracle):
        o.record_assign("m0", [("t", 0), ("t", 1)])
        o.record_assign("m1", [("t", 2), ("t", 3)])
        o.record_poll("m0")
        o.record_poll("m1")

    def test_clean_group_passes(self):
        o = DeliveryOracle()
        self._seed_clean_group(o)
        r = o.verify(check_group=True, group_topic="t",
                     group_partitions=4, converged_s=1.2)
        assert r["ok"]
        assert r["group"]["coverage"]["converged"]
        assert r["group"]["converged_s"] == 1.2
        assert r["group"]["live"] == 2

    def test_unconverged_and_coverage_trip(self, tmp_path):
        o = DeliveryOracle(dump_dir=str(tmp_path))
        o.record_assign("m0", [("t", 0), ("t", 1)])
        o.record_assign("m1", [("t", 1)])        # overlap; 2,3 unowned
        o.record_poll("m0")
        o.record_poll("m1")
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_group=True, group_topic="t",
                     group_partitions=4, converged_s=None)
        rows = ei.value.report["violations"]["unconverged"]
        assert rows[0]["reason"] == "no_convergence_within_bound"
        assert rows[0]["missing"] == [2, 3]
        assert "t:1" in rows[0]["overlaps"]

    def test_stuck_consumer_trips(self):
        o = DeliveryOracle()
        self._seed_clean_group(o)
        o.record_poll("never-assigned")          # joined, no assignment
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_group=True, group_topic="t",
                     group_partitions=4, converged_s=0.5)
        stuck = ei.value.report["violations"]["stuck_consumer"]
        assert [s["member"] for s in stuck] == ["never-assigned"]
        assert stuck[0]["reason"] == "never_assigned"

    def test_stopped_polling_trips_and_departed_exempt(self):
        o = DeliveryOracle()
        self._seed_clean_group(o)
        o.record_assign("m2", [])
        with o._lock:       # age m2's poll stamp past the bound
            o.members["m2"]["last_poll"] = time.monotonic() - 60.0
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_group=True, group_topic="t",
                     group_partitions=4, converged_s=0.5)
        stuck = ei.value.report["violations"]["stuck_consumer"]
        assert stuck[0]["reason"] == "stopped_polling"
        o.record_member_closed("m2")             # deliberate departure
        r = o.verify(check_group=True, group_topic="t",
                     group_partitions=4, converged_s=0.5)
        assert r["ok"] and r["group"]["departed"] == 1

    def test_group_checks_off_by_default(self):
        o = DeliveryOracle()
        o.record_poll("stuck-if-checked")
        assert o.verify()["ok"]


# ============================================= scenario library / CLI ==
class TestScenarioLibrary:
    def test_every_scenario_has_tier_seed_invariants(self):
        tiers = {"fast", "slow", "soak"}
        for name, sc in SCENARIOS.items():
            assert sc.tier in tiers, name
            assert isinstance(sc.seed, int), name
            assert sc.invariants, name
        assert any(sc.tier == "soak" for sc in SCENARIOS.values())

    def test_cli_list_prints_tier_seed_invariants(self):
        from librdkafka_tpu.chaos.__main__ import main
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert main(["--list"]) == 0
        out = buf.getvalue()
        for name, sc in SCENARIOS.items():
            assert name in out
        assert "external_kill9_eos" in out and "soak" in out
        assert "loss,dup,order,atomicity,group" in out


# ================================================ external (subprocess) ==
@pytest.mark.chaos
class TestClusterHandle:
    def test_lifecycle_kill9_restart_pause_and_registry(self):
        """One launch, the whole control surface: handshake, status,
        pid-verified SIGKILL, same-port restart with a fresh pid,
        SIGSTOP/SIGCONT, control queries, and registry hygiene."""
        h = ClusterHandle(brokers=3, topics={"t": 4})
        try:
            hs = h.handshake
            assert set(hs) >= {"bootstrap", "control", "pid", "brokers"}
            assert len(h.broker_pids) == 3
            assert all(pid_alive(p) for p in h.broker_pids.values())
            # every spawned pid is registered for the leak fixture
            reg = active_subprocess_pids()
            assert h._proc.pid in reg
            assert all(p in reg for p in h.broker_pids.values())

            st = h.status()
            assert st["alive"] == [1, 2, 3] and st["down"] == []
            assert st["topics"]["t"] == [1, 2, 3, 1]

            # deterministic coordinator placement (stable hash — the
            # cross-process replay contract)
            assert h.coordinator_for("g-x") == h.coordinator_for("g-x")

            old_pid = h.broker_pids[2]
            old_port = h.broker_ports[2]
            r = h.kill9(2)
            assert r["exit"] == -9 and not pid_alive(old_pid)
            assert h.alive_brokers() == [1, 3]
            assert h.status()["down"] == [2]
            # migrated leadership is visible through the handle
            assert all(pv.leader != 2 for pv in h.topics["t"])
            with pytest.raises(ConnectionRefusedError):
                socket.create_connection(("127.0.0.1", old_port),
                                         timeout=2)

            r = h.restart_broker(2)
            assert r["port"] == old_port and r["pid"] != old_pid
            assert pid_alive(r["pid"])
            assert h.alive_brokers() == [1, 2, 3]
            s = socket.create_connection(("127.0.0.1", old_port),
                                         timeout=2)
            s.close()

            h.pause_broker(1)
            assert h.status()["paused"] == [1]
            h.resume_broker(1)
            assert h.status()["paused"] == []

            h.set_partition_leader("t", 0, 3)
            assert h.partition("t", 0).leader == 3

            kills = [e for e in h.proc_events if e["verb"] == "kill9"]
            assert kills and kills[0]["verified_dead"]
        finally:
            h.stop()
        # stop() reaps everything: registry empty, pids gone
        assert h._proc.pid not in active_subprocess_pids()
        assert not pid_alive(h._proc.pid)
        assert all(not pid_alive(p) for p in h.broker_pids.values())

    def test_replay_key_identical_across_supervisor_launches(self):
        """ACCEPTANCE: same seed => identical replay_key AGAINST THE
        EXTERNAL CLUSTER — two fresh supervisor processes must resolve
        every rng-drawn target ("any" broker, coordinator placement)
        identically."""
        def run_once(seed):
            h = ClusterHandle(brokers=3, topics={"t": 3})
            try:
                chaos = ChaosScheduler(h, min_alive=1)
                chaos.run(Schedule(seed=seed)
                          .at(0, proc_pause("any"))
                          .at(0, proc_kill9("any"))
                          .at(0, proc_cont())
                          .at(0, proc_kill9("coordinator:replay-g"))
                          .at(0, proc_restart())
                          .at(0, proc_restart()))
                assert not chaos.errors, chaos.errors
                chaos.heal()
                return chaos.replay_key()
            finally:
                h.stop()
        k1, k2 = run_once(4242), run_once(4242)
        assert k1 == k2
        assert any(a == "proc_kill9" for _i, _t, a, _r in k1)


# =============================================== fast external storms ==
@pytest.mark.chaos
class TestFastExternalScenarios:
    def test_fast_external_kill9(self):
        t0 = time.monotonic()
        r = fast_external_kill9()
        assert r["ok"], r["violations"]
        assert r["external"] and not r["errors"]
        assert not r["schedule_errors"]
        kills = r["pids_killed"]
        assert kills and all(e["verified_dead"] for e in kills), \
            "SIGKILL must be pid-verified"
        assert r["acked"] > 100 and r["consumed"] == r["acked"]
        m = r["storm_metrics"]
        assert m["storm_msgs_s"] > 0 and m["kills"] >= 1
        assert m["recovery_ms"]["p99"] is not None
        assert m["recovery_ms"]["unrecovered"] == 0
        assert time.monotonic() - t0 < 25, "fast-tier budget blown"

    def test_fast_session_kill9(self):
        """ISSUE 14: the KIP-227 session cache dies with the SIGKILLed
        broker process; the client renegotiates (epoch-0 full fetch)
        and keeps delivering with zero acked loss."""
        t0 = time.monotonic()
        r = fast_session_kill9()
        assert r["ok"], r["violations"]
        assert r["external"] and not r["errors"]
        kills = r["pids_killed"]
        assert len(kills) == 2 and all(e["verified_dead"] for e in kills)
        assert r["consumed"] == r["acked"] > 0
        b1 = next(s for n, s in r["fetch_sessions"].items()
                  if n.endswith("/1"))
        assert b1["resets"] >= 1 and b1["full_fetches"] >= 2
        assert time.monotonic() - t0 < 35, "fast-tier budget blown"

    def test_fast_group_churn(self):
        t0 = time.monotonic()
        r = fast_group_churn()
        assert r["ok"], r["violations"]
        g = r["group"]
        assert g["members"] == 6 and g["departed"] == 2
        assert g["coverage"]["converged"]
        assert r["converged_s"] is not None
        assert not r["violations"]["lost"]
        assert time.monotonic() - t0 < 30, "fast-tier budget blown"


# ======================================================= full storms ==
@pytest.mark.chaos
@pytest.mark.slow
class TestExternalStorms:
    def test_flagship_external_kill9_eos(self):
        """ISSUE 9 acceptance storm: >=3 SIGKILLs of real broker OS
        processes (pid liveness verified) under sustained EOS produce +
        read_committed consume by a 2-member group; zero loss / zero
        dup / per-partition order / txn atomicity / group assignment
        invariants all clean."""
        r = external_kill9_eos(seed=21)
        assert r["ok"], r["violations"]
        assert r["kills_fired"] >= 3
        kills = r["pids_killed"]
        assert len(kills) >= 3
        assert all(e["verified_dead"] and e["exit"] == -9 for e in kills)
        assert len({e["pid"] for e in kills}) == len(kills), \
            "each SIGKILL must hit a distinct live process"
        assert r["txns"]["committed"] > 10
        assert r["txns"]["aborted"] > 0          # atomicity exercised
        assert r["txns"]["unknown"] == 0
        assert not r["schedule_errors"]
        assert r["group"]["coverage"]["converged"]
        assert r["storm_metrics"]["recovery_ms"]["unrecovered"] == 0

    def test_group_churn_coordinator_storm(self):
        r = group_churn_coordinator_storm(seed=31)
        assert r["ok"], r["violations"]
        g = r["group"]
        assert g["members"] == 20 and g["departed"] == 8
        # churn + two coordinator deaths force many rebalance rounds
        assert g["assignments"] > 25
        assert g["coverage"]["converged"] and r["converged_s"] is not None
        assert not r["violations"]["lost"]
        coord_kills = [e for e in r["timeline"]
                       if e["action"] == "broker_kill"
                       and (e.get("resolved") or {}).get("broker")]
        assert len(coord_kills) == 2


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.soak
class TestSoak:
    def test_soak_kill9_txn_storm(self):
        """Endurance tier (scripts/chaos.sh --soak): minutes of
        unpaced EOS transactions against the external cluster under
        repeated SIGKILL cycles — thousands of txns, dozens of real
        process kills, every invariant checked at the end, zero leaked
        subprocesses (conftest)."""
        r = soak_kill9_txn_storm(seed=41, minutes=2.5)
        assert r["ok"], r["violations"]
        assert r["kills_fired"] >= 20
        # ~550 txns/min on this 1-core host; generous margin for the
        # occasional multi-second reconnect wedge under back-to-back
        # kills of the same broker
        assert r["txns"]["committed"] >= 800, \
            f"soak should sustain txn throughput: {r['txns']}"
        assert r["acked"] >= 2500, \
            f"soak should push thousands of txn messages: {r['acked']}"
        assert r["txns"]["unknown"] == 0
        assert r["group"]["coverage"]["converged"]
        assert r["storm_metrics"]["recovery_ms"]["p99"] is not None


def test_chaos_bench_emits_robustness_metrics_schema():
    """bench.py --chaos artifact contract (cheap static check — the
    full bench leg runs the storms): the emitter surfaces storm
    throughput + recovery latency at top level."""
    import ast
    import os
    src = open(os.path.join(os.path.dirname(__file__), "..",
                            "bench.py")).read()
    tree = ast.parse(src)
    fn = next(n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)
              and n.name == "chaos_bench")
    keys = {getattr(k, "value", None)
            for n in ast.walk(fn) if isinstance(n, ast.Dict)
            for k in n.keys}
    for want in ("storm_msgs_s", "recovery_p99_ms", "recovery_p50_ms",
                 "recovery_max_ms", "storm_kills"):
        assert want in keys, f"chaos_bench must emit {want!r}"
