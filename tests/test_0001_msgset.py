"""MessageSet writer/reader round-trip tests — the wire-bytes contract of
the north-star seam (SURVEY.md §3.2). Also validates the three-phase
build/compress/finalize split used for batched TPU offload."""
import pytest

from librdkafka_tpu.ops import cpu
from librdkafka_tpu.protocol import msgset, proto
from librdkafka_tpu.protocol.msgset import (MsgsetWriterV2, Record,
                                            iter_batches, parse_msgset_v01,
                                            parse_records_v2,
                                            verify_crc_v2, write_msgset_v01)

NOW = 1_690_000_000_000


def mkmsgs(n=20, headers=False):
    out = []
    for i in range(n):
        hdrs = [("h1", b"v%d" % i), ("h2", None)] if headers else ()
        out.append(Record(key=b"key-%d" % i if i % 3 else None,
                          value=b"value-%04d-" % i + b"x" * (i * 7 % 50),
                          headers=hdrs, timestamp=NOW + i))
    return out


@pytest.mark.parametrize("codec", [None, "gzip", "snappy", "lz4", "zstd"])
def test_v2_roundtrip(codec):
    if codec == "zstd":
        from conftest import require_zstd
        require_zstd()
    msgs = mkmsgs(50, headers=True)
    w = MsgsetWriterV2(base_offset=100, codec=codec)
    compress = (lambda b: cpu.CODECS[codec][0](b)) if codec else None
    wire = w.write_batch(msgs, NOW, compress)

    batches = list(iter_batches(wire))
    assert len(batches) == 1
    info, payload, full = batches[0]
    assert info.magic == 2
    assert info.base_offset == 100
    assert info.record_count == 50
    assert verify_crc_v2(info, full)
    if info.codec:
        payload = cpu.CODECS[info.codec][1](payload, 0)
    recs = parse_records_v2(info, payload)
    assert len(recs) == 50
    for i, r in enumerate(recs):
        assert r.offset == 100 + i
        assert r.timestamp == NOW + i
        assert r.key == (b"key-%d" % i if i % 3 else None)
        assert r.value.startswith(b"value-%04d-" % i)
        assert r.headers[0] == ("h1", b"v%d" % i)
        assert r.headers[1] == ("h2", None)


def test_v2_crc_detects_corruption():
    wire = bytearray(MsgsetWriterV2().write_batch(mkmsgs(5), NOW))
    info, _, full = next(iter_batches(bytes(wire)))
    assert verify_crc_v2(info, full)
    wire[70] ^= 0xFF  # flip a record byte
    info2, _, full2 = next(iter_batches(bytes(wire)))
    assert not verify_crc_v2(info2, full2)


def test_v2_three_phase_equals_oneshot():
    """build() + external compress + finalize() == write_batch() — the
    batched-offload decomposition must not change wire bytes."""
    msgs = mkmsgs(30)
    one = MsgsetWriterV2(codec="lz4").write_batch(msgs, NOW, cpu.lz4_compress)
    w = MsgsetWriterV2(codec="lz4")
    w.build(msgs, NOW)
    blob = cpu.lz4_compress(w.records_bytes)
    three = w.finalize(blob)
    assert one == three


def test_v2_incompressible_falls_back_to_plain():
    import numpy as np
    rng = np.random.default_rng(0)
    msgs = [Record(value=rng.integers(0, 256, 100, dtype=np.uint8).tobytes())
            for _ in range(5)]
    w = MsgsetWriterV2(codec="lz4")
    wire = w.write_batch(msgs, NOW, cpu.lz4_compress)
    info, payload, _ = next(iter_batches(wire))
    assert info.codec is None  # stored uncompressed
    assert len(parse_records_v2(info, payload)) == 5


def test_v2_idempotent_fields():
    w = MsgsetWriterV2(producer_id=9001, producer_epoch=3, base_sequence=42)
    wire = w.write_batch(mkmsgs(3), NOW)
    info, _, _ = next(iter_batches(wire))
    assert (info.producer_id, info.producer_epoch, info.base_sequence) == (9001, 3, 42)


def test_v2_multiple_batches_and_partial_tail():
    w1 = MsgsetWriterV2(base_offset=0).write_batch(mkmsgs(3), NOW)
    w2 = MsgsetWriterV2(base_offset=3).write_batch(mkmsgs(4), NOW)
    blob = w1 + w2 + w2[:30]  # truncated partial batch at tail
    infos = [i for i, _, _ in iter_batches(blob)]
    assert [i.base_offset for i in infos] == [0, 3]
    assert [i.record_count for i in infos] == [3, 4]


@pytest.mark.parametrize("magic", [0, 1])
@pytest.mark.parametrize("codec", [None, "gzip", "snappy"])
def test_v01_roundtrip(magic, codec):
    msgs = mkmsgs(10)
    compress = (lambda b: cpu.CODECS[codec][0](b)) if codec else None
    wire = write_msgset_v01(msgs, magic=magic, codec=codec, now_ms=NOW,
                            compress_fn=compress, base_offset=50)
    dec = (lambda c, b: cpu.CODECS[c][1](b, 0))
    recs = parse_msgset_v01(wire, dec)
    assert len(recs) == 10
    for i, r in enumerate(recs):
        assert r.value == msgs[i].value
        assert r.key == msgs[i].key
        if magic == 1 and codec:
            assert r.offset == 50 + i  # wrapper-relative offset fixup
    if magic == 1:
        assert all(r.timestamp == NOW + i for i, r in enumerate(recs))


def test_control_batch_flag():
    w = MsgsetWriterV2()
    wire = bytearray(w.write_batch(mkmsgs(1), NOW))
    # set the control bit in attributes and re-CRC
    import struct
    attrs = struct.unpack(">h", wire[proto.V2_OF_Attributes:proto.V2_OF_Attributes + 2])[0]
    attrs |= proto.ATTR_CONTROL
    wire[proto.V2_OF_Attributes:proto.V2_OF_Attributes + 2] = struct.pack(">h", attrs)
    from librdkafka_tpu.utils.crc import crc32c
    wire[proto.V2_OF_CRC:proto.V2_OF_CRC + 4] = struct.pack(
        ">I", crc32c(bytes(wire[proto.V2_OF_Attributes:])))
    info, _, full = next(iter_batches(bytes(wire)))
    assert info.is_control
    assert verify_crc_v2(info, full)


def test_log_append_time_uses_max_timestamp():
    """LOG_APPEND_TIME batches: per-record deltas still carry producer
    create times; every record must report the batch MaxTimestamp
    (reference rdkafka_msgset_reader.c:902-908)."""
    from librdkafka_tpu.protocol import proto
    from librdkafka_tpu.protocol.msgset import (
        MsgsetWriterV2, Record, iter_batches, parse_fetch_messages_v2,
        parse_records_v2)

    recs = [Record(key=None, value=b"v%d" % i, headers=[],
                   timestamp=1_000_000 + i * 50) for i in range(5)]
    w = MsgsetWriterV2(codec=None)
    w.build(recs, now_ms=1_000_000)
    w.assemble(None)
    wire = bytearray(w.patch_crc(0))
    # flip the timestamp-type attribute bit and stamp MaxTimestamp the
    # way a broker does for log.message.timestamp.type=LogAppendTime
    attrs_off = proto.V2_OF_Attributes
    wire[attrs_off + 1] |= proto.ATTR_TIMESTAMP_TYPE & 0xFF
    append_ms = 2_000_000
    maxts_off = proto.V2_OF_MaxTimestamp
    wire[maxts_off:maxts_off + 8] = append_ms.to_bytes(8, "big")
    (info, payload, full), = iter_batches(bytes(wire))
    assert info.attrs & proto.ATTR_TIMESTAMP_TYPE
    for r in parse_records_v2(info, payload):
        assert r.timestamp == append_ms
        assert r.timestamp_type == proto.TSTYPE_LOG_APPEND_TIME
    msgs, _ = parse_fetch_messages_v2(info, payload, "t", 0, 0)
    assert all(m.timestamp == append_ms for m in msgs)
