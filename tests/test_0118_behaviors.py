"""Reference behavioral parity batch (one test per numbered reference
test not yet covered elsewhere): 0002-unkpart, 0003-msgmaxsize,
0008-reqacks, 0013-null-msgs, 0061-consumer_lag, 0092-mixed_msgver,
0095-all_brokers_down, 0099-commit_metadata."""
import json
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.msgset import Record, write_msgset_v01


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"bh": 2})
    yield c
    c.stop()


def test_unknown_partition_fails_delivery(cluster):
    """0002-unkpart: produce to a partition beyond the topic's count
    gets an error delivery report, not silence."""
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2, "message.timeout.ms": 3000,
                  "dr_msg_cb": lambda e, m: drs.append((e, m))})
    p.produce("bh", value=b"nope", partition=99)
    assert p.flush(10.0) == 0
    p.close()
    assert len(drs) == 1
    err, _m = drs[0]
    # reference fails these with the LOCAL unknown-partition error
    # (rd_kafka_topic_partition_cnt_update → _UNKNOWN_PARTITION DRs)
    assert err is not None and err.code == Err._UNKNOWN_PARTITION


def test_msg_size_too_large(cluster):
    """0003-msgmaxsize: oversize messages are rejected at produce()."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "message.max.bytes": 5000})
    with pytest.raises(KafkaException) as ei:
        p.produce("bh", value=b"Z" * 6000, partition=0)
    assert ei.value.error.code == Err.MSG_SIZE_TOO_LARGE
    p.produce("bh", value=b"ok" * 100, partition=0)   # under the limit
    assert p.flush(10.0) == 0
    p.close()


@pytest.mark.parametrize("acks", [-1, 0, 1])
def test_required_acks(cluster, acks):
    """0008-reqacks: every acks level delivers (acks=0 without waiting
    for a broker response)."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "acks": acks, "linger.ms": 2})
    for i in range(20):
        p.produce("bh", value=b"a%d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    blobs = cluster.partition("bh", 0).log
    assert blobs, f"nothing stored with acks={acks}"


def test_null_key_and_value_round_trip(cluster):
    """0013-null-msgs: None key/value survive the wire as None."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("bh", value=None, key=b"onlykey", partition=0)
    p.produce("bh", value=b"onlyvalue", key=None, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gnull", "auto.offset.reset": "earliest"})
    c.subscribe(["bh"])
    got = []
    deadline = time.monotonic() + 15
    while len(got) < 2 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append((m.key, m.value))
    c.close()
    assert (b"onlykey", None) in got
    assert (None, b"onlyvalue") in got


def test_consumer_lag_stat(cluster):
    """0061-consumer_lag: the stats blob reports end-minus-consumed."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(10):
        p.produce("bh", value=b"l%d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    blobs = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "glag", "auto.offset.reset": "earliest",
                  "statistics.interval.ms": 100,
                  "stats_cb": lambda s: blobs.append(json.loads(s))})
    c.subscribe(["bh"])
    # consume slowly (slower than the stats interval) so blobs capture
    # intermediate positions; every blob must satisfy
    # lag == hi_offset - app_offset (clamped), and the final one is 0
    got = 0
    deadline = time.monotonic() + 30
    while got < 10 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got += 1
            time.sleep(0.15)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 1:
        c.poll(0.1)
    c.close()
    assert got == 10
    checked = mid_stream = 0
    for b in blobs:
        part = b.get("topics", {}).get("bh", {}) \
                .get("partitions", {}).get("0")
        if not part or part["hi_offset"] < 0 or part["app_offset"] < 0:
            continue
        want = max(0, part["hi_offset"]
                   - max(part["app_offset"], part["committed_offset"]))
        assert part["consumer_lag"] == want, part
        checked += 1
        if part["consumer_lag"] > 0:
            mid_stream += 1
    assert checked > 0 and mid_stream > 0, \
        f"no mid-stream lag observed across {len(blobs)} blobs"
    final = blobs[-1]["topics"]["bh"]["partitions"]["0"]
    assert final["consumer_lag"] == 0, final


def test_mixed_msgver_log(cluster):
    """0092-mixed_msgver: one partition log holding legacy v1 messagesets
    followed by v2 batches parses end to end."""
    legacy = write_msgset_v01(
        [Record(key=b"k%d" % i, value=b"old-%d" % i, timestamp=1_690_000_000_000)
         for i in range(3)], magic=1, codec=None, now_ms=1_690_000_000_000)
    cluster.partition("bh", 1).append(legacy)

    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(3):
        p.produce("bh", value=b"new-%d" % i, partition=1)
    assert p.flush(10.0) == 0
    p.close()

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gmix", "auto.offset.reset": "earliest",
                  "check.crcs": True})
    c.subscribe(["bh"])
    got = []
    deadline = time.monotonic() + 15
    while len(got) < 6 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None and m.partition == 1:
            got.append(m.value)
    c.close()
    assert got == [b"old-0", b"old-1", b"old-2",
                   b"new-0", b"new-1", b"new-2"]


def test_all_brokers_down_event():
    """0095-all_brokers_down: connecting to nothing surfaces
    _ALL_BROKERS_DOWN via error_cb."""
    errs = []
    p = Producer({"bootstrap.servers": "127.0.0.1:1",
                  "reconnect.backoff.ms": 50,
                  "error_cb": lambda e: errs.append(e)})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            not any(e.code == Err._ALL_BROKERS_DOWN for e in errs):
        p.poll(0.1)
    p.close()
    assert any(e.code == Err._ALL_BROKERS_DOWN for e in errs), errs


def test_commit_metadata_round_trip(cluster):
    """0099-commit_metadata: app-supplied commit metadata survives
    commit() → committed()."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gmeta"})
    c.subscribe(["bh"])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not c.assignment():
        c.poll(0.2)
    c.commit(offsets=[TopicPartition("bh", 0, 7,
                                     metadata="checkpoint-alpha")])
    out = c.committed([TopicPartition("bh", 0)], timeout=10)
    c.close()
    assert out[0].offset == 7
    assert out[0].metadata == "checkpoint-alpha"


def test_topic_scope_compression_codec(cluster):
    """Reference topic-scope compression.codec (rdkafka_conf.c:1360):
    'inherit' uses the global codec; a per-topic override compresses
    that topic's batches with its own codec. Asserted on the wire
    Attributes bits of the stored mock blobs."""
    from librdkafka_tpu.protocol import proto
    CODEC_BITS = {"none": 0, "gzip": 1, "snappy": 2, "lz4": 3, "zstd": 4}
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "compression.codec": "lz4", "linger.ms": 2})
    p.set_topic_conf("bh2", {"compression.codec": "snappy"})
    for i in range(50):
        p.produce("bh", value=b"x" * 512, partition=0)
        p.produce("bh2", value=b"y" * 512, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    for topic, codec in (("bh", "lz4"), ("bh2", "snappy")):
        log = cluster.partition(topic, 0).log
        assert log, topic
        for _base, blob in log:
            import struct
            (attrs,) = struct.unpack_from(">h", blob, proto.V2_OF_Attributes)
            assert attrs & 0x07 == CODEC_BITS[codec], (topic, attrs)


def test_ut_handle_produce_response_hook(cluster):
    """Hidden ut_handle_ProduceResponse hook (rdkafka_conf.c:849): the
    injected retriable error forces a retry; the message still delivers."""
    from librdkafka_tpu.client.errors import Err, KafkaError
    seen = []

    def hook(broker_id, base_msgid, err):
        if not seen:
            seen.append((broker_id, base_msgid))
            return KafkaError(Err.REQUEST_TIMED_OUT, "ut injected",
                              retriable=True)
        return None

    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "ut_handle_ProduceResponse": hook, "linger.ms": 2,
                  "retry.backoff.ms": 50,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    p.produce("bh", value=b"retry-me", partition=0)
    assert p.flush(10.0) == 0
    p.close()
    assert seen, "hook never ran"
    assert drs and drs[-1] is None       # delivered after the retry


def test_invalid_topic_fails_delivery(cluster):
    """0057-invalid_topic: a broker-rejected topic name (bad charset /
    too long) fails queued messages promptly with INVALID_TOPIC, not at
    message.timeout.ms."""
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2, "message.timeout.ms": 300000,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    t0 = time.monotonic()
    p.produce("bad topic!", value=b"x")           # space + '!' invalid
    p.produce("x" * 250, value=b"y")              # > 249 chars
    deadline = time.monotonic() + 15
    while len(drs) < 2 and time.monotonic() < deadline:
        p.poll(0.2)
    p.close()
    assert len(drs) == 2
    assert all(e is not None and e.code == Err.TOPIC_EXCEPTION
               for e in drs), drs
    # prompt (metadata round trip), nowhere near message.timeout.ms
    assert time.monotonic() - t0 < 15


def test_long_valid_topic_name(cluster):
    """0028-long_topicnames: a 249-char name is VALID and round-trips."""
    name = "t" + "x" * 248
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    p.produce(name, value=b"long-name", partition=0)
    assert p.flush(15.0) == 0
    p.close()
    assert drs == [None]


def test_cluster_and_controller_id(cluster):
    """0063-clusterid: rd_kafka_clusterid/controllerid analogs."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers()})
    assert p.cluster_id(10.0) == "mockCluster"
    assert p.controller_id(10.0) >= 0
    p.close()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcid"})
    assert c.cluster_id(10.0) == "mockCluster"
    c.close()


def test_allow_auto_create_topics_flag(cluster):
    """0007-autotopic + KIP-204/361: a PRODUCER always triggers broker
    auto-creation on metadata; a CONSUMER only does so with
    allow.auto.create.topics=true (default false)."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gauto"})
    c.subscribe(["auto-no"])
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        c.poll(0.2)
    c.close()
    assert "auto-no" not in cluster.topics      # flag default false
    c2 = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                   "group.id": "gauto2",
                   "allow.auto.create.topics": True})
    c2.subscribe(["auto-yes-c"])
    deadline = time.monotonic() + 10
    while "auto-yes-c" not in cluster.topics \
            and time.monotonic() < deadline:
        c2.poll(0.2)
    c2.close()
    assert "auto-yes-c" in cluster.topics
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("auto-yes-p", value=b"y")
    assert p.flush(15.0) == 0
    p.close()
    assert "auto-yes-p" in cluster.topics


def test_partition_count_growth(cluster):
    """0044-partition_cnt: after create_partitions grows the topic,
    produces to the new partitions deliver (metadata refresh picks up
    the count)."""
    from librdkafka_tpu.client.admin import AdminClient, NewPartitions
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("bh", value=b"p0", partition=0)
    assert p.flush(10.0) == 0
    a = AdminClient({"bootstrap.servers": cluster.bootstrap_servers()})
    futs = a.create_partitions([NewPartitions("bh", 4)],
                               operation_timeout=10.0)
    for f in futs.values():
        f.result(10.0)
    a.close()
    drs = []
    p._rk.conf.set("dr_msg_cb", lambda e, m: drs.append((e, m.partition)))
    p._rk.metadata_refresh("test growth")
    deadline = time.monotonic() + 15
    sent = False
    while time.monotonic() < deadline:
        if not sent:
            try:
                p.produce("bh", value=b"p3", partition=3)
                sent = True
            except KafkaException:
                time.sleep(0.2)       # count not refreshed yet
                continue
        if drs:
            break
        p.poll(0.2)
    p.close()
    assert drs and drs[0][0] is None and drs[0][1] == 3, drs


def test_close_does_not_hang_with_broker_down(cluster):
    """0020-destroy_hang: close() with undeliverable messages in the
    queues returns within its bound instead of hanging."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2, "message.timeout.ms": 60000})
    p.produce("bh", value=b"will-not-deliver", partition=0)
    cluster.set_broker_down(1)
    t0 = time.monotonic()
    p.close(timeout=2.0)
    assert time.monotonic() - t0 < 10.0
    cluster.set_broker_down(1, down=False)


def test_reconsume_after_seek_identical(cluster):
    """0014-reconsume-191: seeking back and re-consuming yields the
    exact same messages (offsets, keys, values) as the first pass."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2, "compression.codec": "lz4"})
    for i in range(40):
        p.produce("bh", value=b"rc%02d" % i, key=b"k%02d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "grc", "auto.offset.reset": "earliest"})
    c.subscribe(["bh"])

    def read40():
        out = []
        deadline = time.monotonic() + 20
        while len(out) < 40 and time.monotonic() < deadline:
            m = c.poll(0.2)
            if m is not None and m.error is None and m.partition == 0:
                out.append((m.offset, m.key, m.value))
        return out

    first = read40()
    assert len(first) == 40
    c.seek(TopicPartition("bh", 0, 0))
    second = read40()
    c.close()
    assert first == second


def test_subscribe_update_adds_topic(cluster):
    """0045-subscribe_update / 0050-subscribe_adds: re-subscribing with
    an extra topic rebalances onto it and its messages flow without
    recreating the consumer."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(10):
        p.produce("bh", value=b"a%d" % i, partition=0)
    assert p.flush(10.0) == 0
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gsub", "auto.offset.reset": "earliest"})
    c.subscribe(["bh"])
    got_a = 0
    deadline = time.monotonic() + 20
    while got_a < 10 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got_a += 1
    assert got_a == 10
    # widen the subscription; produce into the new topic
    c.subscribe(["bh", "bh2"])
    for i in range(10):
        p.produce("bh2", value=b"b%d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()
    got_b = 0
    deadline = time.monotonic() + 25
    while got_b < 10 and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None and m.topic == "bh2":
            got_b += 1
    c.close()
    assert got_b == 10, f"only {got_b}/10 from the added topic"


def test_memberid_after_join(cluster):
    """rd_kafka_memberid: empty before joining, the coordinator-assigned
    id once assigned."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gmid"})
    assert c.memberid() == ""
    c.subscribe(["bh"])
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and not c.assignment():
        c.poll(0.2)
    mid = c.memberid()
    c.close()
    assert mid and isinstance(mid, str)
