"""Every declared conf property must actually be read by the client
(VERDICT r2 weak #6: no decorative table rows). The reference's table
(rdkafka_conf.c:224) has no dead rows either — each property feeds a
struct field consumed somewhere.

The test walks PROPERTIES and asserts each non-alias row's name appears
in package source outside conf.py (all access goes through literal
conf.get("name") strings, so a grep is a faithful usage check).
"""
import pathlib
import re

from librdkafka_tpu.client.conf import PROPERTIES

PKG = pathlib.Path(__file__).resolve().parents[1] / "librdkafka_tpu"

# Rows that legitimately have no consumer in package code:
ALLOWED_UNREAD = {
    # surfaced to apps via conf introspection only (reference also only
    # reports it: the CONFIGURATION.md "builtin.features" row)
    "builtin.features",
    # signal shim: POSIX signal handling intentionally absent (Python
    # runtime owns signals); kept for conf-compat like the reference's
    # no-op on non-signal builds
    "internal.termination.signal",
    # owned and consumed by the Conf class itself (topic-scope set
    # fall-through + Conf.topic_conf()); all external access goes
    # through those methods, never the literal name
    "default_topic_conf",
    # consumed dynamically: the default sasl.kerberos.kinit.cmd template
    # expands %{sasl.kerberos.keytab} via render_conf_template (the
    # reference uses it the same way, rdkafka_conf.c keytab row)
    "sasl.kerberos.keytab",
}


def _source_blob() -> str:
    out = []
    for p in PKG.rglob("*.py"):
        if p.name == "conf.py":
            continue
        out.append(p.read_text())
    for p in PKG.rglob("*.cpp"):
        out.append(p.read_text())
    return "\n".join(out)


def test_every_property_is_read_outside_conf():
    blob = _source_blob()
    dead = []
    for prop in PROPERTIES:
        if prop.alias or prop.name in ALLOWED_UNREAD:
            continue
        if prop.ptype == "invalid":
            # reference _RK_C_INVALID rows (ssl.truststore.location,
            # sasl.jaas.config): their whole job is the error conf.py
            # raises on set — there is nothing to read elsewhere
            continue
        if prop.deprecated:
            # accepted no-ops, like the reference's _RK_DEPRECATED rows
            # (e.g. reconnect.backoff.jitter.ms, rdkafka_conf.c:437) —
            # marked so the doc generator labels them; anything unread
            # and NOT marked deprecated is still a decorative row
            continue
        if prop.name not in blob:
            dead.append(prop.name)
    assert not dead, f"decorative conf rows (declared, never read): {dead}"


def test_deprecated_rows_are_accepted_noops():
    from librdkafka_tpu.client.conf import Conf
    dep = [p for p in PROPERTIES if p.deprecated]
    assert any(p.name == "reconnect.backoff.jitter.ms" for p in dep)
    c = Conf()
    for p in dep:
        c.set(p.name, p.default)      # must not raise


def test_aliases_point_at_real_rows():
    names = {p.name for p in PROPERTIES}
    for prop in PROPERTIES:
        if prop.alias:
            assert prop.alias in names, (prop.name, prop.alias)


def test_union_matches_reference_table():
    """VERDICT r4 #9: the documented union equals the reference table.
    Every (scope, name) row in rdkafka_conf.c:224's declarative table
    exists here, and every row here that the reference lacks is listed
    in conf.TPU_ADDITIONS (rendered as the CONFIGURATION.md appendix)."""
    import pytest
    ref_src = pathlib.Path("/root/reference/src/rdkafka_conf.c")
    if not ref_src.exists():
        pytest.skip("reference source tree not present")
    rows = re.findall(r'\{\s*_RK_(GLOBAL|TOPIC)[^,]*,\s*"([^"]+)"',
                      ref_src.read_text())
    ref = {(s.lower(), n) for s, n in rows}
    assert len(ref) >= 150, "reference table parse regressed"
    from librdkafka_tpu.client.conf import TPU_ADDITIONS
    ours = {(p.scope, p.name) for p in PROPERTIES}
    assert ref - ours == set(), f"reference rows absent: {sorted(ref - ours)}"
    assert ours - ref == set(TPU_ADDITIONS), (
        f"undocumented additions: {sorted((ours - ref) ^ set(TPU_ADDITIONS))}")


def test_invalid_rows_raise_guidance():
    """ssl.truststore.location / sasl.jaas.config are _RK_C_INVALID rows:
    setting them fails with a pointer at the supported property
    (reference rdkafka_conf.c:715-729)."""
    from librdkafka_tpu.client.conf import Conf
    from librdkafka_tpu.client.errors import KafkaException
    c = Conf()
    for name, hint in (("ssl.truststore.location", "ssl.ca.location"),
                       ("sasl.jaas.config", "sasl.mechanisms")):
        try:
            c.set(name, "x")
        except KafkaException as e:
            assert hint in str(e)
        else:
            raise AssertionError(f"{name} set did not raise")


def test_both_scope_rows_are_independent():
    """compression.codec exists global AND topic scope; the topic row
    defaults to 'inherit' and overrides per topic."""
    from librdkafka_tpu.client.conf import Conf, TopicConf
    c = Conf()
    c.set("compression.codec", "lz4")          # global row
    assert c.get("compression.codec") == "lz4"
    tc = TopicConf()
    assert tc.get("compression.codec") == "inherit"
    tc.set("compression.codec", "snappy")
    assert tc.get("compression.codec") == "snappy"
    assert c.get("compression.codec") == "lz4"  # untouched


def test_global_offset_store_method_roundtrips():
    """The deprecated global offset.store.method row routes to the topic
    row and round-trips on get(); 'none' is accepted (reference
    RD_KAFKA_OFFSET_METHOD_NONE, rdkafka_conf.c:1000)."""
    from librdkafka_tpu.client.conf import Conf
    c = Conf()
    assert c.get("offset.store.method") == "broker"
    for v in ("none", "file", "broker"):
        c.set("offset.store.method", v)
        assert c.get("offset.store.method") == v
        assert c.topic_conf().get("offset.store.method") == v
