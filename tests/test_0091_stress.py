"""Concurrency stress tests — the rebuild's answer to the reference's
race-detection tier (tests/run-test.sh helgrind/drd harness +
dev-conf.sh TSAN builds, SURVEY.md §5): hammer the client's thread
boundaries (app produce threads x broker threads x codec worker x main
thread timers x rebalancing consumers x broker bounces) and assert the
invariants the locking discipline must hold: no message lost, no
message duplicated, accounting drains to zero."""
import threading
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.msgset import iter_batches, parse_records_v2


def _log_values(cluster, topic, parts):
    vals = []
    for i in range(parts):
        for _base, blob in cluster.partition(topic, i).log:
            for info, payload, _full in iter_batches(blob):
                if info.codec:
                    from librdkafka_tpu.ops import cpu
                    payload = cpu.lz4_decompress(payload)
                vals += [r.value for r in parse_records_v2(info, payload)]
    return vals


def test_multithreaded_producers_exactly_once():
    """4 app threads x 2000 msgs through one idempotent producer with
    the codec pipeline on: every message lands exactly once."""
    cluster = MockCluster(num_brokers=2, topics={"st": 8})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "enable.idempotence": True,
                  "compression.codec": "lz4", "linger.ms": 5})
    N_THREADS, PER = 4, 2000
    errors = []

    def worker(tid):
        try:
            for i in range(PER):
                p.produce("st", value=b"t%d-%05d" % (tid, i),
                          partition=(tid * PER + i) % 8)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert p.flush(60.0) == 0
    assert p._rk.msg_cnt == 0 and p._rk.msg_bytes == 0
    p.close()

    vals = _log_values(cluster, "st", 8)
    expect = [b"t%d-%05d" % (t, i) for t in range(N_THREADS)
              for i in range(PER)]
    assert len(vals) == len(expect), (len(vals), len(expect))
    assert sorted(vals) == sorted(expect), "loss or duplication"
    cluster.stop()


def test_produce_during_broker_bounce_no_duplication():
    """Produce continuously while a broker bounces down/up: idempotent
    retries must deliver every message exactly once."""
    cluster = MockCluster(num_brokers=1, topics={"bounce": 2})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "enable.idempotence": True,
                  "message.send.max.retries": 10000,
                  "retry.backoff.ms": 50,
                  "message.timeout.ms": 60000,
                  "compression.codec": "lz4", "linger.ms": 5})
    stop = threading.Event()

    def bouncer():
        while not stop.is_set():
            time.sleep(0.4)
            cluster.set_broker_down(1)
            time.sleep(0.25)
            cluster.set_broker_down(1, down=False)

    bt = threading.Thread(target=bouncer)
    bt.start()
    N = 3000
    try:
        for i in range(N):
            p.produce("bounce", value=b"b%05d" % i, partition=i % 2)
            if i % 500 == 0:
                time.sleep(0.05)    # let the bounce actually interleave
    finally:
        stop.set()
        bt.join()
        cluster.set_broker_down(1, down=False)
    assert p.flush(90.0) == 0
    p.close()
    vals = _log_values(cluster, "bounce", 2)
    expect = [b"b%05d" % i for i in range(N)]
    assert sorted(vals) == sorted(expect), \
        f"{len(vals)} in log vs {len(expect)} produced"
    cluster.stop()


def test_two_consumers_rebalance_under_load():
    """A second consumer joins mid-consumption; across the rebalance
    every message is seen at least once and the group ends balanced."""
    cluster = MockCluster(num_brokers=1, topics={"rb": 4})
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    N = 2000
    for i in range(N):
        p.produce("rb", value=b"r%05d" % i, partition=i % 4)
    assert p.flush(20.0) == 0
    p.close()

    seen = []
    per_consumer = {1: 0, 2: 0}
    assigned = {1: 0, 2: 0}
    seen_lock = threading.Lock()

    def consume(cid):
        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "grb", "auto.offset.reset": "earliest",
                      "session.timeout.ms": 30000})
        c.subscribe(["rb"])
        deadline = time.monotonic() + 40
        idle = 0
        while time.monotonic() < deadline and idle < 12:
            m = c.poll(0.25)
            assigned[cid] = len(c.assignment())   # final assignment wins
            if m is not None and m.error is None:
                with seen_lock:
                    seen.append(m.value)
                    per_consumer[cid] += 1
                idle = 0
            else:
                idle += 1
        c.close()

    c1 = threading.Thread(target=consume, args=(1,))
    c1.start()
    time.sleep(1.5)            # c1 mid-consumption
    c2 = threading.Thread(target=consume, args=(2,))
    c2.start()
    c1.join()
    c2.join()
    cluster.stop()
    missing = set(b"r%05d" % i for i in range(N)) - set(seen)
    assert not missing, f"{len(missing)} messages never consumed"
    # the rebalance must actually have moved partitions to c2
    assert assigned[2] >= 1, "consumer 2 was never assigned partitions"
