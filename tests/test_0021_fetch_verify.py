"""Consumer-side batched verify/decompress tests (reference:
rdkafka_msgset_reader.c:950-1016 CRC verify + :258-530 decompress; the
rebuild runs both as ONE provider call per Fetch response): corrupted
wire bytes are rejected by the batched CRC check, compressed multi-
partition fetches decode through the batched decompress, and clean
traffic round-trips — including through the ASYNC ticketed fetch
pipeline (ISSUE 2): CRC mismatch semantics, seek-stamp discard and
wire-visible delivery must be identical when phases B/C resolve as
offload tickets instead of synchronous provider calls."""
import struct
import threading
import time

import numpy as np
import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.ops.cpu import CpuCodecProvider
from librdkafka_tpu.protocol import proto


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"fv": 3})
    yield c
    c.stop()


def _produce(cluster, n, codec="lz4", parts=3):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5, "compression.codec": codec})
    for i in range(n):
        p.produce("fv", value=b"fetch-%04d-" % i * 20, key=b"k%d" % i,
                  partition=i % parts)
    assert p.flush(10.0) == 0
    p.close()


def test_batched_decompress_multi_partition_round_trip(cluster):
    _produce(cluster, 120, codec="lz4")
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gfv", "auto.offset.reset": "earliest",
                  "check.crcs": True})
    c.subscribe(["fv"])
    got = []
    deadline = time.monotonic() + 25
    while len(got) < 120 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m.value)
    c.close()
    assert sorted(got) == sorted(b"fetch-%04d-" % i * 20 for i in range(120))


def test_corrupted_batch_rejected_by_batched_crc(cluster):
    _produce(cluster, 10, codec="none", parts=1)
    # flip a bit inside the records region of the stored wire blob
    part = cluster.partition("fv", 0)
    base, blob = part.log[0]
    corrupt = bytearray(blob)
    corrupt[proto.V2_HEADER_SIZE + 2] ^= 0xFF
    part.log[0] = (base, bytes(corrupt))

    errs = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcrc", "auto.offset.reset": "earliest",
                  "check.crcs": True,
                  "error_cb": lambda e: errs.append(e)})
    c.subscribe(["fv"])
    deadline = time.monotonic() + 10
    got = []
    while time.monotonic() < deadline and not errs:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c.close()
    assert any(e.code == Err._BAD_MSG for e in errs), errs
    assert not got, "corrupted batch must not be delivered"


# ------------------------------------------------ async ticketed fetch ----

class _GatedTicket:
    """Ticket that refuses to resolve until the shared gate opens —
    deterministic control over when the broker's _PendingFetch reap can
    run phase D."""

    def __init__(self, fn, gate):
        self._fn = fn
        self._gate = gate
        self._result = None
        self._resolved = False

    def done(self):
        if not self._gate.is_set():
            return False
        self._resolve()
        return True

    def _resolve(self):
        if not self._resolved:
            self._result = self._fn()
            self._resolved = True

    def result(self, timeout=None):
        if not self._gate.wait(timeout):
            raise TimeoutError("gated fetch ticket")
        self._resolve()
        return self._result


class _GatedProvider:
    """CPU-correct provider whose submit seams hand out _GatedTickets:
    the broker parks _PendingFetch entries until the test opens the
    gate — an engine round trip with a hand on the clock."""

    def __init__(self, gate_open=False):
        self._cpu = CpuCodecProvider()
        self.gate = threading.Event()
        if gate_open:
            self.gate.set()
        self.submits = 0

    def _ticket(self, fn):
        self.submits += 1
        return _GatedTicket(fn, self.gate)

    def crc32c_submit(self, bufs):
        bufs = [bytes(b) for b in bufs]
        return self._ticket(lambda: np.asarray(
            self._cpu.crc32c_many(bufs), dtype=np.uint32))

    def crc32_submit(self, bufs):
        bufs = [bytes(b) for b in bufs]
        return self._ticket(lambda: np.asarray(
            self._cpu.crc32_many(bufs), dtype=np.uint32))

    def decompress_submit(self, codec, bufs, size_hints=None):
        bufs = [bytes(b) for b in bufs]
        return self._ticket(
            lambda: self._cpu.decompress_many(codec, bufs, size_hints))

    def __getattr__(self, name):          # sync interface passthrough
        return getattr(self._cpu, name)


def test_crc_mismatch_through_ticket_errs_and_backs_off(cluster):
    """Phase B resolving through an async ticket must keep the exact
    mismatch semantics: Err._BAD_MSG via op_err, a 0.5s fetch backoff,
    and the partition's batches dropped undelivered."""
    _produce(cluster, 10, codec="none", parts=1)
    part = cluster.partition("fv", 0)
    base, blob = part.log[0]
    corrupt = bytearray(blob)
    corrupt[proto.V2_HEADER_SIZE + 2] ^= 0xFF
    part.log[0] = (base, bytes(corrupt))

    errs = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gtcrc", "auto.offset.reset": "earliest",
                  "check.crcs": True,
                  "error_cb": lambda e: errs.append(e)})
    prov = _GatedProvider(gate_open=True)
    c._rk.codec_provider = prov
    c.subscribe(["fv"])
    got = []
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not errs:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    tp = c._rk.get_toppar("fv", 0, create=False)
    backoff_left = (tp.fetch_backoff_until - time.monotonic()
                    if tp is not None else -1.0)
    c.close()
    assert prov.submits > 0, "fetch path never used the async seam"
    assert any(e.code == Err._BAD_MSG for e in errs), errs
    assert not got, "corrupted batch must not be delivered"
    assert 0.0 < backoff_left <= 0.5, backoff_left


def test_seek_with_ticket_in_flight_discards_stale_delivery():
    """A seek() bumping tp.version while the partition's codec tickets
    are parked in the _PendingFetch FIFO must discard that resolution
    (no stale offsets delivered) and resume exactly at the seek
    point."""
    from librdkafka_tpu.client.consumer import TopicPartition

    cluster = MockCluster(num_brokers=1, topics={"fvs": 1})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 5, "compression.codec": "lz4"})
        for i in range(200):
            p.produce("fvs", value=b"s%05d" % i, partition=0)
        assert p.flush(15.0) == 0
        p.close()

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "gseek", "auto.offset.reset": "earliest",
                      "check.crcs": True})
        prov = _GatedProvider(gate_open=False)   # park every resolution
        c._rk.codec_provider = prov
        c.subscribe(["fvs"])
        # poll until the first fetch's tickets are parked (assignment +
        # fetch + _begin_fetch_partition all happened); no messages can
        # arrive while the gate is shut
        deadline = time.monotonic() + 20
        while prov.submits == 0 and time.monotonic() < deadline:
            assert c.poll(0.1) is None, "delivery while codec gate shut"
        assert prov.submits > 0, "no ticket ever submitted"
        c.seek(TopicPartition("fvs", 0, 120))    # stale: tickets cover 0..
        prov.gate.set()                          # resolve the parked entry
        seq = []
        deadline = time.monotonic() + 20
        while len(seq) < 80 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                seq.append(m.offset)
        c.close()
        assert seq, "stream lost after seek"
        assert seq[0] == 120, f"stale pre-seek delivery leaked: {seq[:5]}"
        assert seq == list(range(120, 120 + len(seq))), "gap/dup after seek"
        assert len(seq) == 80
    finally:
        cluster.stop()


def _consume_all(cluster, group, n, provider=None):
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": group, "auto.offset.reset": "earliest",
                  "check.crcs": True})
    if provider is not None:
        c._rk.codec_provider = provider
    c.subscribe(["fv"])
    got, errs = [], []
    deadline = time.monotonic() + 25
    while len(got) < n and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None:
            (errs if m.error is not None else got).append(m)
    c.close()
    assert not errs, [m.error for m in errs]
    return sorted((m.partition, m.offset, m.key, m.value) for m in got)


def _have_codec(codec):
    try:
        CpuCodecProvider().compress_many(codec, [b"probe" * 10])
        return True
    except Exception:
        return False


@pytest.mark.parametrize("codec", ["lz4", "snappy", "gzip", "zstd"])
def test_sync_vs_ticketed_delivery_bit_identical(cluster, codec):
    """Acceptance: with check.crcs=on, the ticketed fetch pipeline's
    wire-visible behavior (delivered records, offsets, partitions) is
    bit-identical to the synchronous path for every codec."""
    if not _have_codec(codec):
        if codec == "zstd":
            pytest.skip("zstd support not available: "
                        "pip install '.[zstd]'")
        pytest.skip(f"{codec} support not available in this build")
    _produce(cluster, 45, codec=codec)
    sync = _consume_all(cluster, f"gsync-{codec}", 45, provider=None)
    ticketed = _consume_all(cluster, f"gtick-{codec}", 45,
                            provider=_GatedProvider(gate_open=True))
    assert sync == ticketed
    assert len(sync) == 45


def test_check_crcs_disabled_skips_verify(cluster):
    """check.crcs=false: corrupted CRC field itself is ignored (payload
    intact), messages still delivered — proving the verify is gated by
    conf like the reference."""
    _produce(cluster, 5, codec="none", parts=1)
    part = cluster.partition("fv", 0)
    base, blob = part.log[0]
    corrupt = bytearray(blob)
    # corrupt the stored CRC field (not the payload)
    struct.pack_into(">I", corrupt, proto.V2_OF_CRC, 0xDEADBEEF)
    part.log[0] = (base, bytes(corrupt))

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gnocrc", "auto.offset.reset": "earliest",
                  "check.crcs": False})
    c.subscribe(["fv"])
    got = []
    deadline = time.monotonic() + 15
    while len(got) < 5 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c.close()
    assert len(got) == 5
