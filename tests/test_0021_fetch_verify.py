"""Consumer-side batched verify/decompress tests (reference:
rdkafka_msgset_reader.c:950-1016 CRC verify + :258-530 decompress; the
rebuild runs both as ONE provider call per Fetch response): corrupted
wire bytes are rejected by the batched CRC check, compressed multi-
partition fetches decode through the batched decompress, and clean
traffic round-trips."""
import struct
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol import proto


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"fv": 3})
    yield c
    c.stop()


def _produce(cluster, n, codec="lz4", parts=3):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 5, "compression.codec": codec})
    for i in range(n):
        p.produce("fv", value=b"fetch-%04d-" % i * 20, key=b"k%d" % i,
                  partition=i % parts)
    assert p.flush(10.0) == 0
    p.close()


def test_batched_decompress_multi_partition_round_trip(cluster):
    _produce(cluster, 120, codec="lz4")
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gfv", "auto.offset.reset": "earliest",
                  "check.crcs": True})
    c.subscribe(["fv"])
    got = []
    deadline = time.monotonic() + 25
    while len(got) < 120 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m.value)
    c.close()
    assert sorted(got) == sorted(b"fetch-%04d-" % i * 20 for i in range(120))


def test_corrupted_batch_rejected_by_batched_crc(cluster):
    _produce(cluster, 10, codec="none", parts=1)
    # flip a bit inside the records region of the stored wire blob
    part = cluster.partition("fv", 0)
    base, blob = part.log[0]
    corrupt = bytearray(blob)
    corrupt[proto.V2_HEADER_SIZE + 2] ^= 0xFF
    part.log[0] = (base, bytes(corrupt))

    errs = []
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gcrc", "auto.offset.reset": "earliest",
                  "check.crcs": True,
                  "error_cb": lambda e: errs.append(e)})
    c.subscribe(["fv"])
    deadline = time.monotonic() + 10
    got = []
    while time.monotonic() < deadline and not errs:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c.close()
    assert any(e.code == Err._BAD_MSG for e in errs), errs
    assert not got, "corrupted batch must not be delivered"


def test_check_crcs_disabled_skips_verify(cluster):
    """check.crcs=false: corrupted CRC field itself is ignored (payload
    intact), messages still delivered — proving the verify is gated by
    conf like the reference."""
    _produce(cluster, 5, codec="none", parts=1)
    part = cluster.partition("fv", 0)
    base, blob = part.log[0]
    corrupt = bytearray(blob)
    # corrupt the stored CRC field (not the payload)
    struct.pack_into(">I", corrupt, proto.V2_OF_CRC, 0xDEADBEEF)
    part.log[0] = (base, bytes(corrupt))

    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "gnocrc", "auto.offset.reset": "earliest",
                  "check.crcs": False})
    c.subscribe(["fv"])
    got = []
    deadline = time.monotonic() + 15
    while len(got) < 5 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c.close()
    assert len(got) == 5
