"""OAUTHBEARER end-to-end against the mock cluster (reference:
rdkafka_sasl_oauthbearer.c — unsecured-JWS builtin handler, app token
via rd_kafka_oauthbearer_set_token, refresh callback flow)."""
import time

import pytest

from librdkafka_tpu import Producer
from librdkafka_tpu.mock.cluster import MockCluster


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"auth": 1})
    yield c
    c.stop()


def _conf(cluster, **extra):
    return {"bootstrap.servers": cluster.bootstrap_servers(),
            "security.protocol": "sasl_plaintext",
            "sasl.mechanisms": "OAUTHBEARER", **extra}


def test_unsecured_jws_builtin_handler(cluster):
    """enable.sasl.oauthbearer.unsecure.jwt=true: the builtin handler
    fabricates an unsecured JWS and auth succeeds."""
    p = Producer(_conf(cluster, **{
        "enable.sasl.oauthbearer.unsecure.jwt": True,
        "sasl.oauthbearer.config": "principal=tester"}))
    p.produce("auth", value=b"jws-ok", partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_refresh_cb_supplies_token(cluster):
    """The refresh callback path: no unsecured-JWS handler, the app cb
    sets the token (rd_kafka_oauthbearer_set_token)."""
    calls = []

    def refresh(rk_handle, cfg):
        calls.append(cfg)
        rk_handle.set_oauthbearer_token(
            "eyJhbGciOiJub25lIn0.eyJzdWIiOiJ0In0.",
            lifetime_ms=int((time.time() + 300) * 1000),
            principal="t")

    p = Producer(_conf(cluster, **{
        "oauthbearer_token_refresh_cb": refresh}))
    p.produce("auth", value=b"refresh-ok", partition=0)
    assert p.flush(15.0) == 0
    assert calls, "refresh callback never invoked"
    p.close()


def test_no_token_and_handler_disabled_fails_auth(cluster):
    """Default enable.sasl.oauthbearer.unsecure.jwt=false and no app
    token: auth must FAIL (never a silent unsecured-JWS fallback)."""
    drs = []
    p = Producer(_conf(cluster, **{
        "message.timeout.ms": 1500,
        "dr_msg_cb": lambda e, m: drs.append(e)}))
    p.produce("auth", value=b"denied", partition=0)
    assert p.flush(10.0) == 0
    assert len(drs) == 1 and drs[0] is not None
    p.close()
