"""KIP-227 incremental fetch sessions + interest-set metadata
(ISSUE 14): the client-side ``FetchSession`` epoch protocol, the mock
broker's session cache (create / incremental / forgotten / error
codes / eviction), the fallback-and-renegotiate paths for both
top-level session errors, session survival across an incremental
cooperative rebalance, the sessionless opt-out knob, and the
Metadata v1+ null-vs-empty topic-list semantics."""
import time
from types import SimpleNamespace

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.client.fetch_session import (INITIAL_EPOCH,
                                                 SESSIONLESS_EPOCH,
                                                 FetchSession)
from librdkafka_tpu.mock.cluster import MockCluster

TOPIC = "fs"


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={TOPIC: 2})
    yield c
    c.stop()


def _produce(cluster, n, start=0, topic=TOPIC, parts=2):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(start, start + n):
        p.produce(topic, value=b"m%04d" % i, partition=i % parts)
    assert p.flush(10.0) == 0
    p.close()


def _consume(c, n, timeout=15.0):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m)
    return got


def _data_sessions(c):
    """The consumer's non-bootstrap broker FetchSessions."""
    rk = c._rk
    with rk._brokers_lock:
        return [b._fetch_session for b in rk.brokers.values()]


# ===================================================== unit: the FSM ==
class TestFetchSessionUnit:
    def test_epoch0_sends_everything(self):
        fs = FetchSession()
        wanted = {("t", 0): (0, 1 << 20), ("t", 1): (5, 1 << 20)}
        epoch, to_send, forgotten = fs.build(wanted)
        assert epoch == INITIAL_EPOCH
        assert set(to_send) == set(wanted) and forgotten == []
        fs.on_success(77)
        assert fs.session_id == 77 and fs.epoch == 1
        assert fs.book == wanted

    def test_incremental_sends_only_changes(self):
        fs = FetchSession()
        wanted = {("t", 0): (0, 1), ("t", 1): (0, 1)}
        fs.build(wanted)
        fs.on_success(9)
        # only partition 0 moved; partition 1 is unchanged
        wanted2 = {("t", 0): (10, 1), ("t", 1): (0, 1)}
        epoch, to_send, forgotten = fs.build(wanted2)
        assert epoch == 1 and to_send == [("t", 0)] and forgotten == []
        fs.on_success(9)
        # partition 1 dropped from the interest set -> forgotten
        epoch, to_send, forgotten = fs.build({("t", 0): (10, 1)})
        assert epoch == 2 and to_send == [] and forgotten == [("t", 1)]
        fs.on_success(9)
        assert fs.book == {("t", 0): (10, 1)}

    def test_epoch_wraps_past_int32(self):
        fs = FetchSession()
        fs.build({("t", 0): (0, 1)})
        fs.on_success(3)
        fs.epoch = 0x7FFFFFFF
        fs.build({("t", 0): (0, 1)})
        fs.on_success(3)
        assert fs.epoch == 1          # wraps to 1, never back to 0/-1

    def test_reset_noop_before_first_negotiation(self):
        fs = FetchSession()
        fs.reset("disconnect")        # nothing negotiated: not a reset
        assert fs.stats()["resets"] == 0
        fs.build({("t", 0): (0, 1)})
        fs.on_success(4)
        fs.reset("disconnect")
        assert fs.stats()["resets"] == 1
        assert fs.session_id == 0 and fs.epoch == INITIAL_EPOCH
        assert fs.book == {} and not fs.inflight
        assert SESSIONLESS_EPOCH == -1


# ============================================== e2e: session lifecycle ==
def test_session_negotiated_and_epoch_increments(cluster):
    """Consuming negotiates a session (broker-assigned id), epochs
    increment per fetch, and the mock caches the partition book."""
    _produce(cluster, 20)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "fs-g", "auto.offset.reset": "earliest"})
    c.assign([TopicPartition(TOPIC, 0), TopicPartition(TOPIC, 1)])
    got = _consume(c, 20)
    assert len(got) == 20
    # steady state: a few long-poll cycles advance the epoch
    for _ in range(5):
        c.poll(0.1)
    fss = [fs for fs in _data_sessions(c) if fs.session_id > 0]
    assert fss, "no fetch session negotiated"
    fs = fss[0]
    assert fs.epoch >= 2 and fs.stats()["full_fetches"] == 1
    assert fs.stats()["partitions_total"] == 2
    sids = cluster.fetch_session_ids()
    assert fs.session_id in sids
    with cluster._lock:
        book = cluster._fetch_sessions[fs.session_id]["book"]
        assert set(book) == {(TOPIC, 0), (TOPIC, 1)}
    # steady state is incremental: far fewer partition entries were
    # serialized than fetches were sent
    assert fs.stats()["partitions_sent"] < fs.epoch * 2
    c.close()


def test_forgotten_partitions_on_incremental_unassign(cluster):
    """Dropping a partition from the assignment rides the next fetch's
    forgotten_topics — the mock's session book shrinks; the kept
    partition keeps delivering on the SAME session."""
    _produce(cluster, 10)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "fs-g2", "auto.offset.reset": "earliest"})
    c.assign([TopicPartition(TOPIC, 0), TopicPartition(TOPIC, 1)])
    assert len(_consume(c, 10)) == 10
    fs = next(f for f in _data_sessions(c) if f.session_id > 0)
    sid = fs.session_id
    c.incremental_unassign([TopicPartition(TOPIC, 1)])
    _produce(cluster, 5, start=100, parts=1)   # partition 0 only
    got = _consume(c, 5)
    assert [m.value for m in got] == [b"m%04d" % i for i in range(100, 105)]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with cluster._lock:
            book = dict(cluster._fetch_sessions.get(sid, {}).get("book", {}))
        if set(book) == {(TOPIC, 0)}:
            break
        c.poll(0.1)
    assert set(book) == {(TOPIC, 0)}, book
    assert fs.session_id == sid and fs.stats()["resets"] == 0
    c.close()


def test_seek_relists_partition_in_session(cluster):
    """seek() moves the fetch offset -> the partition no longer matches
    the session book and must be re-listed: the data is redelivered
    from the seek point without any session reset."""
    _produce(cluster, 8)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "fs-g3", "auto.offset.reset": "earliest"})
    c.assign([TopicPartition(TOPIC, 0), TopicPartition(TOPIC, 1)])
    first = _consume(c, 8)
    assert len(first) == 8
    fs = next(f for f in _data_sessions(c) if f.session_id > 0)
    sent_before = fs.stats()["partitions_sent"]
    c.seek(TopicPartition(TOPIC, 0, 0))
    again = _consume(c, 4)
    assert sorted(m.offset for m in again) == [0, 1, 2, 3]
    assert fs.stats()["partitions_sent"] > sent_before
    assert fs.stats()["resets"] == 0
    c.close()


@pytest.mark.parametrize("corrupt", ["evict", "epoch"])
def test_session_error_falls_back_and_renegotiates(cluster, corrupt):
    """Both top-level session errors force renegotiation: the broker
    forgetting the session (FETCH_SESSION_ID_NOT_FOUND) and an epoch
    mismatch (INVALID_FETCH_SESSION_EPOCH).  Either way the client
    resets, full-fetches from epoch 0, and delivery continues."""
    _produce(cluster, 6)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": f"fs-e-{corrupt}",
                  "auto.offset.reset": "earliest"})
    c.assign([TopicPartition(TOPIC, 0), TopicPartition(TOPIC, 1)])
    assert len(_consume(c, 6)) == 6
    fs = next(f for f in _data_sessions(c) if f.session_id > 0)
    old_sid = fs.session_id
    if corrupt == "evict":
        assert cluster.evict_fetch_sessions() >= 1
    else:
        with cluster._lock:
            cluster._fetch_sessions[old_sid]["epoch"] += 7
    _produce(cluster, 6, start=50)
    got = _consume(c, 6)
    assert len(got) == 6, "delivery stalled after session error"
    assert fs.stats()["resets"] >= 1
    assert fs.stats()["full_fetches"] >= 2, "no epoch-0 renegotiation"
    assert fs.session_id > 0, "no new session after renegotiation"
    if corrupt == "evict":
        assert fs.session_id != old_sid
    c.close()


def test_session_survives_cooperative_rebalance(cluster):
    """KIP-429 + KIP-227: an incremental cooperative rebalance revokes
    only the moved partitions — the incumbent's fetch session is NOT
    reset; the revoked partitions leave via forgotten_topics while the
    kept ones keep flowing on the same session id."""
    _produce(cluster, 16)
    conf = {"bootstrap.servers": cluster.bootstrap_servers(),
            "group.id": "fs-coop", "auto.offset.reset": "earliest",
            "partition.assignment.strategy": "cooperative-sticky",
            "heartbeat.interval.ms": 300, "session.timeout.ms": 6000}
    c1 = Consumer(dict(conf, **{"client.id": "c1"}))
    c1.subscribe([TOPIC])
    got1 = _consume(c1, 16)
    assert len(got1) == 16
    fs = next(f for f in _data_sessions(c1) if f.session_id > 0)
    sid = fs.session_id
    c2 = Consumer(dict(conf, **{"client.id": "c2"}))
    c2.subscribe([TOPIC])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        c1.poll(0.1)
        c2.poll(0.1)
        if len(c1.assignment()) == 1 and len(c2.assignment()) == 1:
            break
    assert len(c1.assignment()) == 1 and len(c2.assignment()) == 1
    # the rebalance moved one partition off c1 WITHOUT a session reset
    assert fs.session_id == sid, "cooperative rebalance reset the session"
    assert fs.stats()["resets"] == 0
    _produce(cluster, 10, start=200)
    got = _consume(c1, 1, timeout=10) + _consume(c2, 1, timeout=10)
    assert got, "no delivery after cooperative handoff"
    c1.close()
    c2.close()


def test_sessionless_when_disabled(cluster):
    """fetch.session.enable=false: every fetch goes out with epoch -1,
    no session is negotiated on either side, delivery unaffected."""
    _produce(cluster, 10)
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "fs-off", "auto.offset.reset": "earliest",
                  "fetch.session.enable": False})
    c.assign([TopicPartition(TOPIC, 0), TopicPartition(TOPIC, 1)])
    assert len(_consume(c, 10)) == 10
    for fs in _data_sessions(c):
        s = fs.stats()
        assert s["session_id"] == 0 and s["epoch"] == 0
        assert s["full_fetches"] == 0 and s["partitions_total"] == 0
    assert cluster.fetch_session_ids() == []
    c.close()


# ======================================================= conf knobs ==
class TestConfKnobs:
    def test_defaults_on(self):
        from librdkafka_tpu.client.conf import Conf
        conf = Conf()
        assert conf.get("fetch.session.enable") is True
        assert conf.get("topic.metadata.interest.only") is True

    @pytest.mark.parametrize("knob", ["fetch.session.enable",
                                      "topic.metadata.interest.only"])
    def test_set_time_validation(self, knob):
        from librdkafka_tpu.client.conf import Conf
        conf = Conf()
        conf.set(knob, "false")
        assert conf.get(knob) is False
        conf.set(knob, True)
        assert conf.get(knob) is True
        with pytest.raises(KafkaException) as ei:
            conf.set(knob, "not-a-bool")
        assert ei.value.error.code == Err._INVALID_ARG


# =========================================== mock: session cache rules ==
class TestMockSessionCache:
    def _fetch(self, cluster, body, ver=11, broker=1):
        conn = SimpleNamespace(broker_id=broker, closed=False)
        hdr = {"api_version": ver}
        return cluster._h_Fetch(conn, 1, hdr, dict(body), None)

    @staticmethod
    def _body(epoch, sid=0, topics=(), forgotten=()):
        return {"replica_id": -1, "max_wait_time": 0, "min_bytes": 1,
                "max_bytes": 1 << 20, "isolation_level": 0,
                "session_id": sid, "session_epoch": epoch,
                "topics": [{"topic": t, "partitions": [
                    {"partition": p, "fetch_offset": o,
                     "max_bytes": 1 << 20}]} for t, p, o in topics],
                "forgotten_topics": [{"topic": t, "partitions": ps}
                                     for t, ps in forgotten]}

    def test_unknown_session_id(self, cluster):
        r = self._fetch(cluster, self._body(5, sid=424242))
        assert r["error_code"] == Err.FETCH_SESSION_ID_NOT_FOUND.wire
        assert r["topics"] == [] and r["session_id"] == 0

    def test_epoch_mismatch(self, cluster):
        _produce(cluster, 2, parts=1)   # data -> immediate responses
        r = self._fetch(cluster,
                        self._body(0, topics=[(TOPIC, 0, 0)]))
        sid = r["session_id"]
        assert sid > 0
        r = self._fetch(cluster, self._body(3, sid=sid))  # expected 1
        assert r["error_code"] == Err.INVALID_FETCH_SESSION_EPOCH.wire

    def test_lru_eviction_caps_cache(self, cluster):
        _produce(cluster, 2, parts=1)   # data -> immediate responses
        cluster.fetch_session_slots = 4
        for _ in range(7):
            self._fetch(cluster, self._body(0, topics=[(TOPIC, 0, 0)]))
        sids = cluster.fetch_session_ids()
        assert len(sids) == 4
        # oldest sessions were the victims
        assert min(sids) == 4 and max(sids) == 7

    def test_incremental_omits_empty_partitions(self, cluster):
        _produce(cluster, 4, parts=1)        # data on partition 0 only
        r = self._fetch(cluster, self._body(
            0, topics=[(TOPIC, 0, 0), (TOPIC, 1, 0)]))
        sid = r["session_id"]
        # full response (epoch 0) lists BOTH partitions
        assert sum(len(t["partitions"]) for t in r["topics"]) == 2
        # incremental with new data on p0 only: p1 is omitted
        _produce(cluster, 2, start=10, parts=1)
        r = self._fetch(cluster, self._body(
            1, sid=sid, topics=[(TOPIC, 0, 4)]))
        assert r["error_code"] == 0 and r["session_id"] == sid
        listed = [(t["topic"], p["partition"]) for t in r["topics"]
                  for p in t["partitions"]]
        assert listed == [(TOPIC, 0)]

    def test_session_dies_with_broker(self, cluster):
        _produce(cluster, 2, parts=1)   # data -> immediate responses
        r = self._fetch(cluster, self._body(0, topics=[(TOPIC, 0, 0)]))
        sid = r["session_id"]
        assert sid in cluster.fetch_session_ids()
        cluster.set_broker_down(1, True)
        assert cluster.fetch_session_ids() == []
        cluster.set_broker_down(1, False)
        r = self._fetch(cluster, self._body(1, sid=sid))
        assert r["error_code"] == Err.FETCH_SESSION_ID_NOT_FOUND.wire


# ================================= metadata: null vs empty topic list ==
class TestMetadataInterestSet:
    def _md(self, cluster, names):
        conn = SimpleNamespace(broker_id=1, closed=False)
        return cluster._h_Metadata(conn, 1, {"api_version": 4},
                                   {"topics": names}, None)

    def test_null_list_is_full_enumeration(self, cluster):
        r = self._md(cluster, None)
        assert [t["topic"] for t in r["topics"]] == [TOPIC]

    def test_empty_list_is_no_topics(self, cluster):
        """The brokers-only probe: an empty topic array must NOT
        enumerate the cluster's topic table (KIP-227's metadata twin —
        interest-set clients rely on it at 100k-topic scale)."""
        r = self._md(cluster, [])
        assert r["topics"] == []
        assert r["brokers"], "broker list must still be served"

    def test_named_list_is_sparse(self, cluster):
        cluster.create_topic("other", partitions=1)
        r = self._md(cluster, [TOPIC])
        assert [t["topic"] for t in r["topics"]] == [TOPIC]
