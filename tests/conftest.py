"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on 8
virtual CPU devices per the driver contract.  NOTE: under the axon TPU
tunnel the JAX_PLATFORMS / XLA_FLAGS *environment variables are ignored*
— only the jax.config API takes effect, and only before the backend
initializes (so this must run before any test imports jax).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # backend already initialized (e.g. single-test re-entry)


import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--lockdep", action="store_true", default=False,
        help="run the whole suite under instrumented locks "
             "(analysis/lockdep.py): every Lock/RLock/Condition created "
             "during the session feeds the lock-order graph; the "
             "summary reports AB/BA inversions, cycles and locks held "
             "across blocking calls, and a finding fails the run "
             "(exit 3). See ANALYSIS.md.")
    parser.addoption(
        "--races", action="store_true", default=False,
        help="run the whole suite under the Eraser-style lockset "
             "data-race detector (analysis/races.py; implies "
             "instrumented locks — locksets come from the lockdep "
             "held-stack): every declared shared-field access refines "
             "its candidate lockset, and an empty-lockset write fails "
             "the run (exit 3) with both access stacks. See "
             "ANALYSIS.md.")


def pytest_configure(config):
    if config.getoption("--lockdep"):
        from librdkafka_tpu.analysis import lockdep
        lockdep.reset()
        lockdep.enable()
        config._lockdep_session = True
    if config.getoption("--races"):
        from librdkafka_tpu.analysis import races
        races.reset()
        races.enable()
        config._races_session = True


def pytest_sessionfinish(session, exitstatus):
    if getattr(session.config, "_races_session", False):
        from librdkafka_tpu.analysis import races
        races.disable()
        rep = races.report()
        print("\n" + races.format_report(rep))
        if not races.clean(rep) and session.exitstatus == 0:
            session.exitstatus = 3
    if not getattr(session.config, "_lockdep_session", False):
        return
    from librdkafka_tpu.analysis import lockdep
    lockdep.disable()
    rep = lockdep.report()
    print("\n" + lockdep.format_report(rep))
    if not lockdep.clean(rep) and session.exitstatus == 0:
        session.exitstatus = 3


def require_zstd():
    """Skip the calling test, actionably, when the optional zstandard
    module is absent (codec sweeps run their zstd legs wherever it is
    installed — `pip install '.[zstd]'`)."""
    try:
        import zstandard  # noqa: F401
    except ImportError:
        pytest.skip("zstd support not available: pip install '.[zstd]'")


@pytest.fixture(autouse=True)
def _no_leaked_engine_threads():
    """Every test must leave zero live offload-engine dispatch OR
    warmup threads (AsyncOffloadEngine close() joins both — warmup
    thread names carry the '-warmup' suffix on the engine name, so the
    'engine' match below covers them): a leaked engine means some
    provider/client teardown path lost track of its pipeline, and such
    regressions should fail HERE as a thread leak instead of surfacing
    later as flaky cross-test timeouts or stuck teardowns.

    ISSUE 5 extends the same contract to the observability subsystem:
    a test may not leave the flight-recorder tracer enabled (trace
    rings held — Kafka.close releases this client's refcount) nor a
    stats-emit timer registered (Kafka.close stops and deregisters
    it); both would silently tax or confuse every later test."""
    yield
    deadline = time.monotonic() + 2.0   # grace for in-progress close()

    # ISSUE 7 widens the thread contract to the chaos subsystem: a
    # leaked "sockem-*" pump means a SockemConn outlived its test (its
    # sockets still open), and a leaked "chaos-sched-*" thread means a
    # ChaosScheduler was started but never joined/stopped — both keep
    # injecting faults into whatever runs next.  ISSUE 11 adds the
    # fleet driver's "fleet-rd-*" reader threads: one still alive
    # means a FleetDriver (and likely its worker subprocesses) was
    # never stopped.
    def leaked():
        return [t.name for t in threading.enumerate()
                if t.is_alive() and ("engine" in t.name
                                     or t.name.startswith("sockem-")
                                     or t.name.startswith("fleet-rd-")
                                     or t.name.startswith("chaos-sched"))]

    while leaked() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not leaked(), \
        f"leaked engine/sockem/chaos threads: {leaked()}"

    from librdkafka_tpu.client.stats import _ACTIVE_STATS_TIMERS
    from librdkafka_tpu.obs import trace as _trace
    assert not _trace.enabled and _trace.active_ring_count() == 0, (
        f"leaked trace rings: tracer still enabled={_trace.enabled}, "
        f"{_trace.active_ring_count()} ring(s) registered — a client "
        f"with trace.enable was not closed (or disable() was skipped)")
    assert not _ACTIVE_STATS_TIMERS, (
        f"leaked stats-emit timer(s): {len(_ACTIVE_STATS_TIMERS)} "
        f"still registered — a client with statistics.interval.ms "
        f"was not closed")

    # ISSUE 20 extends the contract to the rest of the obs plane: the
    # unified metrics registry is refcounted exactly like the tracer
    # (the last disable() clears it), and every cross-process dump dir
    # the collector made must have been released (they hold flight
    # dumps and worker rings on disk — a leak accumulates temp dirs
    # across the suite).
    from librdkafka_tpu.obs import collect as _collect
    from librdkafka_tpu.obs import metrics as _metrics
    assert not _metrics.enabled and _metrics.registered_count() == 0, (
        f"leaked metrics registry: enabled={_metrics.enabled}, "
        f"{_metrics.registered_count()} instrument(s) registered — an "
        f"enable() was never paired with disable()")
    assert _collect.active_dump_dir_count() == 0, (
        f"leaked collector dump dir(s): "
        f"{_collect.active_dump_dir_count()} still registered — a "
        f"FleetDriver with trace=True was not stopped (or "
        f"release_dump_dir was skipped)")

    # ISSUE 6: no compiled shard_map step may outlive its test —
    # compiled steps pin per-device buffers (Q-matrix constants on
    # every chip), so a leak taxes all later tests.  Engine close()
    # (multi-lane) and TpuCodecProvider.close() (lz4 mesh) release the
    # cache; tests driving parallel/mesh.py directly must call
    # release_step_cache() themselves.  sys.modules guard: most tests
    # never import the mesh module and should not pay for it here.
    # ISSUE 9: no standalone broker SUBPROCESS may outlive its test —
    # a ClusterHandle registers every pid it spawns (supervisor +
    # per-broker relays) and stop() reaps + deregisters them all.
    # ISSUE 11 extends the same registry to fleet workers: the fleet
    # driver registers every client process as "fleet-worker-<name>"
    # at spawn and deregisters on stop(), so a lost fleet fails HERE
    # too.  A leaked rig would keep real OS processes (and their
    # ports) alive under every later test; reap first so one failure
    # can't cascade, then fail the leaking test here.
    import sys
    ext_mod = sys.modules.get("librdkafka_tpu.mock.external")
    if ext_mod is not None:
        leaked_pids = ext_mod.active_subprocess_pids()
        if leaked_pids:
            ext_mod.reap_leaked()
        assert not leaked_pids, (
            f"leaked broker/fleet-worker subprocess(es): {leaked_pids} "
            f"— a ClusterHandle or FleetDriver was not stopped (now "
            f"SIGKILLed)")

    mesh_mod = sys.modules.get("librdkafka_tpu.parallel.mesh")
    if mesh_mod is not None:
        n = mesh_mod.step_cache_count()
        assert n == 0, (
            f"leaked compiled sharded steps: {n} still cached in "
            f"parallel.mesh._STEP_CACHE — a mesh engine/provider was "
            f"not closed (or a direct mesh test skipped "
            f"release_step_cache())")

    # ISSUE 17: the device compress route's fused/AOT kernels are
    # engine-owned like the mesh step cache — engine close() calls
    # lz4_jax.release_device_kernels(); anything left here means a
    # provider with the route open was not closed.  (The plain
    # per-shape _jit_for cache is deliberately process-amortized and
    # NOT counted — see ops/lz4_jax.py.)
    lz4_mod = sys.modules.get("librdkafka_tpu.ops.lz4_jax")
    if lz4_mod is not None:
        n = lz4_mod.device_kernel_count()
        assert n == 0, (
            f"leaked device compress kernels: {n} still cached in "
            f"ops.lz4_jax (_FUSED/_READY) — an engine with the device "
            f"compress route was not closed (or a direct lz4_jax test "
            f"skipped release_device_kernels())")


# The interop tier's reference build lives in test_0200_interop.py as a
# module-scoped fixture — it only builds when that module actually runs
# (a conftest-level hook stalled every pytest invocation for minutes).
