"""Transactional producer (EOS) tests (reference: 0103-transactions.c,
which librdkafka grows in 1.4 — this tree builds the subsystem the
v1.3.0 reference stops short of): the txn FSM end-to-end through the
real Producer API against the mock cluster's transaction-coordinator
role. Produced-and-aborted transactions must be invisible to
read_committed consumers and fully visible (control records suppressed)
to read_uncommitted ones; committed transactions deliver exactly their
records; a second producer instance with the same transactional.id
bumps the epoch and fences the first (PRODUCER_FENCED, fatal);
send_offsets_to_transaction lands group offsets atomically with the
commit and discards them on abort."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.mock.cluster import MockCluster


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=3, topics={"txn": 2, "src": 1})
    yield c
    c.stop()


def _txn_producer(cluster, tid, **extra):
    conf = {"bootstrap.servers": cluster.bootstrap_servers(),
            "transactional.id": tid, "linger.ms": 2}
    conf.update(extra)
    return Producer(conf)


def _consume_all(cluster, isolation, topic="txn", idle_limit=8):
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": f"g-{isolation}-{time.monotonic_ns()}",
                  "auto.offset.reset": "earliest",
                  "isolation.level": isolation})
    c.subscribe([topic])
    got = []
    deadline = time.monotonic() + 20
    idle = 0
    while time.monotonic() < deadline and idle < idle_limit:
        m = c.poll(0.25)
        if m is not None and m.error is None:
            got.append(m.value)
            idle = 0
        else:
            idle += 1
    c.close()
    return got


def test_commit_delivers_exactly_committed_records(cluster):
    p = _txn_producer(cluster, "tx-commit")
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"c-0", partition=0)
    p.produce("txn", b"c-1", partition=0)
    p.commit_transaction(30)
    p.close()
    assert _consume_all(cluster, "read_committed") == [b"c-0", b"c-1"]
    # control records are never delivered under either isolation level
    assert _consume_all(cluster, "read_uncommitted") == [b"c-0", b"c-1"]


def test_abort_invisible_to_read_committed(cluster):
    """The acceptance-criteria path: produce-in-txn -> flush -> abort.
    read_committed sees nothing; read_uncommitted sees the data (and
    has the ABORT control record suppressed)."""
    p = _txn_producer(cluster, "tx-abort")
    p.init_transactions(30)
    p.begin_transaction()
    for i in range(3):
        p.produce("txn", b"a-%d" % i, partition=0)
    assert p.flush(15) == 0      # data reaches the log BEFORE the abort
    p.abort_transaction(30)

    # a follow-up committed txn from the same producer: the epoch bump
    # restarted sequencing and the aborted range must not shadow it
    p.begin_transaction()
    p.produce("txn", b"after", partition=0)
    p.commit_transaction(30)
    p.close()

    assert _consume_all(cluster, "read_committed") == [b"after"]
    assert _consume_all(cluster, "read_uncommitted") == \
        [b"a-0", b"a-1", b"a-2", b"after"]


def test_open_txn_invisible_until_commit(cluster):
    """LSO semantics: data of a still-open transaction must not reach a
    read_committed consumer even before any marker exists."""
    p = _txn_producer(cluster, "tx-open")
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"open-0", partition=0)
    assert p.flush(15) == 0
    assert _consume_all(cluster, "read_committed", idle_limit=6) == []
    p.commit_transaction(30)
    p.close()
    assert _consume_all(cluster, "read_committed") == [b"open-0"]


def test_zombie_fencing(cluster):
    """Second producer with the same transactional.id bumps the epoch;
    the first becomes a zombie and fails fatally with PRODUCER_FENCED."""
    p1 = _txn_producer(cluster, "tx-zombie")
    p1.init_transactions(30)
    e1 = p1.rk.txnmgr.epoch
    p2 = _txn_producer(cluster, "tx-zombie")
    p2.init_transactions(30)
    assert p2.rk.txnmgr.pid == p1.rk.txnmgr.pid
    assert p2.rk.txnmgr.epoch == e1 + 1

    p1.begin_transaction()
    p1.produce("txn", b"zombie", partition=0)
    with pytest.raises(KafkaException) as ei:
        p1.commit_transaction(15)
    assert p1.rk.fatal_error is not None
    assert p1.rk.fatal_error.code == Err.PRODUCER_FENCED
    assert ei.value.error.fatal or ei.value.error.code == Err.PRODUCER_FENCED
    # a fenced producer rejects further produce with the fatal error
    with pytest.raises(KafkaException):
        p1.produce("txn", b"more", partition=0)
    p1.close(2)

    # the new instance is unaffected
    p2.begin_transaction()
    p2.produce("txn", b"fresh", partition=0)
    p2.commit_transaction(30)
    p2.close()
    assert _consume_all(cluster, "read_committed") == [b"fresh"]


def test_send_offsets_to_transaction(cluster):
    """AddOffsetsToTxn + TxnOffsetCommit: offsets land in the group
    atomically with the commit, and abort discards staged ones."""
    p = _txn_producer(cluster, "tx-offsets")
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"v", partition=0)
    p.send_offsets_to_transaction(
        [TopicPartition("src", 0, 42, metadata="m1")], "grp-eos", 30)
    # staged only: not visible in the group before EndTxn(commit)
    g = cluster.groups.get("grp-eos")
    assert g is None or g.offsets.get(("src", 0)) is None
    p.commit_transaction(30)
    assert cluster.groups["grp-eos"].offsets[("src", 0)] == (42, "m1")

    p.begin_transaction()
    p.produce("txn", b"v2", partition=0)
    p.send_offsets_to_transaction(
        [TopicPartition("src", 0, 99)], "grp-eos", 30)
    p.abort_transaction(30)
    assert cluster.groups["grp-eos"].offsets[("src", 0)] == (42, "m1")
    p.close()


def test_consumer_group_metadata_object(cluster):
    """send_offsets accepts the consumer_group_metadata() handle."""
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "grp-md",
                  "auto.offset.reset": "earliest"})
    c.subscribe(["src"])
    md = c.consumer_group_metadata()
    assert md.group_id == "grp-md"
    p = _txn_producer(cluster, "tx-md")
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"v", partition=0)
    p.send_offsets_to_transaction([TopicPartition("src", 0, 7)], md, 30)
    p.commit_transaction(30)
    p.close()
    c.close()
    assert cluster.groups["grp-md"].offsets[("src", 0)][0] == 7


def test_state_machine_guards(cluster):
    p = _txn_producer(cluster, "tx-fsm")
    # begin before init
    with pytest.raises(KafkaException) as ei:
        p.begin_transaction()
    assert ei.value.error.code == Err._STATE
    p.init_transactions(30)
    # produce outside a transaction
    with pytest.raises(KafkaException) as ei:
        p.produce("txn", b"x", partition=0)
    assert ei.value.error.code == Err._STATE
    # commit without begin
    with pytest.raises(KafkaException) as ei:
        p.commit_transaction(5)
    assert ei.value.error.code == Err._STATE
    # double begin
    p.begin_transaction()
    with pytest.raises(KafkaException):
        p.begin_transaction()
    # empty transaction commits without touching the coordinator log
    p.commit_transaction(30)
    assert cluster.partition("txn", 0).log == []
    p.close()


def test_txn_api_requires_transactional_id(cluster):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers()})
    with pytest.raises(KafkaException) as ei:
        p.init_transactions(1)
    assert ei.value.error.code == Err._NOT_IMPLEMENTED
    p.close()


def test_conf_validated_at_set_time():
    from librdkafka_tpu.client.conf import Conf
    c = Conf()
    c.set("transactional.id", "ok-id")          # valid
    with pytest.raises(KafkaException):
        c.set("transactional.id", "x" * 250)    # over the broker bound
    with pytest.raises(KafkaException):
        c.set("transactional.id", "bad\x00id")  # control character
    with pytest.raises(KafkaException):
        c.set("transaction.timeout.ms", 10)     # below vmin
    c.set("transaction.timeout.ms", 60000)
    # implied idempotence: the pid/epoch machinery exists without
    # enable.idempotence being set explicitly
    cluster = MockCluster(num_brokers=1, topics={"txn": 1})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "transactional.id": "tx-implied"})
        assert p.rk.idemp is not None
        assert p.rk.txnmgr is not None
        p.close()
    finally:
        cluster.stop()


def test_broker_rejects_oversize_txn_timeout(cluster):
    """transaction.timeout.ms above the broker's transaction.max.
    timeout.ms fails init_transactions fatally
    (INVALID_TRANSACTION_TIMEOUT)."""
    p = _txn_producer(cluster, "tx-tmo",
                      **{"transaction.timeout.ms": 1000000})
    with pytest.raises(KafkaException) as ei:
        p.init_transactions(15)
    assert ei.value.error.code == Err.INVALID_TRANSACTION_TIMEOUT
    p.close(2)


def test_failed_message_makes_txn_abortable(cluster):
    """A message failing inside the txn (injected non-retriable produce
    error) parks the FSM in ABORTABLE_ERROR: commit refuses, abort
    recovers, and the next transaction works."""
    p = _txn_producer(cluster, "tx-abortable",
                      **{"message.send.max.retries": 0})
    p.init_transactions(30)
    p.begin_transaction()
    cluster.push_request_errors(
        __import__("librdkafka_tpu.protocol.proto",
                   fromlist=["ApiKey"]).ApiKey.Produce,
        [Err.INVALID_MSG])
    p.produce("txn", b"doomed", partition=0)
    assert p.flush(15) == 0
    with pytest.raises(KafkaException) as ei:
        p.commit_transaction(15)
    assert ei.value.error.code == Err._STATE
    assert p.rk.txnmgr.state == "ABORTABLE_ERROR"
    p.abort_transaction(30)
    assert p.rk.txnmgr.state == "READY"
    p.begin_transaction()
    p.produce("txn", b"recovered", partition=0)
    p.commit_transaction(30)
    p.close()
    assert _consume_all(cluster, "read_committed") == [b"recovered"]


def test_unflushed_abort_purges_queued_messages(cluster):
    """abort without flush: queued messages are purged (never reach the
    log) and their DRs carry _PURGE_QUEUE."""
    drs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "transactional.id": "tx-purge", "linger.ms": 5000,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"never-sent", partition=0)
    p.abort_transaction(30)
    p.poll(1.0)
    assert drs and drs[0] is not None and drs[0].code == Err._PURGE_QUEUE
    assert cluster.partition("txn", 0).log == []   # no data, no marker
    p.close()


def test_interrupted_producer_txn_aborted_on_reinit(cluster):
    """A producer dying mid-transaction: the next init_transactions of
    the same id makes the coordinator abort the dangling txn, so its
    records never surface under read_committed."""
    p1 = _txn_producer(cluster, "tx-crash")
    p1.init_transactions(30)
    p1.begin_transaction()
    p1.produce("txn", b"dangling", partition=0)
    assert p1.flush(15) == 0
    # p1 "crashes" (no abort); a new instance takes over the id
    p2 = _txn_producer(cluster, "tx-crash")
    p2.init_transactions(30)
    p2.begin_transaction()
    p2.produce("txn", b"takeover", partition=0)
    p2.commit_transaction(30)
    p2.close()
    p1.close(2)
    assert _consume_all(cluster, "read_committed") == [b"takeover"]


def test_stats_blob_carries_txn_state(cluster):
    import json
    blobs = []
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "transactional.id": "tx-stats", "linger.ms": 2,
                  "statistics.interval.ms": 100,
                  "stats_cb": lambda js: blobs.append(json.loads(js))})
    p.init_transactions(30)
    p.begin_transaction()
    p.produce("txn", b"s", partition=0)
    p.commit_transaction(30)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not blobs:
        p.poll(0.1)
    p.close()
    assert blobs
    eos = blobs[-1]["eos"]
    assert eos["txn_state"] in ("READY", "IN_TXN", "COMMITTING")
    assert eos["transactional_id"] == "tx-stats"
    assert eos["producer_id"] >= 0 and eos["producer_epoch"] >= 0
    assert "txn_registered_partitions" in eos
    assert "txn_coordinator" in eos
