"""Fused native batch builder: tk_enqlane.build_batch must be
bit-identical to the 3-phase writer pipeline (frame -> compress_many ->
assemble -> patch_crc) for every codec it claims, because the broker
swaps one for the other purely as an optimization.

Reference behavior being matched: rd_kafka_msgset_writer_finalize
(rdkafka_msgset_writer.c:1230) — header + CRC written in place over the
accumulated buffer.
"""
import time

import numpy as np
import pytest

from librdkafka_tpu.client.arena import ArenaBatch
from librdkafka_tpu.ops.cpu import CpuCodecProvider
from librdkafka_tpu.protocol.msgset import (MsgsetWriterV2,
                                            read_batch_header,
                                            parse_records_v2)
from librdkafka_tpu.utils.buf import Slice


def _builder():
    from librdkafka_tpu.client.broker import _fused_builder
    b = _fused_builder()
    if b is None:
        pytest.skip("tk_enqlane extension unavailable")
    return b


def _records(n, size, keyed=False):
    vals = [(b'{"seq": %07d, "pad": "' % i) + b"ab" * (size // 2) + b'"}'
            for i in range(n)]
    keys = [b"k%04d" % i if keyed else None for i in range(n)]
    base = b"".join(
        (k if k else b"") + v for k, v in zip(keys, vals))
    klens = np.array([len(k) if k else -1 for k in keys],
                     np.int32).tobytes()
    vlens = np.array([len(v) for v in vals], np.int32).tobytes()
    return base, klens, vlens, n


def _writer_path(base, klens, vlens, count, now_ms, pid, epoch, seq,
                 codec):
    batch = ArenaBatch(base, klens, vlens, count, len(base), 0, 0)
    w = MsgsetWriterV2(producer_id=pid, producer_epoch=epoch,
                       base_sequence=seq,
                       codec=None if codec == "none" else codec)
    w.build_arena(batch, now_ms)
    prov = CpuCodecProvider()
    blob = None
    if codec != "none":
        blob = prov.compress_many(codec, [w.records_bytes])[0]
        if len(blob) >= len(w.records_bytes):
            blob = None
            w.codec = None
    region = w.assemble(blob)
    return w.patch_crc(int(prov.crc32c_many([region])[0]))


@pytest.mark.parametrize("codec,cid", [("none", 0), ("lz4", 3),
                                       ("snappy", 2)])
@pytest.mark.parametrize("keyed", [False, True])
def test_bit_identical(codec, cid, keyed):
    build = _builder()
    base, klens, vlens, n = _records(400, 512, keyed)
    now_ms = int(time.time() * 1000)
    ref = _writer_path(base, klens, vlens, n, now_ms, -1, -1, -1, codec)
    got = build(base, klens, vlens, n, now_ms, -1, -1, -1, cid)
    assert got == ref


def test_idempotence_fields():
    build = _builder()
    base, klens, vlens, n = _records(64, 256)
    now_ms = 1721000000123
    got = build(base, klens, vlens, n, now_ms, 7777, 5, 1234, 3)
    ref = _writer_path(base, klens, vlens, n, now_ms, 7777, 5, 1234,
                       "lz4")
    assert got == ref
    info = read_batch_header(Slice(got))
    assert (info.producer_id, info.producer_epoch) == (7777, 5)
    assert info.base_sequence == 1234
    assert info.record_count == n


def test_incompressible_falls_back_plain():
    build = _builder()
    rng = np.random.default_rng(3)
    vals = [rng.integers(0, 256, 300, dtype=np.uint8).tobytes()
            for _ in range(20)]
    base = b"".join(vals)
    klens = np.full(20, -1, np.int32).tobytes()
    vlens = np.array([len(v) for v in vals], np.int32).tobytes()
    got = build(base, klens, vlens, 20, 1721000000000, -1, -1, -1, 3)
    info = read_batch_header(Slice(got))
    assert info.codec is None          # stored plain, attrs codec bits 0
    ref = _writer_path(base, klens, vlens, 20, 1721000000000, -1, -1,
                       -1, "lz4")
    assert got == ref


def test_round_trip_parse():
    build = _builder()
    base, klens, vlens, n = _records(200, 700, keyed=True)
    got = build(base, klens, vlens, n, 1721000000456, -1, -1, -1, 3)
    info = read_batch_header(Slice(got))
    prov = CpuCodecProvider()
    payload = bytes(got[61:])
    records = prov.decompress_many("lz4", [payload])[0]
    recs = parse_records_v2(info, records)
    assert len(recs) == n
    assert recs[0].key == b"k0000"
    assert recs[n - 1].value.startswith(b'{"seq": %07d' % (n - 1))
    # CRC over [Attributes..end] must verify
    from librdkafka_tpu.utils.crc import crc32c
    import struct
    (crc,) = struct.unpack_from(">I", got, 17)
    patched = bytearray(got)
    struct.pack_into(">I", patched, 17, 0)
    assert crc == crc32c(bytes(patched[21:]))


def test_producer_uses_fused_path():
    """End-to-end: fast-lane batches flow through _FusedJob and arrive
    intact (consumer reads back exactly what was produced)."""
    from librdkafka_tpu import Consumer, Producer
    from librdkafka_tpu.mock.cluster import MockCluster

    mc = MockCluster(num_brokers=1, topics={"t0122": 2})
    try:
        p = Producer({"bootstrap.servers": mc.bootstrap_servers(),
                      "compression.codec": "lz4", "linger.ms": 5})
        sent = {}
        for i in range(500):
            v = (b'{"i": %d, "pad": "' % i) + b"xy" * 200 + b'"}'
            p.produce("t0122", value=v, partition=i % 2)
            sent[i] = v
        assert p.flush(30.0) == 0
        # the fused path must actually have been taken (provider says
        # lz4 is fused-eligible on the CPU backend)
        assert p.rk.codec_provider.fused_codec_id("lz4") == 3
        p.close()

        c = Consumer({"bootstrap.servers": mc.bootstrap_servers(),
                      "group.id": "g0122",
                      "auto.offset.reset": "earliest",
                      "check.crcs": True})
        c.subscribe(["t0122"])
        got = []
        deadline = time.monotonic() + 30
        while len(got) < 500 and time.monotonic() < deadline:
            m = c.poll(0.5)
            if m is not None and m.error is None:
                got.append(m.value)
        c.close()
        assert sorted(got) == sorted(sent.values())
    finally:
        mc.stop()
