"""Test-certificate factory for TLS tests (the role the reference's
tests/fixtures + trivup SSL setup play). Generates a throwaway CA, a
server cert for 127.0.0.1/localhost, and a client cert, all PEM, plus a
PKCS#12 keystore bundling the client pair."""
import datetime
import os

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.hazmat.primitives.serialization import pkcs12
    from cryptography.x509.oid import NameOID
except ImportError:
    # optional extra (like zstd): the TLS suites skip, actionably,
    # wherever the module is absent instead of ERRORing at collection
    import pytest
    pytest.skip("cryptography not installed: pip install '.[ssl]' "
                "(TLS test-certificate factory needs it)",
                allow_module_level=True)

_ONE_DAY = datetime.timedelta(days=1)


def _key():
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _name(cn):
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def _cert(subject_cn, issuer_name, issuer_key, pubkey, *, is_ca=False,
          san=None):
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (x509.CertificateBuilder()
         .subject_name(_name(subject_cn))
         .issuer_name(issuer_name)
         .public_key(pubkey)
         .serial_number(x509.random_serial_number())
         .not_valid_before(now - _ONE_DAY)
         .not_valid_after(now + 30 * _ONE_DAY)
         .add_extension(x509.BasicConstraints(ca=is_ca, path_length=None),
                        critical=True))
    if san:
        b = b.add_extension(x509.SubjectAlternativeName(san), critical=False)
    return b.sign(issuer_key, hashes.SHA256())


def make_certs(tmpdir: str) -> dict:
    """Returns paths: ca, server_cert, server_key, client_cert,
    client_key, client_p12 (password 'kstore')."""
    import ipaddress
    ca_key = _key()
    ca_cert = _cert("mock-ca", _name("mock-ca"), ca_key,
                    ca_key.public_key(), is_ca=True)

    srv_key = _key()
    srv_cert = _cert("localhost", ca_cert.subject, ca_key,
                     srv_key.public_key(),
                     san=[x509.DNSName("localhost"),
                          x509.IPAddress(ipaddress.ip_address("127.0.0.1"))])

    cli_key = _key()
    cli_cert = _cert("mock-client", ca_cert.subject, ca_key,
                     cli_key.public_key())

    paths = {}

    def w(name, data):
        p = os.path.join(tmpdir, name)
        with open(p, "wb") as f:
            f.write(data)
        paths[name] = p
        return p

    pem_priv = lambda k: k.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    pem_cert = lambda c: c.public_bytes(serialization.Encoding.PEM)

    w("ca.pem", pem_cert(ca_cert))
    w("server.pem", pem_cert(srv_cert))
    w("server.key", pem_priv(srv_key))
    w("client.pem", pem_cert(cli_cert))
    w("client.key", pem_priv(cli_key))
    w("client.p12", pkcs12.serialize_key_and_certificates(
        b"client", cli_key, cli_cert, [ca_cert],
        serialization.BestAvailableEncryption(b"kstore")))
    return {
        "ca": paths["ca.pem"],
        "server_cert": paths["server.pem"],
        "server_key": paths["server.key"],
        "client_cert": paths["client.pem"],
        "client_key": paths["client.key"],
        "client_p12": paths["client.p12"],
        # objects for tests that need to issue more material from the
        # SAME CA (e.g. CRLs revoking the server cert's serial)
        "_ca_key": ca_key,
        "_ca_cert": ca_cert,
        "_server_cert_obj": srv_cert,
    }


def load_key_and_cert(certs: dict):
    """(ca_key, ca_cert, server_cert) objects for CRL issuance."""
    return certs["_ca_key"], certs["_ca_cert"], certs["_server_cert_obj"]
