"""Codec tests — the analog of the reference's 0017-compression.c plus
format-conformance oracles: our own LZ4/snappy *encoders* must produce
streams that the real liblz4/libsnappy system libraries decode to the
original input (proving spec compliance, not just self-consistency).
"""
import ctypes
import os

import numpy as np
import pytest

from librdkafka_tpu.ops import cpu

# ---------------------------------------------------------------- corpora --

def corpora():
    rng = np.random.default_rng(7)
    out = {
        "empty": b"",
        "one": b"x",
        "short": b"hello snappy/lz4 world",
        "zeros_1k": b"\x00" * 1024,
        "zeros_200k": b"\x00" * 200_000,
        "ascii_rep": b"the quick brown fox jumps over the lazy dog. " * 500,
        "json_like": (b'{"user_id": 12345, "event": "click", "ts": 1690000000}\n'
                      * 2000),
        "random_1k": rng.integers(0, 256, 1024, dtype=np.uint8).tobytes(),
        "random_100k": rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes(),
        "semi": b"".join(b"msg-%06d:" % i + bytes(rng.integers(0, 4, 32,
                        dtype=np.uint8) + 97) for i in range(2000)),
        "edge_12": b"abcdabcdabcd",
        "edge_13": b"abcdabcdabcda",
        "near_64k": b"ab" * 32767 + b"xyz",       # straddles one frame block
        "over_64k": b"pattern-" * 20000,          # multi-block frame
    }
    return out


CORPORA = corpora()
IDS = list(CORPORA)


# ----------------------------------------------------------- self round-trip
@pytest.mark.parametrize("name", IDS)
@pytest.mark.parametrize("codec", ["gzip", "snappy", "lz4", "zstd"])
def test_roundtrip(codec, name):
    if codec == "zstd":
        from conftest import require_zstd
        require_zstd()
    data = CORPORA[name]
    comp, dec = cpu.CODECS[codec]
    assert dec(comp(data), len(data)) == data


def test_compresses_compressible():
    z = CORPORA["zeros_200k"]
    # match length is capped (MAXMATCH) by the shared TPU-greedy spec, so
    # ratios are bounded: lz4 ~45x on zeros, snappy (64-byte copies) ~20x
    assert len(cpu.lz4_compress(z)) < len(z) // 40
    assert len(cpu.snappy_compress(z)) < len(z) // 15


def test_incompressible_not_expanded_much():
    r = CORPORA["random_100k"]
    assert len(cpu.lz4_compress(r)) < len(r) + 1024  # raw-block fallback


# ------------------------------------------------------------ lz4 oracle ---
_LZ4SO = "/lib/x86_64-linux-gnu/liblz4.so.1"


@pytest.fixture(scope="module")
def lz4lib():
    if not os.path.exists(_LZ4SO):
        pytest.skip("no system liblz4 oracle")
    L = ctypes.CDLL(_LZ4SO)
    L.LZ4_decompress_safe.restype = ctypes.c_int
    L.LZ4_decompress_safe.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int, ctypes.c_int]
    return L


@pytest.mark.parametrize("name", IDS)
def test_lz4_block_vs_system_decoder(lz4lib, name):
    data = CORPORA[name]
    if len(data) > 65536:
        data = data[:65536]  # block API is per-64KB-block
    comp = cpu.lz4_block_compress(data)
    dst = ctypes.create_string_buffer(max(len(data), 1))
    r = lz4lib.LZ4_decompress_safe(comp, dst, len(comp), len(data))
    assert r == len(data)
    assert dst.raw[:r] == data


@pytest.mark.parametrize("name", IDS)
def test_lz4_frame_vs_system_decoder(lz4lib, name):
    data = CORPORA[name]
    comp = cpu.lz4_compress(data)
    # LZ4F streaming decode via the real library
    ctx = ctypes.c_void_p()
    ver = lz4lib.LZ4F_getVersion()
    err = lz4lib.LZ4F_createDecompressionContext(ctypes.byref(ctx), ver)
    assert err == 0
    try:
        dst = ctypes.create_string_buffer(max(len(data), 1))
        src = ctypes.create_string_buffer(comp, len(comp))
        dst_sz = ctypes.c_size_t(len(data))
        src_sz = ctypes.c_size_t(len(comp))
        lz4lib.LZ4F_decompress.restype = ctypes.c_size_t
        rc = lz4lib.LZ4F_decompress(ctx, dst, ctypes.byref(dst_sz),
                                    src, ctypes.byref(src_sz), None)
        assert rc == 0, f"LZ4F_decompress hint/err={rc}"
        assert src_sz.value == len(comp)
        assert dst.raw[:dst_sz.value] == data
    finally:
        lz4lib.LZ4F_freeDecompressionContext(ctx)


@pytest.mark.parametrize("name", IDS)
def test_lz4_fast_block_vs_system_decoder(lz4lib, name):
    """The throughput-first fast-parse encoder (the broker hot path's
    default, tk_lz4_block_compress_fast) must also emit spec-compliant
    streams the REAL liblz4 decodes byte-exactly."""
    data = CORPORA[name]
    if len(data) > 65536:
        data = data[:65536]
    L = cpu.lib()
    cap = L.tk_lz4_block_bound(len(data))
    buf = ctypes.create_string_buffer(cap)
    p = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
    n = L.tk_lz4_block_compress_fast(bytes(data), len(data), p, cap)
    assert n >= 0
    comp = buf.raw[:n]
    dst = ctypes.create_string_buffer(max(len(data), 1))
    r = lz4lib.LZ4_decompress_safe(comp, dst, len(comp), len(data))
    assert r == len(data)
    assert dst.raw[:r] == data


@pytest.mark.parametrize("name", IDS)
def test_lz4_fast_frame_roundtrip(name):
    data = CORPORA[name]
    comp, = cpu.lz4f_compress_many([data])          # fast default
    assert cpu.lz4_decompress(comp, len(data)) == data
    det, = cpu.lz4f_compress_many([data], deterministic=True)
    assert det == cpu.lz4_compress(data)            # spec anchor intact


def test_lz4_frame_decode_foreign(lz4lib):
    """Our decoder must read frames produced by the real liblz4 too."""
    data = CORPORA["json_like"]
    bound_fn = lz4lib.LZ4F_compressFrameBound
    bound_fn.restype = ctypes.c_size_t
    bound_fn.argtypes = [ctypes.c_size_t, ctypes.c_void_p]
    cap = bound_fn(len(data), None)
    dst = ctypes.create_string_buffer(cap)
    cf = lz4lib.LZ4F_compressFrame
    cf.restype = ctypes.c_size_t
    cf.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                   ctypes.c_size_t, ctypes.c_void_p]
    n = cf(dst, cap, data, len(data), None)
    assert not lz4lib.LZ4F_isError(n)
    assert cpu.lz4_decompress(dst.raw[:n], len(data)) == data


# --------------------------------------------------------- snappy oracle ---
_SNSO = "/lib/x86_64-linux-gnu/libsnappy.so.1"


@pytest.fixture(scope="module")
def snlib():
    if not os.path.exists(_SNSO):
        pytest.skip("no system libsnappy oracle")
    L = ctypes.CDLL(_SNSO)
    L.snappy_uncompress.restype = ctypes.c_int
    L.snappy_uncompress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                    ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_size_t)]
    return L


@pytest.mark.parametrize("name", IDS)
def test_snappy_vs_system_decoder(snlib, name):
    data = CORPORA[name]
    comp = cpu.snappy_compress(data)
    out_len = ctypes.c_size_t(max(len(data), 1))
    dst = ctypes.create_string_buffer(out_len.value)
    rc = snlib.snappy_uncompress(comp, len(comp), dst, ctypes.byref(out_len))
    assert rc == 0  # SNAPPY_OK
    assert out_len.value == len(data)
    assert dst.raw[:len(data)] == data


def test_snappy_decode_foreign(snlib):
    data = CORPORA["semi"]
    snlib.snappy_max_compressed_length.restype = ctypes.c_size_t
    cap = snlib.snappy_max_compressed_length(ctypes.c_size_t(len(data)))
    dst = ctypes.create_string_buffer(cap)
    out_len = ctypes.c_size_t(cap)
    rc = snlib.snappy_compress(data, len(data), dst, ctypes.byref(out_len))
    assert rc == 0
    assert cpu.snappy_decompress(dst.raw[:out_len.value]) == data


def test_snappy_java_framing():
    data = CORPORA["ascii_rep"]
    import struct
    body = cpu.snappy_compress(data)
    framed = (cpu.SNAPPY_JAVA_MAGIC + struct.pack(">ii", 1, 1)
              + struct.pack(">i", len(body)) + body)
    assert cpu.snappy_java_decompress(framed) == data


# ---------------------------------------------------------------- native ---
def test_native_crc32c_matches_python():
    from librdkafka_tpu.utils.crc import crc32c as py_crc32c
    for name, data in CORPORA.items():
        assert cpu.crc32c(data) == py_crc32c(data), name
    assert cpu.crc32c(b"123456789") == 0xE3069283


def test_crc32c_many():
    bufs = [CORPORA["short"], b"", CORPORA["random_1k"], CORPORA["zeros_1k"]]
    out = cpu.crc32c_many(bufs)
    assert list(out) == [cpu.crc32c(b) for b in bufs]


def test_xxh32_known_vectors():
    # public xxHash reference vectors
    assert cpu.xxh32(b"", 0) == 0x02CC5D05
    assert cpu.xxh32(b"Hello World", 0) == 0xB1FD16EE


def test_lz4_decompress_growth_no_hint():
    """Regression: frames decoding to >4x+64KB must grow-and-retry (the
    native decoder returns -4, not a corruption error, on capacity
    shortfall mid-block) — found driving a 200KB all-'x' record e2e."""
    data = b"x" * 200_000
    comp = cpu.lz4_compress(data)
    assert cpu.lz4_decompress(comp) == data
