"""Broker version fallback / feature negotiation tests (reference:
rdkafka_feature.c — feature bitmask from ApiVersion ranges, legacy
version map via broker.version.fallback; MsgVersion selection
rdkafka_msgset_writer.c:100): the client must interoperate with brokers
that predate ApiVersions (which close the connection on unknown
requests), selecting magic 0/1 messagesets and old request versions."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.feature import (
    MSGVER1, MSGVER2, fallback_api_versions, features_from_api_versions)
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol import proto
from librdkafka_tpu.protocol.proto import ApiKey


def test_feature_map():
    av_new = fallback_api_versions("2.0.0")
    f = features_from_api_versions(av_new)
    assert MSGVER2 in f and MSGVER1 in f and "IDEMPOTENT_PRODUCER" in f

    av_010 = fallback_api_versions("0.10.0")
    f = features_from_api_versions(av_010)
    assert MSGVER1 in f and MSGVER2 not in f

    av_09 = fallback_api_versions("0.9.0")
    f = features_from_api_versions(av_09)
    assert MSGVER1 not in f and MSGVER2 not in f
    assert "BROKER_BALANCED_CONSUMER" in f
    assert "THROTTLETIME" in f

    av_08 = fallback_api_versions("0.8.2")
    f = features_from_api_versions(av_08)
    assert "BROKER_BALANCED_CONSUMER" not in f


@pytest.mark.parametrize("bver,magic", [("0.9.0", 0), ("0.10.0", 1)])
def test_produce_consume_legacy_broker(bver, magic):
    """Against a pre-0.11 mock: ApiVersions closes the connection for
    <0.10 (the client must reconnect without it and apply the fallback),
    produce uses magic-0/1 messagesets, and the consumer reads them
    back — including a compressed wrapper round trip."""
    cluster = MockCluster(num_brokers=1, topics={"old": 1},
                          broker_version=bver)
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": bver,
                      "linger.ms": 5, "compression.codec": "gzip"})
        for i in range(40):
            p.produce("old", value=b"legacy-%02d" % i, key=b"k%d" % i,
                      partition=0)
        assert p.flush(15.0) == 0

        # wire check: stored blobs are v0/v1 messagesets, not v2 batches
        blobs = [blob for _base, blob in cluster.partition("old", 0).log]
        assert blobs
        for blob in blobs:
            assert blob[proto.V2_OF_Magic] == magic   # same byte position
        p.close()

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "broker.version.fallback": bver,
                      "group.id": "gleg", "auto.offset.reset": "earliest"})
        c.subscribe(["old"])
        got = []
        deadline = time.monotonic() + 25
        while len(got) < 40 and time.monotonic() < deadline:
            m = c.poll(0.3)
            if m is not None and m.error is None:
                got.append((m.key, m.value))
        c.close()
        assert sorted(got) == sorted(
            (b"k%d" % i, b"legacy-%02d" % i) for i in range(40))
    finally:
        cluster.stop()


def test_modern_broker_still_uses_v2():
    p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                  "linger.ms": 2})
    p.produce("new", value=b"modern", partition=0)
    assert p.flush(10.0) == 0
    cluster = p._rk.mock_cluster
    blob = cluster.partition("new", 0).log[0][1]
    assert blob[proto.V2_OF_Magic] == 2
    b = next(iter(p._rk.brokers.values()))
    assert MSGVER2 in b.features
    p.close()
