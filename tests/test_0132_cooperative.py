"""ISSUE 12: cooperative incremental rebalance — KIP-429 end to end.

Covers the cooperative-sticky assignor (stickiness + the
never-move-in-the-revoking-generation invariant), Subscription v1
owned_partitions marshalling, the client's two-phase incremental
revoke→assign→rejoin flow with `incremental_assign`/
`incremental_unassign`, the mock broker's static-member fast path and
generation/ownership validation, the oracle's continuity (flow-gap)
invariant + convergence bound, the thread-cheap LiteMemberFleet churn
harness, and the chaos scenarios built on all of it (tier-1 smoke +
the ≥300-member flagship with a pid-verified coordinator SIGKILL).
"""
import json
import os
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.chaos.members import LiteMemberFleet
from librdkafka_tpu.chaos.oracle import DeliveryOracle, OracleViolation
from librdkafka_tpu.client.assignor import (
    ASSIGNOR_PROTOCOLS, cooperative_sticky_assignor, subscription_decode,
    subscription_encode)
from librdkafka_tpu.mock.cluster import GroupMember, MockCluster, MockGroup


# ===================================================== assignor unit ==
class TestCooperativeStickyAssignor:
    def test_fresh_group_balanced(self):
        out = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 4})
        assert sorted(out["a"].get("t", []) + out["b"].get("t", [])) \
            == [0, 1, 2, 3]
        assert abs(len(out["a"].get("t", []))
                   - len(out["b"].get("t", []))) <= 1

    def test_sticky_keeps_owned(self):
        owned = {"a": {"t": [0, 1]}, "b": {"t": [2, 3]}}
        out = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 4}, owned)
        assert out["a"]["t"] == [0, 1]
        assert out["b"]["t"] == [2, 3]

    def test_never_moves_in_revoking_generation(self):
        """One member owns the world; a second joins.  The overloaded
        member is stripped down, but the stripped partitions go to
        NOBODY this generation — the old owner must revoke first."""
        owned = {"a": {"t": [0, 1, 2, 3]}}
        out = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 4}, owned)
        a = set(out["a"].get("t", []))
        b = set(out["b"].get("t", []))
        assert a < {0, 1, 2, 3} and len(a) == 2
        assert not b, "moved partitions must sit out one generation"
        # next generation: a's claims shrank, the freed ones are free
        owned2 = {"a": {"t": sorted(a)}}
        out2 = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 4}, owned2)
        assert set(out2["a"]["t"]) == a, "stickiness across generations"
        assert set(out2["b"]["t"]) == {0, 1, 2, 3} - a

    def test_conflicting_claims_sit_out(self):
        """A partition claimed by two members (zombie overlap) is kept
        by neither — both revoke, the next generation reassigns."""
        owned = {"a": {"t": [0, 1]}, "b": {"t": [1, 2]}}
        out = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 4}, owned)
        assert 1 not in out["a"].get("t", [])
        assert 1 not in out["b"].get("t", [])

    def test_claims_on_unsubscribed_topic_dropped(self):
        owned = {"a": {"gone": [0]}}
        out = cooperative_sticky_assignor(
            {"a": ["t"], "b": ["t"]}, {"t": 2}, owned)
        all_parts = sorted(out["a"].get("t", []) + out["b"].get("t", []))
        assert all_parts == [0, 1]
        assert not out["a"].get("gone")

    def test_protocol_registry(self):
        assert ASSIGNOR_PROTOCOLS["cooperative-sticky"] == "COOPERATIVE"
        assert ASSIGNOR_PROTOCOLS["range"] == "EAGER"
        assert ASSIGNOR_PROTOCOLS["roundrobin"] == "EAGER"


class TestSubscriptionV1:
    def test_owned_roundtrip(self):
        blob = subscription_encode(["t1", "t2"],
                                   owned={"t1": [2, 0], "t2": []})
        d = subscription_decode(blob)
        assert d["version"] == 1
        assert d["topics"] == ["t1", "t2"]
        assert d["owned_partitions"] == {"t1": [0, 2]}

    def test_v0_compat(self):
        d = subscription_decode(subscription_encode(["t"]))
        assert d["version"] == 0
        assert d["owned_partitions"] == {}


# ================================================ client two-phase ==
def _consume_n(c, n, timeout=20):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        m = c.poll(0.2)
        if m is not None and m.error is None:
            got.append(m.value)
    return got


def _wait(cond, timeout=15, tick=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if tick is not None:
            tick()
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestCooperativeClient:
    def _mk(self, cluster, i, **extra):
        conf = {"bootstrap.servers": cluster.bootstrap_servers(),
                "group.id": "coop-g", "client.id": f"c{i}",
                "partition.assignment.strategy": "cooperative-sticky",
                "auto.offset.reset": "earliest",
                "heartbeat.interval.ms": 300,
                "session.timeout.ms": 6000}
        conf.update(extra)
        return Consumer(conf)

    def test_incremental_two_phase_keeps_survivors_fetching(self):
        """Second member joins: the first keeps half WITHOUT its
        fetchers restarting (toppar.version unchanged = the fetch
        stream was never interrupted), revokes the other half
        incrementally, and the mock's cooperative ownership validator
        sees no same-generation move."""
        cluster = MockCluster(num_brokers=1, topics={"ct": 4})
        try:
            p = Producer({"bootstrap.servers":
                          cluster.bootstrap_servers(), "linger.ms": 2})
            for i in range(40):
                p.produce("ct", value=b"m%d" % i, partition=i % 4)
            assert p.flush(10) == 0
            p.close()

            c1 = self._mk(cluster, 1)
            c1.subscribe(["ct"])
            assert len(_consume_n(c1, 40)) == 40
            assert c1.rebalance_protocol() == "COOPERATIVE"
            assert len(c1.assignment()) == 4
            vers_before = {(tp.topic, tp.partition):
                           c1._rk.get_toppar(tp.topic, tp.partition).version
                           for tp in c1.assignment()}

            c2 = self._mk(cluster, 2)
            c2.subscribe(["ct"])
            ok = _wait(lambda: len(c1.assignment()) == 2
                       and len(c2.assignment()) == 2,
                       tick=lambda: (c1.poll(0.05), c2.poll(0.05)))
            assert ok, (c1.assignment(), c2.assignment())
            s1 = {(tp.topic, tp.partition) for tp in c1.assignment()}
            s2 = {(tp.topic, tp.partition) for tp in c2.assignment()}
            assert not (s1 & s2) and len(s1 | s2) == 4
            # the kept fetchers were NEVER stopped/restarted: version
            # bumps only on stop/seek — zero stop-the-world
            for key in s1:
                assert c1._rk.get_toppar(*key).version \
                    == vers_before[key], f"kept fetcher {key} bounced"
            with c1._rk.cgrp._lock:
                assert c1._rk.cgrp.incremental_revoke_cnt >= 1
            g = cluster.groups["coop-g"]
            assert g.validation_errors == []
            assert g.protocol == "cooperative-sticky"
            c1.close()
            c2.close()
        finally:
            cluster.stop()

    def test_incremental_assign_unassign_api(self):
        """Manual incremental_assign/unassign compose the assignment
        without disturbing unrelated partitions."""
        from librdkafka_tpu.client.consumer import TopicPartition
        cluster = MockCluster(num_brokers=1, topics={"ia": 4})
        try:
            c = Consumer({"bootstrap.servers":
                          cluster.bootstrap_servers(),
                          "group.id": "ia-g",
                          "auto.offset.reset": "earliest"})
            from librdkafka_tpu.client.partition import FetchState
            c.incremental_assign([TopicPartition("ia", 0),
                                  TopicPartition("ia", 1)])
            assert len(c.assignment()) == 2
            # wait out the async fetcher start (it bumps version once
            # on activation) before sampling the stability baseline
            assert _wait(lambda: c._rk.get_toppar("ia", 0).fetch_state
                         in (FetchState.ACTIVE, FetchState.OFFSET_QUERY))
            v0 = c._rk.get_toppar("ia", 0).version
            c.incremental_assign([TopicPartition("ia", 2)])
            assert len(c.assignment()) == 3
            c.incremental_unassign([TopicPartition("ia", 1)])
            keys = {(tp.topic, tp.partition) for tp in c.assignment()}
            assert keys == {("ia", 0), ("ia", 2)}
            assert c._rk.get_toppar("ia", 0).version == v0, \
                "unrelated partition bounced by incremental ops"
            c.close()
        finally:
            cluster.stop()

    def test_mixed_protocol_downgrades_to_eager(self):
        """A group with one cooperative+range member and one
        range-only member settles on the common EAGER assignor."""
        cluster = MockCluster(num_brokers=1, topics={"mx": 2})
        try:
            c1 = self._mk(cluster, 1, **{
                "partition.assignment.strategy":
                    "cooperative-sticky,range"})
            c1.subscribe(["mx"])
            _wait(lambda: c1._rk.cgrp.join_state == "steady",
                  tick=lambda: c1.poll(0.05))
            assert c1.rebalance_protocol() == "COOPERATIVE"
            c2 = self._mk(cluster, 2, **{
                "partition.assignment.strategy": "range"})
            c2.subscribe(["mx"])
            ok = _wait(lambda: c1.rebalance_protocol() == "EAGER"
                       and c2._rk.cgrp.join_state == "steady",
                       tick=lambda: (c1.poll(0.05), c2.poll(0.05)))
            assert ok, c1.rebalance_protocol()
            assert cluster.groups["coop-g"].protocol == "range"
            c1.close()
            c2.close()
        finally:
            cluster.stop()


# ====================================== static × cooperative (KIP-345) ==
class TestStaticCooperative:
    def test_static_restart_reclaims_exact_assignment_zero_revokes(self):
        """ISSUE 12 satellite: a group.instance.id member restarting
        within session.timeout.ms reclaims its EXACT prior assignment
        at the same generation — the other member sees no revoke (its
        rebalance_cnt and incremental_revoke_cnt stay flat, its
        fetcher versions never bump)."""
        cluster = MockCluster(num_brokers=1, topics={"sm": 4})
        try:
            conf = {"bootstrap.servers": cluster.bootstrap_servers(),
                    "group.id": "gstat",
                    "partition.assignment.strategy": "cooperative-sticky",
                    "auto.offset.reset": "earliest",
                    "heartbeat.interval.ms": 300,
                    "session.timeout.ms": 30000}
            other = Consumer(dict(conf, **{"group.instance.id": "n-2",
                                           "client.id": "other"}))
            other.subscribe(["sm"])
            stat = Consumer(dict(conf, **{"group.instance.id": "n-1",
                                          "client.id": "stat"}))
            stat.subscribe(["sm"])
            ok = _wait(lambda: len(other.assignment()) == 2
                       and len(stat.assignment()) == 2,
                       tick=lambda: (other.poll(0.05), stat.poll(0.05)))
            assert ok
            prior = sorted((tp.topic, tp.partition)
                           for tp in stat.assignment())
            gen_before = cluster.groups["gstat"].generation
            other_reb = other._rk.cgrp.rebalance_cnt
            with other._rk.cgrp._lock:
                other_rev = other._rk.cgrp.incremental_revoke_cnt
            other_vers = {(tp.topic, tp.partition):
                          other._rk.get_toppar(tp.topic,
                                               tp.partition).version
                          for tp in other.assignment()}
            mid = stat._rk.cgrp.member_id
            stat.close()

            stat2 = Consumer(dict(conf, **{"group.instance.id": "n-1",
                                           "client.id": "stat"}))
            stat2.subscribe(["sm"])
            ok = _wait(lambda: sorted(
                (tp.topic, tp.partition)
                for tp in stat2.assignment()) == prior,
                tick=lambda: (other.poll(0.05), stat2.poll(0.05)))
            assert ok, stat2.assignment()
            assert stat2._rk.cgrp.member_id == mid
            g = cluster.groups["gstat"]
            assert g.generation == gen_before, \
                "static rejoin must not bump the generation"
            # keep polling a moment: no revoke may reach the survivor
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                other.poll(0.05)
            assert other._rk.cgrp.rebalance_cnt == other_reb
            with other._rk.cgrp._lock:
                assert other._rk.cgrp.incremental_revoke_cnt == other_rev
            for key, v in other_vers.items():
                assert other._rk.get_toppar(*key).version == v, \
                    f"survivor fetcher {key} bounced on static rejoin"
            assert g.validation_errors == []
            stat2.close()
            other.close()
        finally:
            cluster.stop()


# ============================================== mock-side validation ==
class TestMockValidation:
    def test_offset_commit_generation_fencing(self):
        """A zombie member's commit (stale generation / unknown member)
        is rejected per real GroupCoordinator semantics; simple
        consumers (generation -1) pass."""
        from librdkafka_tpu.client.errors import Err
        cluster = MockCluster(num_brokers=1, topics={"oc": 1})
        try:
            g = cluster._group("ocg")
            with cluster._lock:
                g.generation = 5
                g.members["alive"] = GroupMember(
                    member_id="alive", client_id="x", client_host="h")

            def commit(gen, member):
                return cluster._h_OffsetCommit(
                    None, 0, {}, {"group_id": "ocg",
                                  "generation_id": gen,
                                  "member_id": member,
                                  "topics": [{"topic": "oc",
                                              "partitions": [
                                                  {"partition": 0,
                                                   "offset": 7,
                                                   "metadata": None}]}]},
                    None)

            ec = commit(5, "alive")["topics"][0]["partitions"][0][
                "error_code"]
            assert ec == 0
            ec = commit(4, "alive")["topics"][0]["partitions"][0][
                "error_code"]
            assert ec == Err.ILLEGAL_GENERATION.wire
            ec = commit(5, "ghost")["topics"][0]["partitions"][0][
                "error_code"]
            assert ec == Err.UNKNOWN_MEMBER_ID.wire
            ec = commit(-1, "")["topics"][0]["partitions"][0][
                "error_code"]
            assert ec == 0, "simple-consumer commits skip the check"
            assert g.offsets[("oc", 0)][0] == 7
        finally:
            cluster.stop()

    def test_ownership_validator_flags_same_generation_move(self):
        """A cooperative leader assignment moving a partition directly
        from a live member to another (no intermediate revoke
        generation) — and double-owning one — is recorded."""
        from librdkafka_tpu.client.assignor import assignment_encode
        cluster = MockCluster(num_brokers=1)
        try:
            g = MockGroup(group_id="vg", protocol="cooperative-sticky")
            g.members["a"] = GroupMember("a", "x", "h")
            g.members["b"] = GroupMember("b", "x", "h")
            g.generation = 1
            g.members["a"].assignment = assignment_encode({"t": [0, 1]})
            g.members["b"].assignment = assignment_encode({"t": [2]})
            with cluster._lock:
                cluster._validate_group_assignment(g)
            assert g.validation_errors == []
            # gen 2: partition 0 jumps a -> b while a is still live
            g.generation = 2
            g.members["a"].assignment = assignment_encode({"t": [1]})
            g.members["b"].assignment = assignment_encode({"t": [0, 2]})
            with cluster._lock:
                cluster._validate_group_assignment(g)
            kinds = [e["kind"] for e in g.validation_errors]
            assert "moved_without_revoke" in kinds, g.validation_errors
            # double ownership within one generation
            g.generation = 3
            g.members["a"].assignment = assignment_encode({"t": [1, 2]})
            with cluster._lock:
                cluster._validate_group_assignment(g)
            kinds = [e["kind"] for e in g.validation_errors]
            assert "double_owner" in kinds
        finally:
            cluster.stop()


# ================================================= oracle continuity ==
class TestContinuityOracle:
    def _seed_traffic(self, o, t0, parts=(0,), n=60, step=0.1):
        for p in parts:
            for i in range(n):
                ts = t0 + i * step
                o.record_ack("t", p, i, None, b"%d-%d" % (p, i), ts=ts)
                o.record_consumed_rows([("t", p, i, b"%d-%d" % (p, i),
                                         ts)])

    def test_clean_window_passes(self):
        o = DeliveryOracle(track_flow=True)
        t0 = time.monotonic() - 10
        self._seed_traffic(o, t0)
        with o._lock:
            o.windows.append(("m", t0 + 1, t0 + 5,
                              frozenset({("t", 0)})))
        r = o.verify(check_duplicates=False, check_order=False,
                     check_continuity=True, flow_stall_s=2.0,
                     raise_on_violation=False)
        assert r["ok"] and r["continuity"]["windows"] == 1

    def test_flow_gap_flagged_with_dump(self):
        o = DeliveryOracle(track_flow=True)
        t0 = time.monotonic() - 10
        self._seed_traffic(o, t0)
        with o._lock:
            o.windows.append(("m", t0 + 1, t0 + 5,
                              frozenset({("t", 0)})))
            o.flow[("t", 0)] = [t0 + 1.0, t0 + 4.9]   # 3.9 s hole
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_duplicates=False, check_order=False,
                     check_continuity=True, flow_stall_s=2.0)
        rep = ei.value.report
        assert rep["violations"]["flow_gap"][0]["partition"] == 0
        assert rep["diff_path"] and os.path.exists(rep["diff_path"])

    def test_no_traffic_no_violation(self):
        """A quiet partition (no acks in the window) owes nothing."""
        o = DeliveryOracle(track_flow=True)
        t0 = time.monotonic() - 10
        with o._lock:
            o.windows.append(("m", t0 + 1, t0 + 5,
                              frozenset({("t", 0)})))
        r = o.verify(check_duplicates=False, check_order=False,
                     check_continuity=True, raise_on_violation=False)
        assert r["ok"]

    def test_window_lifecycle(self):
        """rebalance_begin opens; incremental revoke narrows; eager
        full revoke discards; assign closes."""
        o = DeliveryOracle(track_flow=True)
        o.record_assign("m", [("t", 0), ("t", 1)])
        o.record_rebalance_begin("m")
        assert "m" in o._open_windows
        o.record_revoke("m", [("t", 1)])
        assert o._open_windows["m"][1] == {("t", 0)}
        o.record_assign("m", [("t", 1)], incremental=True)
        assert "m" not in o._open_windows
        assert o.windows[-1][3] == frozenset({("t", 0)})
        # eager: full revoke discards the open window
        o.record_rebalance_begin("m")
        o.record_revoke("m")
        assert "m" not in o._open_windows

    def test_converge_bound_violation(self):
        o = DeliveryOracle()
        o.record_assign("m", [("t", 0)])
        o.record_poll("m")
        with pytest.raises(OracleViolation) as ei:
            o.verify(check_duplicates=False, check_order=False,
                     check_group=True, group_topic="t",
                     group_partitions=1, converged_s=9.0,
                     converge_bound_s=5.0)
        rows = ei.value.report["violations"]["unconverged"]
        assert rows[0]["reason"] == "convergence_exceeded_bound"


# ================================================== lite member fleet ==
@pytest.mark.chaos
class TestLiteMemberFleet:
    def test_cooperative_churn_converges_with_continuity(self):
        """In-process: 12 stable + 4 churning thread-cheap members
        converge to exact coverage with zero flow gaps; the coverage
        ledger and rebalance intervals populate."""
        cluster = MockCluster(num_brokers=2, topics={"lm": 8},
                              group_initial_rebalance_delay_ms=300)
        oracle = DeliveryOracle(track_flow=True)
        fleet = LiteMemberFleet(
            cluster.bootstrap_servers(), group_id="lg", topic="lm",
            partitions=8, members=12, oracle=oracle, seed=5,
            strategy="cooperative-sticky", threads=4,
            churn_members=4, churn_start_s=1.0, churn_period_s=0.3,
            churn_lifetime_s=1.5)
        try:
            p = Producer({"bootstrap.servers":
                          cluster.bootstrap_servers(), "linger.ms": 2,
                          "compression.codec": "none"})
            fleet.start()
            deadline = time.monotonic() + 30
            seq = 0
            conv = False
            while time.monotonic() < deadline:
                p.produce("lm", b"v%08d" % seq, partition=seq % 8,
                          on_delivery=oracle.dr())
                seq += 1
                p.poll(0)
                time.sleep(0.002)
                if seq % 100 == 0:
                    cov = oracle.group_coverage("lm", 8)
                    if cov["converged"] and \
                            fleet.live_member_count() == 12:
                        conv = True
                        break
            assert conv, oracle.group_coverage("lm", 8)
            p.flush(10)
            p.close()
            dl = time.monotonic() + 20
            while oracle.missing_count() > 0 and time.monotonic() < dl:
                time.sleep(0.2)
            snap = {"coverage": oracle.group_coverage("lm", 8),
                    "now": time.monotonic()}
            fleet.stop()
            r = oracle.verify(
                check_duplicates=False, check_order=False,
                check_group=True, group_topic="lm",
                group_partitions=8, converged_s=1.0,
                check_continuity=True, flow_stall_s=3.0,
                coverage=snap["coverage"], now=snap["now"])
            assert r["ok"]
            assert not list(fleet.errors)
            assert cluster.groups["lg"].validation_errors == []
            assert fleet.partition_unavailability(
                snap["now"])["total_s"] >= 0
            assert fleet.rebalancing_intervals(snap["now"])
        finally:
            fleet.stop()
            cluster.stop()

    def test_eager_strategy_stops_the_world(self):
        """The eager baseline on the same harness accrues coverage
        gaps (the stop-the-world eager cost the bench leg measures)."""
        cluster = MockCluster(num_brokers=1, topics={"eg": 8},
                              group_initial_rebalance_delay_ms=300)
        oracle = DeliveryOracle(track_flow=True)
        fleet = LiteMemberFleet(
            cluster.bootstrap_servers(), group_id="eg-g", topic="eg",
            partitions=8, members=6, oracle=oracle, seed=7,
            strategy="range", threads=2, churn_members=2,
            churn_start_s=1.0, churn_period_s=0.3,
            churn_lifetime_s=1.2)
        try:
            fleet.start()
            deadline = time.monotonic() + 25
            while time.monotonic() < deadline:
                cov = oracle.group_coverage("eg", 8)
                if cov["converged"] and all(
                        m.state in ("stable", "done")
                        for m in fleet._members):
                    break
                time.sleep(0.2)
            unavail = fleet.partition_unavailability()
            fleet.stop()
            assert not list(fleet.errors)
            # churn under eager: every rejoin revoked the world, so
            # real uncovered seconds accumulated
            assert unavail["total_s"] > 0.2, unavail
        finally:
            fleet.stop()
            cluster.stop()


# ==================================================== fast scenarios ==
@pytest.mark.chaos
class TestCooperativeScenarios:
    def test_fast_cooperative_churn(self):
        from librdkafka_tpu.chaos.scenarios import fast_cooperative_churn
        t0 = time.monotonic()
        r = fast_cooperative_churn()
        assert r["ok"], r["violations"]
        assert not r["errors"] and not r["schedule_errors"]
        assert r["continuity"]["flow_gaps"] == 0
        assert r["converged_s"] is not None
        assert time.monotonic() - t0 < 16, "tier-1 scenario budget"

    def test_oracle_continuity_selftest(self):
        from librdkafka_tpu.chaos.scenarios import (
            oracle_continuity_selftest)
        r = oracle_continuity_selftest()
        assert not r["ok"]
        assert r["violations"]["flow_gap"]
        assert r["diff_path"] and os.path.exists(r["diff_path"])
        assert r["flight_path"] and os.path.exists(r["flight_path"])
        with open(r["flight_path"]) as f:
            flight = json.load(f)
        names = {e.get("name") for e in flight["traceEvents"]}
        assert "oracle_violation" in names


# ================================================== flagship (slow) ==
@pytest.mark.chaos
@pytest.mark.slow
class TestFlagship:
    def test_cooperative_churn_storm_300_members(self):
        """ISSUE 12 acceptance: ≥300 members with overlapping
        join/leave lifetimes + a pid-verified coordinator SIGKILL
        mid-rebalance sustain the continuity invariant (zero
        stop-the-world windows) and converge to exact coverage within
        the stated bound."""
        from librdkafka_tpu.chaos.scenarios import cooperative_churn_storm
        r = cooperative_churn_storm()
        assert r["ok"], r["violations"]
        assert r["members"] >= 300
        assert r["kills_fired"] >= 1
        assert r["pids_killed"] and \
            r["pids_killed"][0]["verified_dead"]
        assert r["continuity"]["flow_gaps"] == 0
        assert r["converged_s"] is not None and r["converged_s"] <= 45
        assert r["group"]["coverage"]["converged"]
        assert not r["errors"] and not r["schedule_errors"]

    def test_flagship_replay_key_identical_across_rigs(self):
        """Same seed ⇒ identical fault replay_key across two separate
        supervisor launches (the PR 9 determinism contract at
        1000-member scale) — run small to keep the double-rig cost
        sane; the resolution path is scale-independent."""
        from librdkafka_tpu.chaos.scenarios import cooperative_churn_storm
        r1 = cooperative_churn_storm(members=30, churners=10,
                                     raise_on_violation=False)
        r2 = cooperative_churn_storm(members=30, churners=10,
                                     raise_on_violation=False)
        assert r1["replay_key"] == r2["replay_key"]
        assert r1["kills_fired"] == r2["kills_fired"] == 1
