"""Static group membership tests (KIP-345, group.instance.id; reference
conf rdkafka_conf.c group.instance.id + JoinGroup v5): a static member
keeps its member_id across restarts so rejoining does not create a new
member or force a full rebalance storm."""
import time

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.mock.cluster import MockCluster


def _consume_n(c, n, timeout=20):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < n and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m.value)
    return got


def test_static_member_keeps_member_id_across_restart():
    cluster = MockCluster(num_brokers=1, topics={"sm": 2})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "linger.ms": 2})
        for i in range(10):
            p.produce("sm", value=b"s%d" % i, partition=i % 2)
        assert p.flush(10.0) == 0
        p.close()

        conf = {"bootstrap.servers": cluster.bootstrap_servers(),
                "group.id": "gstat", "group.instance.id": "node-1",
                "auto.offset.reset": "earliest",
                "session.timeout.ms": 30000}
        c1 = Consumer(dict(conf))
        c1.subscribe(["sm"])
        assert len(_consume_n(c1, 10)) == 10
        mid1 = c1._rk.cgrp.member_id
        assert "static-node-1" in mid1
        c1.close()

        # restart: same instance id → same member_id slot, one member
        c2 = Consumer(dict(conf))
        c2.subscribe(["sm"])
        deadline = time.monotonic() + 15
        while c2._rk.cgrp.join_state != "steady" and \
                time.monotonic() < deadline:
            c2.poll(0.2)
        mid2 = c2._rk.cgrp.member_id
        assert mid2 == mid1, (mid1, mid2)
        g = cluster.groups["gstat"]
        assert len(g.members) == 1
        c2.close()
    finally:
        cluster.stop()
