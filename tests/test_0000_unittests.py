"""Tier-1 unit tests: the analog of the reference's in-library unittest
registry (src/rdunittest.c) run as test 0000 — bit-exactness golden vectors
for exactly the layers the TPU offload must keep bit-exact (SURVEY.md §4).
"""
import struct

import pytest

from librdkafka_tpu.utils import varint
from librdkafka_tpu.utils.buf import BufUnderflow, SegBuf, Slice
from librdkafka_tpu.utils.crc import (crc32, crc32_combine, crc32c,
                                      crc32c_combine)
from librdkafka_tpu.utils.hash import (consistent_partition, murmur2,
                                       murmur2_partition)


# ---------------------------------------------------------------- varint ---
class TestVarint:
    @pytest.mark.parametrize("v,enc", [
        (0, b"\x00"), (-1, b"\x01"), (1, b"\x02"), (-2, b"\x03"),
        (63, b"\x7e"), (64, b"\x80\x01"), (-64, b"\x7f"),
        (2147483647, b"\xfe\xff\xff\xff\x0f"),
        (-2147483648, b"\xff\xff\xff\xff\x0f"),
    ])
    def test_zigzag_golden(self, v, enc):
        assert varint.enc_i64(v) == enc
        assert varint.dec_i64(enc) == (v, len(enc))
        assert varint.size_i64(v) == len(enc)

    def test_roundtrip_sweep(self):
        # the same sweep idea as unittest_rdvarint (rdvarint.c:107)
        for v in [0, 1, -1, 127, 128, -128, 1000, -1000, 2 ** 31, -2 ** 31,
                  2 ** 62, -(2 ** 62), 2 ** 63 - 1, -(2 ** 63)]:
            enc = varint.enc_i64(v)
            assert varint.dec_i64(enc)[0] == v

    def test_truncated_raises(self):
        with pytest.raises(ValueError):
            varint.dec_u64(b"\x80\x80")


# ---------------------------------------------------------------- crc32c ---
class TestCrc32c:
    # RFC 3720 §B.4 vectors — the same set the reference checks in
    # crc32c.c:388 (unit test).
    def test_rfc3720_vectors(self):
        assert crc32c(b"\x00" * 32) == 0x8A9136AA
        assert crc32c(b"\xff" * 32) == 0x62A8AB43
        assert crc32c(bytes(range(32))) == 0x46DD794E
        assert crc32c(bytes(range(31, -1, -1))) == 0x113FDB5C

    def test_check_string(self):
        assert crc32c(b"123456789") == 0xE3069283

    def test_incremental_equals_oneshot(self):
        data = bytes(range(256)) * 7 + b"tail"
        whole = crc32c(data)
        inc = 0
        for i in range(0, len(data), 37):
            inc = crc32c(data[i:i + 37], inc)
        assert inc == whole

    def test_combine(self):
        data = b"The quick brown fox jumps over the lazy dog" * 13
        for split in [0, 1, 7, 64, len(data) - 1, len(data)]:
            a, b = data[:split], data[split:]
            assert crc32c_combine(crc32c(a), crc32c(b), len(b)) == crc32c(data)

    def test_combine_tree(self):
        # associative chunk combine — the TPU parallel-CRC primitive
        import numpy as np
        rng = np.random.default_rng(42)
        data = rng.integers(0, 256, size=1 << 14, dtype=np.uint8).tobytes()
        chunk = 1 << 10
        crcs = [crc32c(data[i:i + chunk]) for i in range(0, len(data), chunk)]
        acc = crcs[0]
        for c in crcs[1:]:
            acc = crc32c_combine(acc, c, chunk)
        assert acc == crc32c(data)

    def test_crc32_zlib_combine(self):
        data = b"hello world, this is crc32" * 9
        a, b = data[:17], data[17:]
        assert crc32_combine(crc32(a), crc32(b), len(b)) == crc32(data)


# --------------------------------------------------------------- murmur2 ---
class TestMurmur2:
    # Golden values from Apache Kafka's Utils.murmur2 (Java) — the
    # compatibility contract checked by rdmurmur2.c:115 and
    # tests/java/Murmur2Cli.java in the reference.
    @pytest.mark.parametrize("key,signed_val", [
        (b"21", -973932308),
        (b"foobar", -790332482),
        (b"a-little-bit-long-string", -985981536),
        (b"a-little-bit-longer-string", -1486304829),
        (b"lkjh234lh9fiuh90y23oiuhsafujhadof229phr9h19h89h8", -58897971),
        (b"", 275646681),
    ])
    def test_java_golden(self, key, signed_val):
        assert murmur2(key) == signed_val & 0xFFFFFFFF

    def test_partitioner_positive(self):
        for key in [b"21", b"foobar", b"x" * 100]:
            p = murmur2_partition(key, 48)
            assert 0 <= p < 48

    def test_consistent(self):
        assert consistent_partition(b"somekey", 7) == crc32(b"somekey") % 7


# ------------------------------------------------------------------ buf ----
class TestSegBuf:
    def test_write_and_read(self):
        b = SegBuf()
        b.write(b"hello ")
        b.write(b"world")
        assert len(b) == 11
        assert b.as_bytes() == b"hello world"

    def test_backpatch_across_segments(self):
        b = SegBuf()
        p = b.write_i32(0)
        b.push_ro(b"RO-SEGMENT")
        b.write(b"tail")
        b.update_i32(p, 0x01020304)
        assert b.as_bytes()[:4] == b"\x01\x02\x03\x04"
        # patch spanning the ro segment forces copy-on-write
        b.write_update(2, b"\xaa\xbb\xcc\xdd")
        assert b.as_bytes()[2:6] == b"\xaa\xbb\xcc\xdd"

    def test_write_seek_rewind(self):
        b = SegBuf()
        b.write(b"0123456789")
        b.push_ro(b"ABCDEF")
        b.write_seek(12)   # keep "0123456789AB"
        assert b.as_bytes() == b"0123456789AB"
        b.write(b"xy")
        assert b.as_bytes() == b"0123456789ABxy"
        b.write_seek(0)
        assert b.as_bytes() == b""

    def test_splice_compressed_pattern(self):
        # the writer_compress pattern: rewind over uncompressed records and
        # splice the compressed blob as a read-only segment
        # (rdkafka_msgset_writer.c:1191-1203)
        b = SegBuf()
        hdr = b.write(b"HDR-")
        body_start = b.write(b"uncompressed-records-uncompressed-records")
        comp = b"COMPRESSED"
        b.write_seek(body_start)
        b.push_ro(comp)
        assert b.as_bytes() == b"HDR-COMPRESSED"
        assert hdr == 0

    def test_crc_over_region(self):
        b = SegBuf()
        b.write(b"aaa")
        b.push_ro(b"bbbb")
        b.write(b"cc")
        assert b.crc32c(3, 7) == crc32c(b"bbbb")

    def test_iovecs(self):
        b = SegBuf()
        b.write(b"one")
        b.push_ro(b"two")
        vs = b.iovecs()
        assert b"".join(bytes(v) for v in vs) == b"onetwo"


class TestSlice:
    def test_reads(self):
        s = Slice(struct.pack(">bhiq", -1, 2, 3, 4) + b"\x06tail")
        assert s.read_i8() == -1
        assert s.read_i16() == 2
        assert s.read_i32() == 3
        assert s.read_i64() == 4
        assert s.read_varint() == 3
        assert s.read(4) == b"tail"

    def test_underflow(self):
        s = Slice(b"\x00\x01")
        with pytest.raises(BufUnderflow):
            s.read_i32()
        assert s.remains() == 2  # failed read consumes nothing

    def test_narrow(self):
        s = Slice(b"AABBBCC")
        s.skip(2)
        sub = s.narrow(3)
        assert sub.read(3) == b"BBB"
        with pytest.raises(BufUnderflow):
            sub.read(1)
        assert s.read(2) == b"CC"
