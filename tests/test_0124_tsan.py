"""Race-detection tier for the native codec layer (the reference's TSAN
discipline: dev-conf.sh:62-74 + tests/Makefile tsan target).

codec.cpp owns real concurrency — the *_many entry points fan work over
std::thread pools and are called from broker/codec-worker threads of
multiple client instances at once. tests/tsan_codec.cpp drives exactly
those shapes; this test builds it with -fsanitize=thread and fails on
ANY ThreadSanitizer report (halt_on_error with a distinct exit code).
"""
import os
import shutil
import subprocess

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
CODEC = os.path.join(HERE, "..", "librdkafka_tpu", "ops", "native",
                     "codec.cpp")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_native_codec_under_tsan(tmp_path):
    exe = str(tmp_path / "tsan_codec")
    probe = tmp_path / "probe.cpp"
    probe.write_text("int main(){return 0;}\n")
    try:
        subprocess.run(["g++", "-fsanitize=thread", str(probe),
                        "-o", str(tmp_path / "probe")],
                       check=True, capture_output=True)
    except subprocess.CalledProcessError:
        pytest.skip("toolchain lacks ThreadSanitizer")
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-g", "-fsanitize=thread",
         "-pthread", CODEC, os.path.join(HERE, "tsan_codec.cpp"),
         "-o", exe],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["TSAN_OPTIONS"] = "halt_on_error=1 exitcode=66"
    r = subprocess.run([exe], capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (
        f"rc={r.returncode} (66 = TSAN report)\n{r.stderr[-4000:]}")
    assert "TSAN-CODEC-OK" in r.stdout
