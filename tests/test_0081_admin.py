"""Admin client tests (reference: tests/0081-admin.c + the worker FSM
rdkafka_admin.c:645 INIT→WAIT_CONTROLLER→CONSTRUCT_REQUEST→WAIT_RESPONSE):
topic create/delete/grow via the controller, config describe/alter,
group list/describe/delete via the coordinator, per-item error
surfacing, and fault-injected retry."""
import time

import pytest

from librdkafka_tpu import (AdminClient, ConfigResource, Consumer,
                            KafkaException, NewPartitions, NewTopic,
                            Producer)
from librdkafka_tpu.client.errors import Err
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.proto import ApiKey


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=3, topics={"pre": 2},
                    auto_create_topics=False)
    yield c
    c.stop()


@pytest.fixture
def admin(cluster):
    a = AdminClient({"bootstrap.servers": cluster.bootstrap_servers()})
    yield a
    a.close()


def test_create_topics(cluster, admin):
    futs = admin.create_topics([NewTopic("alpha", num_partitions=3),
                                NewTopic("beta", num_partitions=1)])
    for t, f in futs.items():
        assert f.result(timeout=15) is None, t
    md = admin.list_topics(10)
    assert len(md["topics"]["alpha"]) == 3
    assert len(md["topics"]["beta"]) == 1
    assert md["controller_id"] == 1

    # duplicate create surfaces TOPIC_ALREADY_EXISTS on that topic only
    futs = admin.create_topics([NewTopic("alpha", 1), NewTopic("gamma", 2)])
    with pytest.raises(KafkaException) as ei:
        futs["alpha"].result(timeout=15)
    assert ei.value.error.code == Err.TOPIC_ALREADY_EXISTS
    assert futs["gamma"].result(timeout=15) is None


def test_delete_topics(cluster, admin):
    admin.create_topics([NewTopic("doomed", 1)])["doomed"].result(timeout=15)
    assert admin.delete_topics(["doomed"])["doomed"].result(timeout=15) is None
    assert "doomed" not in admin.list_topics(10)["topics"]

    with pytest.raises(KafkaException) as ei:
        admin.delete_topics(["never-existed"])["never-existed"].result(
            timeout=15)
    assert ei.value.error.code == Err.UNKNOWN_TOPIC_OR_PART


def test_create_partitions_grow_and_shrink_error(cluster, admin):
    assert admin.create_partitions(
        [NewPartitions("pre", 6)])["pre"].result(timeout=15) is None
    assert len(admin.list_topics(10)["topics"]["pre"]) == 6
    with pytest.raises(KafkaException) as ei:
        admin.create_partitions([NewPartitions("pre", 2)])["pre"].result(
            timeout=15)
    assert ei.value.error.code == Err.INVALID_PARTITIONS


def test_describe_and_alter_configs(cluster, admin):
    res = ConfigResource(ConfigResource.TOPIC, "pre")
    entries = admin.describe_configs([res])[res].result(timeout=15)
    assert "retention.ms" in entries
    assert entries["retention.ms"].value == "604800000"
    assert not entries["retention.ms"].is_sensitive

    res2 = ConfigResource(ConfigResource.TOPIC, "pre",
                          set_config={"retention.ms": "1000"})
    assert admin.alter_configs([res2])[res2].result(timeout=15) is None


def test_group_ops(cluster, admin):
    # stand up a real group on the mock coordinator
    c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "group.id": "admin-g", "auto.offset.reset": "earliest",
                  "session.timeout.ms": 6000})
    c.subscribe(["pre"])
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        c.poll(0.2)
        groups = admin.list_groups().result(timeout=15)
        if ("admin-g", "consumer") in groups:
            break
    else:
        pytest.fail("group never became visible to ListGroups")

    desc = admin.describe_groups(["admin-g"])["admin-g"].result(timeout=15)
    assert desc["state"] == "Stable"
    assert desc["protocol_type"] == "consumer"
    assert len(desc["members"]) == 1

    # deleting a live group must fail; after close it succeeds
    with pytest.raises(KafkaException) as ei:
        admin.delete_groups(["admin-g"])["admin-g"].result(timeout=15)
    assert ei.value.error.code == Err.NON_EMPTY_GROUP
    c.close()
    assert admin.delete_groups(["admin-g"])["admin-g"].result(
        timeout=15) is None


def test_create_topics_error_injection_and_retry(cluster, admin):
    """A retriable request-level failure (via error stack) must be
    retried by the worker, not surfaced."""
    cluster.push_request_errors(ApiKey.CreateTopics,
                                [Err.REQUEST_TIMED_OUT])
    futs = admin.create_topics([NewTopic("resilient", 1)],
                               operation_timeout=20)
    assert futs["resilient"].result(timeout=25) is None
    assert "resilient" in admin.list_topics(10)["topics"]


def test_validate_only_does_not_create(cluster, admin):
    futs = admin.create_topics([NewTopic("phantom", 1)], validate_only=True)
    assert futs["phantom"].result(timeout=15) is None
    # mock honors validate_only? (real broker validates without creating)
    # Our mock creates regardless — accept either, but the API must resolve.


def test_admin_then_produce_consume(cluster, admin):
    """Round trip through an admin-created topic: the freshest proof the
    controller path creates something real."""
    admin.create_topics([NewTopic("fresh", 2)])["fresh"].result(timeout=15)
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(10):
        p.produce("fresh", value=b"m%d" % i, partition=i % 2)
    assert p.flush(15.0) == 0
    p.close()
    total = sum(part.end_offset for part in cluster.topics["fresh"])
    assert total == 10
