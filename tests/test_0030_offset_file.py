"""Legacy file offset store tests (reference: rdkafka_offset.c:98-330,
offset.store.method=file): commits land in per-toppar text files,
committed() reads them back, and a restarted consumer resumes from the
file offset without touching the broker's offset storage."""
import os
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.consumer import TopicPartition
from librdkafka_tpu.mock.cluster import MockCluster
from librdkafka_tpu.protocol.proto import ApiKey


@pytest.fixture
def cluster():
    c = MockCluster(num_brokers=1, topics={"filo": 1})
    yield c
    c.stop()


def _consumer(cluster, tmpdir, group="gfile", **extra):
    conf = {"bootstrap.servers": cluster.bootstrap_servers(),
            "group.id": group, "auto.offset.reset": "earliest",
            "enable.auto.commit": False,
            "offset.store.method": "file",
            "offset.store.path": str(tmpdir),
            "offset.store.sync.interval.ms": 0}
    conf.update(extra)
    return Consumer(conf)


def test_commit_writes_file_and_committed_reads_it(cluster, tmp_path):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(20):
        p.produce("filo", value=b"m%02d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c = _consumer(cluster, tmp_path)
    c.subscribe(["filo"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 10 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    assert len(got) == 10
    c.commit(message=got[-1])

    path = tmp_path / "filo-0.offset"
    assert path.exists(), list(tmp_path.iterdir())
    assert int(path.read_text().strip()) == got[-1].offset + 1

    committed = c.committed([TopicPartition("filo", 0)])
    assert committed[0].offset == got[-1].offset + 1
    c.close()

    # the broker must have seen no OffsetCommit at all
    commits = [a for _, a in cluster.request_log
               if a == int(ApiKey.OffsetCommit)]
    assert not commits, "file-store commit leaked to the broker"


def test_restart_resumes_from_file_offset(cluster, tmp_path):
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(15):
        p.produce("filo", value=b"r%02d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c1 = _consumer(cluster, tmp_path)
    c1.subscribe(["filo"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 7 and time.monotonic() < deadline:
        m = c1.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    c1.commit(message=got[-1])
    c1.close()

    # second consumer instance: resumes at the file offset, not earliest
    c2 = _consumer(cluster, tmp_path)
    c2.subscribe(["filo"])
    got2 = []
    deadline = time.monotonic() + 20
    while len(got2) < 8 and time.monotonic() < deadline:
        m = c2.poll(0.3)
        if m is not None and m.error is None:
            got2.append(m)
    c2.close()
    assert [m.value for m in got2] == [b"r%02d" % i for i in range(7, 15)]


def test_file_corruption_falls_back_to_reset_policy(cluster, tmp_path):
    (tmp_path / "filo-0.offset").write_text("not-a-number\n")
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    p.produce("filo", value=b"only", partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c = _consumer(cluster, tmp_path)
    c.subscribe(["filo"])
    got = []
    deadline = time.monotonic() + 15
    while not got and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m.value)
    c.close()
    assert got == [b"only"]    # auto.offset.reset=earliest kicked in


def test_store_method_none_explicit_commit_reaches_broker(cluster,
                                                          tmp_path):
    """offset.store.method=none must only suppress STORE-DERIVED
    auto-commit offsets (reference RD_KAFKA_OFFSET_METHOD_NONE is about
    the local store): an explicitly requested commit(message=...) /
    commit(offsets=...) still reaches the broker — the r5 filter
    swallowed it behind a synthetic success callback."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(10):
        p.produce("filo", value=b"m%02d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c = _consumer(cluster, tmp_path, group="gnone",
                  **{"offset.store.method": "none"})
    c.subscribe(["filo"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 5 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    assert len(got) == 5
    c.commit(message=got[-1])
    committed = c.committed([TopicPartition("filo", 0)])
    assert committed[0].offset == got[-1].offset + 1
    # and no offset file appeared (method=none stores nowhere locally)
    assert not list(tmp_path.iterdir())
    c.close()


def test_store_method_none_filters_auto_commit(cluster, tmp_path):
    """The store-derived auto-commit path IS filtered under method=none:
    consumed-but-uncommitted progress must not reach the broker."""
    p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                  "linger.ms": 2})
    for i in range(10):
        p.produce("filo", value=b"m%02d" % i, partition=0)
    assert p.flush(10.0) == 0
    p.close()

    c = _consumer(cluster, tmp_path, group="gnone2",
                  **{"offset.store.method": "none",
                     "enable.auto.commit": True,
                     "auto.commit.interval.ms": 50})
    c.subscribe(["filo"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 10 and time.monotonic() < deadline:
        m = c.poll(0.3)
        if m is not None and m.error is None:
            got.append(m)
    assert len(got) == 10
    time.sleep(0.5)               # several auto-commit intervals
    committed = c.committed([TopicPartition("filo", 0)])
    assert committed[0].offset in (-1, None), committed[0].offset
    c.close()
    # close()'s final auto-commit is store-derived too: still nothing
    c2 = _consumer(cluster, tmp_path, group="gnone2",
                   **{"offset.store.method": "none"})
    cm = None
    deadline = time.monotonic() + 10
    while cm is None and time.monotonic() < deadline:
        try:
            cm = c2.committed([TopicPartition("filo", 0)], timeout=5.0)
        except Exception:
            time.sleep(0.2)
    assert cm is not None and cm[0].offset in (-1, None)
    c2.close()
