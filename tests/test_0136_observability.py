"""ISSUE 20: the fleet-wide observability plane.

Pins the three tentpole halves end to end:

  * cross-process trace collection — real-subprocess clock alignment
    (obs/collect.align_offset over a live stdin/stdout exchange),
    ordered merge with per-process Perfetto metadata, sampled
    produce->ack->fetch->deliver flow stitching through the real
    client hot paths, and the fleet-scale acceptance run (>=3 OS
    processes in ONE merged trace with >=1 flow link);
  * the unified metrics registry (obs/metrics.py) — instruments,
    refcounted clear, snapshot schema, and a real registration site
    (engine.launches) observed through a live produce;
  * the SLO trend gate — scripts/trendgate.py comparison semantics
    plus the CLI contract: an injected regression must fail NAMING
    the metric; a fresh clone (no ledger / no anchor) must soft-pass.

Also covers the satellites: FleetDriver flight-dump sweep + inline
payloads, traceview --merge / by_process, and the collector dump-dir
leak registry the conftest fixture enforces.
"""
import importlib.util
import json
import os
import socket
import subprocess
import sys
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.obs import collect, metrics, trace

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
TRACE_PY = os.path.join(ROOT, "librdkafka_tpu", "obs", "trace.py")

WINDOW_KEYS = {"min", "max", "avg", "sum", "cnt", "stddev", "hdrsize",
               "outofrange", "p50", "p75", "p90", "p95", "p99", "p99_99"}


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"tk_{name}_0136", os.path.join(ROOT, "scripts", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- metrics registry --
class TestMetricsRegistry:
    def test_instruments_and_snapshot_schema(self):
        metrics.enable()
        try:
            c = metrics.counter("t.count")
            c.inc()
            c.inc(4)
            assert c.value == 5
            assert metrics.counter("t.count") is c, \
                "get-or-create must hand back the same instrument"
            g = metrics.gauge("t.level")
            g.set(2.5)
            assert g.value == 2.5
            w = metrics.window("t.lat_us")
            for v in (100, 200, 300):
                w.record(v)
            snap = metrics.snapshot()
            assert snap["schema"] == metrics.SCHEMA == 1
            assert snap["enabled"] is True
            assert snap["counters"]["t.count"] == 5
            assert snap["gauges"]["t.level"] == 2.5
            win = snap["windows"]["t.lat_us"]
            assert set(win) == WINDOW_KEYS, set(win) ^ WINDOW_KEYS
            assert win["cnt"] == 3 and win["min"] >= 100
            assert metrics.registered_count() == 3
        finally:
            metrics.disable()
        # the LAST disable clears the registry (conftest contract)
        assert not metrics.enabled
        assert metrics.registered_count() == 0
        snap = metrics.snapshot()
        assert snap["enabled"] is False and not snap["counters"]

    def test_enable_is_refcounted(self):
        metrics.enable()
        metrics.enable()
        try:
            metrics.counter("rc.count").inc()
            metrics.disable()          # one ref left: registry intact
            assert metrics.enabled
            assert metrics.counter("rc.count").value == 1
        finally:
            metrics.disable()
        assert not metrics.enabled and metrics.registered_count() == 0

    def test_disabled_guard_sites_register_nothing(self):
        """The hot-site contract: one module-attribute check, and a
        guarded site that never runs never registers."""
        assert metrics.enabled is False
        if metrics.enabled:            # the exact site idiom
            metrics.counter("never").inc()
        assert metrics.registered_count() == 0

    def test_engine_registers_launch_counter_live(self):
        """A real registration site observed end to end: device
        launches during a traced produce increment engine.launches,
        and the per-client stats blob carries the snapshot."""
        metrics.enable()
        p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                      "compression.backend": "tpu",
                      "tpu.transport.min.mb.s": 0,
                      "tpu.launch.min.batches": 2, "tpu.governor": False,
                      "tpu.warmup": False, "compression.codec": "lz4",
                      "linger.ms": 5})
        try:
            for i in range(64):
                p.produce("mx", value=b"v%d" % i * 20, partition=i % 4)
            assert p.flush(120.0) == 0
            snap = metrics.snapshot()
            assert snap["counters"].get("engine.launches", 0) >= 1, snap
            blob = json.loads(p._rk.stats.emit_json())
            assert blob["obs"]["enabled"] is True
            assert blob["obs"]["counters"]["engine.launches"] >= 1
        finally:
            p.close()
            metrics.disable()
        assert metrics.registered_count() == 0


# ------------------------------------------------- clock alignment --
_CHILD_SRC = r"""
import importlib.util, json, os, sys, time
spec = importlib.util.spec_from_file_location("tk_child_trace", sys.argv[1])
tr = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tr)
tr.enable()
for line in sys.stdin:
    cmd = json.loads(line)
    if "clock" in cmd:
        print(json.dumps({"mono_ns": tr.now()}), flush=True)
    elif "span" in cmd:
        t0 = tr.now()
        time.sleep(cmd["span"])
        tr.complete("xp", "work", t0, {"who": cmd["who"]})
        print(json.dumps({"ok": True}), flush=True)
    elif "dump" in cmd:
        print(json.dumps({"pid": os.getpid(),
                          "events": tr.collect_events()}), flush=True)
        break
"""


def _rpc(proc, obj):
    proc.stdin.write(json.dumps(obj) + "\n")
    proc.stdin.flush()
    line = proc.stdout.readline()
    assert line, "child died mid-exchange"
    return json.loads(line)


class TestClockAlignment:
    def test_align_offset_math(self):
        # peer clock 1000ns behind: peer read 5000 at collector
        # midpoint 6000 -> offset +1000, err = half the 200ns rtt
        off, err = collect.align_offset(5900, 5000, 6100)
        assert off == 1000 and err == 100
        # exact agreement -> zero offset
        off, err = collect.align_offset(0, 500, 1000)
        assert off == 0 and err == 500

    def test_two_real_subprocesses_align_and_merge(self, tmp_path):
        """ACCEPTANCE (clock half): two live child processes running
        their own obs/trace.py rings, clock-sampled over real pipes;
        the merge must label both processes, order events on one
        timeline, and the measured offsets must agree with the
        machine-wide CLOCK_MONOTONIC ground truth within the
        exchange's own error bound."""
        child = tmp_path / "child.py"
        child.write_text(_CHILD_SRC)
        procs = []
        try:
            for _ in range(2):
                procs.append(subprocess.Popen(
                    [sys.executable, str(child), TRACE_PY],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True))
            clocks = []
            for p in procs:
                best = None
                for _ in range(3):          # keep the tightest round
                    t_send = time.monotonic_ns()
                    r = _rpc(p, {"clock": 1})
                    t_recv = time.monotonic_ns()
                    off, err = collect.align_offset(
                        t_send, r["mono_ns"], t_recv)
                    if best is None or err < best[1]:
                        best = (off, err)
                clocks.append(best)
            # A's span completes before B's starts: wall-clock order
            # the merged timeline must reproduce across processes
            _rpc(procs[0], {"span": 0.02, "who": "a"})
            _rpc(procs[1], {"span": 0.02, "who": "b"})
            dumps = []
            for i, p in enumerate(procs):
                d = _rpc(p, {"dump": 1})
                dumps.append(collect.ProcessDump(
                    f"child-{i}", d["pid"], d["events"],
                    offset_ns=clocks[i][0], err_ns=clocks[i][1]))
            for p in procs:
                assert p.wait(timeout=30) == 0

            # same machine, CLOCK_MONOTONIC: the measured offset must
            # be ~0, within the exchange's own half-RTT bound (+ slack
            # for a descheduled child between its clock read and our
            # recv stamp on a loaded host)
            for off, err in clocks:
                assert 0 <= err < 250_000_000, err
                assert abs(off) <= err + 50_000_000, (off, err)

            events = collect.merge(dumps)
            meta = [e for e in events if e.get("ph") == "M"
                    and e["name"] == "process_name"]
            assert {m["args"]["name"] for m in meta} == \
                {"child-0", "child-1"}
            for m in meta:
                assert "clock_err_us" in m["args"]
            body = [e for e in events if e.get("ph") != "M"]
            assert len({e["pid"] for e in body}) == 2
            ts = [e["ts"] for e in body]
            assert ts == sorted(ts), "merge must ts-sort the timeline"
            spans = [e for e in body if e.get("ph") == "X"
                     and e["name"] == "work"]
            by_who = {e["args"]["who"]: e for e in spans}
            assert set(by_who) == {"a", "b"}
            assert by_who["a"]["ts"] + by_who["a"]["dur"] <= \
                by_who["b"]["ts"] + 1, \
                "aligned timeline must preserve cross-process order"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)


# --------------------------------------------------- flow stitching --
class TestFlowStitching:
    def _pt(self, stage, ts, pid, off=0):
        return {"name": stage, "ph": "i", "cat": "flow", "pid": pid,
                "tid": 0, "ts": ts,
                "args": {"topic": "t", "partition": 0, "offset": off}}

    def test_stitch_unit_links_stage_chain(self):
        events = [self._pt("flow_produce", 10.0, 1),
                  self._pt("flow_ack", 20.0, 1),
                  self._pt("flow_fetch", 30.0, 2),
                  self._pt("flow_deliver", 40.0, 2),
                  # a lone point must NOT become a flow
                  self._pt("flow_produce", 50.0, 1, off=64)]
        out, links = collect.stitch_flows(events)
        assert links == 3
        flows = [e for e in out if e.get("ph") in ("s", "t", "f")]
        assert [f["ph"] for f in flows] == ["s", "t", "t", "f"]
        assert len({f["id"] for f in flows}) == 1
        assert flows[-1]["bp"] == "e", "Chrome flow end needs bp:e"
        assert [f["args"]["stage"] for f in flows] == \
            list(collect.FLOW_STAGES)
        # the consumer-side points keep their own pid: the arrow
        # genuinely crosses processes
        assert {f["pid"] for f in flows} == {1, 2}
        assert collect.flow_link_count(out) == 3

    def test_flow_points_through_real_client_paths(self):
        """The real hot-path emitters: a produce+consume run with
        flow_sample_every=1 must emit all four stages for the same
        (topic, partition, offset) and stitch into one chain."""
        old = trace.flow_sample_every
        trace.flow_sample_every = 1
        trace.enable()
        c = None
        p = Producer({"bootstrap.servers": "", "test.mock.num.brokers": 1,
                      "linger.ms": 2})
        try:
            bs = p._rk.mock_cluster.bootstrap_servers()
            for i in range(3):
                p.produce("fl", value=b"v%d" % i, partition=0)
            assert p.flush(60.0) == 0
            c = Consumer({"bootstrap.servers": bs, "group.id": "g-flow",
                          "auto.offset.reset": "earliest"})
            c.subscribe(["fl"])
            got = 0
            deadline = time.monotonic() + 60
            while got < 3 and time.monotonic() < deadline:
                m = c.poll(0.2)
                if m is not None and m.error is None:
                    got += 1
            assert got == 3, f"consumed {got}/3"
            events = trace.collect_events()
        finally:
            if c is not None:
                c.close()
            p.close()
            trace.disable()
            trace.flow_sample_every = old
        names = {e["name"] for e in events if e.get("ph") == "i"}
        assert set(collect.FLOW_STAGES) <= names, \
            set(collect.FLOW_STAGES) - names
        stitched, links = collect.stitch_flows(events)
        assert links >= 3, "offset 0 must stitch produce->deliver"
        # at least one full 4-stage chain: an id carrying all stages
        by_id = {}
        for e in stitched:
            if e.get("ph") in ("s", "t", "f"):
                by_id.setdefault(e["id"], []).append(e["args"]["stage"])
        assert any(set(v) == set(collect.FLOW_STAGES)
                   for v in by_id.values()), by_id


# ----------------------------------------------- collector registry --
class TestCollectorDumpDirs:
    def test_dump_dir_registry_and_release(self):
        n0 = collect.active_dump_dir_count()
        d = collect.make_dump_dir()
        try:
            assert os.path.isdir(d)
            assert collect.active_dump_dir_count() == n0 + 1
        finally:
            collect.release_dump_dir(d)
        assert collect.active_dump_dir_count() == n0
        assert not os.path.exists(d)
        # double release is harmless (driver.stop() is idempotent)
        collect.release_dump_dir(d)
        assert collect.active_dump_dir_count() == n0

    def test_write_is_perfetto_loadable(self, tmp_path):
        path = str(tmp_path / "m.json")
        events = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                   "args": {"name": "x"}},
                  {"name": "s", "ph": "X", "pid": 1, "tid": 0,
                   "ts": 1.0, "dur": 2.0}]
        assert collect.write(path, events) == 1   # non-metadata count
        with open(path) as f:
            data = json.load(f)
        assert isinstance(data["traceEvents"], list)
        assert data["displayTimeUnit"] == "ms"


# ------------------------------------------------ fleet observability --
class TestFlightDumpSweep:
    def test_driver_flight_dumps_inline_and_sweep(self, tmp_path):
        """The chaos-evidence satellite, unit-scale: streamed flight
        paths come back with inline payloads, and a dump whose
        announcement line died with its worker is still found by the
        trace-dir sweep."""
        from librdkafka_tpu.fleet.driver import FleetDriver
        from librdkafka_tpu.fleet.traffic import TrafficPlan

        plan = TrafficPlan(7, producers=1, groups=1, group_size=1,
                           topics=["t"], partitions=1)
        d = FleetDriver("127.0.0.1:9", plan, trace=True)
        try:
            assert d.trace_dir and os.path.isdir(d.trace_dir)
            streamed = os.path.join(d.trace_dir,
                                    "tk_flight_111_0_fatal.json")
            with open(streamed, "w") as f:
                json.dump({"traceEvents": [
                    {"name": "flight_record", "ph": "i", "pid": 111,
                     "tid": 0, "ts": 1.0,
                     "args": {"reason": "fatal"}}]}, f)
            d.flight_paths.append({"worker": "p00", "path": streamed})
            recs = d.flight_dumps()
            assert len(recs) == 1, recs      # sweep must not duplicate
            assert recs[0]["worker"] == "p00" and recs[0]["exists"]
            assert recs[0]["events"] == 1
            assert recs[0]["payload"]["traceEvents"][0]["args"] == \
                {"reason": "fatal"}
            # the orphan: written but never announced
            orphan = os.path.join(d.trace_dir,
                                  "tk_flight_222_0_kill.json")
            with open(orphan, "w") as f:
                json.dump({"traceEvents": []}, f)
            recs = d.flight_dumps()
            assert len(recs) == 2
            swept = [r for r in recs if r["path"] == orphan]
            assert swept and swept[0]["worker"] is None
            assert swept[0]["exists"] and swept[0]["events"] == 0
        finally:
            d.stop()
        assert collect.active_dump_dir_count() == 0


@pytest.mark.fleet
class TestFleetMergedTrace:
    def test_fleet_mini_one_perfetto_trace_many_processes(self, tmp_path):
        """ACCEPTANCE: a fleet_mini-scale run with trace_path must
        produce ONE Perfetto-loadable merged trace containing >=3
        distinct OS processes with aligned clocks and >=1 stitched
        produce->deliver flow link."""
        from librdkafka_tpu.fleet.scenarios import fleet_mini
        path = str(tmp_path / "fleet_trace.json")
        r = fleet_mini(trace_path=path)
        assert r["ok"], r
        tr = r["trace"]
        assert tr["path"] == path
        assert tr["processes"] >= 3, tr
        assert len(tr["pids"]) >= 3, tr
        assert tr["flow_links"] >= 1, tr
        assert isinstance(r["flight_dumps"], list)   # clean run: evidence
        with open(path) as f:                        # channel still wired
            data = json.load(f)
        events = data["traceEvents"]
        meta = {e["args"]["name"]: e["pid"] for e in events
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert "fleet-driver" in meta, meta
        assert any(n.startswith("worker-") for n in meta), meta
        assert "supervisor" in meta, meta
        assert len(set(meta.values())) >= 3, meta
        for e in events:
            if e.get("ph") == "M" and e["name"] == "process_name":
                assert "clock_err_us" in e["args"]
        assert collect.flow_link_count(events) == tr["flow_links"]
        # the flow chain crosses processes: producer- and consumer-side
        # points carry different pids under one flow id
        by_id = {}
        for e in events:
            if e.get("ph") in ("s", "t", "f") and e.get("cat") == "flow":
                by_id.setdefault(e["id"], set()).add(e["pid"])
        assert any(len(pids) >= 2 for pids in by_id.values()), by_id


# ------------------------------------------------------ rig traces --
class TestRigTraces:
    def test_cluster_handle_collects_supervisor_and_relay_rings(self):
        """The rig half of the collection protocol: ctl trace verbs
        reach the supervisor AND its per-broker relays; collect_traces
        returns ProcessDumps with composed clock offsets and real
        connection spans from the relay."""
        from librdkafka_tpu.mock.external import ClusterHandle
        h = ClusterHandle(brokers=1, topics={"rt": 1})
        try:
            h.trace_enable()
            bs = h.bootstrap_servers()
            host, port = bs.split(",")[0].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=10)
            s.close()
            time.sleep(0.3)              # let the relay log the close
            dumps = h.collect_traces()
        finally:
            h.stop()
        names = {d.name for d in dumps}
        assert "supervisor" in names, names
        assert any(n.startswith("relay-") for n in names), names
        assert len({d.pid for d in dumps}) == len(dumps)
        for d in dumps:
            assert d.err_ns >= 0
        sup = next(d for d in dumps if d.name == "supervisor")
        assert any(e.get("name") == "ctl_cmd" for e in sup.events), \
            "supervisor must span its control commands"
        relay = next(d for d in dumps if d.name.startswith("relay-"))
        assert any(e.get("name") in ("conn", "conn_setup")
                   for e in relay.events), \
            "relay must span the connection we made"
        events = collect.merge(dumps)
        assert len([e for e in events if e.get("ph") == "M"
                    and e["name"] == "process_name"]) == len(dumps)


# ------------------------------------------------------- traceview --
class TestTraceviewMerge:
    def _dump(self, tmp_path, name, pid, spans):
        path = str(tmp_path / f"{name}.json")
        evs = [{"name": n, "ph": "X", "pid": pid, "tid": 0,
                "ts": ts, "dur": dur, "cat": "t"}
               for n, ts, dur in spans]
        with open(path, "w") as f:
            json.dump({"traceEvents": evs}, f)
        return path

    def test_merge_files_labels_bare_dumps(self, tmp_path):
        tv = _load_script("traceview")
        a = self._dump(tmp_path, "prod", 5, [("enqueue", 1.0, 10.0)])
        b = self._dump(tmp_path, "cons", 5, [("deliver", 2.0, 20.0)])
        merged = tv.merge_files([a, b])
        meta = [e for e in merged if e.get("ph") == "M"]
        assert {m["args"]["name"] for m in meta} == {"prod", "cons"}
        # same original pid in both files: the merge must keep the two
        # processes apart
        assert len({m["pid"] for m in meta}) == 2
        summary = tv.summarize(merged)
        procs = {(p["name"], p["process"]) for p in summary["by_process"]}
        assert procs == {("enqueue", "prod"), ("deliver", "cons")}

    def test_single_process_summary_unchanged(self, tmp_path):
        tv = _load_script("traceview")
        a = self._dump(tmp_path, "solo", 1, [("enqueue", 1.0, 10.0)])
        summary = tv.summarize(tv.load_events(a))
        assert summary["by_process"] == []   # no labels -> no table
        assert summary["stages"][0]["name"] == "enqueue"

    def test_merged_trace_from_fleet_summarizes(self, tmp_path):
        """--merge output of already-labelled dumps keeps labels."""
        tv = _load_script("traceview")
        path = str(tmp_path / "labelled.json")
        evs = [{"name": "process_name", "ph": "M", "pid": 9, "tid": 0,
                "args": {"name": "w0"}},
               {"name": "ack", "ph": "X", "pid": 9, "tid": 0,
                "ts": 1.0, "dur": 5.0, "cat": "produce"}]
        with open(path, "w") as f:
            json.dump({"traceEvents": evs}, f)
        merged = tv.merge_files([path])
        summary = tv.summarize(merged)
        assert summary["by_process"] == [
            {"name": "ack", "process": "w0", "cnt": 1, "p50_us": 5.0,
             "max_us": 5.0, "total_us": 5.0}]
        out = tv.render(summary)
        assert "per-process attribution" in out and "w0" in out


# -------------------------------------------------------- trendgate --
def _row(leg, rev, anchor=False, **mx):
    return {"schema": 1, "rev": rev, "utc": "2026-08-07T00:00:00Z",
            "leg": leg, "anchor": anchor, "ok": True,
            "metrics": {k: dict(v) for k, v in mx.items()}}


class TestTrendgate:
    def test_compare_direction_aware(self):
        tg = _load_script("trendgate")
        anchor = _row("smoke", "aaa", True,
                      produce_ns_per_msg={"v": 1000.0, "dir": "lower"},
                      msgs_s={"v": 100.0, "dir": "higher"})
        # latency doubled -> regression; rate unchanged -> fine
        cur = _row("smoke", "bbb",
                   produce_ns_per_msg={"v": 2000.0, "dir": "lower"},
                   msgs_s={"v": 100.0, "dir": "higher"})
        regs = tg.compare(anchor, cur)
        assert [r["metric"] for r in regs] == ["produce_ns_per_msg"]
        assert regs[0]["worse_pct"] == 100.0
        # rate halved -> higher-dir regression
        cur = _row("smoke", "ccc",
                   produce_ns_per_msg={"v": 1000.0, "dir": "lower"},
                   msgs_s={"v": 40.0, "dir": "higher"})
        regs = tg.compare(anchor, cur)
        assert [r["metric"] for r in regs] == ["msgs_s"]
        # within the default 50% tolerance -> pass
        cur = _row("smoke", "ddd",
                   produce_ns_per_msg={"v": 1400.0, "dir": "lower"},
                   msgs_s={"v": 60.0, "dir": "higher"})
        assert tg.compare(anchor, cur) == []
        # an IMPROVEMENT must never trip the gate
        cur = _row("smoke", "eee",
                   produce_ns_per_msg={"v": 100.0, "dir": "lower"},
                   msgs_s={"v": 900.0, "dir": "higher"})
        assert tg.compare(anchor, cur) == []

    def test_compare_per_metric_tolerance_and_skips(self):
        tg = _load_script("trendgate")
        anchor = _row("chaos", "aaa", True,
                      tight={"v": 100.0, "dir": "lower", "tol": 0.1},
                      zeroed={"v": 0.0, "dir": "lower"},
                      gone={"v": 5.0, "dir": "lower"})
        cur = _row("chaos", "bbb",
                   tight={"v": 120.0, "dir": "lower"},
                   zeroed={"v": 50.0, "dir": "lower"})
        regs = tg.compare(anchor, cur)
        # 20% > the row's own 10% tol; zero anchors and metrics the
        # current row lost are skipped, not crashed on
        assert [r["metric"] for r in regs] == ["tight"]
        assert regs[0]["tol_pct"] == 10.0

    def test_gate_statuses(self):
        tg = _load_script("trendgate")
        assert tg.gate([])["status"] == "empty"
        rows = [_row("smoke", "aaa",
                     m={"v": 1.0, "dir": "lower"})]
        assert tg.gate(rows)["status"] == "no-anchor"
        rows = [_row("smoke", "aaa", True, m={"v": 1.0, "dir": "lower"}),
                _row("smoke", "bbb", m={"v": 1.1, "dir": "lower"})]
        v = tg.gate(rows)
        assert v["status"] == "pass"
        assert v["legs"]["smoke"]["anchor_rev"] == "aaa"
        rows.append(_row("smoke", "ccc", m={"v": 9.0, "dir": "lower"}))
        assert tg.gate(rows)["status"] == "fail"
        # an anchor row that IS the latest row gates against the
        # previous anchor, not itself
        rows.append(_row("smoke", "ddd", True,
                         m={"v": 9.0, "dir": "lower"}))
        v = tg.gate(rows)
        assert v["status"] == "fail"
        assert v["legs"]["smoke"]["anchor_rev"] == "aaa"

    def test_load_rows_skips_junk_and_foreign_schema(self, tmp_path):
        tg = _load_script("trendgate")
        path = str(tmp_path / "ledger.jsonl")
        good = _row("smoke", "aaa", True, m={"v": 1.0, "dir": "lower"})
        with open(path, "w") as f:
            f.write("not json\n\n")
            f.write(json.dumps({"schema": 99, "leg": "smoke",
                                "metrics": {}}) + "\n")
            f.write(json.dumps(good) + "\n")
        rows = tg.load_rows(path)
        assert len(rows) == 1 and rows[0]["rev"] == "aaa"

    def _cli(self, *args, env=None):
        e = dict(os.environ)
        e.pop("BENCH_TREND_PATH", None)
        if env:
            e.update(env)
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "trendgate.py"), *args],
            capture_output=True, text=True, timeout=60, env=e)

    def test_cli_injected_regression_names_the_metric(self, tmp_path):
        """ACCEPTANCE: an injected slowdown must FAIL the gate naming
        which metric regressed and by how much."""
        path = str(tmp_path / "ledger.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(_row(
                "smoke", "abc1234", True,
                produce_ns_per_msg={"v": 1000.0, "dir": "lower"})) + "\n")
            f.write(json.dumps(_row(
                "smoke", "def5678",
                produce_ns_per_msg={"v": 2100.0, "dir": "lower"})) + "\n")
        r = self._cli("--ledger", path)
        assert r.returncode == 1, (r.stdout, r.stderr)
        assert "FAIL smoke.produce_ns_per_msg" in r.stdout
        assert "2100" in r.stdout and "anchor 1000" in r.stdout
        assert "worse by 110.0%" in r.stdout
        assert "tolerance 50.0%" in r.stdout
        assert "abc1234" in r.stdout and "def5678" in r.stdout

    def test_cli_soft_passes(self, tmp_path):
        # no ledger at all: a fresh clone must not fail tier-1
        r = self._cli("--ledger", str(tmp_path / "absent.jsonl"))
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "no ledger" in r.stderr
        # rows but no anchor
        path = str(tmp_path / "ledger.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(_row(
                "smoke", "aaa",
                produce_ns_per_msg={"v": 1.0, "dir": "lower"})) + "\n")
        r = self._cli("--ledger", path)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "no anchor" in r.stderr

    def test_cli_respects_env_ledger_default(self, tmp_path):
        path = str(tmp_path / "env.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(_row(
                "smoke", "aaa", True, m={"v": 1.0, "dir": "lower"})) + "\n")
            f.write(json.dumps(_row(
                "smoke", "bbb", m={"v": 5.0, "dir": "lower"})) + "\n")
        r = self._cli(env={"BENCH_TREND_PATH": path})
        assert r.returncode == 1, (r.stdout, r.stderr)
        assert "FAIL smoke.m" in r.stdout


class TestBenchTrendAppend:
    def _bench(self):
        spec = importlib.util.spec_from_file_location(
            "tk_bench_0136", os.path.join(ROOT, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_trend_metrics_pick_per_leg(self):
        b = self._bench()
        mx = b._trend_metrics("smoke", {
            "elapsed_s": 12.5,
            "trace_overhead": {"produce_ns_per_msg": 1500.0,
                               "combined_overhead_pct": 0.4}})
        assert mx["produce_ns_per_msg"] == {"v": 1500.0, "dir": "lower"}
        assert mx["obs_overhead_pct"] == {"v": 0.4, "dir": "lower"}
        assert mx["elapsed_s"]["dir"] == "lower"
        mx = b._trend_metrics("fleet_smoke", {
            "fleet_msgs_s": 800.0, "client_p99_ms_max": 40.0,
            "converged_s": 3.0})
        assert mx["fleet_msgs_s"] == {"v": 800.0, "dir": "higher"}
        assert mx["client_p99_ms_max"]["dir"] == "lower"
        # non-numeric / missing values are dropped, not fabricated
        assert "recovery_p99_ms" not in mx

    def test_trend_append_writes_schema_row(self, tmp_path, monkeypatch):
        b = self._bench()
        path = str(tmp_path / "trend.jsonl")
        monkeypatch.setenv("BENCH_TREND_PATH", path)
        monkeypatch.setattr(sys, "argv",
                            ["bench.py", "--smoke", "--anchor"])
        b._trend_append({
            "elapsed_s": 9.0,
            "trace_overhead": {"produce_ns_per_msg": 1234.0}})
        tg = _load_script("trendgate")
        rows = tg.load_rows(path)
        assert len(rows) == 1
        row = rows[0]
        assert row["leg"] == "smoke" and row["anchor"] is True
        assert row["metrics"]["produce_ns_per_msg"]["v"] == 1234.0
        assert row["rev"] and row["utc"]
