"""TLS transport tests (reference: 0097-ssl_verify.cpp + the handshake
path rdkafka_transport.c:612-719 / rdkafka_ssl.c): e2e produce+consume
over security.protocol=ssl against the mock cluster's TLS listener,
certificate verification on and off, mutual TLS via PKCS#12 keystore,
and sasl_ssl composing TLS with a full SCRAM exchange."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.mock.cluster import MockCluster

from tlsutil import make_certs


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return make_certs(str(tmp_path_factory.mktemp("tls")))


@pytest.fixture
def tls_cluster(certs):
    c = MockCluster(num_brokers=2, topics={"sec": 2},
                    tls={"certfile": certs["server_cert"],
                         "keyfile": certs["server_key"]})
    yield c
    c.stop()


def _ssl_conf(cluster, certs, **extra):
    conf = {"bootstrap.servers": cluster.bootstrap_servers(),
            "security.protocol": "ssl",
            "ssl.ca.location": certs["ca"],
            "linger.ms": 5}
    conf.update(extra)
    return conf


def test_produce_consume_over_ssl(tls_cluster, certs):
    drs = []
    p = Producer(_ssl_conf(tls_cluster, certs,
                           dr_msg_cb=lambda e, m: drs.append(e)))
    for i in range(50):
        p.produce("sec", value=b"tls-%d" % i, partition=i % 2)
    assert p.flush(15.0) == 0
    assert len(drs) == 50 and all(e is None for e in drs)
    p.close()

    c = Consumer(_ssl_conf(tls_cluster, certs, **{
        "group.id": "g-ssl", "auto.offset.reset": "earliest"}))
    c.subscribe(["sec"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 50 and time.monotonic() < deadline:
        m = c.poll(0.5)
        if m is not None and m.error is None:
            got.append(m.value)
    assert sorted(got) == sorted(b"tls-%d" % i for i in range(50))
    c.close()


def test_ssl_verification_rejects_unknown_ca(tls_cluster, certs):
    """Without the CA the handshake must fail closed: no silent
    plaintext downgrade (round-1 VERDICT missing #2), no delivery."""
    drs = []
    p = Producer({"bootstrap.servers": tls_cluster.bootstrap_servers(),
                  "security.protocol": "ssl",
                  # no ssl.ca.location → system CAs → unknown issuer
                  "message.timeout.ms": 1500,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    p.produce("sec", value=b"nope", partition=0)
    assert p.flush(10.0) == 0
    assert len(drs) == 1 and drs[0] is not None
    p.close()


def test_ssl_verification_disabled_allows_unknown_ca(tls_cluster, certs):
    p = Producer({"bootstrap.servers": tls_cluster.bootstrap_servers(),
                  "security.protocol": "ssl",
                  "enable.ssl.certificate.verification": False})
    p.produce("sec", value=b"trusting", partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_endpoint_identification_https(tls_cluster, certs):
    """ssl.endpoint.identification.algorithm=https turns on hostname
    matching; the server cert's SAN covers 127.0.0.1 so it passes."""
    p = Producer(_ssl_conf(tls_cluster, certs, **{
        "ssl.endpoint.identification.algorithm": "https"}))
    p.produce("sec", value=b"hostname-checked", partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_mutual_tls_with_pkcs12_keystore(certs):
    """Server requires a client cert; client supplies it via the PKCS#12
    keystore path (rdkafka_cert.c PKCS12 load)."""
    cluster = MockCluster(num_brokers=1, topics={"mtls": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "ssl.keystore.location": certs["client_p12"],
            "ssl.keystore.password": "kstore"}))
        p.produce("mtls", value=b"mutual", partition=0)
        assert p.flush(15.0) == 0
        p.close()

        # and without a client cert the server rejects the handshake
        drs = []
        p2 = Producer(_ssl_conf(cluster, certs, **{
            "message.timeout.ms": 1500,
            "dr_msg_cb": lambda e, m: drs.append(e)}))
        p2.produce("mtls", value=b"rejected", partition=0)
        assert p2.flush(10.0) == 0
        assert len(drs) == 1 and drs[0] is not None
        p2.close()
    finally:
        cluster.stop()


def test_mutual_tls_with_pem_cert_key(certs):
    cluster = MockCluster(num_brokers=1, topics={"mtls2": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "ssl.certificate.location": certs["client_cert"],
            "ssl.key.location": certs["client_key"]}))
        p.produce("mtls2", value=b"pem-pair", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_sasl_ssl_scram(certs):
    """sasl_ssl composes: TLS handshake first, then the full RFC 5802
    SCRAM-SHA-256 exchange (client proof + server signature verified on
    both sides) over the encrypted channel."""
    cluster = MockCluster(num_brokers=1, topics={"auth": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"]},
                          sasl_users={"alice": "wonderland"})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "security.protocol": "sasl_ssl",
            "sasl.mechanisms": "SCRAM-SHA-256",
            "sasl.username": "alice",
            "sasl.password": "wonderland"}))
        p.produce("auth", value=b"authenticated", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_sasl_ssl_scram_bad_password(certs):
    cluster = MockCluster(num_brokers=1, topics={"auth": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"]},
                          sasl_users={"alice": "wonderland"})
    try:
        drs = []
        p = Producer(_ssl_conf(cluster, certs, **{
            "security.protocol": "sasl_ssl",
            "sasl.mechanisms": "SCRAM-SHA-512",
            "sasl.username": "alice",
            "sasl.password": "wrong",
            "message.timeout.ms": 1500,
            "dr_msg_cb": lambda e, m: drs.append(e)}))
        p.produce("auth", value=b"denied", partition=0)
        assert p.flush(10.0) == 0
        assert len(drs) == 1 and drs[0] is not None
        p.close()
    finally:
        cluster.stop()


def test_gssapi_rejected_at_creation():
    """GSSAPI is not linked in this build: selecting it must fail fast
    at client creation (rdkafka_sasl.c provider selection), not at
    first connect."""
    with pytest.raises(KafkaException) as ei:
        Producer({"bootstrap.servers": "127.0.0.1:1",
                  "security.protocol": "sasl_plaintext"})
    assert ei.value.error.code == Err._UNSUPPORTED_FEATURE


# ---------------------------------------------------- r4: ssl.* breadth ----

def test_mtls_with_in_memory_pems(certs):
    """mTLS from in-memory PEM strings (ssl.certificate.pem /
    ssl.key.pem / ssl_ca) — no file paths in the client conf at all
    (reference rdkafka_cert.c in-memory certs via
    rd_kafka_conf_set_ssl_cert)."""
    cluster = MockCluster(num_brokers=1, topics={"mem": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        with open(certs["client_cert"]) as f:
            cert_pem = f.read()
        with open(certs["client_key"]) as f:
            key_pem = f.read()
        with open(certs["ca"], "rb") as f:
            ca_pem = f.read()
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "security.protocol": "ssl",
                      "ssl_ca": ca_pem,
                      "ssl.certificate.pem": cert_pem,
                      "ssl.key.pem": key_pem,
                      "linger.ms": 5})
        p.produce("mem", value=b"in-memory-mtls", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_mtls_cert_file_key_in_memory(certs):
    """Mixed material: ssl.certificate.location (file) +
    ssl.key.pem (in-memory) — the reference allows any mix of
    rd_kafka_conf_set_ssl_cert and file rows (rdkafka_cert.c)."""
    cluster = MockCluster(num_brokers=1, topics={"mix": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        with open(certs["client_key"]) as f:
            key_pem = f.read()
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "security.protocol": "ssl",
                      "ssl.ca.location": certs["ca"],
                      "ssl.certificate.location": certs["client_cert"],
                      "ssl.key.pem": key_pem,
                      "linger.ms": 5})
        p.produce("mix", value=b"mixed-material-mtls", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_ssl_key_bytes_variant(certs):
    """ssl_certificate / ssl_key accept raw PEM bytes (the C
    set_ssl_cert path hands buffers, not str)."""
    cluster = MockCluster(num_brokers=1, topics={"memb": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "security.protocol": "ssl",
                      "ssl_ca": open(certs["ca"], "rb").read(),
                      "ssl_certificate": open(certs["client_cert"], "rb").read(),
                      "ssl_key": open(certs["client_key"], "rb").read(),
                      "linger.ms": 5})
        p.produce("memb", value=b"bytes-mtls", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_certificate_verify_cb_rejects(tls_cluster, certs):
    """ssl.certificate.verify_cb returning False must fail the
    connection (reference rd_kafka_conf_set_ssl_cert_verify_cb)."""
    calls = []

    def reject(broker_name, broker_id, depth, der, ok):
        calls.append((broker_name, bool(der), ok))
        return False

    drs = []
    p = Producer(_ssl_conf(tls_cluster, certs, **{
        "ssl.certificate.verify_cb": reject,
        "socket.timeout.ms": 3000,
        "message.timeout.ms": 2000,
        "dr_msg_cb": lambda e, m: drs.append(e)}))
    p.produce("sec", value=b"never", partition=0)
    assert p.flush(8.0) == 0
    # the message must have FAILED (timed out unreachable), not delivered
    assert drs and drs[0] is not None and drs[0].code == Err._MSG_TIMED_OUT
    assert calls and calls[0][1], "verify_cb saw no DER certificate"
    p.close()


def test_certificate_verify_cb_accepts(tls_cluster, certs):
    seen = []

    def accept(broker_name, broker_id, depth, der, ok):
        seen.append(der)
        return True

    p = Producer(_ssl_conf(tls_cluster, certs, **{
        "ssl.certificate.verify_cb": accept}))
    p.produce("sec", value=b"allowed", partition=0)
    assert p.flush(15.0) == 0
    assert seen and seen[0]                 # got the DER bytes
    p.close()


def test_curves_and_sigalgs_lists(tls_cluster, certs):
    """ssl.curves.list / ssl.sigalgs.list reach OpenSSL (a handshake
    still succeeds with mainstream values; junk fails loudly at
    client-create time, proving the knob is applied, not decorative)."""
    p = Producer(_ssl_conf(tls_cluster, certs, **{
        "ssl.curves.list": "X25519:P-256",
        "ssl.sigalgs.list": "RSA-PSS+SHA256:rsa_pkcs1_sha256"}))
    p.produce("sec", value=b"curves", partition=0)
    assert p.flush(15.0) == 0
    p.close()

    with pytest.raises(KafkaException):
        Producer(_ssl_conf(tls_cluster, certs,
                           **{"ssl.curves.list": "NOT-A-CURVE"}))
    with pytest.raises(KafkaException):
        Producer(_ssl_conf(tls_cluster, certs,
                           **{"ssl.sigalgs.list": "NOT-A-SIGALG"}))


def test_crl_location_rejects_revoked(certs, tmp_path):
    """ssl.crl.location: a CRL revoking the server cert must fail the
    handshake; an empty CRL from the same CA lets it through."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from tlsutil import load_key_and_cert

    ca_key, ca_cert, srv_cert = load_key_and_cert(certs)
    now = datetime.datetime.now(datetime.timezone.utc)

    def build_crl(revoke_serial=None):
        b = (x509.CertificateRevocationListBuilder()
             .issuer_name(ca_cert.subject)
             .last_update(now)
             .next_update(now + datetime.timedelta(days=1)))
        if revoke_serial is not None:
            b = b.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(revoke_serial)
                .revocation_date(now).build())
        return b.sign(ca_key, hashes.SHA256()).public_bytes(
            serialization.Encoding.PEM)

    crl_rev = tmp_path / "revoked.crl"
    crl_rev.write_bytes(build_crl(srv_cert.serial_number))
    crl_ok = tmp_path / "empty.crl"
    crl_ok.write_bytes(build_crl(None))

    cluster = MockCluster(num_brokers=1, topics={"crl": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"]})
    try:
        # revoked: handshake must fail -> the message FAILS (timeout DR)
        drs = []
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "security.protocol": "ssl",
                      "ssl.ca.location": certs["ca"],
                      "ssl.crl.location": str(crl_rev),
                      "message.timeout.ms": 2500, "linger.ms": 5,
                      "dr_msg_cb": lambda e, m: drs.append(e)})
        p.produce("crl", value=b"no", partition=0)
        assert p.flush(8.0) == 0
        assert drs and drs[0] is not None \
            and drs[0].code == Err._MSG_TIMED_OUT
        p.close()
        # empty CRL: fine
        p2 = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                       "security.protocol": "ssl",
                       "ssl.ca.location": certs["ca"],
                       "ssl.crl.location": str(crl_ok),
                       "linger.ms": 5})
        p2.produce("crl", value=b"yes", partition=0)
        assert p2.flush(15.0) == 0
        p2.close()
    finally:
        cluster.stop()


def test_open_and_closesocket_cbs(certs, tmp_path):
    """open_cb feeds the file offset store's opens; closesocket_cb fires
    on broker socket close (reference open_cb/closesocket_cb rows)."""
    import os as _os

    opened = []
    closed = []

    def open_cb(path, flags):
        opened.append(path)
        return _os.open(path, flags | _os.O_CREAT, 0o644)

    cluster = MockCluster(num_brokers=1, topics={"oc": 1})
    try:
        p = Producer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "closesocket_cb": lambda s: closed.append(True),
                      "linger.ms": 5})
        p.produce("oc", value=b"x", partition=0)
        assert p.flush(10.0) == 0
        p.close()
        assert closed, "closesocket_cb never fired"

        c = Consumer({"bootstrap.servers": cluster.bootstrap_servers(),
                      "group.id": "goc", "auto.offset.reset": "earliest",
                      "open_cb": open_cb,
                      "offset.store.method": "file",
                      "offset.store.path": str(tmp_path) + _os.sep})
        c.subscribe(["oc"])
        deadline = time.monotonic() + 15
        m = None
        while m is None and time.monotonic() < deadline:
            m = c.poll(0.2)
        assert m is not None and m.error is None
        c.commit(asynchronous=False)
        c.close()
        assert opened and opened[0].endswith("oc-0.offset")
    finally:
        cluster.stop()
