"""TLS transport tests (reference: 0097-ssl_verify.cpp + the handshake
path rdkafka_transport.c:612-719 / rdkafka_ssl.c): e2e produce+consume
over security.protocol=ssl against the mock cluster's TLS listener,
certificate verification on and off, mutual TLS via PKCS#12 keystore,
and sasl_ssl composing TLS with a full SCRAM exchange."""
import time

import pytest

from librdkafka_tpu import Consumer, Producer
from librdkafka_tpu.client.errors import Err, KafkaException
from librdkafka_tpu.mock.cluster import MockCluster

from tlsutil import make_certs


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return make_certs(str(tmp_path_factory.mktemp("tls")))


@pytest.fixture
def tls_cluster(certs):
    c = MockCluster(num_brokers=2, topics={"sec": 2},
                    tls={"certfile": certs["server_cert"],
                         "keyfile": certs["server_key"]})
    yield c
    c.stop()


def _ssl_conf(cluster, certs, **extra):
    conf = {"bootstrap.servers": cluster.bootstrap_servers(),
            "security.protocol": "ssl",
            "ssl.ca.location": certs["ca"],
            "linger.ms": 5}
    conf.update(extra)
    return conf


def test_produce_consume_over_ssl(tls_cluster, certs):
    drs = []
    p = Producer(_ssl_conf(tls_cluster, certs,
                           dr_msg_cb=lambda e, m: drs.append(e)))
    for i in range(50):
        p.produce("sec", value=b"tls-%d" % i, partition=i % 2)
    assert p.flush(15.0) == 0
    assert len(drs) == 50 and all(e is None for e in drs)
    p.close()

    c = Consumer(_ssl_conf(tls_cluster, certs, **{
        "group.id": "g-ssl", "auto.offset.reset": "earliest"}))
    c.subscribe(["sec"])
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 50 and time.monotonic() < deadline:
        m = c.poll(0.5)
        if m is not None and m.error is None:
            got.append(m.value)
    assert sorted(got) == sorted(b"tls-%d" % i for i in range(50))
    c.close()


def test_ssl_verification_rejects_unknown_ca(tls_cluster, certs):
    """Without the CA the handshake must fail closed: no silent
    plaintext downgrade (round-1 VERDICT missing #2), no delivery."""
    drs = []
    p = Producer({"bootstrap.servers": tls_cluster.bootstrap_servers(),
                  "security.protocol": "ssl",
                  # no ssl.ca.location → system CAs → unknown issuer
                  "message.timeout.ms": 1500,
                  "dr_msg_cb": lambda e, m: drs.append(e)})
    p.produce("sec", value=b"nope", partition=0)
    assert p.flush(10.0) == 0
    assert len(drs) == 1 and drs[0] is not None
    p.close()


def test_ssl_verification_disabled_allows_unknown_ca(tls_cluster, certs):
    p = Producer({"bootstrap.servers": tls_cluster.bootstrap_servers(),
                  "security.protocol": "ssl",
                  "enable.ssl.certificate.verification": False})
    p.produce("sec", value=b"trusting", partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_endpoint_identification_https(tls_cluster, certs):
    """ssl.endpoint.identification.algorithm=https turns on hostname
    matching; the server cert's SAN covers 127.0.0.1 so it passes."""
    p = Producer(_ssl_conf(tls_cluster, certs, **{
        "ssl.endpoint.identification.algorithm": "https"}))
    p.produce("sec", value=b"hostname-checked", partition=0)
    assert p.flush(15.0) == 0
    p.close()


def test_mutual_tls_with_pkcs12_keystore(certs):
    """Server requires a client cert; client supplies it via the PKCS#12
    keystore path (rdkafka_cert.c PKCS12 load)."""
    cluster = MockCluster(num_brokers=1, topics={"mtls": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "ssl.keystore.location": certs["client_p12"],
            "ssl.keystore.password": "kstore"}))
        p.produce("mtls", value=b"mutual", partition=0)
        assert p.flush(15.0) == 0
        p.close()

        # and without a client cert the server rejects the handshake
        drs = []
        p2 = Producer(_ssl_conf(cluster, certs, **{
            "message.timeout.ms": 1500,
            "dr_msg_cb": lambda e, m: drs.append(e)}))
        p2.produce("mtls", value=b"rejected", partition=0)
        assert p2.flush(10.0) == 0
        assert len(drs) == 1 and drs[0] is not None
        p2.close()
    finally:
        cluster.stop()


def test_mutual_tls_with_pem_cert_key(certs):
    cluster = MockCluster(num_brokers=1, topics={"mtls2": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"],
                               "cafile": certs["ca"],
                               "require_client_cert": True})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "ssl.certificate.location": certs["client_cert"],
            "ssl.key.location": certs["client_key"]}))
        p.produce("mtls2", value=b"pem-pair", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_sasl_ssl_scram(certs):
    """sasl_ssl composes: TLS handshake first, then the full RFC 5802
    SCRAM-SHA-256 exchange (client proof + server signature verified on
    both sides) over the encrypted channel."""
    cluster = MockCluster(num_brokers=1, topics={"auth": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"]},
                          sasl_users={"alice": "wonderland"})
    try:
        p = Producer(_ssl_conf(cluster, certs, **{
            "security.protocol": "sasl_ssl",
            "sasl.mechanisms": "SCRAM-SHA-256",
            "sasl.username": "alice",
            "sasl.password": "wonderland"}))
        p.produce("auth", value=b"authenticated", partition=0)
        assert p.flush(15.0) == 0
        p.close()
    finally:
        cluster.stop()


def test_sasl_ssl_scram_bad_password(certs):
    cluster = MockCluster(num_brokers=1, topics={"auth": 1},
                          tls={"certfile": certs["server_cert"],
                               "keyfile": certs["server_key"]},
                          sasl_users={"alice": "wonderland"})
    try:
        drs = []
        p = Producer(_ssl_conf(cluster, certs, **{
            "security.protocol": "sasl_ssl",
            "sasl.mechanisms": "SCRAM-SHA-512",
            "sasl.username": "alice",
            "sasl.password": "wrong",
            "message.timeout.ms": 1500,
            "dr_msg_cb": lambda e, m: drs.append(e)}))
        p.produce("auth", value=b"denied", partition=0)
        assert p.flush(10.0) == 0
        assert len(drs) == 1 and drs[0] is not None
        p.close()
    finally:
        cluster.stop()


def test_gssapi_rejected_at_creation():
    """GSSAPI is not linked in this build: selecting it must fail fast
    at client creation (rdkafka_sasl.c provider selection), not at
    first connect."""
    with pytest.raises(KafkaException) as ei:
        Producer({"bootstrap.servers": "127.0.0.1:1",
                  "security.protocol": "sasl_plaintext"})
    assert ei.value.error.code == Err._UNSUPPORTED_FEATURE
