#!/usr/bin/env bash
# Concurrency + invariant gate (ANALYSIS.md): the AST project lint over
# the whole package, the lockdep-enabled stress pass (engine pipeline +
# txn commit/abort + chaos storms) asserting a clean lock-order graph,
# and the lockset races pass (the same legs under the Eraser-style
# detector, plus seeded schedule-explorer reruns of the engine-pipeline
# and txn legs).  Exits nonzero on ANY finding — invoked at the top of
# scripts/tier1.sh and scripts/chaos.sh; run it alone after touching
# anything concurrent.  Deeper sweeps: pytest --lockdep / --races run
# the whole suite under the instrumented locks / lockset detector.
cd "$(dirname "$0")/.."
set -o pipefail
# SLO trend gate (ISSUE 20): latest BENCH_TREND.jsonl row per leg vs
# that leg's anchor row — soft-warns with no ledger/anchor, hard-fails
# naming the regressed metric otherwise
timeout -k 10 60 python scripts/trendgate.py || exit $?
# 540s: the stress + races passes each grew a multi-process fleet leg
# (ISSUE 11) on top of the external SIGKILL storm
timeout -k 10 540 env JAX_PLATFORMS=cpu \
    python -m librdkafka_tpu.analysis all
