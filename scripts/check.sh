#!/usr/bin/env bash
# Concurrency + invariant gate (ANALYSIS.md): the AST project lint over
# the whole package, then the lockdep-enabled stress pass (engine
# pipeline + txn commit/abort + a fast chaos storm) asserting a clean
# lock-order graph.  Exits nonzero on ANY finding — invoked at the top
# of scripts/tier1.sh and scripts/chaos.sh; run it alone after touching
# anything concurrent.  Deeper sweep: pytest --lockdep runs the whole
# suite under instrumented locks.
cd "$(dirname "$0")/.."
set -o pipefail
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m librdkafka_tpu.analysis all
