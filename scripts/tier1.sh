#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim.  Run from the repo
# root (pytest.ini_options pins testpaths=tests).  Pair with the quick
# pre-commit gate: `python bench.py --smoke` (<60 s, one bit-exactness
# pass over every engine leg).
cd "$(dirname "$0")/.."
# concurrency + invariant gate first: SLO trend gate + lint + lockdep
# stress (check.sh exits nonzero on any finding — including a
# BENCH_TREND.jsonl regression past the anchor — failing the tier here)
scripts/check.sh || exit $?
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
