#!/usr/bin/env bash
# Fleet tier (FLEET.md): multi-process client populations against the
# supervised out-of-process cluster.  Default runs the fast tier —
# traffic/verb unit tests, the 4-worker smoke fleet with a real
# SIGKILL, determinism + ledger-merge checks.  --soak adds the slow
# legs, including the ≥24-worker flagship (diurnal+burst traffic,
# 3 SIGKILLs + asymmetric brownout + EIO window, per-group verify).
# Pair with scripts/chaos.sh; the quick pre-commit gate is
# `python bench.py --fleet --smoke` (2-worker mini fleet).
cd "$(dirname "$0")/.."
# concurrency + invariant gate first (lint + lockdep stress, which
# includes the fleet smoke leg)
scripts/check.sh || exit $?
set -o pipefail
MARK='fleet and not slow'
LIMIT=600
ARGS=()
for a in "$@"; do
    if [ "$a" = "--soak" ]; then
        MARK='fleet'          # everything, flagship included
        LIMIT=1200
    else
        ARGS+=("$a")
    fi
done
timeout -k 10 "$LIMIT" env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m "$MARK" -p no:cacheprovider -p no:xdist -p no:randomly "${ARGS[@]}"
