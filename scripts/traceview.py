#!/usr/bin/env python
"""traceview — offline summarizer for flight-recorder trace dumps.

Reads a Chrome trace-event JSON file (``Kafka.trace_dump(path)`` or a
flight-recorder auto-dump, obs/trace.py) and prints, without needing
Perfetto:

  * per-stage latency: count, p50, p90, p99, max for every span name
    (ph == "X" complete events), sorted by total time descending;
  * the top-10 widest individual spans (the "where did THIS ticket
    spend its 800 us" table), with their args.

Used by humans (``python scripts/traceview.py dump.json``) and by the
``bench.py --smoke`` trace leg, which loads :func:`summarize` to assert
a traced e2e run decomposes into the expected pipeline stages.

Cross-process traces (ISSUE 20): merged fleet dumps carry one
``process_name`` metadata record per OS process, and the summary's
``by_process`` table attributes span latency per process the way
``by_device`` attributes launches per chip.  ``--merge a.json b.json
[--out merged.json]`` concatenates several single-process dumps into
one timeline (labelling each file's events by basename when the dump
carries no process metadata) and summarizes the union — the offline
path when the fleet driver's live collection wasn't running.
"""
from __future__ import annotations

import json
import os
import sys


def load_events(path: str) -> list[dict]:
    """Chrome trace JSON → event list. Accepts both the object form
    ({"traceEvents": [...]}) and the bare JSON-array form."""
    with open(path) as f:
        data = json.load(f)
    events = data["traceEvents"] if isinstance(data, dict) else data
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome trace-event file")
    return events


def _pct(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[k]


def summarize(events: list[dict], top: int = 10) -> dict:
    """{"stages": [{name, cat, cnt, total_us, p50_us, p90_us, p99_us,
    max_us}...] (total-time desc), "widest": [top-N span dicts],
    "instants": {name: count}, "by_device": [{name, device, cnt,
    p50_us, max_us, total_us}...]}.  ``by_device`` splits every span
    stamped with a ``device`` arg (engine ``device_launch``/
    ``readback``; device -1 = whole-mesh sharded launch) so launch
    latency is attributable per chip (ISSUE 6)."""
    by_name: dict[tuple, list[float]] = {}
    by_dev: dict[tuple, list[float]] = {}
    by_proc: dict[tuple, list[float]] = {}
    proc_names: dict = {}
    spans: list[dict] = []
    instants: dict[str, int] = {}
    for e in events:
        ph = e.get("ph")
        if ph == "M" and e.get("name") == "process_name":
            proc_names[e.get("pid")] = (e.get("args") or {}).get(
                "name", str(e.get("pid")))
    for e in events:
        ph = e.get("ph")
        if ph == "X":
            dur = float(e.get("dur", 0.0))
            by_name.setdefault((e.get("cat", ""), e["name"]),
                               []).append(dur)
            args = e.get("args") or {}
            if "device" in args:
                by_dev.setdefault((e["name"], args["device"]),
                                  []).append(dur)
            # per-process attribution only once processes are labelled
            # (single-process dumps keep their summary unchanged)
            if proc_names:
                proc = proc_names.get(e.get("pid"), str(e.get("pid")))
                by_proc.setdefault((e["name"], proc), []).append(dur)
            spans.append(e)
        elif ph == "i":
            instants[e["name"]] = instants.get(e["name"], 0) + 1
    stages = []
    for (cat, name), durs in by_name.items():
        durs.sort()
        stages.append({
            "name": name, "cat": cat, "cnt": len(durs),
            "total_us": round(sum(durs), 1),
            "p50_us": round(_pct(durs, 50), 1),
            "p90_us": round(_pct(durs, 90), 1),
            "p99_us": round(_pct(durs, 99), 1),
            "max_us": round(durs[-1], 1),
        })
    stages.sort(key=lambda s: -s["total_us"])
    spans.sort(key=lambda e: -float(e.get("dur", 0.0)))
    widest = [{"name": e["name"], "cat": e.get("cat", ""),
               "dur_us": round(float(e.get("dur", 0.0)), 1),
               "ts_us": round(float(e.get("ts", 0.0)), 1),
               "tid": e.get("tid"), "args": e.get("args")}
              for e in spans[:top]]
    by_device = []
    for (name, dev), durs in sorted(by_dev.items(),
                                    key=lambda kv: (kv[0][0],
                                                    kv[0][1])):
        durs.sort()
        by_device.append({
            "name": name, "device": dev, "cnt": len(durs),
            "p50_us": round(_pct(durs, 50), 1),
            "max_us": round(durs[-1], 1),
            "total_us": round(sum(durs), 1),
        })
    by_process = []
    for (name, proc), durs in sorted(by_proc.items()):
        durs.sort()
        by_process.append({
            "name": name, "process": proc, "cnt": len(durs),
            "p50_us": round(_pct(durs, 50), 1),
            "max_us": round(durs[-1], 1),
            "total_us": round(sum(durs), 1),
        })
    return {"stages": stages, "widest": widest, "instants": instants,
            "by_device": by_device, "by_process": by_process}


def merge_files(paths: list[str]) -> list[dict]:
    """Concatenate several trace dumps into one event list.  Files that
    already carry ``process_name`` metadata keep their labels; a bare
    single-process dump gets one synthesized from its basename (pid
    collisions across bare files are disambiguated by index so the
    per-process attribution stays honest)."""
    merged: list[dict] = []
    for i, path in enumerate(paths):
        events = load_events(path)
        labelled = any(e.get("ph") == "M"
                       and e.get("name") == "process_name"
                       for e in events)
        if not labelled:
            pid = next((e.get("pid") for e in events
                        if e.get("pid") is not None), i)
            label = os.path.splitext(os.path.basename(path))[0]
            events = [dict(e, pid=f"{pid}.{i}") for e in events]
            merged.append({"ph": "M", "name": "process_name",
                           "pid": f"{pid}.{i}", "tid": 0, "ts": 0,
                           "args": {"name": label}})
        merged.extend(events)
    merged.sort(key=lambda e: (e.get("ph") != "M",
                               float(e.get("ts", 0.0))))
    return merged


def render(summary: dict) -> str:
    out = []
    out.append("per-stage latency (X spans, total-time desc)")
    out.append(f"{'stage':<22}{'cat':<10}{'cnt':>6}{'p50us':>10}"
               f"{'p90us':>10}{'p99us':>10}{'maxus':>10}{'totalus':>12}")
    for s in summary["stages"]:
        out.append(f"{s['name']:<22}{s['cat']:<10}{s['cnt']:>6}"
                   f"{s['p50_us']:>10}{s['p90_us']:>10}{s['p99_us']:>10}"
                   f"{s['max_us']:>10}{s['total_us']:>12}")
    out.append("")
    out.append("top widest spans")
    out.append(f"{'#':<3}{'stage':<22}{'durus':>10}  args")
    for i, w in enumerate(summary["widest"], 1):
        out.append(f"{i:<3}{w['name']:<22}{w['dur_us']:>10}  "
                   f"{w['args'] if w['args'] else ''}")
    if summary.get("by_device"):
        out.append("")
        out.append("per-device launch attribution (device -1 = "
                   "whole-mesh sharded)")
        out.append(f"{'stage':<22}{'device':>7}{'cnt':>6}{'p50us':>10}"
                   f"{'maxus':>10}{'totalus':>12}")
        for d in summary["by_device"]:
            out.append(f"{d['name']:<22}{d['device']:>7}{d['cnt']:>6}"
                       f"{d['p50_us']:>10}{d['max_us']:>10}"
                       f"{d['total_us']:>12}")
    if summary.get("by_process"):
        out.append("")
        out.append("per-process attribution (merged cross-process "
                   "trace)")
        out.append(f"{'stage':<22}{'process':<18}{'cnt':>6}{'p50us':>10}"
                   f"{'maxus':>10}{'totalus':>12}")
        for p in summary["by_process"]:
            out.append(f"{p['name']:<22}{p['process']:<18}{p['cnt']:>6}"
                       f"{p['p50_us']:>10}{p['max_us']:>10}"
                       f"{p['total_us']:>12}")
    if summary["instants"]:
        out.append("")
        out.append("instant events: " + ", ".join(
            f"{n}x{c}" for n, c in sorted(summary["instants"].items())))
    return "\n".join(out)


def main(argv: list[str]) -> int:
    usage = ("usage: traceview.py <trace.json>\n"
             "       traceview.py --merge <trace.json>... "
             "[--out merged.json]")
    args = argv[1:]
    if not args or args[0] in ("-h", "--help"):
        print(__doc__.strip(), file=sys.stderr)
        print("\n" + usage, file=sys.stderr)
        return 2
    if args[0] == "--merge":
        out_path = None
        files = args[1:]
        if "--out" in files:
            i = files.index("--out")
            if i + 1 >= len(files):
                print(usage, file=sys.stderr)
                return 2
            out_path = files[i + 1]
            files = files[:i] + files[i + 2:]
        if not files:
            print(usage, file=sys.stderr)
            return 2
        events = merge_files(files)
        if out_path:
            with open(out_path, "w") as f:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms"}, f)
            print(f"merged {len(files)} dumps -> {out_path} "
                  f"({len(events)} events)", file=sys.stderr)
        print(render(summarize(events)))
        return 0
    if len(args) != 1:
        print(usage, file=sys.stderr)
        return 2
    print(render(summarize(load_events(args[0]))))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
