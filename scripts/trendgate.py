#!/usr/bin/env python
"""trendgate — the persistent SLO trend gate over BENCH_TREND.jsonl.

Every ``bench.py --smoke/--fleet/--chaos/--partitions`` run appends one
ledger row (git rev, leg, direction-tagged headline metrics — see
OBSERVABILITY.md for the row format).  This gate compares each leg's
LATEST row against that leg's latest **anchor** row (``bench.py ...
--anchor`` marks one) and fails — naming the metric, both values and
the relative delta — when a metric regressed past its tolerance:

  * ``dir: lower``  (latencies, overheads): current > anchor * (1+tol)
  * ``dir: higher`` (rates, reductions):    current < anchor * (1-tol)

Tolerance is per-metric (``tol`` in the row) with a deliberately
generous default — the ledger spans different hosts and loaded CI
machines, so the gate only catches real cliffs, not noise.

Exit codes: 0 = pass (or soft-warn: no ledger / no anchor / unknown
schema rows only — a fresh clone must not fail tier-1); 1 = at least
one metric regressed.  Wired into scripts/check.sh.

usage: trendgate.py [--ledger PATH] [--tolerance X] [--quiet]
"""
from __future__ import annotations

import json
import os
import sys

#: ledger row schema this gate understands (bench.py TREND_SCHEMA)
SCHEMA = 1
#: default relative tolerance: 50% — cross-host CI noise on the
#: latency legs is routinely 2x smaller than this, a real regression
#: (an injected sleep, an O(n) slip) is routinely larger
DEFAULT_TOL = 0.5


def default_ledger() -> str:
    return os.environ.get("BENCH_TREND_PATH") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_TREND.jsonl")


def load_rows(path: str) -> list[dict]:
    """Parse the ledger, skipping malformed lines and rows from a
    schema this gate does not understand (forward-compat: a newer
    bench must not brick an older checkout's gate)."""
    rows = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return rows
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if (isinstance(row, dict) and row.get("schema") == SCHEMA
                and isinstance(row.get("metrics"), dict)
                and row.get("leg")):
            rows.append(row)
    return rows


def compare(anchor: dict, current: dict,
            default_tol: float = DEFAULT_TOL) -> list[dict]:
    """Regressions of ``current`` vs ``anchor`` (same leg): one dict
    per failed metric — name, direction, both values, relative delta,
    tolerance.  Metrics missing from either row are skipped (legs gain
    and lose headline metrics across PRs)."""
    out = []
    for name, am in anchor["metrics"].items():
        cm = current["metrics"].get(name)
        if cm is None:
            continue
        av, cv = float(am["v"]), float(cm["v"])
        direction = am.get("dir", "lower")
        tol = float(am.get("tol", cm.get("tol", default_tol)))
        if av == 0:
            continue
        # signed relative change in the BAD direction: positive means
        # "worse by this fraction"
        worse = ((cv - av) / abs(av) if direction == "lower"
                 else (av - cv) / abs(av))
        if worse > tol:
            out.append({"metric": name, "dir": direction,
                        "anchor": av, "current": cv,
                        "worse_pct": round(worse * 100.0, 1),
                        "tol_pct": round(tol * 100.0, 1)})
    return out


def gate(rows: list[dict], default_tol: float = DEFAULT_TOL) -> dict:
    """{"status": "pass"|"fail"|"no-anchor"|"empty", "legs": {leg:
    {"anchor_rev", "current_rev", "regressions": [...]}}}."""
    if not rows:
        return {"status": "empty", "legs": {}}
    by_leg: dict[str, list[dict]] = {}
    for row in rows:
        by_leg.setdefault(row["leg"], []).append(row)
    legs = {}
    any_anchor = False
    failed = False
    for leg, lrows in sorted(by_leg.items()):
        current = lrows[-1]
        anchors = [r for r in lrows if r.get("anchor")
                   and r is not current]
        if not anchors:
            legs[leg] = {"anchor_rev": None,
                         "current_rev": current.get("rev"),
                         "regressions": []}
            continue
        any_anchor = True
        anchor = anchors[-1]
        regs = compare(anchor, current, default_tol)
        failed = failed or bool(regs)
        legs[leg] = {"anchor_rev": anchor.get("rev"),
                     "current_rev": current.get("rev"),
                     "regressions": regs}
    if failed:
        status = "fail"
    elif any_anchor:
        status = "pass"
    else:
        status = "no-anchor"
    return {"status": status, "legs": legs}


def main(argv: list[str]) -> int:
    args = argv[1:]
    if "-h" in args or "--help" in args:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    ledger = default_ledger()
    tol = DEFAULT_TOL
    quiet = "--quiet" in args
    if "--ledger" in args:
        ledger = args[args.index("--ledger") + 1]
    if "--tolerance" in args:
        tol = float(args[args.index("--tolerance") + 1])

    if not os.path.exists(ledger):
        print(f"trendgate: no ledger at {ledger} — nothing to gate "
              "(run a bench.py SLO leg to start one)", file=sys.stderr)
        return 0
    verdict = gate(load_rows(ledger), tol)
    if verdict["status"] == "empty":
        print(f"trendgate: {ledger} has no schema-{SCHEMA} rows — "
              "soft pass", file=sys.stderr)
        return 0
    if verdict["status"] == "no-anchor":
        print("trendgate: no anchor row in any leg — soft pass "
              "(mark one with `bench.py <leg> --anchor`)",
              file=sys.stderr)
        return 0
    rc = 0
    for leg, res in verdict["legs"].items():
        if res["anchor_rev"] is None:
            if not quiet:
                print(f"trendgate: {leg}: no anchor — skipped")
            continue
        if not res["regressions"]:
            if not quiet:
                print(f"trendgate: {leg}: ok (anchor "
                      f"{res['anchor_rev']} -> {res['current_rev']})")
            continue
        rc = 1
        for r in res["regressions"]:
            arrow = ">" if r["dir"] == "lower" else "<"
            print(f"trendgate: FAIL {leg}.{r['metric']}: "
                  f"{r['current']:g} {arrow} anchor {r['anchor']:g} "
                  f"— worse by {r['worse_pct']}% "
                  f"(tolerance {r['tol_pct']}%) "
                  f"[anchor rev {res['anchor_rev']}, current rev "
                  f"{res['current_rev']}]")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv))
