#!/usr/bin/env bash
# Full chaos tier: every fault-schedule storm, including the slow ones
# tier-1 excludes (rolling EOS restarts, the out-of-process SIGKILL
# flagship, coordinator death, group churn, leader migration,
# slow-network rebalance).  The multi-minute soak storms stay out of
# the default run; add --soak to include them (longer timeout).
# Pair with scripts/tier1.sh; the quick pre-commit gate is
# `python bench.py --chaos` (<60 s, fast scenarios only — including
# the fast external SIGKILL storm).  See CHAOS.md for the
# replay-from-seed workflow.
cd "$(dirname "$0")/.."
# concurrency + invariant gate first (lint + lockdep stress, which
# includes the fast external-storm leg)
scripts/check.sh || exit $?
set -o pipefail
MARK='chaos and not soak'
LIMIT=600
ARGS=()
for a in "$@"; do
    if [ "$a" = "--soak" ]; then
        MARK='chaos'          # everything, soak storms included
        LIMIT=1800
    else
        ARGS+=("$a")
    fi
done
timeout -k 10 "$LIMIT" env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m "$MARK" -p no:cacheprovider -p no:xdist -p no:randomly "${ARGS[@]}"
