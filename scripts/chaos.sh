#!/usr/bin/env bash
# Full chaos tier: every fault-schedule storm, including the slow ones
# tier-1 excludes (rolling EOS restarts, coordinator death, leader
# migration, slow-network rebalance).  Pair with scripts/tier1.sh; the
# quick pre-commit gate is `python bench.py --chaos` (<30 s, fast
# scenarios only).  See CHAOS.md for the replay-from-seed workflow.
cd "$(dirname "$0")/.."
# concurrency + invariant gate first (lint + lockdep stress)
scripts/check.sh || exit $?
set -o pipefail
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m chaos -p no:cacheprovider -p no:xdist -p no:randomly "$@"
