"""Wheel build: compile the native codec + enqueue-lane libraries at
build time so installed environments never shell out to g++ on first
import (they still can, as a fallback, when a wheel is built without a
toolchain — ops/native/build.py keeps the mtime-cached lazy path)."""
import importlib.util
import os

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

HERE = os.path.dirname(os.path.abspath(__file__))


def _native_build():
    spec = importlib.util.spec_from_file_location(
        "_native_build",
        os.path.join(HERE, "librdkafka_tpu", "ops", "native", "build.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class BuildPyWithNative(build_py):
    """Compile the .so artifacts into the source tree before build_py
    copies package data (pyproject ships *.so as package-data)."""

    def run(self):
        try:
            nb = _native_build()
            nb.build()
            nb.build_enqlane()
        except Exception as e:      # no toolchain: fall back to lazy
            self.announce(f"native prebuild skipped: {e}", level=3)
        super().run()


class BinaryDistribution(Distribution):
    """The wheel carries compiled .so files — tag it platform-specific."""

    def has_ext_modules(self):
        return True


setup(cmdclass={"build_py": BuildPyWithNative},
      distclass=BinaryDistribution)
