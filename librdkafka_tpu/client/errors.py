"""Error codes and exceptions.

Mirrors the reference's two error spaces (src/rdkafka.h:222-589):
internal/client-local errors are negative (the reference reserves -200..-1),
broker/protocol errors are the non-negative Kafka protocol error codes.
Broker codes are public Apache Kafka protocol constants.
"""
from __future__ import annotations

import enum


class Err(enum.IntEnum):
    """Error codes. Negative = client-local, >= 0 = Kafka protocol codes."""

    # --- client-local (reference: RD_KAFKA_RESP_ERR__* in rdkafka.h:229-330) ---
    _BAD_MSG = -199
    _BAD_COMPRESSION = -198
    _DESTROY = -197
    _FAIL = -196
    _TRANSPORT = -195
    _CRIT_SYS_RESOURCE = -194
    _RESOLVE = -193
    _MSG_TIMED_OUT = -192
    _PARTITION_EOF = -191
    _UNKNOWN_PARTITION = -190
    _FS = -189
    _UNKNOWN_TOPIC = -188
    _ALL_BROKERS_DOWN = -187
    _INVALID_ARG = -186
    _TIMED_OUT = -185
    _QUEUE_FULL = -184
    _ISR_INSUFF = -183
    _NODE_UPDATE = -182
    _SSL = -181
    _WAIT_COORD = -180
    _UNKNOWN_GROUP = -179
    _IN_PROGRESS = -178
    _PREV_IN_PROGRESS = -177
    _EXISTING_SUBSCRIPTION = -176
    _ASSIGN_PARTITIONS = -175
    _REVOKE_PARTITIONS = -174
    _CONFLICT = -173
    _STATE = -172
    _UNKNOWN_PROTOCOL = -171
    _NOT_IMPLEMENTED = -170
    _AUTHENTICATION = -169
    _NO_OFFSET = -168
    _OUTDATED = -167
    _TIMED_OUT_QUEUE = -166
    _UNSUPPORTED_FEATURE = -165
    _WAIT_CACHE = -164
    _INTR = -163
    _KEY_SERIALIZATION = -162
    _VALUE_SERIALIZATION = -161
    _KEY_DESERIALIZATION = -160
    _VALUE_DESERIALIZATION = -159
    _PARTIAL = -158
    _READ_ONLY = -157
    _NOENT = -156
    _UNDERFLOW = -155
    _INVALID_TYPE = -154
    _RETRY = -153
    _PURGE_QUEUE = -152
    _PURGE_INFLIGHT = -151
    _FATAL = -150
    _INCONSISTENT = -149
    _GAPLESS_GUARANTEE = -148
    _MAX_POLL_EXCEEDED = -147
    _UNKNOWN_BROKER = -146

    # --- Kafka broker/protocol error codes (public protocol constants) ---
    NO_ERROR = 0
    UNKNOWN = -1001  # wire value -1; remapped to avoid clashing with local codes
    OFFSET_OUT_OF_RANGE = 1
    INVALID_MSG = 2  # CORRUPT_MESSAGE
    UNKNOWN_TOPIC_OR_PART = 3
    INVALID_MSG_SIZE = 4
    LEADER_NOT_AVAILABLE = 5
    NOT_LEADER_FOR_PARTITION = 6
    REQUEST_TIMED_OUT = 7
    BROKER_NOT_AVAILABLE = 8
    REPLICA_NOT_AVAILABLE = 9
    MSG_SIZE_TOO_LARGE = 10
    STALE_CTRL_EPOCH = 11
    OFFSET_METADATA_TOO_LARGE = 12
    NETWORK_EXCEPTION = 13
    COORDINATOR_LOAD_IN_PROGRESS = 14
    COORDINATOR_NOT_AVAILABLE = 15
    NOT_COORDINATOR = 16
    TOPIC_EXCEPTION = 17  # INVALID_TOPIC_EXCEPTION
    RECORD_LIST_TOO_LARGE = 18
    NOT_ENOUGH_REPLICAS = 19
    NOT_ENOUGH_REPLICAS_AFTER_APPEND = 20
    INVALID_REQUIRED_ACKS = 21
    ILLEGAL_GENERATION = 22
    INCONSISTENT_GROUP_PROTOCOL = 23
    INVALID_GROUP_ID = 24
    UNKNOWN_MEMBER_ID = 25
    INVALID_SESSION_TIMEOUT = 26
    REBALANCE_IN_PROGRESS = 27
    INVALID_COMMIT_OFFSET_SIZE = 28
    TOPIC_AUTHORIZATION_FAILED = 29
    GROUP_AUTHORIZATION_FAILED = 30
    CLUSTER_AUTHORIZATION_FAILED = 31
    INVALID_TIMESTAMP = 32
    UNSUPPORTED_SASL_MECHANISM = 33
    ILLEGAL_SASL_STATE = 34
    UNSUPPORTED_VERSION = 35
    TOPIC_ALREADY_EXISTS = 36
    INVALID_PARTITIONS = 37
    INVALID_REPLICATION_FACTOR = 38
    INVALID_REPLICA_ASSIGNMENT = 39
    INVALID_CONFIG = 40
    NOT_CONTROLLER = 41
    INVALID_REQUEST = 42
    UNSUPPORTED_FOR_MESSAGE_FORMAT = 43
    POLICY_VIOLATION = 44
    OUT_OF_ORDER_SEQUENCE_NUMBER = 45
    DUPLICATE_SEQUENCE_NUMBER = 46
    INVALID_PRODUCER_EPOCH = 47
    INVALID_TXN_STATE = 48
    INVALID_PRODUCER_ID_MAPPING = 49
    INVALID_TRANSACTION_TIMEOUT = 50
    CONCURRENT_TRANSACTIONS = 51
    TRANSACTION_COORDINATOR_FENCED = 52
    TRANSACTIONAL_ID_AUTHORIZATION_FAILED = 53
    SECURITY_DISABLED = 54
    OPERATION_NOT_ATTEMPTED = 55
    KAFKA_STORAGE_ERROR = 56
    LOG_DIR_NOT_FOUND = 57
    SASL_AUTHENTICATION_FAILED = 58
    UNKNOWN_PRODUCER_ID = 59
    REASSIGNMENT_IN_PROGRESS = 60
    DELEGATION_TOKEN_AUTH_DISABLED = 61
    DELEGATION_TOKEN_NOT_FOUND = 62
    DELEGATION_TOKEN_OWNER_MISMATCH = 63
    DELEGATION_TOKEN_REQUEST_NOT_ALLOWED = 64
    DELEGATION_TOKEN_AUTHORIZATION_FAILED = 65
    DELEGATION_TOKEN_EXPIRED = 66
    INVALID_PRINCIPAL_TYPE = 67
    NON_EMPTY_GROUP = 68
    GROUP_ID_NOT_FOUND = 69
    FETCH_SESSION_ID_NOT_FOUND = 70
    INVALID_FETCH_SESSION_EPOCH = 71
    LISTENER_NOT_FOUND = 72
    TOPIC_DELETION_DISABLED = 73
    FENCED_LEADER_EPOCH = 74
    UNKNOWN_LEADER_EPOCH = 75
    UNSUPPORTED_COMPRESSION_TYPE = 76
    STALE_BROKER_EPOCH = 77
    OFFSET_NOT_AVAILABLE = 78
    MEMBER_ID_REQUIRED = 79
    PREFERRED_LEADER_NOT_AVAILABLE = 80
    GROUP_MAX_SIZE_REACHED = 81
    FENCED_INSTANCE_ID = 82
    # KIP-360 era: the broker's explicit zombie-fencing code for a
    # producer whose (pid, epoch) was superseded by a newer instance of
    # the same transactional.id
    PRODUCER_FENCED = 90

    @property
    def is_local(self) -> bool:
        return self.value < 0 and self.value > -1000

    @property
    def wire(self) -> int:
        """The int16 value sent on the wire (UNKNOWN is -1 on the wire)."""
        return -1 if self is Err.UNKNOWN else int(self.value)

    @classmethod
    def from_wire(cls, code: int) -> "Err":
        if code == -1:
            return cls.UNKNOWN
        try:
            return cls(code)
        except ValueError:
            return cls.UNKNOWN

    def __str__(self) -> str:  # e.g. "Local: Broker transport failure"
        return self.name.lstrip("_").replace("_", " ").title()


#: Errors on which a Produce request may be retried without risking
#: reordering/duplication policy violations (reference:
#: rd_kafka_handle_Produce_error, rdkafka_request.c:2415).
RETRIABLE_ERRS = frozenset({
    Err._TRANSPORT, Err._TIMED_OUT, Err.REQUEST_TIMED_OUT,
    Err.NOT_LEADER_FOR_PARTITION, Err.LEADER_NOT_AVAILABLE,
    Err.UNKNOWN_TOPIC_OR_PART, Err.NOT_ENOUGH_REPLICAS,
    Err.NOT_ENOUGH_REPLICAS_AFTER_APPEND, Err.COORDINATOR_LOAD_IN_PROGRESS,
    Err.COORDINATOR_NOT_AVAILABLE, Err.NOT_COORDINATOR,
    Err.NETWORK_EXCEPTION, Err.FENCED_LEADER_EPOCH, Err.UNKNOWN_LEADER_EPOCH,
    Err.KAFKA_STORAGE_ERROR, Err.PREFERRED_LEADER_NOT_AVAILABLE,
})


class KafkaError:
    """Rich error object (reference: rd_kafka_error_t / rd_kafka_resp_err_t)."""

    __slots__ = ("code", "reason", "fatal", "retriable")

    def __init__(self, code: Err, reason: str = "", *, fatal: bool = False,
                 retriable: bool | None = None):
        self.code = code
        self.reason = reason or str(code)
        self.fatal = fatal
        self.retriable = (code in RETRIABLE_ERRS) if retriable is None else retriable

    def __repr__(self):
        return f"KafkaError({self.code.name}, {self.reason!r})"

    def __eq__(self, other):
        if isinstance(other, KafkaError):
            return self.code == other.code
        if isinstance(other, Err):
            return self.code == other
        return NotImplemented

    def __hash__(self):
        return hash(self.code)


class KafkaException(Exception):
    """Exception wrapper carrying a KafkaError."""

    def __init__(self, error: KafkaError | Err, reason: str = ""):
        if isinstance(error, Err):
            error = KafkaError(error, reason)
        self.error = error
        super().__init__(repr(error))
