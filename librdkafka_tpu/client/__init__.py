"""librdkafka_tpu.client"""
