"""Configuration system.

A single declarative property table, the same design as the reference's
``rd_kafka_properties`` table (src/rdkafka_conf.c:224): each property has a
scope (global/topic), type, range/enum, default, producer/consumer
applicability, and optional aliases. Docs are generated from the table
(``python -m librdkafka_tpu.client.conf`` emits CONFIGURATION.md).

New TPU-specific knobs live in the same table (SURVEY.md §5 "config"):
``compression.backend`` selects the codec provider (cpu|tpu), defaulting to
cpu, so the TPU path is strictly opt-in — the analog of gating through the
reference's plugin boundary (src/rdkafka_plugin.c).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .errors import Err, KafkaException

# Scopes
GLOBAL, TOPIC = "global", "topic"
# Applicability
P, C, PC = "P", "C", "PC"   # producer / consumer / both


@dataclass
class Prop:
    name: str
    scope: str                 # GLOBAL or TOPIC
    ptype: str                 # "str" | "int" | "bool" | "enum" | "float" | "ptr" | "list"
    default: Any
    doc: str
    app: str = PC              # P, C or PC
    vmin: Optional[float] = None
    vmax: Optional[float] = None
    enum: Optional[tuple] = None
    alias: Optional[str] = None          # alias target property name
    # validator(coerced_value) -> error string, or None when valid;
    # runs at set() time so a bad value fails HERE with a clear error,
    # never at first use (ISSUE 3 satellite)
    validator: Optional[Callable[[Any], Optional[str]]] = None
    deprecated: bool = False             # accepted no-op (reference
                                         # _RK_DEPRECATED rows)
    hidden: bool = False                 # excluded from generated docs
                                         # (reference _RK_HIDDEN rows)
    fallthrough: bool = False            # global row that writes the
                                         # same-name topic-scope knob
                                         # via the default topic conf


def _p(*args, **kw) -> Prop:
    return Prop(*args, **kw)


def _valid_cache_dir(v: Any) -> Optional[str]:
    """tpu.compile.cache.dir: empty (disabled) or a usable directory —
    one that exists, or whose parent exists so jax can create it."""
    import os
    s = str(v)
    if not s:
        return None
    if os.path.isdir(s):
        return None
    if os.path.exists(s):
        return f"{s!r} exists and is not a directory"
    parent = os.path.dirname(os.path.abspath(s)) or "/"
    if not os.path.isdir(parent):
        return (f"parent directory {parent!r} does not exist "
                "(the cache dir must be creatable)")
    return None


def _valid_ring_events(v) -> Optional[str]:
    """trace.ring.events: a power of two (the ring index wraps with a
    mask) within 64..4194304 — validated HERE so a bad capacity fails
    at set() time, not at the first recorded event."""
    try:
        n = int(str(v).strip())
    except ValueError:
        return f"expected an integer, got {v!r}"
    if n < 64 or n > (1 << 22):
        return f"{n} outside allowed range 64..{1 << 22}"
    if n & (n - 1):
        return f"{n} is not a power of two"
    return None


def _valid_transactional_id(v) -> Optional[str]:
    """transactional.id: empty (non-transactional) or a usable id —
    printable, and within the broker's 249-char resource-name bound, so
    a bad id fails at set() time instead of at init_transactions()."""
    s = str(v)
    if not s:
        return None
    if len(s) > 249:
        return f"id is {len(s)} chars; the broker bound is 249"
    if any(ord(c) < 0x20 or ord(c) == 0x7F for c in s):
        return "id contains control characters"
    return None


#: The declarative property table. Mirrors rdkafka_conf.c:224's table shape.
PROPERTIES: list[Prop] = [
    # ---- global: general ----
    _p("builtin.features", GLOBAL, "str",
       "gzip,snappy,lz4,zstd,ssl,sasl,regex,mocks,tpu-codec",
       "Indicates builtin features for this build."),
    _p("client.id", GLOBAL, "str", "rdkafka", "Client identifier."),
    _p("client.rack", GLOBAL, "str", "",
       "Rack identifier sent in Fetch v11+ (KIP-392): brokers may "
       "redirect this consumer to a same-rack follower replica."),
    _p("bootstrap.servers", GLOBAL, "str", "", "Initial list of brokers host:port,..."),
    _p("metadata.broker.list", GLOBAL, "str", "", "Alias for bootstrap.servers.",
       alias="bootstrap.servers"),
    _p("message.max.bytes", GLOBAL, "int", 1000000, "Maximum Kafka protocol request message size.",
       vmin=1000, vmax=1000000000),
    _p("message.copy.max.bytes", GLOBAL, "int", 65535,
       "Maximum size for message to be copied to buffer (larger are referenced).",
       vmin=0, vmax=1000000000),
    _p("receive.message.max.bytes", GLOBAL, "int", 100000000,
       "Maximum Kafka protocol response message size.", vmin=1000, vmax=2147483647),
    _p("max.in.flight.requests.per.connection", GLOBAL, "int", 1000000,
       "Maximum number of in-flight requests per broker connection.", vmin=1, vmax=1000000),
    _p("max.in.flight", GLOBAL, "int", 1000000, "Alias.",
       alias="max.in.flight.requests.per.connection"),
    _p("metadata.request.timeout.ms", GLOBAL, "int", 60000, "Non-topic request timeout.",
       vmin=10, vmax=900000),
    _p("topic.metadata.refresh.interval.ms", GLOBAL, "int", 300000,
       "Period of topic/broker metadata refresh; -1 disables.", vmin=-1, vmax=3600000),
    _p("metadata.max.age.ms", GLOBAL, "int", 900000,
       "Metadata cache max age.", vmin=1, vmax=86400000),
    _p("topic.metadata.refresh.fast.interval.ms", GLOBAL, "int", 250,
       "Refresh interval while leaders are unknown.", vmin=1, vmax=60000),
    _p("topic.metadata.refresh.sparse", GLOBAL, "bool", True,
       "Sparse metadata requests (only subscribed topics)."),
    _p("topic.metadata.interest.only", GLOBAL, "bool", True,
       "Interest-set metadata (ISSUE 14, beyond the reference): "
       "refreshes request only subscribed/produced topics with "
       "per-topic staleness — an empty interest set sends a "
       "brokers-only probe instead of a full sweep; full enumerations "
       "happen only for regex subscriptions, the periodic refresh and "
       "explicit all-topics requests. false restores the reference's "
       "empty-set full-sweep shape."),
    _p("topic.blacklist", GLOBAL, "list", "", "Topic blacklist regex list."),
    _p("debug", GLOBAL, "list", "",
       "Comma-separated debug contexts: generic,broker,topic,metadata,feature,queue,msg,"
       "protocol,cgrp,security,fetch,interceptor,plugin,consumer,admin,eos,mock,all"),
    _p("socket.timeout.ms", GLOBAL, "int", 60000, "Network request timeout.", vmin=10, vmax=300000),
    _p("socket.send.buffer.bytes", GLOBAL, "int", 0, "SO_SNDBUF; 0=system default.",
       vmin=0, vmax=100000000),
    _p("socket.receive.buffer.bytes", GLOBAL, "int", 0, "SO_RCVBUF; 0=system default.",
       vmin=0, vmax=100000000),
    _p("socket.keepalive.enable", GLOBAL, "bool", False, "Enable TCP keep-alive."),
    _p("socket.nagle.disable", GLOBAL, "bool", False, "Disable Nagle (TCP_NODELAY)."),
    _p("socket.max.fails", GLOBAL, "int", 1,
       "Disconnect broker after this many send failures.", vmin=0, vmax=1000000),
    _p("broker.address.ttl", GLOBAL, "int", 1000, "DNS resolve cache ttl ms.", vmin=0, vmax=86400000),
    _p("broker.address.family", GLOBAL, "enum", "any", "Address family.",
       enum=("any", "v4", "v6")),
    _p("reconnect.backoff.jitter.ms", GLOBAL, "int", 0,
       "No longer used: a fixed -25%..+50% jitter is applied to every "
       "reconnect backoff (see reconnect.backoff.ms / "
       "reconnect.backoff.max.ms). Accepted for conf compatibility "
       "(reference deprecates it the same way, rdkafka_conf.c:437).",
       vmin=0, vmax=3600000, deprecated=True),
    _p("reconnect.backoff.ms", GLOBAL, "int", 100,
       "Initial reconnect backoff; doubled per failure up to "
       "reconnect.backoff.max.ms, with -25%..+50% jitter per attempt.",
       vmin=0, vmax=3600000),
    _p("reconnect.backoff.max.ms", GLOBAL, "int", 10000, "Max reconnect backoff.",
       vmin=0, vmax=3600000),
    _p("statistics.interval.ms", GLOBAL, "int", 0,
       "Statistics emit interval; 0 disables.", vmin=0, vmax=86400000),
    _p("log_level", GLOBAL, "int", 6, "Max syslog level.", vmin=0, vmax=7),
    _p("log.queue", GLOBAL, "bool", False, "Forward logs to queue instead of stderr."),
    _p("log.thread.name", GLOBAL, "bool", True, "Print thread name in logs."),
    _p("log.connection.close", GLOBAL, "bool", True, "Log broker disconnects."),
    _p("internal.termination.signal", GLOBAL, "int", 0, "Unused (signal shim).", vmin=0, vmax=128),
    _p("api.version.request", GLOBAL, "bool", True,
       "Request broker supported api versions (ApiVersionRequest)."),
    _p("api.version.request.timeout.ms", GLOBAL, "int", 10000, "", vmin=1, vmax=300000),
    _p("api.version.fallback.ms", GLOBAL, "int", 0,
       "How long to use broker.version.fallback after ApiVersion failure.",
       vmin=0, vmax=604800000),
    _p("broker.version.fallback", GLOBAL, "str", "0.10.0",
       "Assumed broker version when ApiVersionRequest unsupported."),
    # ---- global: security ----
    _p("security.protocol", GLOBAL, "enum", "plaintext", "Protocol to talk to brokers.",
       enum=("plaintext", "ssl", "sasl_plaintext", "sasl_ssl")),
    _p("ssl.cipher.suites", GLOBAL, "str", "", "Cipher suites."),
    _p("ssl.curves.list", GLOBAL, "str", "",
       "Colon-separated supported curves/groups in preference order "
       "(OpenSSL SSL_CTX_set1_groups_list; reference rdkafka_conf.c "
       "ssl.curves.list)."),
    _p("ssl.sigalgs.list", GLOBAL, "str", "",
       "Colon-separated signature algorithms in preference order "
       "(OpenSSL SSL_CTX_set1_sigalgs_list)."),
    _p("ssl.key.location", GLOBAL, "str", "", "Client private key path (PEM)."),
    _p("ssl.key.password", GLOBAL, "str", "", "Key passphrase."),
    _p("ssl.key.pem", GLOBAL, "str", "",
       "Client private key as a PEM string (in-memory alternative to "
       "ssl.key.location; reference ssl.key.pem)."),
    _p("ssl_key", GLOBAL, "ptr", None,
       "Client private key as in-memory PEM/DER bytes (the "
       "rd_kafka_conf_set_ssl_cert analog)."),
    _p("ssl.certificate.location", GLOBAL, "str", "", "Client cert path (PEM)."),
    _p("ssl.certificate.pem", GLOBAL, "str", "",
       "Client certificate as a PEM string (in-memory alternative to "
       "ssl.certificate.location)."),
    _p("ssl_certificate", GLOBAL, "ptr", None,
       "Client certificate as in-memory PEM/DER bytes."),
    _p("ssl.ca.location", GLOBAL, "str", "", "CA bundle path."),
    _p("ssl_ca", GLOBAL, "ptr", None,
       "CA certificate(s) as in-memory PEM/DER bytes."),
    _p("ssl.crl.location", GLOBAL, "str", "",
       "CRL file for broker certificate revocation checking."),
    _p("ssl.keystore.location", GLOBAL, "str", "", "PKCS#12 keystore path."),
    _p("ssl.keystore.password", GLOBAL, "str", "", "Keystore password."),
    _p("enable.ssl.certificate.verification", GLOBAL, "bool", True, "Verify broker cert."),
    _p("ssl.endpoint.identification.algorithm", GLOBAL, "enum", "none",
       "Endpoint identification.", enum=("none", "https")),
    _p("ssl.certificate.verify_cb", GLOBAL, "ptr", None,
       "Certificate verification callback: cb(broker_name, broker_id, "
       "depth, der_bytes, openssl_ok) -> bool; returning False rejects "
       "the connection (reference ssl.certificate.verify_cb)."),
    _p("open_cb", GLOBAL, "ptr", None,
       "File-open hook: cb(path, os_flags) -> OS fd or file object; "
       "used by the file offset store (reference open_cb opens files "
       "with CLOEXEC)."),
    _p("closesocket_cb", GLOBAL, "ptr", None,
       "Socket-close hook: cb(socket) called before every broker "
       "socket close (pairs with connect_cb; reference closesocket_cb)."),
    _p("sasl.mechanisms", GLOBAL, "str", "GSSAPI",
       "SASL mechanism: GSSAPI, PLAIN, SCRAM-SHA-256, SCRAM-SHA-512, OAUTHBEARER."),
    _p("sasl.mechanism", GLOBAL, "str", "GSSAPI", "Alias.", alias="sasl.mechanisms"),
    _p("sasl.username", GLOBAL, "str", "", "SASL username (PLAIN/SCRAM)."),
    _p("sasl.password", GLOBAL, "str", "", "SASL password (PLAIN/SCRAM)."),
    _p("sasl.oauthbearer.config", GLOBAL, "str", "", "OAUTHBEARER unsecured token config."),
    _p("enable.sasl.oauthbearer.unsecure.jwt", GLOBAL, "bool", False,
       "Enable builtin unsecured JWT handler."),
    _p("sasl.kerberos.service.name", GLOBAL, "str", "kafka", "Kerberos service name."),
    _p("sasl.kerberos.principal", GLOBAL, "str", "kafkaclient", "Client principal."),
    _p("sasl.kerberos.kinit.cmd", GLOBAL, "str",
       'kinit -R -t "%{sasl.kerberos.keytab}" -k %{sasl.kerberos.principal}'
       ' || kinit -t "%{sasl.kerberos.keytab}" -k'
       ' %{sasl.kerberos.principal}',
       "Shell command refreshing/acquiring the client's Kerberos ticket; "
       "run at client creation and every "
       "sasl.kerberos.min.time.before.relogin ms. %{prop} expands to "
       "config values."),
    _p("sasl.kerberos.keytab", GLOBAL, "str", "",
       "Kerberos keytab path (used via %{sasl.kerberos.keytab} in "
       "sasl.kerberos.kinit.cmd)."),
    _p("sasl.kerberos.min.time.before.relogin", GLOBAL, "int", 60000,
       "Minimum ms between Kerberos ticket refreshes; 0 disables.",
       vmin=0, vmax=86400000),
    # ---- global: plugins/interceptors ----
    _p("plugin.library.paths", GLOBAL, "str", "",
       "List of plugin libraries/modules to load (module:... python entry points)."),
    _p("interceptors", GLOBAL, "ptr", None, "Interceptors added through the API."),
    # ---- global: consumer group ----
    _p("group.id", GLOBAL, "str", "", "Consumer group id.", app=C),
    _p("group.instance.id", GLOBAL, "str", "",
       "Static membership instance id.", app=C),
    _p("partition.assignment.strategy", GLOBAL, "str", "range,roundrobin",
       "Assignor names in preference order: range, roundrobin (EAGER "
       "protocol) and cooperative-sticky (KIP-429 COOPERATIVE "
       "incremental rebalancing). The broker picks the first strategy "
       "every group member supports, so a group mixing cooperative and "
       "eager-only members downgrades to the common eager assignor; "
       "list an eager fallback after cooperative-sticky for rolling "
       "upgrades.", app=C),
    _p("session.timeout.ms", GLOBAL, "int", 10000, "Group session timeout.", app=C,
       vmin=1, vmax=3600000),
    _p("heartbeat.interval.ms", GLOBAL, "int", 3000, "Group heartbeat interval.", app=C,
       vmin=1, vmax=3600000),
    _p("group.protocol.type", GLOBAL, "str", "consumer", "Group protocol type.", app=C),
    _p("coordinator.query.interval.ms", GLOBAL, "int", 600000,
       "Coordinator re-query interval.", app=C, vmin=1, vmax=3600000),
    _p("max.poll.interval.ms", GLOBAL, "int", 300000,
       "Max time between polls before leaving the group.", app=C, vmin=1, vmax=86400000),
    _p("enable.auto.commit", GLOBAL, "bool", True, "Auto offset commit.", app=C),
    _p("auto.commit.interval.ms", GLOBAL, "int", 5000,
       "Auto commit interval.", app=C, vmin=0, vmax=86400000),
    _p("enable.auto.offset.store", GLOBAL, "bool", True,
       "Auto-store offset of last consumed message.", app=C),
    _p("queued.min.messages", GLOBAL, "int", 100000,
       "Min messages to keep in local fetch queue.", app=C, vmin=1, vmax=10000000),
    _p("queued.max.messages.kbytes", GLOBAL, "int", 1048576,
       "Max kbytes in local fetch queue.", app=C, vmin=1, vmax=2097151),
    _p("fetch.wait.max.ms", GLOBAL, "int", 100, "Fetch max wait.", app=C, vmin=0, vmax=300000),
    _p("fetch.message.max.bytes", GLOBAL, "int", 1048576,
       "Initial max bytes per topic+partition to fetch.", app=C, vmin=1, vmax=1000000000),
    _p("max.partition.fetch.bytes", GLOBAL, "int", 1048576, "Alias.", app=C,
       alias="fetch.message.max.bytes"),
    _p("fetch.max.bytes", GLOBAL, "int", 52428800, "Max bytes per fetch request.", app=C,
       vmin=0, vmax=2147483135),
    _p("fetch.num.inflight", GLOBAL, "int", 4,
       "Max outstanding FetchRequests per broker, over disjoint "
       "partition sets (the reference keeps the fetch pipe full instead "
       "of serializing one Fetch per round trip, rdkafka_broker.c:4279).",
       app=C, vmin=1, vmax=64),
    _p("fetch.min.bytes", GLOBAL, "int", 1, "Min bytes broker should accumulate.", app=C,
       vmin=1, vmax=100000000),
    _p("fetch.error.backoff.ms", GLOBAL, "int", 500, "Backoff on fetch error.", app=C,
       vmin=0, vmax=300000),
    _p("fetch.session.enable", GLOBAL, "bool", True,
       "KIP-227 incremental fetch sessions (ISSUE 14, beyond the "
       "reference): negotiate a per-broker session on Fetch v7+ and "
       "send only changed partitions per request (removals ride "
       "forgotten_topics); steady state is an O(1)-byte request for "
       "any partition count. Session errors fall back to a full fetch "
       "and renegotiate. false restores sessionless full fetches.",
       app=C),
    _p("isolation.level", GLOBAL, "enum", "read_committed",
       "Transactional read isolation.", app=C, enum=("read_uncommitted", "read_committed")),
    _p("enable.partition.eof", GLOBAL, "bool", False,
       "Emit PARTITION_EOF event at end of partition.", app=C),
    _p("check.crcs", GLOBAL, "bool", False, "Verify CRC32C of consumed messages.", app=C),
    _p("allow.auto.create.topics", GLOBAL, "bool", False,
       "Allow broker auto topic creation on metadata.", app=C),
    # ---- global: producer ----
    _p("enable.idempotence", GLOBAL, "bool", False,
       "Exactly-once-ish producer: no dupes, no reordering (EOS v1).", app=P),
    _p("transactional.id", GLOBAL, "str", "",
       "Enables the transactional producer: a stable id identifying the "
       "same producer instance across restarts, used by the transaction "
       "coordinator to fence zombie instances (a newer init_transactions "
       "with the same id bumps the epoch; the older instance fails "
       "fatally with PRODUCER_FENCED). Setting it implies "
       "enable.idempotence; produce() is only allowed inside "
       "begin_transaction()..commit/abort_transaction(). Validated at "
       "set() time.", app=P, validator=_valid_transactional_id),
    _p("transaction.timeout.ms", GLOBAL, "int", 60000,
       "Maximum time the transaction coordinator waits for a transaction "
       "status update from this producer before proactively aborting the "
       "ongoing transaction. Sent in InitProducerId; also bounds the "
       "default timeout of the blocking transaction APIs.",
       app=P, vmin=1000, vmax=2147483647),
    _p("enable.gapless.guarantee", GLOBAL, "bool", False,
       "Fatal error if a message could create a sequence gap.", app=P),
    _p("queue.buffering.max.messages", GLOBAL, "int", 100000,
       "Max messages on producer queues.", app=P, vmin=1, vmax=10000000),
    _p("queue.buffering.max.kbytes", GLOBAL, "int", 1048576,
       "Max kbytes on producer queues.", app=P, vmin=1, vmax=2147483647),
    _p("queue.buffering.max.ms", GLOBAL, "float", 0.5,
       "Linger: delay before building MessageSets.", app=P, vmin=0, vmax=900000),
    _p("linger.ms", GLOBAL, "float", 0.5, "Alias.", app=P, alias="queue.buffering.max.ms"),
    _p("message.send.max.retries", GLOBAL, "int", 2, "Send retries.", app=P, vmin=0, vmax=10000000),
    _p("retries", GLOBAL, "int", 2, "Alias.", app=P, alias="message.send.max.retries"),
    _p("retry.backoff.ms", GLOBAL, "int", 100, "Retry backoff.", app=P, vmin=1, vmax=300000),
    _p("queue.buffering.backpressure.threshold", GLOBAL, "int", 1,
       "Backpressure threshold on outstanding requests.", app=P, vmin=1, vmax=1000000),
    _p("compression.codec", GLOBAL, "enum", "none",
       "Message compression codec.", app=P,
       enum=("none", "gzip", "snappy", "lz4", "zstd")),
    _p("compression.type", GLOBAL, "enum", "none", "Alias.", app=P,
       enum=("none", "gzip", "snappy", "lz4", "zstd"), alias="compression.codec"),
    _p("batch.num.messages", GLOBAL, "int", 10000,
       "Max messages per MessageSet.", app=P, vmin=1, vmax=1000000),
    _p("delivery.report.only.error", GLOBAL, "bool", False,
       "Only failed DRs.", app=P),
    _p("dr_cb", GLOBAL, "ptr", None, "Delivery report callback.", app=P),
    _p("dr_msg_cb", GLOBAL, "ptr", None, "Per-message delivery report callback.", app=P),
    _p("dr_batch_cb", GLOBAL, "ptr", None,
       "Batched delivery-report callback: called ONCE per delivered "
       "batch with the list of Messages (each carries .error). The "
       "rd_kafka_event_DR message-array idea (rdkafka_event.c:33) as a "
       "direct callback — per-message Python dispatch halves the "
       "produce rate at high throughput.", app=P),
    _p("consume_cb", GLOBAL, "ptr", None,
       "Message consume callback for callback-based consumption "
       "(Consumer.consume_callback; reference rd_kafka_consume_callback).",
       app=C),
    _p("consume.callback.max.messages", GLOBAL, "int", 0,
       "Maximum number of messages dispatched per consume_callback "
       "call (0 = unlimited).", vmin=0, vmax=1000000, app=C),
    # ---- TPU codec sidecar knobs (new; SURVEY.md §5 config section) ----
    _p("compression.backend", GLOBAL, "enum", "cpu",
       "Codec provider for MessageSet compression + CRC32C: 'cpu' uses the "
       "native C++ path, 'tpu' offloads batched compress/CRC to the JAX/Pallas "
       "sidecar (bit-identical wire bytes).", app=PC, enum=("cpu", "tpu")),
    _p("tpu.launch.min.batches", GLOBAL, "int", 4,
       "Min partition batches to coalesce into one TPU launch (launch quorum); "
       "fewer than this falls back to the CPU provider.", vmin=1, vmax=4096),
    _p("codec.pipeline.depth", GLOBAL, "int", 2,
       "Max codec launches in flight per broker; 0 = compress inline on "
       "the broker thread (pipeline overlap of batch build vs codec).",
       vmin=0, vmax=64, app=P),
    _p("tpu.mesh.devices", GLOBAL, "int", 0,
       "Number of devices the async offload engine spreads its "
       "per-device CRC dispatch lanes over (0 = all local devices, "
       "1 = single-lane): each mesh device gets its own staging rings "
       "and in-flight launch tracking, whole launch groups route to "
       "the least-loaded lane, and groups spanning a mesh multiple "
       "split across every chip via shard_map "
       "(parallel/mesh.py sharded_crc_step) — wire bytes bit-identical "
       "on every route. Also shards the DEVICE lz4 encoder's block "
       "compression when tpu.lz4.force=true. No effect with "
       "compression.backend=cpu.",
       vmin=0, vmax=8192),
    _p("tpu.transport.min.mb.s", GLOBAL, "int", 100,
       "Adaptive offload gate: minimum measured host->device bandwidth "
       "(MB/s) for CRC32C launches to leave the host. Below it (e.g. a "
       "slow dev tunnel) every launch costs more in transfer than the "
       "whole CPU checksum, so the provider self-routes to CPU. "
       "0 disables the gate.", vmin=0, vmax=1_000_000),
    _p("tpu.pipeline.depth", GLOBAL, "int", 2,
       "Async offload engine (ops/engine.py): max device launches kept "
       "in flight by the dedicated dispatch thread (double buffering — "
       "the codec worker frames batch k while batch k+1 executes on the "
       "device). 0 disables the engine: every provider call dispatches "
       "synchronously. No effect with compression.backend=cpu.",
       vmin=0, vmax=8),
    _p("tpu.pipeline.fanin.us", GLOBAL, "int", 500,
       "Async offload engine: bounded fan-in window (microseconds) a "
       "below-quorum async CRC submission waits for other brokers' "
       "batches to merge into one launch (cross-broker micro-batch "
       "aggregation), so tpu.launch.min.batches is met at high toppar "
       "counts instead of falling back to the CPU provider. 0 "
       "dispatches immediately. With tpu.governor=true this is the CAP "
       "of the adaptive window (sized from the observed submission "
       "inter-arrival EWMA — low-rate traffic skips the wait "
       "entirely). No effect with compression.backend=cpu.",
       vmin=0, vmax=100_000),
    _p("tpu.governor", GLOBAL, "bool", True,
       "Adaptive offload governor (ops/engine.py): online cost-model "
       "CPU/TPU routing of at-quorum CRC launch groups (EWMA of "
       "per-bucket device launch time vs observed CPU-provider "
       "ns/byte, with periodic exploration launches so the model "
       "tracks host drift), adaptive fan-in window sizing, and fused "
       "multi-polynomial launches (crc32c + legacy crc32 in one padded "
       "launch with per-row Q selection). false restores the static "
       "policy: always-device above tpu.launch.min.batches, fixed "
       "fan-in window, per-polynomial launches. tpu.launch.min.batches "
       "remains a hard floor either way; wire bytes are bit-identical "
       "on every route. No effect with compression.backend=cpu."),
    _p("tpu.warmup", GLOBAL, "bool", True,
       "Background kernel warmup: a low-priority engine thread "
       "pre-compiles every (batch-bucket, 64KB) CRC kernel shape for "
       "both polynomials plus the fused variant at engine start; until "
       "a bucket's kernel is ready its launches are served by the CPU "
       "provider (bit-identical), so an XLA compile never stalls a "
       "hot-path launch — and the legacy-crc32 device path opens "
       "end-to-end. false: the dispatch thread compiles inline on "
       "first use (pre-governor behavior). No effect with "
       "compression.backend=cpu."),
    _p("tpu.compile.cache.dir", GLOBAL, "str", "",
       "Persistent JAX compilation-cache directory for the offload "
       "kernels: warmed kernels compile once per machine instead of "
       "once per process (jax_compilation_cache_dir). Empty disables. "
       "The path must be an existing directory or creatable (existing "
       "parent) — validated at set() time.",
       validator=_valid_cache_dir),
    _p("tpu.fetch.pipeline.depth", GLOBAL, "int", 4,
       "Consumer fetch codec pipeline: max fetch partitions per broker "
       "whose CRC-verify/decompress offload tickets may be in flight "
       "before the serve loop blocks on the oldest (the consumer-side "
       "mirror of tpu.pipeline.depth — that knob still sizes the device "
       "engine's launch depth; this one bounds how many partitions may "
       "be decompressed ahead of the queued.max.messages.kbytes "
       "accounting). With compression.backend=cpu tickets resolve "
       "eagerly, so the depth has no effect there.", vmin=1, vmax=64,
       app=C),
    _p("tpu.lz4.force", GLOBAL, "bool", False,
       "Route lz4 block compression to the device encoder even though it "
       "is slower than the native CPU path (PERF.md: LZ4's match search "
       "is gather/sort-bound, ~3 orders of magnitude off CPU on TPU "
       "vector units). Default off: backend=tpu runs lz4 on CPU and only "
       "CRC32C on the MXU, so the TPU backend is never slower than cpu.",
       app=P),
    _p("tpu.compress.device", GLOBAL, "bool", False,
       "Producer lz4 device-compression route: batch 64KB blocks into "
       "the engine's staging rings and run the fused compress+CRC32C "
       "kernel — one launch and one readback per bucket yields the "
       "LZ4F frames AND their MessageSet v2 batch CRCs (the host folds "
       "the final CRC with crc32c_combine, never re-scanning the frame "
       "bytes). Wire bytes are bit-identical to the CPU encoder on "
       "every route: the device kernel implements the deterministic "
       "TPU-greedy spec, the governor's cost model may still send any "
       "bucket to the matching deterministic CPU encoder, and warmup "
       "misses are served there too. Off (default): lz4 compresses on "
       "the native CPU fast path as an engine host job (PERF.md §3 — "
       "on a 1-core tunnel-limited host the CPU path usually wins; "
       "this knob exists for real accelerators and the bit-exactness "
       "gates). Non-lz4 codecs and consumer decompress always stay "
       "host-side. No effect with compression.backend=cpu.", app=P),
    # ---- flight-recorder tracing (obs/trace.py; TRACING.md) ----
    _p("trace.enable", GLOBAL, "bool", False,
       "Flight-recorder event tracing (obs/trace.py): per-thread ring "
       "buffers record spans across the whole offload pipeline — "
       "produce() enqueue, batch assembly, compress/CRC tickets, the "
       "engine's fan-in/launch/readback, ProduceRequest tx and ack, and "
       "the consumer fetch mirror (CRC verify, decompress, deliver) — "
       "with governor route decisions attached as span args. Export "
       "with Kafka.trace_dump(path) as Chrome trace-event JSON "
       "(Perfetto / chrome://tracing / scripts/traceview.py). Disabled, "
       "every hook costs one attribute check (bench.py --smoke gates "
       "the overhead at < 2% of the produce budget)."),
    _p("trace.ring.events", GLOBAL, "int", 8192,
       "Per-thread trace ring capacity in events; a power of two "
       "(validated at set() time). Each ring keeps the LAST this-many "
       "events of its thread — sizing bounds both memory and how far "
       "back a flight-recorder dump can see.",
       vmin=64, vmax=4194304, validator=_valid_ring_events),
    _p("trace.dump.on.fatal", GLOBAL, "bool", True,
       "Flight-recorder mode: with tracing enabled, auto-dump the last "
       "trace.ring.events events per thread to a JSON file on fatal "
       "error, CRC mismatch, or request timeout (bounded dumps per "
       "process; see TRACING.md for the dump location and format)."),
    # ---- concurrency analysis (analysis/lockdep.py; ANALYSIS.md) ----
    _p("analysis.lockdep", GLOBAL, "bool", False,
       "Run this client under the lockdep lock-order checker "
       "(analysis/lockdep.py): every Lock/RLock/Condition the client "
       "creates is instrumented, feeding the global lock-order graph "
       "(AB/BA inversions, cycles, locks held across blocking calls). "
       "Inspect with analysis.lockdep.report(). Debug/CI tool — "
       "instrumented acquisitions cost a few microseconds; disabled "
       "(default) the factory returns plain threading primitives and "
       "the hot path pays nothing (bench.py --smoke gates this at "
       "< 1% of the produce budget)."),
    _p("analysis.races", GLOBAL, "bool", False,
       "Run this client under the Eraser-style lockset data-race "
       "detector (analysis/races.py; implies the lockdep checker — "
       "locksets come from its held-stack): every declared shared "
       "field access refines a candidate lockset, and an empty-lockset "
       "write is reported with both access stacks. Inspect with "
       "analysis.races.report(). Debug/CI tool — disabled (default) "
       "the shared() declarations resolve to plain attributes and the "
       "hot path pays nothing (bench.py --smoke races_overhead gate, "
       "< 1% of the produce budget)."),
    # ---- callbacks / opaque ----
    _p("error_cb", GLOBAL, "ptr", None, "Error callback."),
    _p("throttle_cb", GLOBAL, "ptr", None, "Throttle callback."),
    _p("stats_cb", GLOBAL, "ptr", None, "Statistics callback."),
    _p("background_event_cb", GLOBAL, "ptr", None,
       "Background event callback: events are served from a dedicated "
       "background thread instead of poll() (rdkafka_background.c)."),
    _p("enabled_events", GLOBAL, "list", "",
       "Event types to generate for queue_poll()/background consumption "
       "(rd_kafka_conf_set_events analog): dr, error, log, stats."),
    _p("log_cb", GLOBAL, "ptr", None, "Log callback."),
    _p("oauthbearer_token_refresh_cb", GLOBAL, "ptr", None, "OAUTHBEARER refresh callback."),
    _p("socket_cb", GLOBAL, "ptr", None, "Socket creation callback (sockem hook)."),
    _p("connect_cb", GLOBAL, "ptr", None, "Socket connect callback (sockem hook)."),
    _p("rebalance_cb", GLOBAL, "ptr", None, "Rebalance callback.", app=C),
    _p("offset_commit_cb", GLOBAL, "ptr", None, "Offset commit result callback.", app=C),
    _p("opaque", GLOBAL, "ptr", None, "Application opaque."),
    _p("default_topic_conf", GLOBAL, "ptr", None, "Default topic config object."),
    # ---- test / mock ----
    _p("test.mock.num.brokers", GLOBAL, "int", 0,
       "Create an in-process mock cluster with this many brokers "
       "(reference: rdkafka_mock.c via rdkafka_conf.c).", vmin=0, vmax=10000),
    _p("test.mock.default.partitions", GLOBAL, "int", 4,
       "Partition count for topics auto-created by the mock cluster.",
       vmin=1, vmax=10000),

    # ---- topic scope ----
    _p("request.required.acks", TOPIC, "int", -1,
       "Required acks: -1=all ISR, 0=none, 1=leader.", app=P, vmin=-1, vmax=1000),
    _p("acks", TOPIC, "int", -1, "Alias.", app=P, alias="request.required.acks"),
    _p("request.timeout.ms", TOPIC, "int", 5000,
       "Ack timeout of produce request.", app=P, vmin=1, vmax=900000),
    _p("message.timeout.ms", TOPIC, "int", 300000,
       "Local message delivery timeout; 0=infinite.", app=P, vmin=0, vmax=2147483647),
    _p("delivery.timeout.ms", TOPIC, "int", 300000, "Alias.", app=P,
       alias="message.timeout.ms"),
    _p("partitioner", TOPIC, "enum", "consistent_random",
       "Partitioner: random, consistent, consistent_random, murmur2, murmur2_random.",
       app=P, enum=("random", "consistent", "consistent_random", "murmur2",
                    "murmur2_random")),
    _p("partitioner_cb", TOPIC, "ptr", None, "Custom partitioner callback.", app=P),
    _p("compression.level", TOPIC, "int", -1,
       "Codec-specific compression level.", app=P, vmin=-1, vmax=12),
    _p("auto.offset.reset", TOPIC, "enum", "largest",
       "Offset reset policy when no committed offset.", app=C,
       enum=("smallest", "earliest", "beginning", "largest", "latest", "end", "error")),
    _p("offset.store.method", TOPIC, "enum", "broker",
       "Offset commit store method; none = offsets are not stored.",
       app=C, enum=("none", "file", "broker")),
    _p("offset.store.path", TOPIC, "str", ".",
       "Path to local offset file store (legacy).", app=C),
    _p("offset.store.sync.interval.ms", TOPIC, "int", -1,
       "fsync interval for file store.", app=C, vmin=-1, vmax=86400000),

    # ---- reference-parity tail (rdkafka_conf.c rows absent until r5) ----
    # Deprecated no-ops the reference still accepts (_RK_DEPRECATED):
    _p("socket.blocking.max.ms", GLOBAL, "int", 1000,
       "No longer used.", vmin=1, vmax=60000, deprecated=True),
    _p("topic.metadata.refresh.fast.cnt", GLOBAL, "int", 10,
       "No longer used.", vmin=0, vmax=1000, deprecated=True),
    _p("offset.store.method", GLOBAL, "enum", "broker",
       "Offset commit store method (deprecated at global scope; routes "
       "to the topic property).", app=C, enum=("none", "file", "broker"),
       deprecated=True, fallthrough=True),
    _p("produce.offset.report", TOPIC, "bool", False,
       "No longer used.", app=P, deprecated=True),
    _p("queuing.strategy", TOPIC, "enum", "fifo",
       "Producer queuing strategy (EXPERIMENTAL, deprecated in the "
       "reference; only FIFO preserves produce ordering).", app=P,
       enum=("fifo", "lifo"), deprecated=True),
    _p("msg_order_cmp", TOPIC, "ptr", None,
       "Message queue ordering comparator (deprecated, see "
       "queuing.strategy).", app=P, deprecated=True),
    _p("auto.commit.enable", TOPIC, "bool", True,
       "Legacy simple-consumer topic-scope auto commit (deprecated; use "
       "the global enable.auto.commit).", app=C, deprecated=True),
    _p("enable.auto.commit", TOPIC, "bool", True, "Alias.", app=C,
       alias="auto.commit.enable", deprecated=True),
    _p("auto.commit.interval.ms", TOPIC, "int", 60000,
       "Legacy simple-consumer topic-scope commit interval (deprecated).",
       app=C, vmin=10, vmax=86400000, deprecated=True),
    # Java-client guidance rows (_RK_C_INVALID): setting them fails with
    # a pointer at the right property (rdkafka_conf.c:715-729)
    _p("ssl.truststore.location", GLOBAL, "invalid", None,
       "Java TrustStores are not supported, use `ssl.ca.location` and a "
       "certificate file instead."),
    _p("sasl.jaas.config", GLOBAL, "invalid", None,
       "Java JAAS configuration is not supported, see sasl.mechanisms / "
       "sasl.username / sasl.password and the sasl.* properties instead."),
    # Hidden rows (_RK_HIDDEN: functional, excluded from generated docs)
    _p("enable.sparse.connections", GLOBAL, "bool", True,
       "Only connect to brokers the client needs to talk to (bootstrap "
       "brokers and brokers with led partitions or queued requests); "
       "when disabled, connect to every discovered broker.", hidden=True),
    _p("ut_handle_ProduceResponse", GLOBAL, "ptr", None,
       "Unit-test interceptor for ProduceResponse handling: "
       "fn(broker_id, base_msgid, err) -> err-or-None override.",
       hidden=True),
    # Per-topic codec override (reference topic-scope compression.codec,
    # rdkafka_conf.c:1360: 'inherit' falls through to the global row)
    _p("compression.codec", TOPIC, "enum", "inherit",
       "Compression codec for this topic; inherit = use the global "
       "compression.codec.", app=P,
       enum=("none", "gzip", "snappy", "lz4", "zstd", "inherit")),
    _p("compression.type", TOPIC, "enum", "inherit", "Alias.", app=P,
       enum=("none", "gzip", "snappy", "lz4", "zstd", "inherit"),
       alias="compression.codec"),
    _p("topic.qos.weight", TOPIC, "float", 1.0,
       "Per-topic quality-of-service weight for the offload engine's "
       "governor (compression.backend=tpu with the device compress "
       "route): weighted fan-in admission — a high-weight topic's "
       "submissions shrink the fan-in window so latency-sensitive "
       "batches launch sooner — weight-ordered host-job dispatch, and "
       "shed-based isolation: when every lane is saturated, topics "
       "whose recent byte share exceeds 1.5x their weight share are "
       "served on the bit-identical CPU encoder instead of queueing "
       "ahead of higher-weight work. 1.0 (default) = neutral; > 1 "
       "prioritizes, < 1 marks bulk/background traffic. Per-topic "
       "routed/shed counts surface in statistics "
       "(codec_engine.compress.qos). No effect with "
       "compression.backend=cpu.", vmin=0.001, vmax=1000.0, app=P),
    _p("opaque", TOPIC, "ptr", None,
       "Per-topic application opaque (rd_kafka_topic_conf_set_opaque)."),
    _p("consume.callback.max.messages", TOPIC, "int", 0,
       "Maximum number of messages dispatched per consume_callback call "
       "(0 = unlimited; topic-scope row mirrors the reference, the global "
       "row is this tree's addition).", vmin=0, vmax=1000000, app=C),
]

#: Rows this tree adds over the reference's 154-row table
#: (rdkafka_conf.c:224). Everything in the reference table exists here
#: too (test_0110 asserts the union both ways against the reference
#: source); these are the intentional extras — the TPU codec-sidecar
#: knobs plus three client conveniences.
TPU_ADDITIONS = frozenset({
    (GLOBAL, "compression.backend"),
    (GLOBAL, "tpu.launch.min.batches"),
    (GLOBAL, "tpu.lz4.force"),
    (GLOBAL, "tpu.mesh.devices"),
    (GLOBAL, "tpu.transport.min.mb.s"),
    (GLOBAL, "tpu.pipeline.depth"),
    (GLOBAL, "tpu.pipeline.fanin.us"),
    (GLOBAL, "tpu.fetch.pipeline.depth"),
    (GLOBAL, "tpu.governor"),
    (GLOBAL, "tpu.warmup"),
    (GLOBAL, "tpu.compile.cache.dir"),
    (GLOBAL, "tpu.compress.device"),
    (TOPIC, "topic.qos.weight"),
    (GLOBAL, "codec.pipeline.depth"),
    (GLOBAL, "allow.auto.create.topics"),       # KIP-361 (post-1.3.0)
    (GLOBAL, "consume.callback.max.messages"),  # global mirror of the
                                                # reference's topic row
    (GLOBAL, "fetch.num.inflight"),             # fetch pipelining depth
    (GLOBAL, "dr_batch_cb"),                    # batched DR callback
    (GLOBAL, "test.mock.default.partitions"),   # mock-cluster knob
    # transactional producer (librdkafka grows these in 1.4; the
    # 1.3.0 reference table stops at the idempotent producer)
    (GLOBAL, "transactional.id"),
    (GLOBAL, "transaction.timeout.ms"),
    # flight-recorder tracing (ISSUE 5; no reference analog — the
    # reference's nearest is the debug-context log stream, rdlog.c)
    (GLOBAL, "trace.enable"),
    (GLOBAL, "trace.ring.events"),
    (GLOBAL, "trace.dump.on.fatal"),
    # concurrency analysis (ISSUE 8 lockdep, ISSUE 10 lockset races;
    # the reference's analog is
    # build-time helgrind/TSAN CI, not a conf row)
    (GLOBAL, "analysis.lockdep"),
    (GLOBAL, "analysis.races"),
})

# Scope-keyed lookup: the reference's table has rows of the same name in
# both scopes (compression.codec, opaque, offset.store.method, ...)
_BY_NAME: dict[tuple, Prop] = {}
for prop in PROPERTIES:
    assert (prop.scope, prop.name) not in _BY_NAME, prop.name
    _BY_NAME[(prop.scope, prop.name)] = prop

_TRUE = {"true", "t", "1", "yes", "on"}
_FALSE = {"false", "f", "0", "no", "off"}


class _ConfBase:
    """Shared get/set machinery for global and topic config."""

    _scope = GLOBAL

    def __init__(self, initial: Optional[dict] = None):
        self._values: dict[str, Any] = {}
        self._explicit: set[str] = set()
        if initial:
            for k, v in initial.items():
                self.set(k, v)

    # -- core API (reference: rd_kafka_conf_set, rdkafka_conf.c) --
    def set(self, name: str, value: Any) -> None:
        prop = _BY_NAME.get((self._scope, name))
        if prop is None:
            raise KafkaException(Err._INVALID_ARG,
                                 f"No such {self._scope} configuration property: {name!r}")
        if prop.ptype == "invalid":
            # reference _RK_C_INVALID rows: fail with guidance
            raise KafkaException(Err._INVALID_ARG,
                                 f"{name!r}: {prop.doc}")
        if prop.alias:
            return self.set(prop.alias, value)
        val = self._coerce(prop, value)
        if prop.validator is not None:
            err = prop.validator(val)
            if err is not None:
                raise KafkaException(
                    Err._INVALID_ARG,
                    f"Configuration property {prop.name!r}: {err}")
        self._values[prop.name] = val
        self._explicit.add(prop.name)
        # mutation counter + listeners: cached eligibility decisions
        # (e.g. the produce fast lane keyed on dr callbacks) revalidate
        # on change
        self.version = getattr(self, "version", 0) + 1
        for cb in getattr(self, "_listeners", ()):
            cb()

    def add_listener(self, cb) -> None:
        """Invoke ``cb()`` after every set() (post-creation conf
        mutations must invalidate cached eligibility decisions)."""
        if not hasattr(self, "_listeners"):
            self._listeners = []
        self._listeners.append(cb)

    def get(self, name: str) -> Any:
        prop = _BY_NAME.get((self._scope, name))
        if prop is None:
            raise KafkaException(Err._INVALID_ARG,
                                 f"No such {self._scope} configuration property: {name!r}")
        if prop.alias:
            return self.get(prop.alias)
        return self._values.get(prop.name, prop.default)

    def is_set(self, name: str) -> bool:
        prop = _BY_NAME.get((self._scope, name))
        if prop and prop.alias:
            name = prop.alias
        return name in self._explicit

    def update(self, d: dict) -> None:
        for k, v in d.items():
            self.set(k, v)

    def dump(self) -> dict:
        """All effective values (reference: rd_kafka_conf_dump)."""
        out = {}
        for prop in PROPERTIES:
            if (prop.scope == self._scope and not prop.alias
                    and prop.ptype not in ("ptr", "invalid")):
                out[prop.name] = self.get(prop.name)
        return out

    def copy(self):
        dup = type(self)()
        dup._values = dict(self._values)
        dup._explicit = set(self._explicit)
        return dup

    @staticmethod
    def _coerce(prop: Prop, value: Any) -> Any:
        t = prop.ptype
        if t == "ptr":
            return value
        if t == "bool":
            if isinstance(value, bool):
                return value
            sval = str(value).strip().lower()
            if sval in _TRUE:
                return True
            if sval in _FALSE:
                return False
            raise KafkaException(Err._INVALID_ARG,
                                 f"Expected bool for {prop.name!r}, got {value!r}")
        if t == "int":
            try:
                ival = int(str(value).strip())
            except ValueError:
                raise KafkaException(Err._INVALID_ARG,
                                     f"Expected int for {prop.name!r}, got {value!r}")
            if prop.vmin is not None and not (prop.vmin <= ival <= prop.vmax):
                raise KafkaException(
                    Err._INVALID_ARG,
                    f"Configuration property {prop.name!r} value {ival} is outside "
                    f"allowed range {int(prop.vmin)}..{int(prop.vmax)}")
            return ival
        if t == "float":
            try:
                fval = float(str(value).strip())
            except ValueError:
                raise KafkaException(Err._INVALID_ARG,
                                     f"Expected float for {prop.name!r}, got {value!r}")
            if prop.vmin is not None and not (prop.vmin <= fval <= prop.vmax):
                raise KafkaException(Err._INVALID_ARG,
                                     f"{prop.name!r} value {fval} outside range")
            return fval
        if t == "enum":
            sval = str(value).strip().lower()
            if sval not in prop.enum:
                raise KafkaException(
                    Err._INVALID_ARG,
                    f"Invalid value {value!r} for enum property {prop.name!r} "
                    f"(allowed: {', '.join(prop.enum)})")
            return sval
        if t == "list":
            if isinstance(value, (list, tuple)):
                return list(value)
            return [s for s in re.split(r"[,\s]+", str(value)) if s]
        return str(value)


class Conf(_ConfBase):
    """Global client configuration (reference: rd_kafka_conf_t).

    Topic-scoped properties set here fall through to the default topic
    config (the reference's conf fallthrough behavior)."""
    _scope = GLOBAL

    def set(self, name: str, value: Any) -> None:
        # fallthrough: names that only exist topic-scope route to the
        # default topic conf, as do explicit fallthrough rows (global
        # offset.store.method); names in BOTH scopes otherwise
        # (compression.codec, opaque, ...) take the global row, as the
        # reference does
        gprop = _BY_NAME.get((GLOBAL, name))
        if ((gprop is None or gprop.fallthrough)
                and (TOPIC, name) in _BY_NAME):
            tc = super().get("default_topic_conf")
            if tc is None:
                tc = TopicConf()
                super().set("default_topic_conf", tc)
            tc.set(name, value)
            return
        super().set(name, value)

    def get(self, name: str) -> Any:
        # fallthrough rows read back from where set() wrote (the
        # default topic conf), so set→get round-trips
        gprop = _BY_NAME.get((GLOBAL, name))
        if (gprop is not None and gprop.fallthrough
                and (TOPIC, name) in _BY_NAME):
            tc = super().get("default_topic_conf")
            if tc is not None:
                return tc.get(name)
            return _BY_NAME[(TOPIC, name)].default
        return super().get(name)

    def topic_conf(self) -> "TopicConf":
        tc = self.get("default_topic_conf")
        return tc.copy() if tc is not None else TopicConf()


class TopicConf(_ConfBase):
    """Per-topic configuration (reference: rd_kafka_topic_conf_t)."""
    _scope = TOPIC


def generate_configuration_md() -> str:
    """Auto-generate CONFIGURATION.md from the table, like the reference does."""
    out = ["# Configuration properties", ""]
    for scope, title in ((GLOBAL, "Global configuration properties"),
                         (TOPIC, "Topic configuration properties")):
        out += [f"## {title}", "",
                "Property | C/P | Range | Default | Description",
                "---------|-----|-------|---------|------------"]
        for prop in PROPERTIES:
            if prop.scope != scope or prop.hidden:
                continue
            rng = ""
            if prop.vmin is not None:
                rng = f"{int(prop.vmin)} .. {int(prop.vmax)}"
            elif prop.enum:
                rng = ", ".join(prop.enum)
            doc = prop.doc if not prop.alias else f"Alias for `{prop.alias}`: {prop.doc}"
            if prop.deprecated:
                doc = f"**DEPRECATED** {doc}"
            out.append(f"{prop.name} | {prop.app} | {rng} | {prop.default} | {doc}")
        out.append("")
    out += [
        "## Appendix: delta vs the reference table", "",
        "Every property in librdkafka 1.3.0's declarative table "
        "(src/rdkafka_conf.c:224, 154 rows incl. both scopes) exists in "
        "this table with the same name, scope and semantics — including "
        "the deprecated no-op rows, the hidden rows "
        "(enable.sparse.connections, ut_handle_ProduceResponse) and the "
        "Java-guidance error rows (ssl.truststore.location, "
        "sasl.jaas.config). Windows-only behavior (SSPI) is out of "
        "scope but its conf rows are accepted.", "",
        "Rows this tree ADDS over the reference:", ""]
    for scope, name in sorted(TPU_ADDITIONS):
        prop = _BY_NAME[(scope, name)]
        out.append(f"- `{name}` ({scope}): {prop.doc}")
    out.append("")
    return "\n".join(out)


if __name__ == "__main__":
    print(generate_configuration_md())
