"""Legacy local file offset store (reference: src/rdkafka_offset.c:98-330).

``offset.store.method=file`` (topic conf, deprecated in the reference
but part of the surface): committed offsets are persisted to local text
files instead of the broker. Per toppar, the file is
``<offset.store.path>/<topic>-<partition>.offset`` when the path is a
directory (the reference's layout), else the configured path itself.
``offset.store.sync.interval.ms`` controls fsync: -1 never, 0 after
every write, >0 at most once per interval (reference rdkafka_offset.c:46
syncs from the main thread on that timer).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional, TYPE_CHECKING

from ..analysis.locks import new_lock
from ..analysis.races import shared

if TYPE_CHECKING:
    from .kafka import Kafka


class _OffsetFile:
    __slots__ = ("path", "fd", "last_sync", "dirty", "open_cb", "_fobj")

    def __init__(self, path: str, open_cb=None):
        self.path = path
        self.fd: Optional[int] = None
        self.last_sync = 0.0
        self.dirty = False
        self.open_cb = open_cb
        self._fobj = None       # keeps a cb-returned file object alive

    def open(self):
        if self.fd is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            if self.open_cb is not None:
                # app-supplied file-open hook (reference open_cb,
                # rdkafka_conf.c:524 — used for the offset store's
                # opens): cb(path, os_flags) -> OS fd or file object.
                # A file object must be HELD, not just fileno()'d —
                # dropping the last reference would close the fd
                f = self.open_cb(self.path, os.O_CREAT | os.O_RDWR)
                if isinstance(f, int):
                    self.fd = f
                else:
                    self._fobj = f
                    self.fd = f.fileno()
            else:
                self.fd = os.open(self.path,
                                  os.O_CREAT | os.O_RDWR, 0o644)

    def read(self) -> Optional[int]:
        self.open()
        os.lseek(self.fd, 0, os.SEEK_SET)
        data = os.read(self.fd, 64).strip()
        if not data:
            return None
        try:
            return int(data)
        except ValueError:
            return None

    def write(self, offset: int, sync_interval_ms: int):
        self.open()
        payload = b"%d\n" % offset
        os.lseek(self.fd, 0, os.SEEK_SET)
        os.write(self.fd, payload)
        os.ftruncate(self.fd, len(payload))
        self.dirty = True
        now = time.monotonic()
        if sync_interval_ms == 0 or (
                sync_interval_ms > 0
                and now - self.last_sync >= sync_interval_ms / 1000.0):
            os.fsync(self.fd)
            self.last_sync = now
            self.dirty = False

    def close(self):
        if self.fd is not None:
            if self.dirty:
                try:
                    os.fsync(self.fd)
                except OSError:
                    pass
            if self._fobj is not None:
                self._fobj.close()        # owns the fd
                self._fobj = None
            else:
                os.close(self.fd)
            self.fd = None


class FileOffsetStore:
    """All file-backed offsets for one client instance."""

    # the file-handle table is touched from store (app) and commit
    # (rdk:main) paths, always under offset_store.files
    _files = shared("offset_store.files_map")

    def __init__(self, rk: "Kafka"):
        self.rk = rk
        self._files: dict[tuple[str, int], _OffsetFile] = {}
        self._lock = new_lock("offset_store.files")

    def _file(self, topic: str, partition: int) -> _OffsetFile:
        key = (topic, partition)
        with self._lock:
            f = self._files.get(key)
            if f is None:
                base = self.rk.topic_conf_for(topic).get("offset.store.path")
                if os.path.isdir(base) or base.endswith(os.sep) or base == ".":
                    path = os.path.join(base, f"{topic}-{partition}.offset")
                else:
                    path = base
                f = _OffsetFile(path, self.rk.conf.get("open_cb"))
                self._files[key] = f
            return f

    def method(self, topic: str) -> str:
        """Effective offset.store.method for this topic
        (none | file | broker)."""
        return self.rk.topic_conf_for(topic).get("offset.store.method")

    def uses_file(self, topic: str) -> bool:
        return self.method(topic) == "file"

    def read(self, topic: str, partition: int) -> Optional[int]:
        try:
            return self._file(topic, partition).read()
        except OSError:
            return None

    def commit_all(self, offsets: dict) -> None:
        """Write {(topic, partition): offset} to their files."""
        for (t, p), off in offsets.items():
            ival = self.rk.topic_conf_for(t).get(
                "offset.store.sync.interval.ms")
            self._file(t, p).write(off, ival)

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                f.close()
            self._files.clear()
