"""Public Consumer API: balanced KafkaConsumer + simple consumer.

Reference: the KafkaConsumer API surface of rdkafka.h (subscribe / poll /
commit / assign / seek / pause / position / committed) built over the cgrp
FSM, with all per-partition fetch queues forwarded into one consumer queue
(rd_kafka_q_fwd_set, rdkafka_queue.c:127) so a single poll serves
everything.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

from collections import deque

from ..protocol import proto
from ..protocol.proto import ApiKey
from .broker import Request
from .conf import Conf
from .cgrp import ConsumerGroup
from .errors import Err, KafkaError, KafkaException
from .kafka import CONSUMER, Kafka
from .msg import Message
from .partition import FetchState, Toppar
from .queue import Op, OpQueue, OpType, SyncReply


class _PyCursor:
    """Pure-Python delivery cursor: the fallback for
    tk_enqlane.cursor_new (identical contract, see _next_pending)."""
    __slots__ = ("tp", "msgs", "ver", "key", "i", "n")

    def __init__(self, tp, msgs, ver, key):
        self.tp = tp
        self.msgs = msgs
        self.ver = ver
        self.key = key
        self.i = 0
        self.n = len(msgs)

    def next(self, assignment, auto_store):
        tp = self.tp
        while self.i < self.n:
            m = self.msgs[self.i]
            self.i += 1
            if tp.version != self.ver or self.key not in assignment:
                continue            # stale/revoked: drop
            off1 = m.offset + 1
            tp.app_offset = off1
            if auto_store:
                tp.stored_offset = off1
            return m
        return None


def _cursor_factory():
    try:
        from .arena import _mod
        m = _mod()
        f = getattr(m, "cursor_new", None) if m else None
        return f if f is not None else _PyCursor
    except Exception:
        return _PyCursor


_new_cursor = _cursor_factory()


@dataclass
class TopicPartition:
    """Public topic+partition+offset tuple (rd_kafka_topic_partition_t)."""
    topic: str
    partition: int
    offset: int = proto.OFFSET_INVALID
    error: Optional[KafkaError] = None
    #: app-supplied commit metadata (rd_kafka_topic_partition_t.metadata,
    #: reference test 0099-commit_metadata); round-trips via
    #: commit(offsets=...) / committed()
    metadata: Optional[str] = None

    def __hash__(self):
        return hash((self.topic, self.partition))


@dataclass
class ConsumerGroupMetadata:
    """Opaque consumer-group identity handed to
    Producer.send_offsets_to_transaction
    (rd_kafka_consumer_group_metadata_t)."""
    group_id: str
    generation: int = -1
    member_id: str = ""


class Consumer:
    def __init__(self, conf):
        if isinstance(conf, dict):
            c = Conf()
            c.update(conf)
            conf = c
        self._rk = Kafka(conf, CONSUMER)
        self._rk.consumer = self
        self.queue = OpQueue("consumer")
        # single-queue consumer polling: the main reply queue (errors,
        # stats, logs) forwards into the consumer queue (reference:
        # rd_kafka_poll_set_consumer, rk_rep → rk_consumer fwd)
        self._rk.rep.forward_to(self.queue)
        group_id = conf.get("group.id")
        self._rk.cgrp = ConsumerGroup(self._rk, group_id) if group_id else None
        self._assignment: dict[tuple[str, int], Toppar] = {}
        # messages from a batched FETCH op awaiting delivery via poll()
        self._pending: deque = deque()   # (tp, msgs, version, mbytes)
        self._cur = None                 # delivery cursor over the
                                         # current batch (native
                                         # tk_enqlane.Cursor / _PyCursor)
        self._auto_store = conf.get("enable.auto.offset.store")
        self._next_tick = 0.0            # cgrp tick time-gate (poll)
        self._closed = False

    # ---------------------------------------------------------- subscribe --
    def subscribe(self, topics: list[str], on_assign=None, on_revoke=None):
        if self._rk.cgrp is None:
            raise KafkaException(Err._UNKNOWN_GROUP,
                                 "subscribe requires group.id")
        if on_assign or on_revoke:
            self._rk.conf.set("rebalance_cb",
                              self._make_rebalance_cb(on_assign, on_revoke))
        self._rk.cgrp.subscribe(topics)

    def _make_rebalance_cb(self, on_assign, on_revoke):
        def cb(consumer, code, partitions):
            coop = consumer.rebalance_protocol() == "COOPERATIVE"
            if code == Err._ASSIGN_PARTITIONS:
                if on_assign:
                    on_assign(consumer, partitions)
                elif coop:
                    consumer.incremental_assign(partitions)
                else:
                    consumer.assign(partitions)
            else:
                if on_revoke:
                    on_revoke(consumer, partitions)
                elif coop:
                    consumer.incremental_unassign(partitions)
                else:
                    consumer.unassign()
        return cb

    def unsubscribe(self):
        if self._rk.cgrp:
            self._rk.cgrp.unsubscribe()

    def subscription(self) -> list[str]:
        return list(self._rk.cgrp.subscription) if self._rk.cgrp else []

    # ------------------------------------------------------------- assign --
    def assign(self, partitions: list[TopicPartition]):
        assignment = {}
        for tp in partitions:
            assignment.setdefault(tp.topic, []).append(tp.partition)
        self.apply_assignment(assignment,
                              offsets={(tp.topic, tp.partition): tp.offset
                                       for tp in partitions})
        if self._rk.cgrp:
            self._rk.cgrp.rebalance_done(assigned=True)

    def unassign(self):
        self.apply_assignment({})
        if self._rk.cgrp:
            self._rk.cgrp.rebalance_done(assigned=False)

    def incremental_assign(self, partitions: list[TopicPartition]):
        """KIP-429: ADD ``partitions`` to the current assignment —
        every already-assigned partition is untouched and keeps
        fetching (reference: rd_kafka_incremental_assign).  The
        cooperative rebalance callback's assign-side answer."""
        add: dict[str, list[int]] = {}
        for tp in partitions:
            add.setdefault(tp.topic, []).append(tp.partition)
        self.apply_incremental_assign(
            add, offsets={(tp.topic, tp.partition): tp.offset
                          for tp in partitions})
        if self._rk.cgrp:
            self._rk.cgrp._coop_ack(True)

    def incremental_unassign(self, partitions: list[TopicPartition]):
        """KIP-429: REMOVE only ``partitions`` from the assignment
        (reference: rd_kafka_incremental_unassign) — the cooperative
        revoke-side answer; unrevoked fetchers never stop."""
        rem: dict[str, list[int]] = {}
        for tp in partitions:
            rem.setdefault(tp.topic, []).append(tp.partition)
        self.apply_incremental_unassign(rem)
        if self._rk.cgrp:
            self._rk.cgrp._coop_ack(False)

    def rebalance_protocol(self) -> str:
        """``NONE`` / ``EAGER`` / ``COOPERATIVE`` — the protocol of the
        broker-elected assignor (rd_kafka_rebalance_protocol)."""
        cg = self._rk.cgrp
        return cg.rebalance_protocol if cg is not None else "NONE"

    def assignment(self) -> list[TopicPartition]:
        return [TopicPartition(t, p, tp.app_offset)
                for (t, p), tp in self._assignment.items()]

    def _sync_cgrp_assignment(self):
        """Mirror the live membership into cgrp.assignment (the
        owned_partitions source + stats gauge) under the cgrp lock."""
        cgrp = self._rk.cgrp
        if cgrp is None:
            return
        current: dict[str, list[int]] = {}
        for t, p in sorted(self._assignment):
            current.setdefault(t, []).append(p)
        with cgrp._lock:
            cgrp.assignment = current

    def _stop_partitions(self, keys):
        for key in keys:
            tp = self._assignment.pop(key, None)
            if tp is None:
                continue
            tp.fetch_state = FetchState.STOPPED
            tp.version += 1
            tp.fetchq.forward_to(None)
            with tp.lock:
                tp.fetchq_cnt = 0
                tp.fetchq_bytes = 0
            # out of the O(active) index: stats emit and the broker
            # serve scans stop visiting it; the next fetch-session
            # request forgets it broker-side (absent from the wanted
            # set → forgotten_topics)
            self._rk.toppar_set_active(tp, False)

    def _start_partitions(self, need, explicit: dict, gen: Optional[int]):
        """Register ``need`` synchronously, resolve committed offsets
        asynchronously, then start the fetchers.  ``gen`` is the
        full-assignment generation guard (None on incremental paths:
        a later incremental change must not cancel unrelated pending
        starts — per-key liveness is checked instead)."""
        rk = self._rk

        # membership is registered SYNCHRONOUSLY (rd_kafka_assign sets
        # the assignment list before any async offset resolution —
        # assignment() and the _deliver revocation check must see it
        # immediately); only the committed-offset lookup is async
        for key in need:
            tp = self._assignment.get(key) or rk.get_toppar(*key)
            self._assignment[key] = tp
            tp.fetchq.forward_to(self.queue)
            rk.toppar_set_active(tp, True)
        # interest-set registration: an assign()-based consumer has no
        # subscription, so its topics reach the sparse/interest-only
        # metadata refresh through the topic-handle table (subscribe
        # literals and regex matches already pass through get_topic);
        # creating the handle also fires the "new topic" refresh that
        # resolves leaders for never-seen topics
        for t in {k[0] for k in need}:
            rk.get_topic(t)

        def start(committed: dict):
            if gen is not None and self._assign_gen != gen:
                return              # superseded by a newer assignment
            for key in need:
                t, p = key
                tp = self._assignment.get(key)
                if tp is None:
                    continue        # unassigned while offsets resolved
                off = explicit.get(key, proto.OFFSET_INVALID)
                if off < 0:
                    off = committed.get(key, proto.OFFSET_INVALID)
                if off >= 0:
                    tp.fetch_offset = off
                    tp.fetch_state = FetchState.ACTIVE
                else:
                    policy = rk.topic_conf_for(t).get("auto.offset.reset")
                    tp.fetch_offset = (
                        proto.OFFSET_BEGINNING
                        if policy in ("smallest", "earliest", "beginning")
                        else proto.OFFSET_END)
                    tp.fetch_state = FetchState.OFFSET_QUERY
                tp.version += 1
                rk._wake_leader(tp)

        if rk.cgrp and need:
            def on_fetched(err, resp):
                committed = {}
                if err is None:
                    for tr in resp["topics"]:
                        for pr in tr["partitions"]:
                            if pr["error_code"] == 0 and pr["offset"] >= 0:
                                committed[(tr["topic"], pr["partition"])] = \
                                    pr["offset"]
                start(committed)

            if not rk.cgrp.fetch_committed(list(need), on_fetched):
                start({})
        else:
            start({})

    def apply_assignment(self, assignment: dict[str, list[int]],
                         offsets: Optional[dict] = None):
        """Start/stop fetchers to match the assignment (reference:
        rd_kafka_cgrp_assign → toppar OP_FETCH_START)."""
        # generation stamp: an async committed-offset lookup from an
        # OLDER apply_assignment call must not touch fetch state after
        # an unassign/reassign bounce superseded it (it could resurrect
        # an outdated committed offset and re-deliver messages)
        self._assign_gen = getattr(self, "_assign_gen", 0) + 1
        gen = self._assign_gen
        new_keys = {(t, p) for t, ps in assignment.items() for p in ps}
        # stop removed partitions
        self._stop_partitions([k for k in list(self._assignment)
                               if k not in new_keys])
        cgrp = self._rk.cgrp
        if cgrp:
            with cgrp._lock:
                cgrp.assignment = assignment
        if not new_keys:
            return
        # gather committed offsets for every partition whose fetcher
        # hasn't STARTED — not merely "not registered": a registered
        # partition whose async offset lookup was superseded (gen
        # guard) still needs a restart or it would sit in
        # FetchState.NONE forever
        need = [k for k in new_keys
                if k not in self._assignment
                or self._assignment[k].fetch_state
                in (FetchState.NONE, FetchState.STOPPED)]
        self._start_partitions(need, offsets or {}, gen)

    def apply_incremental_assign(self, assignment: dict[str, list[int]],
                                 offsets: Optional[dict] = None):
        """Start fetchers for ``assignment`` without touching any other
        partition — the mechanics of ``incremental_assign`` (no join-
        FSM side effects; cgrp calls this on the auto-apply path)."""
        new_keys = {(t, p) for t, ps in assignment.items() for p in ps}
        need = [k for k in sorted(new_keys)
                if k not in self._assignment
                or self._assignment[k].fetch_state
                in (FetchState.NONE, FetchState.STOPPED)]
        self._start_partitions(need, offsets or {}, None)
        self._sync_cgrp_assignment()

    def apply_incremental_unassign(self, assignment: dict[str, list[int]]):
        """Stop ONLY the named fetchers; everything else keeps flowing
        (the zero stop-the-world property the chaos continuity
        invariant asserts)."""
        self._stop_partitions([(t, p) for t, ps in assignment.items()
                               for p in ps])
        self._sync_cgrp_assignment()

    # --------------------------------------------------------------- poll --
    def _next_pending(self) -> Optional[Message]:
        """Next deliverable message from the fetched-batch queue.
        Batches stay whole (one deque entry per partition response, the
        op-per-batch axis); a delivery cursor (native tk_enqlane.Cursor
        when available) walks the current batch — the staleness barrier,
        the revocation check and the offset advance run per message in
        ONE C call. A message is stale — dropped — when the partition
        was seeked/paused since the fetch (version barrier) OR revoked
        from the current assignment; assign()/unassign() maintain
        _assignment in group and simple modes alike (reference:
        rd_kafka_op_version_outdated plus the fetchq disconnect on
        rd_kafka_toppar_fetch_stop). Fetchq accounting is released per
        BATCH when its delivery begins (it feeds the queued.min.messages
        fetch gate, where batch granularity is equivalent)."""
        cur = self._cur
        pending = self._pending
        while True:
            if cur is None:
                if not pending:
                    return None
                tp, msgs, ver, mbytes = pending.popleft()
                # under the toppar lock: the broker thread's enqueue
                # accounting (kafka._enq_fetched) is a concurrent RMW
                # on the same counters (--races sweep finding: a GIL
                # switch between load and store lost an update and the
                # clamp silently re-zeroed the fetch budget)
                with tp.lock:
                    fc = tp.fetchq_cnt - len(msgs)
                    tp.fetchq_cnt = fc if fc > 0 else 0
                    fb = tp.fetchq_bytes - mbytes
                    tp.fetchq_bytes = fb if fb > 0 else 0
                cur = _new_cursor(tp, msgs, ver, (tp.topic, tp.partition))
                self._cur = cur
            m = cur.next(self._assignment, self._auto_store)
            if m is not None:
                return m
            cur = None
            self._cur = None

    def poll(self, timeout: float = 1.0) -> Optional[Message]:
        # fast path: drain already-fetched batches without touching the
        # op queue (the per-message consume budget); the cgrp tick
        # (max.poll bookkeeping, rebalance callbacks) is TIME-gated to
        # ~4/s — a count gate would let a slow-consuming app's
        # last-poll timestamp go stale past max.poll.interval.ms even
        # though it polls continuously. The slow path always ticks.
        msg = self._next_pending()
        if msg is not None:
            now = time.monotonic()
            if now >= self._next_tick:
                self._next_tick = now + 0.25
                cgrp = self._rk.cgrp
                if cgrp is not None:
                    cgrp.poll_tick()
            return msg
        cgrp = self._rk.cgrp
        if cgrp is not None:
            cgrp.poll_tick()
        deadline = time.monotonic() + timeout
        while True:
            remain = deadline - time.monotonic()
            op = self.queue.pop(max(0.0, min(remain, 0.1)))
            if op is None:
                if time.monotonic() >= deadline:
                    return None
                continue
            msg = self._serve_op(op)
            if msg is not None:
                return msg
            msg = self._next_pending()
            if msg is not None:
                return msg
            if time.monotonic() >= deadline:
                return None

    def consume_callback(self, timeout: float = 1.0, consume_cb=None,
                         max_messages: Optional[int] = None) -> int:
        """Callback-based consume mode (reference:
        rd_kafka_consume_callback, rdkafka.h): dispatch messages to
        ``consume_cb`` (argument, or the ``consume_cb`` conf property)
        instead of returning them. Waits up to ``timeout`` for the
        first message, then drains without waiting, capped by
        ``max_messages`` (argument, or ``consume.callback.max.messages``
        conf; 0 = unlimited). Returns the number dispatched."""
        cb = consume_cb or self._rk.conf.get("consume_cb")
        if cb is None:
            raise KafkaException(
                Err._INVALID_ARG,
                "consume_callback requires a consume_cb (argument or "
                "conf property)")
        cap = max_messages
        if cap is None:
            cap = self._rk.conf.get("consume.callback.max.messages")
            # topic-scope row (the reference's per-topic cap,
            # rdkafka_conf.c:1365 — its consume_callback is a per-topic
            # call): an explicitly-set subscribed topic's cap bounds
            # this instance-level call conservatively
            for t in (self._rk.cgrp.subscription if self._rk.cgrp else ()):
                tc = self._rk.topic_conf_for(t)
                if tc.is_set("consume.callback.max.messages"):
                    tcap = tc.get("consume.callback.max.messages")
                    if tcap and (not cap or tcap < cap):
                        cap = tcap
        if not cap:
            cap = float("inf")
        n = 0
        t = timeout
        while n < cap:
            m = self.poll(t)
            if m is None:
                break
            t = 0.0          # drain without waiting after the first
            cb(m)
            n += 1
        return n

    def consume(self, num_messages: int = 1, timeout: float = 1.0
                ) -> list[Message]:
        """Batch consume (reference: rd_kafka_consume_batch_queue).
        Drains already-fetched batches without per-message clock reads
        or op-queue round trips; blocks via poll() only while short."""
        cgrp = self._rk.cgrp
        if cgrp is not None:
            cgrp.poll_tick()
        out = []
        nxt = self._next_pending
        while len(out) < num_messages:
            m = nxt()
            if m is None:
                break
            out.append(m)
        deadline = None
        while len(out) < num_messages:
            if deadline is None:
                deadline = time.monotonic() + timeout
            remain = deadline - time.monotonic()
            if remain <= 0:
                break
            m = self.poll(remain)
            if m is None:
                break
            out.append(m)
            while len(out) < num_messages:
                m = nxt()
                if m is None:
                    break
                out.append(m)
        return out

    def _serve_op(self, op: Op) -> Optional[Message]:
        rk = self._rk
        if op.type == OpType.FETCH:
            tp, msgs, version, mbytes = op.payload
            if msgs:
                self._pending.append((tp, msgs, version, mbytes))
            return None
        if op.type == OpType.CONSUMER_ERR:
            tp, msg, version = op.payload
            return msg if tp.version == version else None
        if op.type == OpType.REBALANCE:
            code, assignment, incremental = op.payload
            cb = rk.conf.get("rebalance_cb")
            parts = [TopicPartition(t, p) for t, ps in assignment.items()
                     for p in ps]
            if cb:
                cb(self, code, parts)
                if rk.cgrp is not None and rk.cgrp._wait_rebalance_cb:
                    # the app's callback returned without answering
                    # (no assign/unassign family call): apply the
                    # default action so the join FSM can't wedge in
                    # wait-assign-rebalance-cb (reference:
                    # rd_kafka_poll_cb's rebalance op fallback)
                    if code == Err._ASSIGN_PARTITIONS:
                        (self.incremental_assign if incremental
                         else self.assign)(parts)
                    elif incremental:
                        self.incremental_unassign(parts)
                    else:
                        self.unassign()
            return None
        # forwarded main-queue ops (errors/stats/logs): dispatch to the
        # same handlers rd_kafka_poll would use
        rk._serve_rep_op(op)
        return None

    # ------------------------------------------------------------ offsets --
    def stored_offsets(self) -> dict[tuple[str, int], int]:
        """Offsets pending commit (stored > committed)."""
        out = {}
        for key, tp in self._assignment.items():
            if tp.stored_offset >= 0 and tp.stored_offset != tp.committed_offset:
                out[key] = tp.stored_offset
        return out

    def store_offsets(self, message: Optional[Message] = None,
                      offsets: Optional[list[TopicPartition]] = None):
        if message is not None:
            tp = self._assignment.get((message.topic, message.partition))
            if tp:
                tp.stored_offset = message.offset + 1
        for tpo in offsets or []:
            tp = self._assignment.get((tpo.topic, tpo.partition))
            if tp:
                tp.stored_offset = tpo.offset

    def commit(self, message: Optional[Message] = None,
               offsets: Optional[list[TopicPartition]] = None,
               asynchronous: bool = False):
        if self._rk.cgrp is None:
            raise KafkaException(Err._UNKNOWN_GROUP, "commit requires group.id")
        if message is not None:
            to_commit = {(message.topic, message.partition): message.offset + 1}
        elif offsets is not None:
            to_commit = {(o.topic, o.partition): (o.offset, o.metadata)
                         for o in offsets}
        else:
            to_commit = self.stored_offsets()
        if not to_commit:
            return None
        if asynchronous:
            self._rk.cgrp.commit_offsets(to_commit, None)
            return None
        done = []
        reply = SyncReply()

        def cb(err, resp):
            done.append(err)
            reply.post()

        cgrp = self._rk.cgrp
        # offsets= entries carry (offset, metadata) tuples internally;
        # the returned TopicPartitions must carry the plain offset
        result = [TopicPartition(t, p, off[0] if isinstance(off, tuple)
                                 else off)
                  for (t, p), off in to_commit.items()]
        store = self._rk.offset_store
        deadline = time.monotonic() + 10
        while True:
            if cgrp.commit_offsets(to_commit, cb):
                reply.wait(lambda: bool(done),
                           max(0.0, deadline - time.monotonic()))
                break
            # coordinator not known yet (fresh/assign()-based consumer):
            # commit_offsets already reported _WAIT_COORD into `done` —
            # drop it, wait for the coord FSM (driven by the main-thread
            # serve loop) to come up, and retry until the deadline.
            # File-backed items were committed locally by the failed
            # attempt (commit_offsets does those before the coordinator
            # check) — strip them so retries don't redo the side effects
            done.clear()
            if store is not None:
                to_commit = {k: v for k, v in to_commit.items()
                             if not store.uses_file(k[0])}
                if not to_commit:      # everything was file-backed: done
                    done.append(None)
                    break
            if time.monotonic() >= deadline:
                done.append(KafkaError(Err._WAIT_COORD, "no coordinator"))
                break
            cgrp.coord_ready.wait(
                lambda: cgrp.state == "up",
                min(0.5, max(0.0, deadline - time.monotonic())))
        if not done:
            # request sent but no reply within the deadline — surface it
            # (reference rd_kafka_commit returns _TIMED_OUT), never imply
            # a successful commit the broker may not have applied
            raise KafkaException(Err._TIMED_OUT, "commit reply timed out")
        if done[0] is not None:
            raise KafkaException(done[0])
        return result

    def committed(self, partitions: list[TopicPartition],
                  timeout: float = 10.0) -> list[TopicPartition]:
        if self._rk.cgrp is None:
            raise KafkaException(Err._UNKNOWN_GROUP, "requires group.id")
        result = {}
        done = []
        reply = SyncReply()

        def cb(err, resp):
            if err is None:
                for tr in resp["topics"]:
                    for pr in tr["partitions"]:
                        result[(tr["topic"], pr["partition"])] = (
                            pr["offset"], pr.get("metadata"))
            done.append(err)
            reply.post()

        cgrp = self._rk.cgrp
        keys = [(p.topic, p.partition) for p in partitions]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cgrp.fetch_committed(keys, cb):
                reply.wait(lambda: bool(done),
                           max(0.0, deadline - time.monotonic()))
                break
            # no coordinator yet — wait for the FSM and retry (the
            # failed attempt resets cgrp.state, so this doesn't spin)
            cgrp.coord_ready.wait(
                lambda: cgrp.state == "up",
                min(0.5, max(0.0, deadline - time.monotonic())))
        if not done:
            raise KafkaException(Err._TIMED_OUT,
                                 "committed offsets not available")
        if done[0] is not None:
            raise KafkaException(done[0])
        out = []
        for p in partitions:
            off, meta = result.get((p.topic, p.partition),
                                   (proto.OFFSET_INVALID, None))
            out.append(TopicPartition(p.topic, p.partition, off,
                                      metadata=meta))
        return out

    # ------------------------------------------------------ seek & pause --
    def seek(self, partition: TopicPartition):
        tp = self._assignment.get((partition.topic, partition.partition))
        if tp is None:
            raise KafkaException(Err._STATE, "partition not assigned")
        tp.version += 1
        tp.fetchq.pop_all()
        with tp.lock:
            tp.fetchq_cnt = 0
            tp.fetchq_bytes = 0
        if partition.offset in (proto.OFFSET_BEGINNING, proto.OFFSET_END):
            tp.fetch_offset = partition.offset
            tp.fetch_state = FetchState.OFFSET_QUERY
        else:
            tp.fetch_offset = partition.offset
            tp.fetch_state = FetchState.ACTIVE
        self._rk._wake_leader(tp)

    def pause(self, partitions: list[TopicPartition]):
        for p in partitions:
            tp = self._assignment.get((p.topic, p.partition))
            if tp:
                tp.paused = True

    def resume(self, partitions: list[TopicPartition]):
        for p in partitions:
            tp = self._assignment.get((p.topic, p.partition))
            if tp:
                tp.paused = False
                self._rk._wake_leader(tp)

    def position(self, partitions: list[TopicPartition]
                 ) -> list[TopicPartition]:
        out = []
        for p in partitions:
            tp = self._assignment.get((p.topic, p.partition))
            out.append(TopicPartition(p.topic, p.partition,
                                      tp.app_offset if tp else
                                      proto.OFFSET_INVALID))
        return out

    def get_watermark_offsets(self, partition: TopicPartition,
                              timeout: float = 10.0,
                              cached: bool = False) -> tuple[int, int]:
        """Low/high watermarks (reference: rd_kafka_query_watermark_
        offsets / rd_kafka_get_watermark_offsets). ``cached=True``
        returns the fetcher's last-known value without a query; the
        query path is two ListOffsets lookups through the same
        machinery as offsets_for_times (BEGINNING/END timestamps)."""
        if cached:
            tp = self._rk.get_toppar(partition.topic, partition.partition)
            return (0, tp.hi_offset)
        deadline = time.monotonic() + timeout
        out = []
        for ts in (proto.OFFSET_BEGINNING, proto.OFFSET_END):
            r = self.offsets_for_times(
                [TopicPartition(partition.topic, partition.partition, ts)],
                timeout=max(0.0, deadline - time.monotonic()))[0]
            if r.error is not None:
                raise KafkaException(r.error)
            out.append(r.offset)
        return (out[0], out[1])

    def offsets_for_times(self, partitions: list[TopicPartition],
                          timeout: float = 10.0) -> list[TopicPartition]:
        """Earliest offsets at/after the given timestamps (reference:
        rd_kafka_offsets_for_times -> ListOffsets v1 with real
        timestamps). Input offsets carry the timestamps (ms), like the
        reference API. A timestamp past the end of the log yields
        offset -1 with NO error (reference semantics)."""
        rk = self._rk
        results: dict = {}
        reply = SyncReply()
        deadline = time.monotonic() + timeout   # ONE budget for the call

        def make_cb(keys):
            def cb(err, resp):
                if err is None:
                    for tr in resp["topics"]:
                        for pr in tr["partitions"]:
                            off = pr.get("offset")
                            if off is None:     # ListOffsets v0: plural
                                offs = pr.get("offsets") or [-1]
                                off = offs[0]
                            key = (tr["topic"], pr["partition"])
                            results[key] = (pr["error_code"], off)
                else:
                    for k in keys:
                        results[k] = (-1, proto.OFFSET_INVALID)
                reply.post()
            return cb

        # group by leader broker like the fetch path
        by_broker: dict = {}
        for tpo in partitions:
            tp = rk.get_toppar(tpo.topic, tpo.partition)
            while tp.leader_id < 0 and time.monotonic() < deadline:
                # block on the metadata condvar (notified on every
                # metadata update) instead of sleep-polling; the 0.5s
                # cap re-issues the refresh if an update didn't help
                rk.metadata_refresh("offsets_for_times",
                                    topics=[tpo.topic])
                rk.metadata_wait(
                    lambda: tp.leader_id >= 0,
                    min(0.5, max(0.0, deadline - time.monotonic())))
            by_broker.setdefault(tp.leader_id, []).append(tpo)
        for leader, tpos in by_broker.items():
            b = rk.brokers.get(leader)
            if b is None:
                for tpo in tpos:
                    results[(tpo.topic, tpo.partition)] = (
                        -1, proto.OFFSET_INVALID)
                continue
            body = {"replica_id": -1,
                    "topics": [{"topic": tpo.topic, "partitions": [
                        {"partition": tpo.partition,
                         "timestamp": tpo.offset,
                         "max_num_offsets": 1}]}
                        for tpo in tpos]}
            keys = [(tpo.topic, tpo.partition) for tpo in tpos]
            b.enqueue_request(Request(ApiKey.ListOffsets, body,
                                      retries_left=2, cb=make_cb(keys)))
        reply.wait(lambda: len(results) >= len(partitions),
                   max(0.0, deadline - time.monotonic()))
        out = []
        for tpo in partitions:
            key = (tpo.topic, tpo.partition)
            r = TopicPartition(tpo.topic, tpo.partition,
                               proto.OFFSET_INVALID)
            if key not in results:
                r.error = KafkaError(Err._TIMED_OUT)
            else:
                ec, off = results[key]
                r.offset = off
                if ec == -1:
                    r.error = KafkaError(Err._TRANSPORT)
                elif ec > 0:
                    r.error = KafkaError(Err.from_wire(ec))
                # ec == 0 with offset -1 is the legitimate "no offset
                # at or after this timestamp" result - NOT an error
            out.append(r)
        return out

    def io_event_enable(self, fd: int, payload: bytes = b"1") -> None:
        """select()/epoll() integration: every op landing on the
        consumer queue writes ``payload`` to ``fd`` (reference:
        rd_kafka_queue_io_event_enable on the consumer queue)."""
        self.queue.io_event_enable(fd, payload)

    def list_topics(self, timeout: float = 10.0) -> dict:
        """rd_kafka_metadata analog: full cluster metadata snapshot."""
        return self._rk.list_topics(timeout)

    def cluster_id(self, timeout: float = 5.0):
        """rd_kafka_clusterid analog."""
        return self._rk.cluster_id(timeout)

    def controller_id(self, timeout: float = 5.0) -> int:
        """rd_kafka_controllerid analog."""
        return self._rk.controller_id(timeout)

    def memberid(self) -> str:
        """Group member id after joining (rd_kafka_memberid analog;
        empty string before the first JoinGroup completes)."""
        cg = self._rk.cgrp
        return cg.member_id if cg is not None else ""

    def consumer_group_metadata(self):
        """Opaque group metadata for
        Producer.send_offsets_to_transaction (the
        rd_kafka_consumer_group_metadata analog: group id plus the
        current generation/member identity)."""
        from .errors import Err, KafkaException
        cg = self._rk.cgrp
        if cg is None:
            raise KafkaException(Err._UNKNOWN_GROUP,
                                 "consumer_group_metadata requires group.id")
        return ConsumerGroupMetadata(cg.group_id, cg.generation,
                                     cg.member_id)

    def poll_kafka(self, timeout: float = 0.0) -> int:
        return self._rk.poll(timeout)

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._rk.cgrp:
            self._rk.cgrp.terminate()
        self.apply_assignment({})
        self._rk.close()

    def trace_dump(self, path: str) -> int:
        """Export the flight-recorder trace rings as Chrome trace-event
        JSON (trace.enable=true; see TRACING.md)."""
        return self._rk.trace_dump(path)

    @property
    def rk(self) -> Kafka:
        return self._rk
