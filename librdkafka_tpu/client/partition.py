"""Topic+partition ("toppar") state (reference: src/rdkafka_partition.c).

Producer side: two queues per toppar — ``msgq`` (app enqueues under lock,
reference rktp_msgq) and ``xmit_msgq`` (broker thread drains, rktp_xmit_msgq,
rdkafka_partition.h:105-107) — moved wholesale under the toppar lock at the
top of the producer serve loop (rdkafka_broker.c:3322-3327).

Consumer side: a fetch state machine (NONE→OFFSET_QUERY→OFFSET_WAIT→ACTIVE,
rdkafka_partition.h:227-233) and a per-toppar fetch queue that is forwarded
into the single consumer queue (rd_kafka_q_fwd_set).
"""
from __future__ import annotations

import enum
import threading
import time
from collections import deque
from typing import Optional

from ..protocol import proto
from ..analysis.locks import new_lock
from ..analysis.races import register_slots
from .msg import Message
from .queue import OpQueue


class FetchState(enum.Enum):
    NONE = "none"
    STOPPING = "stopping"
    STOPPED = "stopped"
    OFFSET_QUERY = "offset-query"
    OFFSET_WAIT = "offset-wait"
    ACTIVE = "active"


class Toppar:
    # slotted: the native delivery cursor (enqlane.cpp cursor_next)
    # reads/writes version/app_offset/stored_offset by member offset,
    # and the per-toppar footprint matters at 64+ partitions
    __slots__ = (
        "topic", "partition", "lock",
        # producer
        "msgq", "xmit_msgq", "msgq_bytes", "arena", "arena_ok",
        "next_msgid", "epoch_base_msgid", "inflight", "inflight_msgids",
        "retry_batches", "retry_backoff_until", "leader_id",
        "ts_last_xmit",
        # consumer
        "fetch_state", "fetchq", "fetch_offset", "app_offset",
        "stored_offset", "committed_offset", "hi_offset", "ls_offset",
        "paused", "fetch_backoff_until", "fetch_in_flight",
        "fetch_broker_id", "fetchq_cnt", "fetchq_bytes",
        "eof_reported_at", "aborted_txns", "version", "stats_active")

    def __init__(self, topic: str, partition: int):
        self.topic = topic
        self.partition = partition
        self.lock = new_lock("kafka.toppar")

        # ---- producer ----
        self.msgq: deque[Message] = deque()        # app → (lock) → broker
        self.xmit_msgq: deque[Message] = deque()   # broker-thread owned
        self.msgq_bytes = 0
        # native enqueue fast lane (client/arena.py): created on first
        # eligible produce; permanently demoted (arena_ok=False) the
        # moment a Message-path record targets this toppar so FIFO order
        # can never interleave between the two lanes
        self.arena = None
        self.arena_ok = True
        self.next_msgid = 1
        self.epoch_base_msgid = 0                  # idempotence seq base
        self.inflight = 0                          # in-flight ProduceRequests
        self.inflight_msgids: set[int] = set()     # first msgid per in-flight batch
        self.retry_batches: deque[list[Message]] = deque()  # frozen retries
        self.retry_backoff_until = 0.0   # retry.backoff.ms gate on re-pops
        self.leader_id: int = -1
        self.ts_last_xmit = 0.0

        # ---- consumer ----
        self.fetch_state = FetchState.NONE
        self.fetchq = OpQueue(f"{topic}[{partition}]-fetchq")
        self.fetch_offset: int = proto.OFFSET_INVALID
        self.app_offset: int = proto.OFFSET_INVALID     # next offset app sees
        self.stored_offset: int = proto.OFFSET_INVALID  # to be committed
        self.committed_offset: int = proto.OFFSET_INVALID
        self.hi_offset: int = proto.OFFSET_INVALID      # high watermark
        self.ls_offset: int = proto.OFFSET_INVALID      # last stable
        self.paused = False
        self.fetch_backoff_until = 0.0
        self.fetch_in_flight = False   # included in an outstanding Fetch
        # KIP-392 fetch-from-follower: broker id currently serving this
        # partition's Fetches (None = the leader). Producing always
        # targets the leader regardless.
        self.fetch_broker_id = None
        self.fetchq_cnt = 0        # msgs sitting in fetchq (queued.min)
        self.fetchq_bytes = 0      # queued.max.messages.kbytes accounting
        self.eof_reported_at = proto.OFFSET_INVALID
        self.aborted_txns: dict[int, list[int]] = {}  # pid -> abort offsets
        self.version = 1                 # barrier for stale fetch ops
        # in Kafka._active_toppars (stats/serve iterate only ACTIVE
        # toppars — a metadata-registered one costs nothing per emit);
        # flag checked lock-free on hot paths, index under kafka.toppars
        self.stats_active = False

    # ------------------------------------------------------- producer ----
    def enq_msg(self, msg: Message) -> bool:
        """Enqueue; returns True when the queue was empty (the caller
        wakes the leader broker only on that transition — per-message
        wakeups dominated the produce() profile)."""
        with self.lock:
            msg.msgid = self.next_msgid
            self.next_msgid += 1
            self.msgq.append(msg)
            self.msgq_bytes += msg.size
            return len(self.msgq) == 1

    def xmit_move(self) -> int:
        """Move msgq → xmit_msgq under lock; returns moved count."""
        with self.lock:
            n = len(self.msgq)
            if n:
                self.xmit_msgq.extend(self.msgq)
                self.msgq.clear()
                self.msgq_bytes = 0
            return n

    def insert_retry(self, msgs: list[Message]) -> None:
        """Requeue retried messages preserving msgid (FIFO) order
        (reference: rd_kafka_msgq_insert_msgq order-preserving merge)."""
        with self.lock:
            merged = sorted(list(msgs) + list(self.xmit_msgq),
                            key=lambda m: m.msgid)
            self.xmit_msgq = deque(merged)

    def release_inflight(self, msgs) -> None:
        """Release one batch's in-flight accounting. MUST run only after
        the requeue-or-DR decision (the DRAIN rebase on the main thread
        keys off inflight==0 — releasing early lets it rebase past
        messages still owned by a broker/codec thread)."""
        from .arena import batch_head_msgid
        with self.lock:
            self.inflight -= 1
            self.inflight_msgids.discard(batch_head_msgid(msgs))

    def enqueue_retry_batch(self, msgs) -> None:
        """Requeue a failed produce batch FROZEN — original membership and
        order — so a resend carries the same (BaseSequence, record_count)
        and broker-side idempotent dup detection stays sound.  The
        reference likewise never re-slices a retried batch (the msgset is
        rebuilt from the same message run, rdkafka_msgset_writer.c).
        Accepts list[Message] or a fast-lane ArenaBatch."""
        from .arena import ArenaBatch, batch_head_msgid
        with self.lock:
            self.retry_batches.append(
                msgs if isinstance(msgs, ArenaBatch) else list(msgs))
            if len(self.retry_batches) > 1:
                self.retry_batches = deque(
                    sorted(self.retry_batches, key=batch_head_msgid))

    def demote_arena(self) -> None:
        """Permanently route this toppar through the Message path; any
        arena content is converted to Messages FIRST so produce order is
        preserved exactly.  Caller must hold neither lock."""
        from .msg import Message
        with self.lock:
            self.arena_ok = False
            if self.arena is None or len(self.arena) == 0:
                return
            from .arena import decode_hblob
            recs = self.arena.drain_records()
            for k, v, mts, hb in recs:
                m = Message(self.topic, value=v, key=k,
                            partition=self.partition, timestamp=mts,
                            headers=decode_hblob(hb) if hb else ())
                m.msgid = self.next_msgid
                self.next_msgid += 1
                self.msgq.append(m)
                self.msgq_bytes += m.size

    def total_queued(self) -> int:
        with self.lock:
            return len(self.msgq) + len(self.xmit_msgq)

    def __repr__(self):
        return f"Toppar({self.topic}[{self.partition}])"


# lockset declarations (analysis/races.py; slot form — Toppar is
# __slots__).  Strict set: the producer queues and the fetch-budget
# counters are RMW'd from app + broker + codec threads and every
# access holds kafka.toppar (the fetchq counters' bare cross-thread
# ``+=`` was the headline ISSUE-10 sweep finding).
register_slots(Toppar, "msgq", "xmit_msgq", "msgq_bytes",
               "fetchq_cnt", "fetchq_bytes",
               prefix="toppar")
# Relaxed: in-flight accounting, msgid assignment and the retry queue
# are written under kafka.toppar, but the broker serve loop takes
# lock-free ADVISORY peeks (max-inflight gate, retry/dedup scans) that
# are re-validated under the lock before acting — the double-checked
# pattern Eraser classically false-positives on.  Tracked, reported
# informationally.
register_slots(Toppar, "inflight", "inflight_msgids", "next_msgid",
               "retry_batches", "fetch_in_flight", "stats_active",
               prefix="toppar", relaxed=True)
