"""Statistics: windowed averages with percentiles + the JSON stats blob.

Reference: rd_avg_t (src/rdavg.h) over HdrHistogram (rdhdrhistogram.c),
emitted by rd_kafka_stats_emit_all (rdkafka.c:1473-1700) every
statistics.interval.ms with the schema documented in STATISTICS.md.
"""
from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:
    from .kafka import Kafka


class Avg:
    """Windowed sample set with rollover + percentiles (rd_avg_t analog)."""

    __slots__ = ("_samples", "_lock")

    def __init__(self):
        self._samples: list[float] = []
        self._lock = threading.Lock()

    def add(self, v: float):
        with self._lock:
            if len(self._samples) < 100000:
                self._samples.append(v)

    def rollover(self) -> dict:
        with self._lock:
            s, self._samples = self._samples, []
        if not s:
            return {"min": 0, "max": 0, "avg": 0, "sum": 0, "cnt": 0,
                    "p50": 0, "p75": 0, "p90": 0, "p95": 0, "p99": 0,
                    "p99_99": 0}
        a = np.asarray(s)
        q = np.percentile(a, [50, 75, 90, 95, 99, 99.99])
        return {"min": int(a.min()), "max": int(a.max()),
                "avg": int(a.mean()), "sum": int(a.sum()), "cnt": len(s),
                "p50": int(q[0]), "p75": int(q[1]), "p90": int(q[2]),
                "p95": int(q[3]), "p99": int(q[4]), "p99_99": int(q[5])}


class StatsCollector:
    """Aggregates counters from the client and renders the stats JSON."""

    def __init__(self, rk: "Kafka"):
        self.rk = rk
        self.ts_start = time.time()
        self.c_tx_msgs = 0
        self.c_rx_msgs = 0
        self.int_latency = Avg()      # produce() -> MessageSet write
        self.codec_latency = Avg()    # batched codec provider call

    def emit_json(self) -> str:
        rk = self.rk
        brokers = {}
        for b in list(rk.brokers.values()):
            brokers[b.name] = {
                "name": b.name, "nodeid": b.nodeid, "state": b.state.value,
                "tx": b.c_tx, "txbytes": b.c_tx_bytes,
                "rx": b.c_rx, "rxbytes": b.c_rx_bytes,
                "req_timeouts": b.c_req_timeouts,
                "toppars": {f"{tp.topic}-{tp.partition}":
                            {"topic": tp.topic, "partition": tp.partition}
                            for tp in list(b.toppars)},
            }
        topics = {}
        for (t, p), tp in list(rk._toppars.items()):
            topics.setdefault(t, {"topic": t, "partitions": {}})
            topics[t]["partitions"][str(p)] = {
                "partition": p, "leader": tp.leader_id,
                "msgq_cnt": len(tp.msgq), "xmit_msgq_cnt": len(tp.xmit_msgq),
                "fetchq_cnt": tp.fetchq_cnt,
                "fetch_state": tp.fetch_state.value,
                "app_offset": tp.app_offset,
                "stored_offset": tp.stored_offset,
                "committed_offset": tp.committed_offset,
                "hi_offset": tp.hi_offset,
            }
        blob = {
            "name": rk.conf.get("client.id"),
            "client_id": rk.conf.get("client.id"),
            "type": rk.type,
            "ts": int(time.time() * 1e6),
            "time": int(time.time()),
            "age": int((time.time() - self.ts_start) * 1e6),
            "msg_cnt": rk.msg_cnt,
            "msg_max": rk.conf.get("queue.buffering.max.messages"),
            "txmsgs": self.c_tx_msgs, "rxmsgs": self.c_rx_msgs,
            "int_latency": self.int_latency.rollover(),
            "codec_latency": self.codec_latency.rollover(),
            "brokers": brokers,
            "topics": topics,
        }
        if rk.cgrp is not None:
            blob["cgrp"] = {"state": rk.cgrp.join_state,
                            "rebalance_cnt": rk.cgrp.rebalance_cnt,
                            "assignment_size": len(rk.cgrp.assignment)}
        if rk.idemp is not None:
            blob["eos"] = {"idemp_state": rk.idemp.state,
                           "producer_id": rk.idemp.pid,
                           "producer_epoch": rk.idemp.epoch}
        return json.dumps(blob)
