"""Statistics: windowed averages with percentiles + the JSON stats blob.

Reference: rd_avg_t (src/rdavg.h) over HdrHistogram (rdhdrhistogram.c),
emitted by rd_kafka_stats_emit_all (rdkafka.c:1473-1700) every
statistics.interval.ms with the schema documented in STATISTICS.md.
"""
from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING

from ..utils.hdrhistogram import HdrHistogram
from ..analysis.locks import new_lock
from ..analysis.races import register_slots, shared
from ..obs import metrics as _metrics

if TYPE_CHECKING:
    from .kafka import Kafka

#: live stats-emit timers by id() (registered by Kafka.__init__ when
#: statistics.interval.ms > 0, removed at close); the conftest autouse
#: leak fixture fails any test whose client left one behind — a leaked
#: emitter means close() never ran or lost the timer handle
_ACTIVE_STATS_TIMERS: set[int] = set()


class Avg:
    """Windowed HdrHistogram with rollover (reference: rd_avg_t,
    rdavg.h:37-165 — values accumulate into the current window; the
    stats emitter rolls the window over and renders min/avg/max +
    p50..p99.99, rdkafka.c:1582-1630). O(1) record, constant memory."""

    __slots__ = ("_hist", "_lock")

    #: STATISTICS.md percentile fields
    PCTS = ((50, "p50"), (75, "p75"), (90, "p90"), (95, "p95"),
            (99, "p99"), (99.99, "p99_99"))

    def __init__(self, lowest: int = 1, highest: int = 60_000_000,
                 sigfigs: int = 3):
        self._hist = HdrHistogram(lowest, highest, sigfigs)
        self._lock = new_lock("stats.avg")

    def add(self, v: float):
        with self._lock:
            self._hist.record(int(v))

    def rollover(self) -> dict:
        with self._lock:
            h = self._hist
            vals, stddev = h.snapshot([p for p, _ in self.PCTS])
            out = {"min": h.min_v, "max": h.max_v,
                   "avg": int(h.mean()), "sum": h.sum_v, "cnt": h.total,
                   "stddev": int(stddev),
                   "hdrsize": h.memsize,
                   "outofrange": h.out_of_range}
            for (pct, name), v in zip(self.PCTS, vals):
                out[name] = v
            h.reset()
        return out


# every histogram touch — record from app/broker/codec threads,
# rollover from the stats emitter — holds stats.avg (analysis/races.py
# verifies the discipline; the slot form because Avg is __slots__)
register_slots(Avg, "_hist", prefix="stats.avg")


class StatsCollector:
    """Aggregates counters from the client and renders the stats JSON."""

    # txmsgs/rxmsgs are bumped from broker ack paths and the consumer
    # poll loop while the emitter timer reads them — all under
    # stats.counters since ISSUE 10 (the --races sweep convicted the
    # old bare ``+=`` against the emitter's read; it also surfaced
    # that c_tx_msgs was never bumped at all — txmsgs sat at 0)
    c_tx_msgs = shared("stats.c_tx_msgs")
    c_rx_msgs = shared("stats.c_rx_msgs")

    def __init__(self, rk: "Kafka"):
        self.rk = rk
        self.ts_start = time.time()
        self._clock = new_lock("stats.counters")
        self.c_tx_msgs = 0
        self.c_rx_msgs = 0
        self.int_latency = Avg()      # produce() -> MessageSet write
        self.codec_latency = Avg()    # batched codec provider call

    def add_tx(self, n: int) -> None:
        """Count ``n`` successfully produced (acked) messages."""
        with self._clock:
            self.c_tx_msgs += n

    def add_rx(self, n: int) -> None:
        """Count ``n`` messages delivered to the consumer app."""
        with self._clock:
            self.c_rx_msgs += n

    def emit_json(self) -> str:
        rk = self.rk
        brokers = {}
        # ONE active-toppar snapshot feeds both the per-broker toppar
        # maps and the topics{} tree: the emitter is O(active), never
        # O(registered) — a 100k-partition topic in the metadata cache
        # costs the stats timer nothing (ISSUE 14)
        active = rk.active_toppars()
        with rk._brokers_lock:
            rk_brokers = list(rk.brokers.values())
        for b in rk_brokers:
            brokers[b.name] = {
                "name": b.name, "nodeid": b.nodeid, "state": b.state.value,
                "stateage": int((time.monotonic() - b.ts_state) * 1e6),
                "connects": b.c_connects,
                "outbuf_cnt": len(b._unsent_req_ends),
                "waitresp_cnt": len(b.waitresp),
                "tx": b.c_tx, "txbytes": b.c_tx_bytes,
                "rx": b.c_rx, "rxbytes": b.c_rx_bytes,
                "req_timeouts": b.c_req_timeouts,
                # latency decomposition (STATISTICS.md broker window stats)
                "rtt": b.rtt_avg.rollover(),
                "outbuf_latency": b.outbuf_avg.rollover(),
                "throttle": b.throttle_avg.rollover(),
                # consumer fetch pipeline: codec-ticket submit -> reap
                # (the _PendingFetch window PR 2 added; ISSUE 5)
                "fetch_latency": b.fetch_latency_avg.rollover(),
                # KIP-227 session snapshot + fetch-API wire split
                # (ISSUE 14): the bench reads these to prove on-wire
                # savings; partitions_sent/partitions_total give the
                # incremental ratio
                "fetch_session": {**b._fetch_session.stats(),
                                  "tx_bytes": b.c_fetch_tx_bytes,
                                  "rx_bytes": b.c_fetch_rx_bytes},
                "toppars": {f"{tp.topic}-{tp.partition}":
                            {"topic": tp.topic, "partition": tp.partition}
                            for tp in active if tp in b.toppars},
            }
        topics = {}
        for tp in active:
            t, p = tp.topic, tp.partition
            topics.setdefault(t, {"topic": t, "partitions": {}})
            # reference lag (rdkafka.c:1283-1297): end_offset (ls under
            # read_committed) minus MAX(app, committed), clamped >= 0
            end = (tp.ls_offset if rk.conf.get("isolation.level")
                   == "read_committed" and tp.ls_offset >= 0
                   else tp.hi_offset)
            base = max(tp.app_offset, tp.committed_offset)
            lag = max(0, end - base) if end >= 0 and base >= 0 else -1
            # queue gauges under the toppar lock: the app enqueues and
            # the broker drains while the emitter reads (the --races
            # sweep flagged the old lock-free len()/int peeks against
            # kafka.toppar-guarded writes)
            with tp.lock:
                msgq_cnt = (len(tp.msgq)
                            + (len(tp.arena) if tp.arena is not None
                               else 0))
                msgq_bytes = tp.msgq_bytes
                xmit_cnt = len(tp.xmit_msgq)
                fetchq_cnt = tp.fetchq_cnt
            topics[t]["partitions"][str(p)] = {
                "partition": p, "leader": tp.leader_id,
                "msgq_cnt": msgq_cnt,
                "msgq_bytes": msgq_bytes,
                "xmit_msgq_cnt": xmit_cnt,
                "fetchq_cnt": fetchq_cnt,
                "fetch_state": tp.fetch_state.value,
                "app_offset": tp.app_offset,
                "stored_offset": tp.stored_offset,
                "committed_offset": tp.committed_offset,
                "hi_offset": tp.hi_offset,
                "ls_offset": tp.ls_offset,
                "consumer_lag": lag,
            }
        with rk._metadata_lock:
            metadata_cache_cnt = len(rk.metadata.get("topics", {}))
        with self._clock:
            txmsgs, rxmsgs = self.c_tx_msgs, self.c_rx_msgs
        blob = {
            "name": rk.conf.get("client.id"),
            "client_id": rk.conf.get("client.id"),
            "type": rk.type,
            "ts": int(time.time() * 1e6),
            "time": int(time.time()),
            "age": int((time.time() - self.ts_start) * 1e6),
            "replyq": len(rk.rep),
            "msg_cnt": rk.msg_cnt,
            "msg_size": rk.msg_bytes,
            "msg_max": rk.conf.get("queue.buffering.max.messages"),
            "msg_size_max":
                rk.conf.get("queue.buffering.max.kbytes") * 1024,
            "tx": sum(b["tx"] for b in brokers.values()),
            "tx_bytes": sum(b["txbytes"] for b in brokers.values()),
            "rx": sum(b["rx"] for b in brokers.values()),
            "rx_bytes": sum(b["rxbytes"] for b in brokers.values()),
            # Fetch-API bytes (both directions) across brokers: the
            # incremental-session savings gauge (ISSUE 14)
            "wire_fetch_bytes": sum(
                b["fetch_session"]["tx_bytes"]
                + b["fetch_session"]["rx_bytes"]
                for b in brokers.values()),
            "metadata_cache_cnt": metadata_cache_cnt,
            "txmsgs": txmsgs, "rxmsgs": rxmsgs,
            "int_latency": self.int_latency.rollover(),
            "codec_latency": self.codec_latency.rollover(),
            "brokers": brokers,
            "topics": topics,
            # unified metrics registry (ISSUE 20): every process-wide
            # counter/gauge/window any subsystem registered — always
            # present (a disabled registry snapshots as empty maps) so
            # stats consumers never branch on its existence
            "obs": _metrics.snapshot(),
        }
        if rk.type == "producer":
            # fast-lane engagement: cumulative native-lane appends plus
            # the per-reason fallback/demotion breakdown — "workloads
            # actually ride it" is machine-checkable (ISSUE 16)
            with rk._msg_cnt_lock:
                demoted = dict(rk._demote_reasons)
            blob["arena"] = {**rk._lane.counters(), "demoted": demoted}
        # adaptive offload governor decisions (ISSUE 3): launch /
        # merge / fallback / warmup counters plus the cost-model gauges
        # from the async engine, when the tpu backend has spun one up
        eng = getattr(rk.codec_provider, "_engine", None)
        if eng is not None:
            blob["codec_engine"] = {
                **eng.stats,
                "governor": eng.governor_snapshot(),
                # per-stage latency decomposition + pipeline-occupancy
                # gauges (ISSUE 5; STATISTICS.md codec_engine section)
                "stage_latency": eng.stage_latency_snapshot(),
                "gauges": eng.gauges_snapshot(),
                # per-device dispatch lanes (ISSUE 6): launch counts,
                # in-flight depth, launch-time EWMAs and warm-kernel
                # count per mesh device (STATISTICS.md
                # codec_engine.devices[])
                "devices": eng.devices_snapshot(),
                # device compress route (ISSUE 17): fused launch /
                # routed-per-bucket / bytes counters, the governor's
                # compress cost model, and per-topic QoS routed/shed
                # tallies (STATISTICS.md codec_engine.compress)
                "compress": eng.compress_snapshot()}
        if rk.cgrp is not None:
            cg = rk.cgrp
            with cg._lock:
                assignment_size = len(cg.assignment)
                incremental_revokes = cg.incremental_revoke_cnt
            # stuck partitions: assigned but not fetching (NONE /
            # STOPPED after the rebalance settled) — steady state must
            # read 0, the stats-level echo of the chaos continuity
            # invariant (ISSUE 12)
            stuck = 0
            consumer = getattr(rk, "consumer", None)
            if consumer is not None:
                from .partition import FetchState
                for tp in list(consumer._assignment.values()):
                    if tp.fetch_state in (FetchState.NONE,
                                          FetchState.STOPPED):
                        stuck += 1
            blob["cgrp"] = {"state": cg.join_state,
                            "rebalance_cnt": cg.rebalance_cnt,
                            "assignment_size": assignment_size,
                            "rebalance_proto": cg.rebalance_protocol,
                            "incremental_revokes": incremental_revokes,
                            "stuck_partitions": stuck}
        if rk.idemp is not None:
            blob["eos"] = {"idemp_state": rk.idemp.state,
                           "producer_id": rk.idemp.pid,
                           "producer_epoch": rk.idemp.epoch}
            if rk.txnmgr is not None:
                # transactional FSM snapshot (STATISTICS.md eos blob)
                blob["eos"].update({
                    "txn_state": rk.txnmgr.state,
                    "transactional_id": rk.txnmgr.transactional_id,
                    "txn_registered_partitions":
                        len(rk.txnmgr._registered),
                    "txn_coordinator": (rk.txnmgr.coord_id
                                        if rk.txnmgr.coord_id is not None
                                        else -1)})
        return json.dumps(blob)
