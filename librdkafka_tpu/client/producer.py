"""Public Producer API (reference: rd_kafka_producev / rd_kafka_produce,
src/rdkafka_msg.c:241-478, plus flush/purge from rdkafka.c)."""
from __future__ import annotations

from typing import Optional

from .conf import Conf
from .kafka import Kafka, PRODUCER
from .msg import PARTITION_UA


class Producer:
    """
    >>> p = Producer({"bootstrap.servers": "...", "linger.ms": 5})
    >>> p.produce("topic", b"value", key=b"k", on_delivery=cb)
    >>> p.flush()
    """

    def __init__(self, conf):
        if isinstance(conf, dict):
            c = Conf()
            dr = conf.pop("on_delivery", None)
            c.update(conf)
            if dr:
                c.set("dr_msg_cb", dr)
            conf = c
        self._rk = Kafka(conf, PRODUCER)
        # bound-method alias: produce() goes straight to the client hot
        # path (str encoding + on_delivery handled there)
        self.produce = self._rk.produce

    def io_event_enable(self, fd: int, payload: bytes = b"1") -> None:
        """select()/epoll() integration: every op landing on the reply
        queue (DRs, errors, stats) writes ``payload`` to ``fd``
        (reference: rd_kafka_queue_io_event_enable on the main queue)."""
        self._rk.rep.io_event_enable(fd, payload)

    def list_topics(self, timeout: float = 10.0) -> dict:
        """rd_kafka_metadata analog: full cluster metadata snapshot."""
        return self._rk.list_topics(timeout)

    def cluster_id(self, timeout: float = 5.0):
        """rd_kafka_clusterid analog."""
        return self._rk.cluster_id(timeout)

    def controller_id(self, timeout: float = 5.0) -> int:
        """rd_kafka_controllerid analog."""
        return self._rk.controller_id(timeout)

    def set_topic_conf(self, topic: str, conf: dict) -> None:
        """Per-topic configuration override (rd_kafka_topic_new analog):
        e.g. {'compression.codec': 'snappy'} for one topic."""
        self._rk.set_topic_conf(topic, conf)

    def produce_batch(self, topic: str, msgs: list[dict],
                      partition: int = PARTITION_UA) -> int:
        """Batch produce (reference: rd_kafka_produce_batch,
        rdkafka_msg.c:478). Returns the number enqueued; like the
        reference sets ``rkmessages[i].err``, each failed input dict
        gets an ``"error"`` key with the per-message KafkaError (e.g.
        MSG_SIZE_TOO_LARGE, _QUEUE_FULL) instead of being silently
        dropped."""
        from .errors import Err, KafkaError, KafkaException

        # per-message errors are recorded INTO the dicts; validate the
        # shape up front so a stray non-dict fails fast instead of
        # aborting the batch midway with no error recorded
        for m in msgs:
            if not isinstance(m, dict):
                raise TypeError(
                    f"produce_batch messages must be dicts, got "
                    f"{type(m).__name__}")
        n = 0
        i = 0
        lane = self._rk._lane
        batch_c = getattr(lane, "produce_batch", None)
        total = len(msgs)
        while i < total:
            if batch_c is not None and isinstance(msgs, list):
                # native run: eligible records append straight into
                # their arenas with no Python frame per record; the C
                # side stops at the first item needing the per-item
                # path below — which itself stays on the (widened)
                # fast lane for explicit timestamps, headers, and
                # murmur2 auto-partition via Kafka._produce_slow
                nxt, appended = batch_c(topic, msgs, i, partition)
                n += appended
                i = nxt
                if i >= total:
                    break
            m = msgs[i]
            i += 1
            try:
                self.produce(topic, value=m.get("value"), key=m.get("key"),
                             partition=m.get("partition", partition),
                             headers=m.get("headers", ()),
                             timestamp=m.get("timestamp", 0))
                n += 1
                m.pop("error", None)
            except KafkaException as e:
                m["error"] = e.error
            except Exception as e:
                m["error"] = KafkaError(Err._FAIL, repr(e))
        return n

    # ------------------------------------------------------ transactions --
    def _txnmgr(self):
        from .errors import Err, KafkaException
        t = self._rk.txnmgr
        if t is None:
            raise KafkaException(
                Err._NOT_IMPLEMENTED,
                "transactional API requires transactional.id to be "
                "configured")
        return t

    def init_transactions(self, timeout: float = -1) -> None:
        """Acquire the transactional (pid, epoch) from the transaction
        coordinator; fences any previous instance of the same
        transactional.id (rd_kafka_init_transactions analog). Must be
        called once before the first begin_transaction()."""
        self._txnmgr().init_transactions(timeout)

    def begin_transaction(self) -> None:
        """Start a transaction; all following produce() calls and
        send_offsets_to_transaction() belong to it until
        commit_transaction()/abort_transaction()."""
        self._txnmgr().begin_transaction()

    def send_offsets_to_transaction(self, offsets, group_metadata,
                                    timeout: float = -1) -> None:
        """Commit consumed offsets atomically with this transaction
        (EOS consume-transform-produce). ``offsets`` is a list of
        TopicPartition with .offset; ``group_metadata`` is a
        Consumer.consumer_group_metadata() object or a group id str."""
        self._txnmgr().send_offsets_to_transaction(offsets, group_metadata,
                                                   timeout)

    def commit_transaction(self, timeout: float = -1) -> None:
        """Flush all in-flight messages, then commit the transaction
        (the coordinator writes COMMIT markers into every registered
        partition)."""
        self._txnmgr().commit_transaction(timeout)

    def abort_transaction(self, timeout: float = -1) -> None:
        """Purge queued messages, drain in-flight ones, then abort the
        transaction (ABORT markers make everything produced in it
        invisible to read_committed consumers)."""
        self._txnmgr().abort_transaction(timeout)

    def poll(self, timeout: float = 0.0) -> int:
        return self._rk.poll(timeout)

    def flush(self, timeout: float = 10.0) -> int:
        return self._rk.flush(timeout)

    def purge(self, in_queue: bool = True, in_flight: bool = False) -> None:
        self._rk.purge(in_queue, in_flight)

    def __len__(self) -> int:
        # rd_kafka_outq_len semantics: unacked messages PLUS undelivered
        # delivery-report ops (rdkafka.c:3905) — the documented
        # `while len(p): p.poll(...)` drain pattern must not exit while
        # DR callbacks are still queued
        return self._rk.outq_len

    def close(self, timeout: float = 5.0):
        self._rk.close(timeout)

    def trace_dump(self, path: str) -> int:
        """Export the flight-recorder trace rings as Chrome trace-event
        JSON (trace.enable=true; see TRACING.md)."""
        return self._rk.trace_dump(path)

    # escape hatch for tests / advanced use
    @property
    def rk(self) -> Kafka:
        return self._rk
