"""Event API (reference: src/rdkafka_event.c, 314 LoC).

The reference exposes internal ops as polymorphic ``rd_kafka_event_t``
objects the app polls from a queue (``rd_kafka_event_type``,
rdkafka_event.c:33) as an alternative to callback dispatch; an optional
**background thread** (src/rdkafka_background.c:109, created
rdkafka.c:2189-2196) serves an app-registered event callback off its
own queue so the app never has to poll.

Here: :class:`Event` wraps an internal Op (events ARE ops in the
reference too), ``Kafka.queue_poll()`` pops typed events from the reply
queue, and setting the ``background_event_cb`` conf property spawns the
background thread at client creation.
"""
from __future__ import annotations

import threading
from typing import Optional, TYPE_CHECKING

from .queue import Op, OpQueue, OpType

if TYPE_CHECKING:
    from .kafka import Kafka


#: rd_kafka_event_type_t analog
EVENT_NONE = "NONE"
EVENT_DR = "DR"
EVENT_ERROR = "ERROR"
EVENT_LOG = "LOG"
EVENT_STATS = "STATS"
EVENT_FETCH = "FETCH"
EVENT_REBALANCE = "REBALANCE"
EVENT_OFFSET_COMMIT = "OFFSET_COMMIT"
EVENT_OAUTHBEARER_TOKEN_REFRESH = "OAUTHBEARER_TOKEN_REFRESH"
EVENT_THROTTLE = "THROTTLE"

_OP_TO_EVENT = {
    OpType.DR: EVENT_DR,
    OpType.ERR: EVENT_ERROR,
    OpType.CONSUMER_ERR: EVENT_ERROR,
    OpType.LOG: EVENT_LOG,
    OpType.STATS: EVENT_STATS,
    OpType.FETCH: EVENT_FETCH,
    OpType.REBALANCE: EVENT_REBALANCE,
    OpType.OFFSET_COMMIT: EVENT_OFFSET_COMMIT,
    OpType.OAUTHBEARER_REFRESH: EVENT_OAUTHBEARER_TOKEN_REFRESH,
    OpType.THROTTLE: EVENT_THROTTLE,
}


class Event:
    """Polymorphic event (rd_kafka_event_t): one Op viewed through the
    event-type accessors. Accessors return None when the event is not
    of the matching type, like the reference's NULL returns."""

    __slots__ = ("op",)

    def __init__(self, op: Op):
        self.op = op

    @property
    def type(self) -> str:
        return _OP_TO_EVENT.get(self.op.type, EVENT_NONE)

    # ------------------------------------------------------- accessors ---
    def messages(self) -> list:
        """DR: the acked/failed messages (rd_kafka_event_message_array).
        FETCH: the consumed message batch."""
        if self.op.type == OpType.DR:
            return list(self.op.payload)
        if self.op.type == OpType.FETCH:
            return list(self.op.payload[1])
        return []

    def error(self):
        """ERROR: the KafkaError (rd_kafka_event_error)."""
        if self.op.type == OpType.ERR:
            return self.op.payload
        if self.op.type == OpType.CONSUMER_ERR:
            return self.op.payload[1].error
        return None

    def stats(self) -> Optional[str]:
        """STATS: the JSON blob (rd_kafka_event_stats)."""
        return self.op.payload if self.op.type == OpType.STATS else None

    def log(self) -> Optional[tuple]:
        """LOG: (level, fac, message) (rd_kafka_event_log)."""
        return self.op.payload if self.op.type == OpType.LOG else None

    def throttle(self) -> Optional[tuple]:
        """THROTTLE: (broker_name, broker_id, throttle_ms)
        (rd_kafka_event_throttle_time et al.)."""
        return (self.op.payload if self.op.type == OpType.THROTTLE
                else None)

    def rebalance(self) -> Optional[tuple]:
        """REBALANCE: (err_code, {topic: [partitions]})."""
        return (self.op.payload if self.op.type == OpType.REBALANCE
                else None)

    def __repr__(self):
        return f"Event({self.type})"


class BackgroundThread:  # lint: ok shared-state
    # shared-state pragma: the only cross-thread surfaces are the
    # forwarded OpQueue (declared in queue.py) and a threading.Event.
    """The background event-serving thread (rdkafka_background.c:109):
    the reply queue is forwarded to a private queue served by this
    thread, which invokes the app's ``background_event_cb`` for every
    event — the app never needs to poll."""

    def __init__(self, rk: "Kafka", event_cb):
        self.rk = rk
        self.event_cb = event_cb
        self.queue = OpQueue("background")
        rk.rep.forward_to(self.queue)
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._main,
                                       name="rdk:background", daemon=True)
        self.thread.start()

    def _main(self):
        while not self._stop.is_set():
            op = self.queue.pop(0.1)
            if op is None:
                continue
            try:
                self.event_cb(Event(op))
            except Exception as e:
                self.rk.log("ERROR", f"background_event_cb raised: {e!r}")
            finally:
                if op.type == OpType.DR:
                    self.rk._dr_served(len(op.payload))

    def stop(self):
        self._stop.set()
        self.thread.join(timeout=2.0)
