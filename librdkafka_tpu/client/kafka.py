"""The client handle (reference: rd_kafka_t, src/rdkafka.c).

Owns configuration, the broker set, topics/toppars, the metadata cache,
the reply ("rep") queue the app polls, and the main thread
(rd_kafka_thread_main, rdkafka.c:1834) that drives timers: metadata
refresh, message timeout scans, stats emission, cgrp serving, and
unassigned-partition migration.
"""
from __future__ import annotations

import json
import random
import socket
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..analysis import lockdep as _lockdep
from ..analysis import races as _races
from ..analysis.races import shared
from ..analysis.locks import new_cond, new_lock
from ..obs import trace as _trace
from ..protocol import apis, proto
from ..protocol.msgset import (iter_batches, parse_fetch_messages_v2,
                               parse_msgset_v01, parse_records_v2,
                               verify_crc_v2)
from ..protocol.proto import ApiKey
from ..utils.hash import murmur2_partition
from .arena import (ArenaBatch, arena_new, batch_msgids, decode_hblob,
                    encode_headers, lane_new)
from .broker import Broker, Request
from .conf import Conf, TopicConf
from .errors import Err, KafkaError, KafkaException
from .msg import (FetchMessage, Message, MsgStatus, PARTITION_UA,
                  partitioner_fn)
from .partition import FetchState, Toppar
from .queue import Op, OpQueue, OpType, Timers

PRODUCER, CONSUMER = "producer", "consumer"


class Topic:  # lint: ok shared-state
    """rd_kafka_itopic_t analog: per-topic state + UA message parking.

    shared-state pragma: UA parking and partition_cnt are mutated only
    on rdk:main (metadata/partitioner paths) under ``self.lock``; the
    cross-thread surfaces live at the Toppar/OpQueue level, both
    declared there."""

    def __init__(self, name: str, tconf: TopicConf):
        self.name = name
        self.conf = tconf
        self.partition_cnt = -1
        self.ua_msgq: deque[Message] = deque()   # parked until metadata
        self.partitioner = partitioner_fn(tconf.get("partitioner"))
        self.lock = new_lock("kafka.topic")


class IdempotenceManager:
    """EOS v1 producer-id state machine (reference:
    src/rdkafka_idempotence.c — REQ_PID→WAIT_PID→ASSIGNED, drain+epoch-bump
    recovery at :347-440)."""

    # relaxed: the FSM is single-writer (rdk:main serve loop under
    # kafka.idemp); can_produce() on the produce fast path and the
    # stats emitter read lock-free — str/int snapshots, atomic under
    # the GIL, and a stale read only delays a produce by one serve pass
    state = shared("kafka.idemp.state", relaxed=True)
    pid = shared("kafka.idemp.pid", relaxed=True)
    epoch = shared("kafka.idemp.epoch", relaxed=True)

    def __init__(self, rk: "Kafka"):
        self.rk = rk
        self.state = "INIT"
        self.pid = -1
        self.epoch = -1
        self._lock = new_lock("kafka.idemp")

    def can_produce(self) -> bool:
        return self.state == "ASSIGNED"

    def serve(self):
        with self._lock:
            if self.state == "DRAIN":
                # wait for every in-flight ProduceRequest to resolve, then
                # rebase each toppar's sequence origin to its oldest
                # unacked message and fetch a fresh PID (reference
                # DRAIN_BUMP → REQ_PID, rdkafka_idempotence.c:374-440)
                with self.rk._toppars_lock:
                    tps = list(self.rk._toppars.values())
                for t in tps:
                    with t.lock:
                        # inflight must be observed atomically with the
                        # queue scan: broker threads pop a batch and
                        # claim inflight under this same lock, so per
                        # toppar either the pop already happened
                        # (inflight > 0 → wait) or the batch is still
                        # queued and counted in `pending` below.
                        # Fast-lane arena records hold NO msgids yet
                        # (assigned at take()): they will draw ids from
                        # next_msgid onward, which the default already
                        # rebases to.
                        if t.inflight > 0:
                            return
                        pending = []
                        for b in t.retry_batches:
                            pending += batch_msgids(b)
                        pending += [m.msgid for m in t.xmit_msgq]
                        pending += [m.msgid for m in t.msgq]
                        t.epoch_base_msgid = (
                            min(pending, default=t.next_msgid) - 1)
                self.state = "INIT"
            if self.state in ("INIT", "RETRY"):
                broker = self.rk.any_up_broker()
                if broker is None:
                    return
                self.state = "WAIT_PID"
                broker.enqueue_request(Request(
                    ApiKey.InitProducerId,
                    {"transactional_id": None,
                     "transaction_timeout_ms": 60000},
                    retries_left=3, cb=self._handle_pid))

    def _handle_pid(self, err, resp):
        with self._lock:
            if self.state != "WAIT_PID":
                return          # a drain was requested while in flight
            if err is not None or resp["error_code"] != 0:
                self.state = "RETRY"
                return
            self.pid = resp["producer_id"]
            self.epoch = resp["producer_epoch"]
            self.state = "ASSIGNED"
            self.rk.dbg("eos", f"assigned PID {self.pid} epoch {self.epoch}")

    def drain_epoch_bump(self, reason: str):
        """Enter DRAIN: stop producing; serve() acquires a new PID and
        rebases sequence origins once every in-flight request has
        resolved (reference DRAIN_BUMP, rdkafka_idempotence.c:374-440).
        Used for recoverable gaps the broker never saw (e.g. messages
        timing out locally, rdkafka_broker.c:3291-3309) — NOT for
        head-of-line sequence desync, which is fatal."""
        if self.rk.txnmgr is not None:
            # transactional mode: the txn manager owns the epoch
            # lifecycle (gaps surface as abortable errors; the
            # post-abort InitProducerId bumps the epoch and rebases)
            return
        with self._lock:
            if self.state in ("ASSIGNED", "WAIT_PID"):
                self.rk.dbg("eos", f"drain+epoch bump: {reason}")
                self.state = "DRAIN"


class Kafka:  # lint: ok shared-state
    """Client instance; create via Producer() or Consumer().

    shared-state pragma: the client's cross-thread surfaces are
    declared at their owning layers (OpQueue, Toppar, Broker,
    StatsCollector, the offload engine); the handful of fields below
    that genuinely cross threads are declared individually."""

    # outstanding-count accounting crosses app + broker + codec
    # threads, all under kafka.msg_cnt (the flush() contract)
    dr_cnt = shared("kafka.dr_cnt")
    flushing = shared("kafka.flushing")
    # metadata cache: mutations happen under kafka.metadata on
    # rdk:main; declared so the sweep sees its access pattern
    metadata = shared("kafka.metadata_cache")
    # fast-lane demotion breakdown: RMW'd under kafka.msg_cnt from the
    # app thread (_produce_slow/_partition_and_enq) AND the broker
    # serve thread (concurrent-append race demote); the stats emitter
    # snapshot-reads it
    _demote_reasons = shared("kafka.demote_reasons")

    def __init__(self, conf: Conf, client_type: str):
        self.conf = conf
        self.type = client_type
        # lockdep (analysis/lockdep.py, ANALYSIS.md): must engage
        # BEFORE the first lock below exists — the factory picks plain
        # vs instrumented per object at creation time.  Refcounted like
        # the tracer; released at close().
        self._lockdep_ref = False
        if conf.get("analysis.lockdep"):
            _lockdep.enable()
            self._lockdep_ref = True
        # lockset race detector (analysis/races.py): installs the
        # Guarded descriptors on every declared class and holds a
        # lockdep reference (locksets come from its held-stack) — also
        # before the first lock/container below exists
        self._races_ref = False
        if conf.get("analysis.races"):
            _races.enable()
            self._races_ref = True
        self.is_producer = client_type == PRODUCER
        self.is_consumer = client_type == CONSUMER
        self.rep = OpQueue("rk_rep")          # app-facing reply queue
        self.ops = OpQueue("rk_ops")
        self.timers = Timers()
        self.brokers: dict[int, Broker] = {}
        self._bootstrap: list[Broker] = []
        self._brokers_lock = new_lock("kafka.brokers")
        self.topics: dict[str, Topic] = {}
        self._topics_lock = new_lock("kafka.topics")
        self._toppars: dict[tuple[str, int], Toppar] = {}
        self._toppars_lock = new_lock("kafka.toppars")
        # ACTIVE toppars: produced-to or consumer-started partitions.
        # Metadata registration alone creates Toppar objects for EVERY
        # partition of every known topic — a 100k-partition topic means
        # 100k registered toppars — so anything periodic (stats emit,
        # queued-fetch-bytes sums, the consumer serve scan) iterates
        # THIS index, O(active), never _toppars.  Guarded by
        # _toppars_lock; membership mirrored in tp.stats_active for the
        # lock-free hot-path check.
        self._active_toppars: dict[tuple[str, int], Toppar] = {}
        self.metadata: dict = {"brokers": {}, "topics": {}}
        self._metadata_lock = new_lock("kafka.metadata")
        # notified (under _metadata_lock) after every metadata cache
        # update; sync callers (list_topics, offsets_for_times leader
        # wait) block here instead of sleep-polling (reference pattern:
        # replyq pop in rd_kafka_metadata, rdkafka.c)
        self._metadata_cond = new_cond("kafka.metadata",
                                       self._metadata_lock)
        self._metadata_inflight = False
        self._metadata_refresh_queued = False
        self._metadata_full_ts = 0.0   # completion time of last FULL refresh
        self._fast_refresh_scheduled = False
        self._addr_cache: dict = {}        # broker.address.ttl DNS cache
        self._purge_epoch = 0              # invalidates in-pipeline batches
        self._metadata_topic_ts: dict = {}  # topic -> last metadata time
        self.flushing = False
        self.terminating = False
        self.fatal_error: Optional[KafkaError] = None
        # Queue accounting lives in the enqueue lane (native when the
        # extension builds): C produce() updates the counters atomically
        # under the GIL; Python paths go through lane.acct().  msg_cnt /
        # msg_bytes remain readable as properties.
        self._lane = lane_new()
        # DR ops pushed to the reply queue but not yet served to the app.
        # flush() must wait on msg_cnt + dr_cnt, like the reference's
        # rd_kafka_outq_len which counts undelivered DR ops
        # (rdkafka.c:3905) — otherwise flush() can return between the
        # msg_cnt decrement and the DR callback, losing the report to a
        # post-flush close.
        self.dr_cnt = 0
        # serializes COMPOUND transitions (msg_cnt release + dr_cnt
        # claim) against flush()'s combined read
        self._msg_cnt_lock = new_lock("kafka.msg_cnt")
        # flush() blocks here in DR-event mode; outstanding-count
        # decrements notify it only while flushing is set (one bool
        # check on the hot path, no wakeups otherwise)
        self._outq_cond = new_cond("kafka.msg_cnt", self._msg_cnt_lock)
        self.cgrp = None                       # set by Consumer
        self.consumer = None                   # back-ref set by Consumer
        self.interceptors = conf.get("interceptors") or None
        self.mock_cluster = None
        self.stats = None                      # StatsCollector, set below
        # flight-recorder tracing (obs/trace.py, TRACING.md): the
        # module-level tracer is refcounted — this client holds one
        # reference while trace.enable is set, released at close()
        self._trace_ref = False
        if conf.get("trace.enable"):
            _trace.enable(ring=conf.get("trace.ring.events"),
                          on_fatal=conf.get("trace.dump.on.fatal"))
            self._trace_ref = True
        self.debug_contexts = set(conf.get("debug"))
        # debug contexts force DEBUG visibility (the reference raises
        # log_level to 7 whenever debug is set, rd_kafka_conf_finalize)
        self._log_level = (7 if self.debug_contexts
                           else conf.get("log_level"))
        self.log_cb = conf.get("log_cb")
        # topic.blacklist (reference rdkafka_pattern.c blacklist list):
        # matching topics are invisible to metadata/subscriptions
        import re as _re
        self._blacklist = [_re.compile(pat if pat.startswith("^") else
                                       "^" + _re.escape(pat) + "$")
                           for pat in conf.get("topic.blacklist")]

        # native enqueue fast lane (client/arena.py): engaged per call
        # when there are no DR consumers or interceptors — produce()
        # then marshals key/value into a per-toppar native arena in one
        # C call instead of building a Message object (the app-thread
        # GIL ceiling; reference zero-allocation enqueue rdkafka_msg.c)
        self._fast_lane_ver = -1          # recompute on conf mutation
        self._fast_lane = False
        # validated (topic, partition) -> Toppar with a live arena; one
        # dict hit replaces topic lookup + partition check + toppar
        # lookup on the produce hot path
        self._fast_tp: dict = {}
        # per-reason demotion counts (stats arena.demoted breakdown)
        self._demote_reasons: dict = {}
        # the lane's C produce() is the public entry point: eligible
        # records never touch a Python frame; everything else tails into
        # _produce_slow (the Message pipeline + first-sight setup)
        self._lane.configure(
            self._produce_slow, self._wake_leader,
            conf.get("queue.buffering.max.messages"),
            conf.get("queue.buffering.max.kbytes") * 1024,
            # also capped at message.max.bytes so oversize records always
            # reach the slow path's MSG_SIZE_TOO_LARGE check
            min(conf.get("message.copy.max.bytes"),
                conf.get("message.max.bytes")))
        self.produce = self._lane.produce
        conf.add_listener(self._recompute_fast_lane)
        self._recompute_fast_lane()

        # codec provider selection (compression.backend; SURVEY.md §7 st.5)
        backend = conf.get("compression.backend")
        if backend == "tpu":
            from ..ops.tpu import TpuCodecProvider
            self.codec_provider = TpuCodecProvider(
                min_batches=conf.get("tpu.launch.min.batches"),
                mesh_devices=conf.get("tpu.mesh.devices"),
                lz4_force=conf.get("tpu.lz4.force"),
                min_transport_mb_s=conf.get("tpu.transport.min.mb.s"),
                pipeline_depth=conf.get("tpu.pipeline.depth"),
                fanin_us=conf.get("tpu.pipeline.fanin.us"),
                governor=conf.get("tpu.governor"),
                engine_warmup=conf.get("tpu.warmup"),
                compile_cache_dir=conf.get("tpu.compile.cache.dir"),
                compress_device=conf.get("tpu.compress.device"))
        else:
            from ..ops.cpu import CpuCodecProvider
            self.codec_provider = CpuCodecProvider()

        # transactional.id implies idempotence (the txn FSM layers over
        # the pid/epoch machinery; reference: rd_kafka_conf finalize
        # forces enable.idempotence for transactional producers)
        txn_id = conf.get("transactional.id") if self.is_producer else ""
        self.idemp = (IdempotenceManager(self)
                      if self.is_producer
                      and (conf.get("enable.idempotence") or txn_id)
                      else None)
        self.txnmgr = None
        if txn_id:
            from .txnmgr import TransactionManager
            self.txnmgr = TransactionManager(self)
            # the lane was computed before txnmgr existed; re-gate it
            # on the (UNINIT) txn state
            self._txn_lane_sync()

        # codec pipeline thread (codec.pipeline.depth; SURVEY.md §5
        # axis 2 — overlap batch build/socket IO with codec launches)
        self.codec_pipeline_depth = conf.get("codec.pipeline.depth")
        # consumer fetch codec pipeline: max _PendingFetch entries in
        # flight per broker (broker.py _serve_deferred_fetch)
        self.fetch_pipeline_depth = conf.get("tpu.fetch.pipeline.depth")
        self.codec_worker = None
        if self.is_producer and self.codec_pipeline_depth > 0:
            from .broker import CodecWorker
            self.codec_worker = CodecWorker(self)

        # OAUTHBEARER app-supplied token (set_oauthbearer_token; the
        # refresh flow of rdkafka_sasl_oauthbearer.c's
        # RD_KAFKA_OP_OAUTHBEARER_REFRESH machinery)
        self._oauth_token = None      # (token, principal, expiry_unix)
        self._oauth_failure = None
        self._oauth_timer = None
        self._oauth_cb_lock = new_lock("kafka.oauth_cb")

        # TLS context — one per instance, shared by all broker threads
        # (reference: rd_kafka_ssl_ctx_init, rdkafka_ssl.c)
        from . import tls as _tls
        self._ssl_ctx = _tls.make_client_ctx(conf)

        # SASL mechanism validation happens at client creation so a
        # misconfigured mechanism fails fast (reference: rd_kafka_new
        # sasl checks, rdkafka.c:~2000)
        if self.sasl_required():
            from .sasl import kinit_setup, validate_mechanism
            validate_mechanism(conf)
            # GSSAPI: run sasl.kerberos.kinit.cmd now + on the relogin
            # timer (reference: rd_kafka_sasl_cyrus_kinit_refresh)
            kinit_setup(self)

        from .stats import StatsCollector
        self.stats = StatsCollector(self)

        # legacy file offset store (offset.store.method=file)
        self.offset_store = None
        if self.is_consumer:
            from .offset_store import FileOffsetStore
            self.offset_store = FileOffsetStore(self)

        # optional background event thread (rdkafka_background.c:109,
        # created at rd_kafka_new rdkafka.c:2189-2196)
        self.background = None
        bg_cb = conf.get("background_event_cb")
        if bg_cb is not None:
            from .event import BackgroundThread
            self.background = BackgroundThread(self, bg_cb)

        # implicit mock cluster (test.mock.num.brokers)
        nmock = conf.get("test.mock.num.brokers")
        bootstrap = conf.get("bootstrap.servers")
        if nmock > 0 and not bootstrap:
            from ..mock.cluster import MockCluster
            self.mock_cluster = MockCluster(
                num_brokers=nmock,
                default_partitions=conf.get("test.mock.default.partitions"))
            bootstrap = self.mock_cluster.bootstrap_servers()
        if not bootstrap:
            raise KafkaException(Err._INVALID_ARG,
                                 "bootstrap.servers not configured")

        # plugins (plugin.library.paths; reference rdkafka_plugin.c —
        # each entry's conf_init() registers interceptors)
        plugin_paths = conf.get("plugin.library.paths")
        if plugin_paths:
            from .interceptor import load_plugins
            self.interceptors = load_plugins(plugin_paths, conf)
            conf.set("interceptors", self.interceptors)

        # interceptors on_new
        if self.interceptors:
            self.interceptors.on_new(self)

        nodeid = -1
        for hp in bootstrap.split(","):
            host, _, port = hp.strip().rpartition(":")
            b = Broker(self, nodeid, host, int(port),
                       name=f"{host}:{port}/bootstrap")
            self._bootstrap.append(b)
            self.brokers[nodeid] = b
            nodeid -= 1

        # timers (reference main loop rdkafka.c:1877-1886)
        refresh = conf.get("topic.metadata.refresh.interval.ms")
        if refresh > 0:
            self.timers.add(refresh / 1000.0,
                            lambda: self.metadata_refresh("periodic"))
        self.timers.add(1.0, self._scan_msg_timeouts)
        stats_ival = conf.get("statistics.interval.ms")
        self._stats_timer = None
        if stats_ival > 0:
            self._stats_timer = self.timers.add(stats_ival / 1000.0,
                                                self._emit_stats)
            # process-wide registry: the conftest leak fixture fails any
            # test whose client left its stats emitter registered
            from .stats import _ACTIVE_STATS_TIMERS
            _ACTIVE_STATS_TIMERS.add(id(self._stats_timer))

        self._main = threading.Thread(target=self._thread_main,
                                      name="rdk:main", daemon=True)
        self._main.start()
        for b in self._bootstrap:
            b.start()
        self.metadata_refresh("bootstrap")

    # ------------------------------------------------------------ logging --
    _LOG_LEVELS = {"EMERG": 0, "ALERT": 1, "CRIT": 2, "ERROR": 3,
                   "WARN": 4, "NOTICE": 5, "INFO": 6, "DEBUG": 7}

    def log(self, level: str, msg: str):
        # numeric syslog-style filter (reference log_level, default 6)
        if self._LOG_LEVELS.get(level, 6) > self._log_level:
            return
        # log.thread.name: tag messages with the emitting thread exactly
        # like the reference's "[thrd:...]" prefix (rdlog.c)
        if self.conf.get("log.thread.name"):
            msg = f"[thrd:{threading.current_thread().name}] {msg}"
        # log.queue: logs become LOG events served from the app-facing
        # queue (poll/queue_poll) instead of synchronous output — the
        # log_cb then fires on the POLLING thread (reference
        # rd_kafka_conf "log.queue" + rd_kafka_set_log_queue)
        if self.conf.get("log.queue"):
            self.rep.push(Op(OpType.LOG, payload=(level, "rdkafka", msg)))
            return
        if self.log_cb:
            self.log_cb(level, "rdkafka", msg)
        elif level in ("ERROR", "WARN"):
            print(f"%{level}|rdkafka| {msg}", file=sys.stderr)

    def dbg(self, ctx: str, msg: str):
        if ctx in self.debug_contexts or "all" in self.debug_contexts:
            self.log("DEBUG", f"[{ctx}] {msg}")

    # -------------------------------------------------------- main thread --
    def _thread_main(self):
        if self.interceptors:
            self.interceptors.on_thread_start("main", "rdk:main")
        while not self.terminating:
            timeout = self.timers.next_timeout(0.1)
            op = self.ops.pop(timeout)
            if op is not None:
                self._op_serve(op)
            self.timers.run()
            if self.idemp and self.txnmgr is None:
                # transactional pids are acquired ONLY through
                # init_transactions (the txnmgr owns the epoch
                # lifecycle); the idempotence FSM must not race it with
                # a non-transactional InitProducerId
                self.idemp.serve()
            if self.txnmgr is not None:
                self.txnmgr.serve()
            if self.cgrp:
                self.cgrp.serve()
        if self.interceptors:
            self.interceptors.on_thread_exit("main", "rdk:main")

    def _op_serve(self, op: Op):
        if op.cb:
            op.cb(op)

    # ----------------------------------------------------------- metadata --
    def blacklisted(self, topic: str) -> bool:
        return any(p.search(topic) for p in self._blacklist)

    def any_up_broker(self) -> Optional[Broker]:
        with self._brokers_lock:
            ups = [b for b in self.brokers.values() if b.is_up()]
        return random.choice(ups) if ups else None

    def metadata_refresh(self, reason: str = "",
                         all_topics: bool = False,
                         topics: Optional[list] = None):
        """``topics`` is an interest HINT: the caller knows these
        specific topics need fresh metadata (fetch/produce errors, new
        topic registration) — they bypass the interest-only freshness
        debounce below."""
        if self.terminating:
            return
        if self._metadata_inflight:
            # queue one follow-up so a refresh requested mid-flight (e.g.
            # regex discovery racing a sparse refresh) is not lost until
            # the periodic timer (reference: rd_kafka_metadata_refresh
            # coalescing)
            self._metadata_refresh_queued = True
            return
        b = self.any_up_broker()
        if b is None:
            # will be retried when a broker comes up (broker_state_change)
            return
        self._metadata_inflight = True
        sparse = self.conf.get("topic.metadata.refresh.sparse")
        interest_only = self.conf.get("topic.metadata.interest.only")
        with self._topics_lock:
            names = list(self.topics) if sparse else None
        if names is not None and topics:
            names = list(dict.fromkeys([*names, *topics]))
        if names == [] and not interest_only:
            # legacy shape: an empty interest set falls back to a full
            # sweep; interest-only keeps it empty — a brokers-only
            # request (Metadata v1+ empty topic array = no topics)
            names = None
        if all_topics or reason == "periodic":
            # full enumeration: list_topics, and the periodic refresh —
            # the ONE recurring full sweep interest-only keeps (deleted-
            # topic pruning + regex discovery happen here)
            names = None
        if self.cgrp is not None and self.cgrp.patterns:
            # regex subscriptions need the full cluster topic list
            names = None
        if interest_only and names:
            # per-topic staleness debounce: a topic whose metadata just
            # landed isn't re-requested by an unrelated trigger (bursts
            # of "new topic" refreshes re-listing the whole interest set
            # were O(topics²) on the wire).  Hinted topics and anything
            # older than half the fast-refresh interval pass — the
            # leaderless fast path (250ms) always re-polls.
            cutoff = self.conf.get(
                "topic.metadata.refresh.fast.interval.ms") / 1000.0 * 0.5
            hint = set(topics or ())
            now0 = time.monotonic()
            with self._metadata_lock:
                names = [t for t in names if t in hint
                         or now0 - self._metadata_topic_ts.get(t, 0.0)
                         >= cutoff]
            if not names and hint:
                names = list(hint)
        # metadata.max.age.ms: expire cache entries past their age
        # (reference rdkafka_metadata_cache.c:289). Existing toppar
        # leader delegation is updated by the refresh RESPONSE
        # (_assign_toppar_leader); the expiry only keeps get_toppar and
        # admin list_topics from reading decayed entries meanwhile
        max_age = self.conf.get("metadata.max.age.ms") / 1000.0
        now = time.monotonic()
        with self._metadata_lock:
            for name, ts in list(self._metadata_topic_ts.items()):
                if now - ts > max_age:
                    self.metadata["topics"].pop(name, None)
                    del self._metadata_topic_ts[name]
        self.dbg("metadata", f"refresh ({reason}) via {b.name}")
        # ONLY a null topic array is a full enumeration (Metadata v1+:
        # null = all topics, [] = none — the mock used to conflate the
        # two); [] is a brokers-only liveness probe and must not prune
        full = names is None
        b.enqueue_request(Request(
            ApiKey.Metadata,
            # v4+ carries the auto-creation flag: producers may trigger
            # broker-side topic creation, consumers only when
            # allow.auto.create.topics (KIP-204; reference
            # rd_kafka_MetadataRequest). Older negotiated versions
            # simply don't serialize the key.
            {"topics": names,
             "allow_auto_topic_creation":
                 self.is_producer or
                 bool(self.conf.get("allow.auto.create.topics"))},
            retries_left=2,
            abs_timeout=time.monotonic() +
            self.conf.get("metadata.request.timeout.ms") / 1000.0,
            cb=lambda e, r: self._handle_metadata(e, r, full=full)))

    def _handle_metadata(self, err, resp, full: bool = False):
        self._metadata_inflight = False
        if self._metadata_refresh_queued:
            self._metadata_refresh_queued = False
            self.timers.add(0.05, lambda: self.metadata_refresh("queued"),
                            once=True)
        if err is not None:
            return
        with self._metadata_lock:
            new_brokers = {b["node_id"]: (b["host"], b["port"])
                           for b in resp["brokers"]}
            self.metadata["brokers"] = new_brokers
            self.metadata["controller_id"] = resp.get("controller_id", -1)
            cid = resp.get("cluster_id")
            if cid:
                self.metadata["cluster_id"] = cid
            seen = set()
            failed_topics = []
            for t in resp["topics"]:
                if self.blacklisted(t["topic"]):
                    continue
                terr = Err.from_wire(t["error_code"])
                if terr == Err.UNKNOWN_TOPIC_OR_PART:
                    # topic deleted: drop it from the cache
                    self.metadata["topics"].pop(t["topic"], None)
                    continue
                if terr in (Err.TOPIC_EXCEPTION,
                            Err.TOPIC_AUTHORIZATION_FAILED):
                    # permanent: parked messages must fail NOW, not at
                    # message.timeout.ms (reference: metadata topic err
                    # → rd_kafka_topic_metadata_update NOTEXISTS → DR
                    # failures; tests 0057-invalid_topic analog)
                    self.metadata["topics"].pop(t["topic"], None)
                    failed_topics.append((t["topic"], terr))
                    continue
                if terr != Err.NO_ERROR:
                    # transient (e.g. LEADER_NOT_AVAILABLE during
                    # election): the topic still exists — keep it in
                    # `seen` so prune/regex don't treat it as deleted
                    seen.add(t["topic"])
                    continue
                seen.add(t["topic"])
                self.metadata["topics"][t["topic"]] = {
                    p["partition"]: p["leader"] for p in t["partitions"]}
                self._metadata_topic_ts[t["topic"]] = time.monotonic()
            if full:
                # a full metadata response enumerates every topic: prune
                # cache entries that vanished (deleted topics)
                for name in list(self.metadata["topics"]):
                    if name not in seen:
                        del self.metadata["topics"][name]
            if full:
                # stamped AFTER the cache update, inside the lock:
                # list_topics waits on this to take a coherent snapshot
                self._metadata_full_ts = time.monotonic()
            self._metadata_cond.notify_all()
        for name, terr in failed_topics:
            if self.is_producer:
                self._fail_topic(name, KafkaError(terr, retriable=False))
            else:
                # consumers: surface the permanent topic error as an
                # error event (reference delivers
                # ERR_TOPIC_AUTHORIZATION_FAILED to the app); fetching
                # for the topic stops with the cache entry gone.
                # NOTE: with topic.metadata.refresh.sparse=false the
                # full enumeration never names an invalid topic, so
                # this path needs the (default) sparse refresh; the
                # non-sparse fallback is message.timeout.ms, matching
                # the reference's behavior there.
                self.op_err(KafkaError(
                    terr, f"topic {name!r}: permanent metadata error",
                    retriable=False))
        if self.cgrp is not None:
            # subscription re-evaluation (rdkafka_pattern.c; literal
            # arrival counts on sparse updates too — a topic created
            # after subscribe() must rejoin the group when its
            # per-topic metadata lands, rdkafka_cgrp.c:3412)
            self.cgrp.metadata_update(seen, full=full)
        # leaderless partitions (election in progress): re-query on the
        # fast interval (topic.metadata.refresh.fast.interval.ms;
        # reference rd_kafka_metadata_refresh fast path)
        leaderless = any(
            p["leader"] < 0
            for t in resp["topics"] if t["error_code"] == 0
            for p in t["partitions"])
        if leaderless and not self._fast_refresh_scheduled:
            self._fast_refresh_scheduled = True
            fast = self.conf.get(
                "topic.metadata.refresh.fast.interval.ms") / 1000.0

            def _fast_refresh():
                self._fast_refresh_scheduled = False
                self.metadata_refresh("fast")

            self.timers.add(fast, _fast_refresh, once=True)
        # instantiate broker threads for newly discovered nodes
        with self._brokers_lock:
            for nid, (host, port) in new_brokers.items():
                if nid not in self.brokers:
                    b = Broker(self, nid, host, port)
                    self.brokers[nid] = b
                    b.start()
        # update topic partition counts + migrate UA messages + leaders
        for t in resp["topics"]:
            name = t["topic"]
            topic = self.topics.get(name)
            if topic is not None:
                with topic.lock:
                    topic.partition_cnt = len(t["partitions"])
                # partition count changed ⇒ the lane's cached native
                # auto-partition entry is stale; drop it and let the
                # next produce() re-register via _fast_partition
                self._lane.part_del(name)
                if self.is_producer:
                    self._fail_unknown_partitions(name, len(t["partitions"]))
            for p in t["partitions"]:
                if p["leader"] < 0:
                    continue
                tp = self.get_toppar(name, p["partition"],
                                     create=(topic is not None))
                if tp is not None:
                    self._assign_toppar_leader(tp, p["leader"])
        self._migrate_ua_msgs()
        # second notify AFTER toppar leader assignment: waiters whose
        # predicate is tp.leader_id >= 0 (offsets_for_times) observe the
        # assignment, not just the raw cache update above
        with self._metadata_cond:
            self._metadata_cond.notify_all()

    def list_topics(self, timeout: float = 10.0) -> dict:
        """Synchronous full-metadata snapshot: {brokers, controller_id,
        topics: {topic: {partition: leader}}} (rd_kafka_metadata)."""
        deadline = time.monotonic() + timeout
        t0 = time.monotonic()
        self.metadata_refresh("list_topics", all_topics=True)
        while time.monotonic() < deadline:
            # wait for a FULL refresh completed at/after this call; the
            # 0.5s cap re-issues it in case the first raced broker
            # bring-up and was dropped
            if self.metadata_wait(
                    lambda: self._metadata_full_ts >= t0,
                    min(0.5, max(0.0, deadline - time.monotonic()))):
                with self._metadata_lock:
                    md = self.metadata
                    return {"brokers": dict(md["brokers"]),
                            "controller_id": md.get("controller_id", -1),
                            "topics": {t: dict(ps)
                                       for t, ps in md["topics"].items()}}
            self.metadata_refresh("list_topics retry", all_topics=True)
        raise KafkaException(Err._TIMED_OUT, "metadata not available")

    def cluster_id(self, timeout: float = 5.0) -> Optional[str]:
        """Cluster id from metadata (reference rd_kafka_clusterid;
        Metadata v2+ carries it). None when unknown within timeout."""
        if self.metadata.get("cluster_id") is None:
            self.metadata_refresh("clusterid")
            self.metadata_wait(
                lambda: self.metadata.get("cluster_id") is not None,
                timeout)
        return self.metadata.get("cluster_id")

    def controller_id(self, timeout: float = 5.0) -> int:
        """Controller broker id (reference rd_kafka_controllerid);
        -1 when unknown within timeout."""
        if self.metadata.get("controller_id", -1) < 0:
            self.metadata_refresh("controllerid")
            self.metadata_wait(
                lambda: self.metadata.get("controller_id", -1) >= 0,
                timeout)
        return self.metadata.get("controller_id", -1)

    def metadata_wait(self, predicate, timeout: float) -> bool:
        """Block until ``predicate()`` holds or ``timeout`` elapses,
        waking on every metadata cache update (condvar, no polling)."""
        deadline = time.monotonic() + timeout
        with self._metadata_cond:
            while not predicate():
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._metadata_cond.wait(remain)
            return True

    def _assign_toppar_leader(self, tp: Toppar, leader: int):
        if tp.leader_id == leader:
            return
        # a leadership change invalidates any follower delegation
        # (reference resets the fetch broker on leader updates)
        self.revoke_fetch_delegation(tp, "leader change")
        old = tp.leader_id
        tp.leader_id = leader
        with self._brokers_lock:
            if old in self.brokers:
                self.brokers[old].remove_toppar(tp)
            if leader in self.brokers:
                self.brokers[leader].add_toppar(tp)
        self.dbg("topic", f"{tp}: leader {old} -> {leader}")

    # ------------------------------------------ KIP-392 follower fetch --
    def delegate_fetch(self, tp: Toppar, broker_id: int) -> None:
        """Move a partition's FETCH traffic to a follower replica the
        broker nominated via preferred_read_replica (Fetch v11;
        reference: rd_kafka_fetch_preferred_replica_handle,
        rdkafka_broker.c:3921). Producing still targets the leader."""
        if tp.fetch_broker_id == broker_id or broker_id == tp.leader_id:
            if broker_id == tp.leader_id:
                self.revoke_fetch_delegation(tp, "leader nominated")
            return
        with self._brokers_lock:
            b = self.brokers.get(broker_id)
            if b is None:
                # unknown replica: our metadata is stale — back the fetch
                # off so the leader's record-less redirects don't hot-loop
                # (reference: rd_kafka_fetch_preferred_replica_handle).
                # The refresh itself happens below, after the lock is
                # released: metadata_refresh → any_up_broker re-acquires
                # _brokers_lock, which is non-reentrant.
                tp.fetch_backoff_until = time.monotonic() + \
                    self.conf.get("fetch.error.backoff.ms") / 1000.0
            else:
                old = tp.fetch_broker_id
                tp.fetch_broker_id = broker_id
                if old is not None and old != tp.leader_id \
                        and old in self.brokers:
                    self.brokers[old].remove_toppar(tp)
                b.add_toppar(tp)
        if b is None:
            self.metadata_refresh(
                reason=f"unknown preferred replica {broker_id}")
            return
        self.dbg("fetch",
                 f"{tp}: fetching from follower {broker_id} "
                 f"(leader {tp.leader_id})")

    def revoke_fetch_delegation(self, tp: Toppar, reason: str) -> None:
        with self._brokers_lock:     # fetch_broker_id writes stay
            old = tp.fetch_broker_id  # ordered vs delegate_fetch
            if old is None:
                return
            tp.fetch_broker_id = None
            if old != tp.leader_id and old in self.brokers:
                self.brokers[old].remove_toppar(tp)
            leader = self.brokers.get(tp.leader_id)
            if leader is not None:
                leader._wakeup()
        self.dbg("fetch", f"{tp}: back to leader fetch ({reason})")

    def _fail_topic(self, name: str, kerr: KafkaError) -> None:
        """Fail every message queued for ``name`` — UA-parked and
        per-toppar alike (permanent metadata topic errors:
        INVALID_TOPIC, TOPIC_AUTHORIZATION_FAILED)."""
        with self._topics_lock:
            topic = self.topics.get(name)
        if topic is not None:
            with topic.lock:
                msgs = list(topic.ua_msgq)
                topic.ua_msgq.clear()
            if msgs:
                self.dr_msgq(msgs, kerr)   # dr_msgq stamps m.error
        self._fail_unknown_partitions(name, 0, kerr)

    def _fail_unknown_partitions(self, topic: str, cnt: int,
                                 kerr: Optional[KafkaError] = None):
        """Error-DR messages parked on partitions beyond the topic's real
        partition count (reference: rd_kafka_topic_partition_cnt_update →
        UNKNOWN_PARTITION delivery failures, rdkafka_topic.c). ``kerr``
        overrides the default unknown-partition error (permanent topic
        errors fail with their own code)."""
        with self._toppars_lock:
            tps = [tp for (t, p), tp in self._toppars.items()
                   if t == topic and p >= cnt]
        for tp in tps:
            self._fast_tp.pop((tp.topic, tp.partition), None)
            self._lane.map_del(tp.topic, tp.partition)
            failed: list[Message] = []
            fast_cnt = fast_bytes = 0
            dr_wanted = self._dr_out_wanted()
            with tp.lock:
                failed.extend(tp.msgq)
                tp.msgq.clear()
                tp.msgq_bytes = 0
                failed.extend(tp.xmit_msgq)
                tp.xmit_msgq.clear()
                for b in tp.retry_batches:
                    if not isinstance(b, ArenaBatch):
                        failed.extend(b)
                    elif dr_wanted:   # dr_msgq accounts materialized msgs
                        failed.extend(b.to_messages(tp.topic, tp.partition))
                    else:
                        fast_cnt += b.count
                        fast_bytes += b.nbytes
                tp.retry_batches.clear()
                if tp.arena is not None:
                    if dr_wanted:
                        for k, v, mts, hb in tp.arena.drain_records():
                            failed.append(Message(
                                tp.topic, value=v, key=k,
                                partition=tp.partition, timestamp=mts,
                                headers=decode_hblob(hb) if hb else ()))
                    else:
                        c, nb = tp.arena.clear()
                        fast_cnt += c
                        fast_bytes += nb
            if fast_cnt:
                self._lane.acct(-fast_cnt, -fast_bytes)
            if failed:
                self.dr_msgq(failed, kerr or KafkaError(
                    Err._UNKNOWN_PARTITION,
                    f"{tp}: partition does not exist"))

    def _migrate_ua_msgs(self):
        with self._topics_lock:
            topics = list(self.topics.values())
        for topic in topics:
            with topic.lock:
                if topic.partition_cnt <= 0 or not topic.ua_msgq:
                    continue
                msgs, topic.ua_msgq = topic.ua_msgq, deque()
            for m in msgs:
                self._partition_and_enq(topic, m)

    # -------------------------------------------------------------- topics --
    def get_topic(self, name: str) -> Topic:
        created = False
        with self._topics_lock:
            t = self.topics.get(name)
            if t is None:
                t = Topic(name, self.conf.topic_conf())
                self.topics[name] = t
                created = True
        if created:
            # outside _topics_lock: metadata_refresh re-acquires it
            self.metadata_refresh(f"new topic {name}")
        return t

    def topic_conf_for(self, name: str) -> TopicConf:
        with self._topics_lock:
            t = self.topics.get(name)
        return t.conf if t else self.conf.topic_conf()

    def set_topic_conf(self, name: str, conf: dict) -> None:
        """Per-topic configuration (the rd_kafka_topic_new(rk, name,
        topic_conf) analog, reference rdkafka_topic.c): applies on top
        of the default topic conf for this topic only."""
        t = self.get_topic(name)
        t.conf.update(conf)
        if "partitioner" in conf or "partitioner_cb" in conf:
            t.partitioner = partitioner_fn(t.conf.get("partitioner"))
            # invalidate the lane's cached native auto-partition entry;
            # the next UA produce re-registers via _fast_partition
            self._lane.part_del(name)

    def get_toppar(self, topic: str, partition: int,
                   create: bool = True) -> Optional[Toppar]:
        key = (topic, partition)
        with self._toppars_lock:
            tp = self._toppars.get(key)
            if tp is None and create:
                tp = Toppar(topic, partition)
                self._toppars[key] = tp
                with self._metadata_lock:
                    leader = self.metadata["topics"].get(topic, {}).get(partition)
                if leader is not None and leader >= 0:
                    self._assign_toppar_leader(tp, leader)
            return tp

    # ------------------------------------------------------------ produce --
    @property
    def msg_cnt(self) -> int:
        return self._lane.msg_cnt

    @property
    def msg_bytes(self) -> int:
        return self._lane.msg_bytes

    def _produce_slow(self, topic: str, value=None, key=None,
                      partition=PARTITION_UA, on_delivery=None, timestamp=0,
                      headers=(), opaque=None) -> None:
        """The Message-path produce (and the fast lane's first-sight
        setup).  The PUBLIC entry point is ``self.produce`` — the native
        Lane.produce (enqlane.cpp), which handles every eligible record
        in one C call and tail-calls here for the rest."""
        # positional order matches the confluent-style public API
        # (topic, value, key, partition, on_delivery, timestamp, headers)
        if _trace.enabled:
            # the produce()-enqueue anchor of the producer span chain
            # (fast-lane records never enter a Python frame; their
            # first-sight setup passes through here)
            _trace.instant("produce", "enqueue",
                           {"topic": topic, "partition": partition})
        if isinstance(value, str):
            value = value.encode()
        if isinstance(key, str):
            key = key.encode()
        if self.fatal_error:
            raise KafkaException(self.fatal_error)
        if self.txnmgr is not None and self.txnmgr.state != "IN_TXN":
            # transactional producers may only produce inside a
            # transaction (reference: rd_kafka_produce ERR__STATE gate)
            raise KafkaException(
                Err._STATE,
                f"produce() requires an ongoing transaction "
                f"(state {self.txnmgr.state}; call begin_transaction)")
        sz = (len(value) if value else 0) + (len(key) if key else 0)
        # reference: rd_kafka_msg_new0 rejects oversize messages up
        # front with MSG_SIZE_TOO_LARGE (test 0003-msgmaxsize)
        if sz > self.conf.get("message.max.bytes"):
            raise KafkaException(
                Err.MSG_SIZE_TOO_LARGE,
                f"message size {sz} exceeds message.max.bytes "
                f"{self.conf.get('message.max.bytes')}")
        # lock keeps check+claim atomic on this Python path (the C lane
        # does both inside one GIL-atomic call)
        with self._msg_cnt_lock:
            if self._lane.full(sz):
                raise KafkaException(Err._QUEUE_FULL,
                                     "producer queue is full")
            self._lane.acct(1, sz)
        # native enqueue fast lane: no Message object, one C call into
        # the per-toppar arena (queue accounting above is shared;
        # _fast_lane stays fresh via the conf.add_listener hook).
        # Widened eligibility (PR 16): explicit timestamps ride a side
        # int64 array, headers pre-encode into a wire blob here (the
        # framer memcpys it), and PARTITION_UA engages via the native
        # murmur2 map when the topic's partitioner is murmur2-family.
        if (self._fast_lane and on_delivery is None and opaque is None
                and (value is None or type(value) is bytes)
                and (key is None or type(key) is bytes)
                and type(timestamp) is int and timestamp >= 0):
            hblob = encode_headers(headers) if headers else None
            if not headers or hblob is not None:
                if partition >= 0:
                    if self._produce_fast(topic, key, value, partition,
                                          sz, timestamp, hblob):
                        return
                elif partition == PARTITION_UA:
                    p = self._fast_partition(topic, key)
                    if (p >= 0
                            and self._produce_fast(topic, key, value, p,
                                                   sz, timestamp, hblob)):
                        return
        m = Message(topic, value=value, key=key, partition=partition,
                    headers=headers, timestamp=timestamp, opaque=opaque)
        if on_delivery is not None:
            m.on_delivery = on_delivery   # per-message DR callback
        if self.interceptors:
            self.interceptors.on_send(m)
        # lock-free fast path: dict reads are atomic under the GIL; fall
        # back to the locked creation path on first sight of a topic
        t = self.topics.get(topic)
        if t is None:
            t = self.get_topic(topic)
        if partition == PARTITION_UA:
            with t.lock:
                if t.partition_cnt <= 0:
                    t.ua_msgq.append(m)     # park until metadata
                    return
            self._partition_and_enq(t, m)
        else:
            cnt = t.partition_cnt       # int read: GIL-atomic, no lock
            if 0 < cnt <= partition:
                # known-invalid partition fails at produce() time
                # (reference: rd_kafka_msg_partitioner → UNKNOWN_PARTITION)
                self._lane.acct(-1, -sz)
                raise KafkaException(
                    Err._UNKNOWN_PARTITION,
                    f"{topic}[{partition}]: partition does not exist")
            tp = self._toppars.get((topic, partition))
            if tp is None:
                tp = self.get_toppar(topic, partition)
            if tp.arena_ok:
                # Message path claims this toppar (shape-ineligible
                # produce: interceptors, on_delivery/opaque, str value
                # kept as Message, oversize, ...)
                self._demote(tp, "ineligible")
            if tp.enq_msg(m):
                self._wake_leader(tp)

    def _recompute_fast_lane(self) -> None:
        conf = self.conf
        # DR consumers (dr_msg_cb / dr_cb / "dr" events / background)
        # no longer disable the lane: delivery reports materialize
        # Message objects from the arena run at DR time (dr_msgq), so
        # produce() stays on the zero-alloc path — the reference's
        # headline throughput runs WITH dr_msg_cb set. Interceptors
        # still force the Message path: on_send must fire per message
        # at produce() time.  Transactional producers ride the lane
        # too, but only while produce() is legal — the C entry point
        # cannot check the in-transaction state gate itself, so the
        # txn FSM toggles lane.enabled at every transition
        # (_txn_lane_sync); outside IN_TXN the tail-call into
        # _produce_slow raises the reference's ERR__STATE.
        self._fast_lane = (self.is_producer and not self.interceptors)
        self._fast_lane_ver = getattr(conf, "version", 0)
        # the C entry consults this flag before touching an arena; a
        # conf.set that adds a DR consumer flips it via the listener
        self._txn_lane_sync()

    def _txn_lane_sync(self) -> None:
        """Recompute the native lane's enable flag from the fast-lane
        eligibility AND the txn FSM (transactional producers may only
        fast-enqueue while IN_TXN)."""
        txnmgr = getattr(self, "txnmgr", None)
        try:
            self._lane.enabled = (
                1 if self._fast_lane
                and (txnmgr is None or txnmgr.state == "IN_TXN")
                else 0)
        except AttributeError:
            pass                        # lane not constructed yet

    def _fast_partition(self, topic: str, key) -> int:
        """Auto-partition for the fast lane: murmur2-family partitioners
        compute natively-reproducible partitions (bit-exact vs
        utils/hash.murmur2), so PARTITION_UA produces stay eligible.
        Registers (partition_cnt, mode) with the C lane so subsequent
        UA produces never enter a Python frame.  Returns -1 (fall back
        to the Message path / Python partitioner) for partitioner_cb,
        non-murmur2 partitioners, unknown partition counts, and
        murmur2_random with a falsy key (random must stay Python's
        RNG)."""
        t = self.topics.get(topic)
        if t is None:
            t = self.get_topic(topic)
        if t.conf.get("partitioner_cb"):
            return -1
        mode = {"murmur2": 1,
                "murmur2_random": 2}.get(t.conf.get("partitioner"), 0)
        cnt = t.partition_cnt           # int read: GIL-atomic, no lock
        if mode == 0 or cnt <= 0:
            return -1
        self._lane.part_set(topic, cnt, mode)
        if mode == 2 and not key:
            return -1                   # falsy key → random partitioner
        return murmur2_partition(key or b"", cnt)

    def _produce_fast(self, topic: str, key, value, partition: int,
                      sz: int, timestamp: int = 0, hblob=None) -> bool:
        """Fast-lane enqueue; False = caller falls back to the Message
        path (queue accounting stays — both paths share it)."""
        tp = self._fast_tp.get((topic, partition))
        if tp is not None:
            if not tp.arena_ok:         # demoted since caching
                return False
            if tp.arena.append(key, value, timestamp, hblob) == 1:
                self._wake_leader(tp)   # wake on empty→non-empty only
            return True
        # ---- first sight: validate, create the arena, cache ------------
        t = self.topics.get(topic)
        if t is None:
            t = self.get_topic(topic)
        cnt = t.partition_cnt
        if 0 < cnt <= partition:
            self._lane.acct(-1, -sz)
            raise KafkaException(
                Err._UNKNOWN_PARTITION,
                f"{topic}[{partition}]: partition does not exist")
        tp = self._toppars.get((topic, partition))
        if tp is None:
            tp = self.get_toppar(topic, partition)
        if not tp.arena_ok:
            # cache the demoted toppar too: the next eligible produce
            # short-circuits on one dict hit instead of re-running the
            # topic/partition/toppar lookups before falling back
            self._fast_tp[(topic, partition)] = tp
            return False
        a = tp.arena
        if a is None:
            with tp.lock:
                if tp.arena is None and tp.arena_ok:
                    tp.arena = arena_new()
                a = tp.arena
            if a is None:               # extension unavailable: demote
                tp.arena_ok = False
                self._fast_tp[(topic, partition)] = tp
                return False
        self._fast_tp[(topic, partition)] = tp
        # register with the C entry point: subsequent produces for this
        # toppar never enter a Python frame (map_set keeps the lane's
        # last-topic lookup cache coherent — never mutate map directly)
        self._lane.map_set(topic, partition, (a, tp))
        if a.append(key, value, timestamp, hblob) == 1:
            self._wake_leader(tp)
        return True

    def _partition_and_enq(self, topic: Topic, m: Message):
        pcb = topic.conf.get("partitioner_cb")
        if pcb:
            m.partition = pcb(m.key, topic.partition_cnt)
        else:
            m.partition = topic.partitioner(m.key, topic.partition_cnt)
        tp = self._toppars.get((topic.name, m.partition))
        if tp is None:
            tp = self.get_toppar(topic.name, m.partition)
        if tp.arena_ok:
            # a Python-partitioned message (random/consistent family,
            # partitioner_cb, or murmur2_random falsy key) claims this
            # toppar for the Message path
            self._demote(tp, "partitioner")
        if tp.enq_msg(m):
            self._wake_leader(tp)

    def _demote(self, tp: Toppar, reason: str = "ineligible") -> None:
        """Permanently route a toppar through the Message path: remove
        it from the C entry's map FIRST so no new fast-lane records land
        while the arena drains into the msgq (FIFO preserved).
        ``reason`` feeds the stats ``arena.demoted`` breakdown."""
        key = (tp.topic, tp.partition)
        self._lane.map_del(tp.topic, tp.partition)
        self._fast_tp.pop(key, None)
        with self._msg_cnt_lock:
            self._demote_reasons[reason] = (
                self._demote_reasons.get(reason, 0) + 1)
        tp.demote_arena()

    def _wake_leader(self, tp: Toppar):
        # every wake means "this toppar has work" (first produce enqueue,
        # fetcher start, retry) — the cheapest correct hook for the
        # O(active) index; consumer _stop_partitions deactivates
        if not tp.stats_active:
            self.toppar_set_active(tp, True)
        with self._brokers_lock:
            b = self.brokers.get(tp.leader_id)
        if b is not None:
            b.ops.push(Op(OpType.BROKER_WAKEUP))

    def toppar_set_active(self, tp: Toppar, active: bool) -> None:
        """Add/remove ``tp`` from the active-toppar index (stats emit,
        fetch-serve and queue-budget scans iterate only this set)."""
        with self._toppars_lock:
            if active:
                self._active_toppars[(tp.topic, tp.partition)] = tp
            else:
                self._active_toppars.pop((tp.topic, tp.partition), None)
            tp.stats_active = active

    def active_toppars(self) -> list[Toppar]:
        """Snapshot of the active toppars (O(active), not O(registered))."""
        with self._toppars_lock:
            return list(self._active_toppars.values())

    # ------------------------------------------------------------ DR path --
    def _dr_out_wanted(self) -> bool:
        """Is anyone consuming delivery reports? (dr callback, "dr"
        events, or the background event thread)"""
        conf = self.conf
        return bool(conf.get("dr_msg_cb") or conf.get("dr_cb")
                    or conf.get("dr_batch_cb")
                    or "dr" in conf.get("enabled_events")
                    or self.background is not None)

    def dr_msgq(self, msgs, err: Optional[KafkaError],
                tp=None, base_offset: int = -1):
        """Queue delivery reports (reference: rd_kafka_dr_msgq,
        rdkafka_broker.c:2432).  Accepts list[Message] or a fast-lane
        ArenaBatch.  With no DR consumer an ArenaBatch resolves to pure
        queue accounting; with one, its records materialize into
        Message objects HERE — at delivery-report time, off the
        produce() path — carrying ``tp``'s topic/partition and offsets
        from ``base_offset`` (successful batches)."""
        if err is not None and self.txnmgr is not None:
            # a failed message inside a transaction makes it abortable
            # (reference: rd_kafka_txn_set_abortable_error from the DR
            # path); purge DRs during abort are exempt inside msg_failed
            self.txnmgr.msg_failed(err)
        if self.stats and err is None:
            # stats txmsgs: acked produces (rdkafka.c txmsgs analog;
            # this counter sat permanently at 0 before ISSUE 10 — no
            # path ever bumped it).  Counted before the fast-lane
            # branch so pure-accounting ArenaBatch resolutions (no DR
            # consumer) are included.
            self.stats.add_tx(msgs.count if isinstance(msgs, ArenaBatch)
                              else len(msgs))
        batch_nbytes = None
        if isinstance(msgs, ArenaBatch):
            if self._dr_out_wanted():
                st = (MsgStatus.PERSISTED if err is None
                      else MsgStatus.POSSIBLY_PERSISTED
                      if msgs.possibly_persisted
                      else MsgStatus.NOT_PERSISTED)
                batch_nbytes = msgs.nbytes
                # LAZY DR materialization: messages hold (arena base,
                # packed offsets); .value/.key bytes exist only if the
                # DR callback reads them. The shared error stamps every
                # record here, so the per-message error loop below is
                # skipped for batches.
                msgs = msgs.to_messages_lazy(
                    tp.topic if tp is not None else "",
                    tp.partition if tp is not None else -1,
                    base_offset if err is None else -1, st, err)
            else:
                with self._msg_cnt_lock:
                    self._lane.acct(-msgs.count, -msgs.nbytes)
                    if self.flushing:
                        self._outq_cond.notify_all()
                return
        elif err is not None:
            for m in msgs:
                m.error = err
        if self.interceptors:
            for m in msgs:
                self.interceptors.on_acknowledgement(m)
        out = []
        if (self._dr_out_wanted()
                or any(m.on_delivery is not None for m in msgs)):
            only_err = self.conf.get("delivery.report.only.error")
            out = msgs if (err or not only_err) else \
                [m for m in msgs if m.error]
        # msg_cnt release and dr_cnt claim must be ONE atomic step:
        # a flush() reading between them would see outstanding == 0 and
        # return before the DR reaches the app
        if batch_nbytes is None:
            batch_nbytes = sum(m.size for m in msgs)
        with self._msg_cnt_lock:
            self._lane.acct(-len(msgs), -batch_nbytes)
            self.dr_cnt += len(out)
            if self.flushing and not out:
                self._outq_cond.notify_all()
        if out:
            # one DR op per batch, not per message (queue-push overhead)
            self.rep.push(Op(OpType.DR, payload=out))

    def poll(self, timeout: float = 0.0) -> int:
        """Serve the app reply queue: DRs, errors, stats, logs
        (reference: rd_kafka_poll, rdkafka.c:3574)."""
        served = 0
        t = timeout
        while True:
            op = self.rep.pop(t)
            if op is None:
                return served
            t = 0
            self._serve_rep_op(op)
            served += 1

    def queue_poll(self, timeout: float = 0.0):
        """Pop one typed Event from the reply queue (reference:
        rd_kafka_queue_poll → rd_kafka_event_t). Alternative to the
        callback dispatch of poll()."""
        from .event import Event
        op = self.rep.pop(timeout)
        if op is not None and op.type == OpType.DR:
            self._dr_served(len(op.payload))
        return Event(op) if op is not None else None

    def _dr_served(self, n: int) -> None:
        """A DR op reached the app (callback fired / event popped)."""
        with self._msg_cnt_lock:
            self.dr_cnt -= n
            if self.flushing:
                self._outq_cond.notify_all()

    def _serve_rep_op(self, op: Op):
        if op.type == OpType.DR:
            bcb = self.conf.get("dr_batch_cb")
            cb = self.conf.get("dr_msg_cb") or self.conf.get("dr_cb")
            try:
                if bcb is not None:
                    # ONE call per delivered batch (the
                    # rd_kafka_event_DR message-array contract); any
                    # per-message on_delivery callbacks still fire
                    bcb(op.payload)
                    if cb is None:
                        # fast-lane DR batches are FetchMessage lists —
                        # on_delivery is a class-level None there, so
                        # the per-message scan is skipped entirely
                        if (op.payload
                                and type(op.payload[0]) is FetchMessage):
                            return
                        for m in op.payload:
                            if m.on_delivery is not None:
                                m.on_delivery(m.error, m)
                        return
                for m in op.payload:
                    mcb = m.on_delivery or cb
                    if mcb:
                        mcb(m.error, m)
            finally:
                self._dr_served(len(op.payload))
        elif op.type == OpType.ERR:
            cb = self.conf.get("error_cb")
            if cb:
                cb(op.payload)
        elif op.type == OpType.THROTTLE:
            cb = self.conf.get("throttle_cb")
            if cb:
                cb(*op.payload)       # (broker_name, broker_id, throttle_ms)
        elif op.type == OpType.STATS:
            cb = self.conf.get("stats_cb")
            if cb:
                cb(op.payload)
        elif op.type == OpType.LOG:
            if self.log_cb:
                self.log_cb(*op.payload)
        elif op.cb:
            op.cb(op)

    @property
    def outq_len(self) -> int:
        """rd_kafka_outq_len: unacked messages + undelivered DR ops."""
        with self._msg_cnt_lock:
            return self.msg_cnt + self.dr_cnt

    def op_err(self, err: KafkaError):
        self.rep.push(Op(OpType.ERR, payload=err))

    def set_fatal_error(self, err: KafkaError):
        err.fatal = True
        if self.fatal_error is None:
            self.fatal_error = err
            self._lane.fatal = 1        # C produce must reject now
            if _trace.enabled:
                # flight-recorder trigger: dump the rings that explain
                # how the client got here (TRACING.md)
                _trace.instant("client", "fatal_error",
                               {"code": err.code.name,
                                "reason": err.reason})
                _trace.flight_record(f"fatal_{err.code.name}")
            self.op_err(err)

    # -------------------------------------------------------------- flush --
    def flush(self, timeout: float = 10.0) -> int:
        """Wait for all outstanding messages; returns count still queued
        (reference: rd_kafka_flush, rdkafka.c:3905)."""
        # under the outq lock: broker threads read the flag (under the
        # same lock) to decide whether an outstanding-count decrement
        # must notify — the --races sweep flagged the bare store
        with self._msg_cnt_lock:
            self.flushing = True
        # DR-mode split (reference rk_drmode, rd_kafka_flush): with a dr
        # callback, flush serves the reply queue itself; in event mode
        # (enabled_events has "dr", no callback) it must NOT consume DR
        # events destined for the app's queue_poll — it only waits for
        # another thread (or the background thread) to drain them.
        dr_event_mode = (
            not (self.conf.get("dr_msg_cb") or self.conf.get("dr_cb"))
            and "dr" in self.conf.get("enabled_events")
            and self.background is None)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._msg_cnt_lock:
                    # undelivered DR ops count toward the outstanding
                    # total (reference rd_kafka_outq_len, rdkafka.c:3905)
                    n = self.msg_cnt + self.dr_cnt
                if n == 0:
                    return 0
                self._wake_all_brokers()
                if dr_event_mode:
                    # block on the outq condvar (notified by every
                    # outstanding-count decrement while flushing); the
                    # 100ms cap re-wakes brokers if progress stalls
                    with self._msg_cnt_lock:
                        if self.msg_cnt + self.dr_cnt == 0:
                            return 0
                        self._outq_cond.wait(
                            min(0.1, max(0.0,
                                         deadline - time.monotonic())))
                else:
                    # poll() itself blocks on the reply-queue condvar;
                    # the short cap keeps the outer progress checks live
                    self.poll(0.05)
            with self._msg_cnt_lock:
                return self.msg_cnt + self.dr_cnt
        finally:
            with self._msg_cnt_lock:
                self.flushing = False

    def purge(self, in_queue: bool = True, in_flight: bool = False) -> None:
        """Purge messages (reference: rd_kafka_purge):
        ``in_queue`` — every queued message (msgq, xmit_msgq, frozen
        retry batches, UA parking) gets a _PURGE_QUEUE DR;
        ``in_flight`` — outstanding ProduceRequests are abandoned on the
        broker threads and their messages get _PURGE_INFLIGHT DRs (any
        late broker response is dropped by the corrid filter)."""
        purged = []
        fast_cnt = fast_bytes = 0
        dr_wanted = self._dr_out_wanted()
        with self._toppars_lock:
            tps = list(self._toppars.values())
        for tp in tps:
            with tp.lock:
                if in_queue:
                    purged.extend(tp.msgq)
                    tp.msgq.clear()
                    tp.msgq_bytes = 0
                    purged.extend(tp.xmit_msgq)
                    tp.xmit_msgq.clear()
                    for batch in tp.retry_batches:
                        if not isinstance(batch, ArenaBatch):
                            purged.extend(batch)
                        elif dr_wanted:  # dr_msgq accounts these
                            purged.extend(
                                batch.to_messages(tp.topic, tp.partition))
                        else:
                            fast_cnt += batch.count
                            fast_bytes += batch.nbytes
                    tp.retry_batches.clear()
                    if tp.arena is not None:
                        if dr_wanted:
                            for k, v, mts, hb in tp.arena.drain_records():
                                purged.append(Message(
                                    tp.topic, value=v, key=k,
                                    partition=tp.partition, timestamp=mts,
                                    headers=decode_hblob(hb) if hb else ()))
                        else:
                            c, nb = tp.arena.clear()
                            fast_cnt += c
                            fast_bytes += nb
        with self._topics_lock:
            for t in self.topics.values():
                with t.lock:
                    if in_queue:
                        purged.extend(t.ua_msgq)
                        t.ua_msgq.clear()
        if fast_cnt:
            self._lane.acct(-fast_cnt, -fast_bytes)
        if purged:
            self.dr_msgq(purged, KafkaError(Err._PURGE_QUEUE, "purged"))
        if in_flight:
            # batches inside the codec pipeline are neither queued nor in
            # waitresp: bump the purge epoch so their codec_done results
            # are discarded with _PURGE_INFLIGHT instead of being sent
            self._purge_epoch += 1
            with self._brokers_lock:
                brokers = list(self.brokers.values())
            for b in brokers:
                b.ops.push(Op(OpType.PURGE))
        if self.idemp and (purged or fast_cnt or in_flight):
            # purged messages consumed msgids: the sequence chain has a
            # gap the broker would reject — resync PID/epoch (the DRAIN
            # rebase recomputes the base from what is still pending)
            self.idemp.drain_epoch_bump("purge")

    def _wake_all_brokers(self):
        with self._brokers_lock:
            for b in self.brokers.values():
                b.ops.push(Op(OpType.BROKER_WAKEUP))

    # ------------------------------------------------- broker transitions --
    def broker_state_change(self, broker: Broker):
        if broker.is_up():
            self.metadata_refresh(f"broker {broker.name} up")

    def broker_down(self, broker: Broker, err: KafkaError):
        with self._brokers_lock:
            any_up = any(b.is_up() for b in self.brokers.values())
        if not any_up and not self.terminating:
            self.op_err(KafkaError(Err._ALL_BROKERS_DOWN,
                                   "all brokers are down"))

    # ------------------------------------------------------ msg timeouts --
    def _scan_msg_timeouts(self):
        """(reference: rd_kafka_broker_toppar_msgq_scan,
        rdkafka_broker.c:3093)"""
        if not self.is_producer:
            return
        now = time.monotonic()
        with self._toppars_lock:
            tps = list(self._toppars.values())
        any_possibly_persisted = False
        any_expired = False
        for tp in tps:
            tmo = self.topic_conf_for(tp.topic).get("message.timeout.ms") / 1000.0
            if tmo <= 0:
                continue
            expired = []
            fast_cnt = fast_bytes = 0
            fast_pp = False
            dr_wanted = self._dr_out_wanted()
            with tp.lock:
                if tp.arena is not None and len(tp.arena):
                    # fast-lane records carry a native monotonic µs stamp
                    cutoff = int((now - tmo) * 1e6)
                    if dr_wanted:
                        # materialize for error DRs (dr_msgq accounts)
                        for k, v, mts, hb in tp.arena.expire_records(cutoff):
                            expired.append(Message(
                                tp.topic, value=v, key=k,
                                partition=tp.partition, timestamp=mts,
                                headers=decode_hblob(hb) if hb else ()))
                    else:
                        c, nb = tp.arena.expire(cutoff)
                        fast_cnt += c
                        fast_bytes += nb
                for q in (tp.msgq, tp.xmit_msgq):
                    while q and now - q[0].enq_time > tmo:
                        expired.append(q.popleft())
                # frozen retry batches expire whole (membership must stay
                # intact); a batch expires when its head message has
                # (reference scans all queues, rdkafka_broker.c:3093)
                while tp.retry_batches:
                    b = tp.retry_batches[0]
                    head_enq = (b.enq_first if isinstance(b, ArenaBatch)
                                else b[0].enq_time)
                    if now - head_enq <= tmo:
                        break
                    tp.retry_batches.popleft()
                    if not isinstance(b, ArenaBatch):
                        expired.extend(b)
                    elif dr_wanted:
                        lst = b.to_messages(tp.topic, tp.partition)
                        if b.possibly_persisted:
                            for m in lst:
                                m.status = MsgStatus.POSSIBLY_PERSISTED
                        expired.extend(lst)
                    else:
                        fast_cnt += b.count
                        fast_bytes += b.nbytes
                        fast_pp = fast_pp or b.possibly_persisted
            if fast_cnt:
                any_expired = True
                any_possibly_persisted = any_possibly_persisted or fast_pp
                self._lane.acct(-fast_cnt, -fast_bytes)
                if (self.idemp and fast_pp
                        and self.conf.get("enable.gapless.guarantee")):
                    # an expired SENT fast-lane batch leaves a sequence
                    # gap, same as the Message path below
                    self.set_fatal_error(KafkaError(
                        Err._GAPLESS_GUARANTEE,
                        f"{tp}: message timed out with "
                        "enable.gapless.guarantee set"))
            if expired:
                any_expired = True
                if any(m.status == MsgStatus.POSSIBLY_PERSISTED
                       for m in expired):
                    any_possibly_persisted = True
                terr = KafkaError(Err._MSG_TIMED_OUT, "message timed out")
                if self.idemp and self.conf.get("enable.gapless.guarantee"):
                    # a timed-out message leaves a sequence gap: fatal
                    # under gapless (reference _GAPLESS_GUARANTEE)
                    terr = KafkaError(
                        Err._GAPLESS_GUARANTEE,
                        f"{tp}: message timed out with "
                        "enable.gapless.guarantee set")
                    self.set_fatal_error(terr)
                self.dr_msgq(expired, terr)
        if any_expired and self.idemp:
            # ANY timed-out message leaves a sequence gap the broker will
            # reject — even never-transmitted ones consumed msgids;
            # recover via drain + epoch bump (reference:
            # rdkafka_broker.c:3291-3309)
            self.idemp.drain_epoch_bump("message(s) timed out")

    # --------------------------------------------------------- stats emit --
    def _emit_stats(self):
        blob = self.stats.emit_json()
        self.rep.push(Op(OpType.STATS, payload=blob))

    # -------------------------------------------------------------- trace --
    def trace_dump(self, path: str) -> int:
        """Export the flight-recorder rings as Chrome trace-event JSON
        loadable in Perfetto (obs/trace.py; workflow in TRACING.md).
        Returns the number of events written.  The tracer is module-
        wide, so a dump taken through any client carries every
        instrumented thread — producer, consumer, engine, brokers."""
        return _trace.dump(path)

    # ------------------------------------------------- consumer fetch path --
    def fetch_reply_handle(self, tp: Toppar, pres: dict, broker: Broker,
                           batches: Optional[list] = None,
                           fo: Optional[int] = None,
                           ver: Optional[int] = None):
        """Parse a fetch response partition into messages
        (reference: rd_kafka_fetch_reply_handle → rd_kafka_msgset_parse,
        rdkafka_msgset_reader.c:1410; aborted-txn filtering :1442-1560).

        ``batches``: pre-processed v2 batches from the broker's batched
        phase — [(info, records_bytes_DECOMPRESSED, last_offset)] with
        CRCs already verified in ONE provider call across the whole
        Fetch response (the consumer-side mirror of the producer's
        batched codec seam). None falls back to inline per-batch work
        (legacy v0/v1 messagesets, tests). A batch payload of None marks
        a decompress failure — errored only if the batch would actually
        be delivered (aborted/control batches are skipped unread).

        ``fo``/``ver``: the (fetch_offset, version) snapshot the caller
        took when it decided this response is current; all skip/parse
        decisions use the snapshot so a concurrent seek() can't desync
        them, and deliveries are stamped with ``ver`` so post-seek ops
        get discarded by the consumer's staleness filter.

        Returns False when the range errored without advancing
        fetch_offset (CRC/decompress failure) — a mixed-segment caller
        must then stop, or it would advance past the failed range and
        lose it. True otherwise."""
        if fo is None:
            fo = tp.fetch_offset
        if ver is None:
            ver = tp.version
        blob = pres["records"] or b""
        if not blob:
            if (self.conf.get("enable.partition.eof")
                    and fo >= tp.hi_offset
                    and tp.eof_reported_at != fo):
                tp.eof_reported_at = fo
                m = Message(tp.topic, partition=tp.partition)
                m.offset = fo
                m.error = KafkaError(Err._PARTITION_EOF, "partition EOF")
                tp.fetchq.push(Op(OpType.FETCH, payload=(tp, [m], ver, 0)))
            return True
        check_crcs = self.conf.get("check.crcs")
        read_committed = (self.conf.get("isolation.level") == "read_committed")
        aborted_list = pres.get("aborted_transactions") or []
        aborted = {a["producer_id"]: sorted(x["first_offset"]
                   for x in aborted_list
                   if x["producer_id"] == a["producer_id"])
                   for a in aborted_list}
        active_aborts: set[int] = set()
        msgs: list[Message] = []
        msgs_bytes = 0
        next_offset = fo
        # mixed-format logs (written across a 0.11 upgrade): process
        # each same-format run in order; the single-format common case
        # falls through to the batched paths below untouched
        from ..protocol.msgset import split_msgset_segments
        segs = pres.pop("_segments", None) \
            if isinstance(pres.get("_segments"), list) else None
        if segs is None:
            segs = split_msgset_segments(blob)
        if len(segs) > 1:
            for _kind, seg in segs:
                if tp.version != ver:
                    return True
                sub = dict(pres)
                sub["records"] = seg
                if not self.fetch_reply_handle(tp, sub, broker,
                                               batches=None, fo=fo,
                                               ver=ver):
                    # segment errored without advancing: stop here so
                    # the failed range is re-fetched, not skipped over
                    return False
                fo = tp.fetch_offset
            return True
        is_v2 = (len(blob) > proto.V2_OF_Magic and blob[proto.V2_OF_Magic] == 2)
        if is_v2:
            if batches is None:
                # inline fallback path: per-batch CRC + decompress
                batches = []
                for info, payload, full in iter_batches(blob):
                    last = info.base_offset + info.last_offset_delta
                    if last >= fo:
                        if check_crcs and not verify_crc_v2(info, full):
                            if _trace.enabled:
                                _trace.instant(
                                    "fetch", "crc_mismatch",
                                    {"topic": tp.topic,
                                     "partition": tp.partition,
                                     "offset": info.base_offset})
                                _trace.flight_record("crc_mismatch")
                            self.op_err(KafkaError(
                                Err._BAD_MSG,
                                f"{tp}: CRC mismatch at offset "
                                f"{info.base_offset}"))
                            tp.fetch_backoff_until = time.monotonic() + 0.5
                            return False
                        if info.codec:
                            try:
                                payload = self.codec_provider.decompress_many(
                                    info.codec, [payload])[0]
                            except Exception as e:
                                self.op_err(KafkaError(
                                    Err._BAD_COMPRESSION,
                                    f"{tp}: decompress ({info.codec}): "
                                    f"{e!r}"))
                                tp.fetch_backoff_until = \
                                    time.monotonic() + 0.5
                                return False
                    batches.append((info, payload, last))
            for info, payload, last in batches:
                if last < fo:
                    next_offset = max(next_offset, last + 1)
                    continue
                # aborted-txn bookkeeping
                pid = info.producer_id
                if read_committed and pid in aborted:
                    while aborted[pid] and aborted[pid][0] <= info.base_offset:
                        aborted[pid].pop(0)
                        active_aborts.add(pid)
                if info.is_control:
                    # control record: key = [version i16, type i16]
                    try:
                        recs = (parse_records_v2(info, payload)
                                if payload is not None else [])
                        if recs and recs[0].key and len(recs[0].key) >= 4:
                            ctype = int.from_bytes(recs[0].key[2:4], "big")
                            if ctype == proto.CTRL_ABORT:
                                active_aborts.discard(pid)
                    except Exception:
                        pass
                    next_offset = last + 1
                    continue
                if (read_committed and info.is_transactional
                        and pid in active_aborts):
                    next_offset = last + 1
                    continue
                if payload is None:      # decompress failed (phase C)
                    self.op_err(KafkaError(
                        Err._BAD_COMPRESSION,
                        f"{tp}: decompress ({info.codec}) failed at "
                        f"offset {info.base_offset}"))
                    tp.fetch_backoff_until = time.monotonic() + 0.5
                    return False
                # direct Message materialization off the native field
                # walk (no intermediate Record; ~1.5 us/msg on this path)
                ms, mbytes = parse_fetch_messages_v2(
                    info, payload, tp.topic, tp.partition, fo)
                if _trace.enabled and _trace.flow_sample_every and ms:
                    # flow point 3/4 (ISSUE 20): sampled offsets now
                    # back on the wire consumer-side
                    step = _trace.flow_sample_every
                    lo = ms[0].offset
                    for off in range(lo + (-lo) % step,
                                     ms[-1].offset + 1, step):
                        _trace.instant("flow", "flow_fetch",
                                       {"topic": tp.topic,
                                        "partition": tp.partition,
                                        "offset": off})
                msgs.extend(ms)
                msgs_bytes += mbytes
                next_offset = last + 1
        else:
            dec = lambda codec, b: self.codec_provider.decompress_many(codec, [b])[0]
            for r in parse_msgset_v01(blob, dec):
                if r.offset < fo:
                    continue
                m = Message(tp.topic, value=r.value, key=r.key,
                            partition=tp.partition, timestamp=r.timestamp)
                m.offset = r.offset
                msgs.append(m)
                msgs_bytes += m.size
                next_offset = max(next_offset, r.offset + 1)

        if tp.version != ver:
            return True  # seek/rebalance raced this response: drop it
        tp.fetch_offset = next_offset
        tp.eof_reported_at = proto.OFFSET_INVALID
        if self.interceptors:
            for m in msgs:
                self.interceptors.on_consume(m)
        # accounting BEFORE the push: the app thread may drain the op
        # (decrements clamp at 0) the instant it becomes visible.
        # Under the toppar lock — the app thread's decrement is a
        # concurrent read-modify-write, and the --races sweep convicted
        # the old bare ``+=`` here racing consumer.py's drain (a GIL
        # switch between the load and the store loses an update, and
        # the clamp then silently re-zeroes the budget)
        with tp.lock:
            tp.fetchq_cnt += len(msgs)
            tp.fetchq_bytes += msgs_bytes
        if msgs:
            if _trace.enabled and _trace.flow_sample_every:
                # flow point 4/4: handed to the app-facing fetch queue
                step = _trace.flow_sample_every
                lo = msgs[0].offset
                for off in range(lo + (-lo) % step,
                                 msgs[-1].offset + 1, step):
                    _trace.instant("flow", "flow_deliver",
                                   {"topic": tp.topic,
                                    "partition": tp.partition,
                                    "offset": off})
            # ONE op per parsed partition response (per-message op
            # push/pop dominated the consume profile)
            tp.fetchq.push(Op(OpType.FETCH,
                              payload=(tp, msgs, ver, msgs_bytes)))
        if self.stats:
            self.stats.add_rx(len(msgs))
        return True

    def offset_reset(self, tp: Toppar, reason: str):
        """Apply auto.offset.reset (reference: rdkafka_offset.c
        RD_KAFKA_OP_OFFSET_RESET path)."""
        policy = self.topic_conf_for(tp.topic).get("auto.offset.reset")
        if policy in ("smallest", "earliest", "beginning"):
            tp.fetch_offset = proto.OFFSET_BEGINNING
            tp.fetch_state = FetchState.OFFSET_QUERY
        elif policy in ("largest", "latest", "end"):
            tp.fetch_offset = proto.OFFSET_END
            tp.fetch_state = FetchState.OFFSET_QUERY
        else:
            m = Message(tp.topic, partition=tp.partition)
            m.error = KafkaError(Err._NO_OFFSET, reason)
            tp.fetchq.push(Op(OpType.CONSUMER_ERR, payload=(tp, m, tp.version)))
            tp.fetch_state = FetchState.STOPPED
        self.dbg("fetch", f"{tp}: offset reset ({policy}): {reason}")

    # -------------------------------------------------------------- close --
    def close(self, timeout: float = 5.0):
        if self.is_producer:
            self.flush(timeout)
        self.terminating = True
        if self._stats_timer is not None:
            self.timers.stop(self._stats_timer)
            from .stats import _ACTIVE_STATS_TIMERS
            _ACTIVE_STATS_TIMERS.discard(id(self._stats_timer))
            self._stats_timer = None
        if self._trace_ref:
            # release this client's tracer reference (the last release
            # disables recording and frees every ring)
            self._trace_ref = False
            _trace.disable()
        if self._lockdep_ref:
            # the order graph survives for lockdep.report(); only the
            # recording refcount drops
            self._lockdep_ref = False
            _lockdep.disable()
        if self._races_ref:
            # findings survive for races.report(); the last release
            # uninstalls the Guarded descriptors
            self._races_ref = False
            _races.disable()
        with self._brokers_lock:
            brokers = list(self.brokers.values())
        for b in brokers:
            b.stop()
        for b in brokers:
            b.thread.join(timeout=2.0)
        self._main.join(timeout=2.0)
        if self.interceptors:
            self.interceptors.on_destroy(self)
        if self.mock_cluster:
            self.mock_cluster.stop()
        if self.offset_store is not None:
            self.offset_store.close()
        if self.background is not None:
            self.background.stop()
        if self.codec_worker is not None:
            self.codec_worker.stop()
        # async offload engine: drain in-flight launches + stop its
        # dispatch thread (TpuCodecProvider; CPU provider has no close)
        pclose = getattr(self.codec_provider, "close", None)
        if pclose is not None:
            try:
                pclose()
            except Exception:
                pass
        # Release the fat buffers NOW, not at the next gen2 GC pass:
        # the client object graph is cyclic (rk<->brokers<->toppars<->
        # queues<->callbacks), so without this the arena slabs, socket
        # buffers and queued messages — hundreds of MB on a busy
        # instance — stay live until the collector happens by. A
        # process that closes one client and starts another (the bench
        # shape, also common in tests) then walks its heap through
        # fresh pages instead of recycling (this VM's lazy pager makes
        # a first touch ~21 us/page; rd_kafka_destroy frees eagerly
        # for the same reason).
        with self._toppars_lock:
            tps = list(self._toppars.values())
        for tp in tps:
            tp.arena = None
            tp.msgq.clear()
            tp.xmit_msgq.clear()
            tp.retry_batches.clear()
        if getattr(self, "_lane", None) is not None:
            try:
                for key in list(self._lane.map):
                    self._lane.map_del(*key)
            except Exception:
                pass
        for b in brokers:
            # only reap a broker whose thread really exited: a stuck
            # thread (join timed out above) still OWNS these structures
            # — clearing them under it races its serve loop ("deque
            # mutated during iteration", claims lost mid-release)
            if b.thread.is_alive():
                continue
            b._rbuf = bytearray()
            b._fetch_deferred.clear()
            b.outq.clear()
            b.waitresp.clear()

    # ------------------------------------------------------- oauthbearer --
    def set_oauthbearer_token(self, token: str, lifetime_ms: int = 0,
                              principal: str = "") -> None:
        """App-supplied OAUTHBEARER token (rd_kafka_oauthbearer_set_token).
        A refresh is scheduled at 80% of the token lifetime, firing the
        oauthbearer_token_refresh_cb again (the previous schedule is
        replaced, so proactive re-sets don't accumulate timers)."""
        expiry = (time.time() + lifetime_ms / 1000.0) if lifetime_ms else 0
        self._oauth_token = (token, principal, expiry)
        self._oauth_failure = None
        if self._oauth_timer is not None:
            self.timers.stop(self._oauth_timer)
            self._oauth_timer = None
        if lifetime_ms > 0 and self.conf.get("oauthbearer_token_refresh_cb"):
            self._oauth_timer = self.timers.add(
                max(1.0, lifetime_ms / 1000.0 * 0.8),
                lambda: self._oauth_refresh_fire(force=True), once=True)

    def set_oauthbearer_token_failure(self, errstr: str) -> None:
        """(rd_kafka_oauthbearer_set_token_failure) — the failure stands
        until the next refresh attempt, which clears it and retries."""
        self._oauth_failure = errstr

    def _oauth_refresh_fire(self, force: bool = False):
        """Invoke the app's refresh cb. Serialized: concurrent broker
        reconnects must not fan out duplicate token fetches (the
        reference guarantees single-threaded cb invocation).
        ``force`` is the proactive 80%-lifetime timer path — the token
        is still fresh there by construction, that's the point."""
        cb = self.conf.get("oauthbearer_token_refresh_cb")
        if cb is None or self.terminating:
            return
        with self._oauth_cb_lock:
            if not force and self._oauth_token_fresh():
                return              # another thread already refreshed
            self._oauth_failure = None    # each attempt starts clean
            try:
                cb(self, self.conf.get("sasl.oauthbearer.config"))
            except Exception as e:
                self._oauth_failure = repr(e)
                self.log("ERROR", f"oauthbearer refresh cb raised: {e!r}")

    def _oauth_token_fresh(self) -> bool:
        t = self._oauth_token
        if t is None:
            return False
        _tok, _principal, expiry = t
        return not expiry or time.time() < expiry

    def get_oauthbearer_token(self):
        """Token for the SASL client: a fresh app-set token, else invoke
        the refresh callback (which must call set_oauthbearer_token).
        Returns the (token, principal, expiry) tuple or None — None with
        a refresh cb configured is an authentication FAILURE, never an
        unsecured-JWS fallback."""
        if not self._oauth_token_fresh():
            if self.conf.get("oauthbearer_token_refresh_cb") is not None:
                self._oauth_refresh_fire()
        if self._oauth_failure or not self._oauth_token_fresh():
            return None
        return self._oauth_token

    # ----------------------------------------------------------- security --
    def ssl_ctx(self):
        """The per-instance TLS context, or None for plaintext
        (reference: rk_conf.ssl.ctx built at rd_kafka_ssl_ctx_init)."""
        return self._ssl_ctx

    def connect_cb(self, host: str, port: int, timeout: float):
        """Create the TCP connection for a broker. Honors the app's
        ``connect_cb``/``socket_cb`` conf hooks — the seam the reference
        exposes for sockem-style network shaping (rdkafka_conf.c
        socket_cb/connect_cb; tests/sockem.c interposes here). Also
        applies socket.* buffer/keepalive knobs and
        broker.address.family resolution."""
        cb = self.conf.get("connect_cb")
        if cb is not None:
            return cb(host, port, timeout)
        fam_conf = self.conf.get("broker.address.family")
        family = {"v4": socket.AF_INET, "v6": socket.AF_INET6}.get(
            fam_conf, socket.AF_UNSPEC)
        sock_cb = self.conf.get("socket_cb")
        last_err = None
        for af, stype, sproto, _, addr in self._resolve(host, port, family):
            try:
                s = (sock_cb(af, stype, sproto) if sock_cb is not None
                     else socket.socket(af, stype, sproto))
            except OSError as e:
                last_err = e
                continue
            try:
                sndbuf = self.conf.get("socket.send.buffer.bytes")
                if sndbuf:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
                rcvbuf = self.conf.get("socket.receive.buffer.bytes")
                if rcvbuf:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
                if self.conf.get("socket.keepalive.enable"):
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
                s.settimeout(timeout)
                s.connect(addr)
                return s
            except OSError as e:
                last_err = e
                try:
                    s.close()
                except OSError:
                    pass
        raise last_err or OSError(f"cannot resolve {host}:{port}")

    def _resolve(self, host: str, port: int, family) -> list:
        """getaddrinfo with a broker.address.ttl cache (reference:
        rdaddr.c rd_sockaddr_list caching + rotation)."""
        ttl = self.conf.get("broker.address.ttl") / 1000.0
        key = (host, port, family)
        now = time.monotonic()
        hit = self._addr_cache.get(key)
        if hit is not None and now < hit[0]:
            return hit[1]
        infos = socket.getaddrinfo(host, port, family, socket.SOCK_STREAM)
        if ttl > 0:
            self._addr_cache[key] = (now + ttl, infos)
        return infos

    # ---------------------------------------------------------------- SASL --
    def sasl_required(self) -> bool:
        return self.conf.get("security.protocol") in ("sasl_plaintext",
                                                      "sasl_ssl")

    def sasl_start(self, broker: Broker):
        from .sasl import sasl_client_start
        sasl_client_start(self, broker)
